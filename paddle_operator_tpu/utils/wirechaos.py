"""Deterministic wire-fault proxy for fleet edges (ISSUE 20).

``infer/chaos.py`` injects faults at ring **dispatch indices** — it can
kill a device or poison an all-reduce, but it cannot touch the wires
the fleet actually runs on.  This module is the missing half: a
seeded, jax-free HTTP proxy that sits on any fleet edge and injures
traffic at deterministic **request indices**, generalizing the one-off
truncating/corrupting proxies the serve-prefillpool gate used to
hand-roll (``__graft_entry__._serve_prefillpool_gate``).

Edges (the names double as schedule keys)::

    client-router    production client  -> fleet router
    router-replica   fleet router       -> decode replica
    replica-broker   decode replica     -> router broker (/v1/kv/*)
    decode-prefill   decode replica     -> prefill pod
    replica-store    decode replica     -> durable prefix store front

Fault kinds (applied to POSTs only — GETs, i.e. /readyz and /metrics
scrapes, always relay transparently so fault indices stay pinned to
the *work* stream, independent of scrape timing)::

    drop        read half the request body, then close the socket —
                the request never reaches the upstream (connection
                drop mid-body; client sees a reset and retries)
    truncate    relay the response but cut the body to one third
                (min 8 bytes) and close without the chunked
                terminator — mid-stream death
    corrupt     flip one byte of the response payload (position
                drawn from the seeded rng)
    dup         deliver the request to the upstream TWICE; relay the
                second response — duplicate delivery, the edge's
                idempotency (router dedupe / broker migration replay /
                side-effect-free prefill) is what keeps it correct
    burst503    answer ``503`` + ``Retry-After: 1`` without contacting
                the upstream; ``arg`` = burst length in consecutive
                POSTs (default 1)
    blackhole   accept the request, then hang ``arg`` seconds
                (default 30) and close without a response — the fault
                the router's circuit breaker exists for
    trickle     relay the response byte-identically but spread over
                ``arg`` seconds (default 1.0) in small chunks

Schedules mirror ``TPUJOB_CHAOS``: ``kind@index[:arg]`` atoms, comma
separated, grouped per edge with ``edge=...`` and ``;`` between edges::

    TPUJOB_WIRE_CHAOS="client-router=drop@2,burst503@5:3;router-replica=blackhole@4:6"
    TPUJOB_WIRE_CHAOS_SEED=7

``index`` is the Nth POST (0-based) through that proxy.  Unknown kinds
and unknown edge names raise ``ValueError`` — a typo'd schedule that
silently injected nothing would fake a green chaos gate.  Every fault
is counted per edge and pinned in ``fired`` so tests assert exactly
what was injected (``tpujob_wirechaos_*`` counters,
docs/observability.md).

Fault-free traffic through a proxy is byte-identical to the direct
path — the serve-wirechaos gate pins this with a byte-compare, so the
proxy can be left installed on a production edge at zero risk.

Standalone (so an edge of a real deployment can be injured without
touching either endpoint)::

    python -m paddle_operator_tpu.utils.wirechaos client-router 127.0.0.1:8800 --port 8899
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass, field
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from random import Random
from typing import Dict, List, Optional, Tuple

WIRE_CHAOS_ENV = "TPUJOB_WIRE_CHAOS"
WIRE_CHAOS_SEED_ENV = "TPUJOB_WIRE_CHAOS_SEED"

EDGES = ("client-router", "router-replica", "replica-broker",
         "decode-prefill", "replica-store")

KINDS = ("drop", "truncate", "corrupt", "dup", "burst503", "blackhole",
         "trickle")


@dataclass(frozen=True)
class WireEvent:
    kind: str
    at: int                     # Nth POST through the edge, 0-based
    arg: float = 0.0


def parse_schedule(spec: str) -> Dict[str, List[WireEvent]]:
    """``edge=kind@index[:arg],...[;edge=...]`` -> events per edge.

    Raises ``ValueError`` on unknown edges or kinds — same discipline
    as ``chaos.parse_schedule``: a schedule that silently matches
    nothing would fake a green gate.
    """
    out: Dict[str, List[WireEvent]] = {}
    for group in spec.split(";"):
        group = group.strip()
        if not group:
            continue
        edge, eq, atoms = group.partition("=")
        edge = edge.strip()
        if not eq:
            raise ValueError(
                f"wirechaos group {group!r} missing 'edge=' prefix")
        if edge not in EDGES:
            raise ValueError(
                f"unknown wirechaos edge {edge!r} (known: {EDGES})")
        events = out.setdefault(edge, [])
        for atom in atoms.split(","):
            atom = atom.strip()
            if not atom:
                continue
            kind, _, rest = atom.partition("@")
            kind = kind.strip()
            if kind not in KINDS:
                raise ValueError(
                    f"unknown wirechaos kind {kind!r} (known: {KINDS})")
            at_s, _, arg_s = rest.partition(":")
            events.append(WireEvent(kind, int(at_s),
                                    float(arg_s) if arg_s else 0.0))
        events.sort(key=lambda e: e.at)
    return out


# Response headers worth relaying verbatim — Content-Length /
# Transfer-Encoding are recomputed by the relay itself.
_FWD_RESP = ("content-type", "retry-after")


class WireChaosProxy:
    """One injured edge: a threading HTTP proxy in front of
    ``upstream`` (``host:port``) applying ``events`` at deterministic
    POST indices.  ``counters["faults"][kind]`` and ``fired``
    [(kind, index)] are the assertion surface."""

    def __init__(self, upstream: str,
                 events: Optional[List[WireEvent]] = None, *,
                 edge: str = "client-router", seed: int = 0,
                 host: str = "127.0.0.1", port: int = 0,
                 upstream_timeout: float = 120.0) -> None:
        if edge not in EDGES:
            raise ValueError(
                f"unknown wirechaos edge {edge!r} (known: {EDGES})")
        for ev in events or []:
            if ev.kind not in KINDS:
                raise ValueError(f"unknown wirechaos kind {ev.kind!r}")
        self.upstream = upstream.strip().rstrip("/")
        self.edge = edge
        self.rng = Random(seed)
        self.upstream_timeout = upstream_timeout
        self._sched: Dict[int, WireEvent] = {}
        for ev in events or []:
            # one fault per index — first scheduled wins
            self._sched.setdefault(ev.at, ev)
        self._lock = threading.Lock()
        self._idx = 0
        self._burst_left = 0
        self.fired: List[Tuple[str, int]] = []
        self.counters: Dict[str, object] = {
            "requests": 0, "upstream_errors": 0,
            "faults": {k: 0 for k in KINDS}}

        proxy = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):            # quiet
                pass

            def do_GET(self):                      # scrapes: transparent
                proxy._relay_get(self)

            def do_POST(self):
                proxy._serve_post(self)

        self._srv = ThreadingHTTPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self.host = host
        self.port = int(self._srv.server_address[1])
        self.endpoint = f"{self.host}:{self.port}"
        self.url = f"http://{self.endpoint}"
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------
    def start(self) -> "WireChaosProxy":
        self._thread = threading.Thread(
            target=self._srv.serve_forever,
            name=f"wirechaos-{self.edge}", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- metrics ------------------------------------------------------
    def metrics_text(self) -> str:
        lines = [f'tpujob_wirechaos_requests_total{{edge="{self.edge}"}}'
                 f' {float(self.counters["requests"])}']
        for kind in KINDS:
            n = self.counters["faults"][kind]
            lines.append(
                f'tpujob_wirechaos_faults_total{{edge="{self.edge}",'
                f'kind="{kind}"}} {float(n)}')
        lines.append(
            f'tpujob_wirechaos_upstream_errors_total'
            f'{{edge="{self.edge}"}}'
            f' {float(self.counters["upstream_errors"])}')
        return "\n".join(lines) + "\n"

    # -- relay internals ----------------------------------------------
    def _conn(self) -> HTTPConnection:
        host, _, port = self.upstream.rpartition(":")
        return HTTPConnection(host, int(port),
                              timeout=self.upstream_timeout)

    @staticmethod
    def _req_headers(h) -> Dict[str, str]:
        out = {}
        for k, v in h.headers.items():
            lk = k.lower()
            if lk == "content-type" or lk.startswith("x-"):
                out[k] = v
        return out

    def _relay_get(self, h) -> None:
        conn = self._conn()
        try:
            conn.request("GET", h.path, headers=self._req_headers(h))
            resp = conn.getresponse()
            body = resp.read()
        except (OSError, socket.timeout):
            with self._lock:
                self.counters["upstream_errors"] += 1
            self._plain(h, 503, b'{"error": "wirechaos: upstream down"}')
            return
        finally:
            conn.close()
        self._respond(h, resp, body)

    def _respond(self, h, resp, body: bytes) -> None:
        """Non-streamed relay of an upstream response."""
        try:
            h.send_response(resp.status)
            for k, v in resp.getheaders():
                if k.lower() in _FWD_RESP or k.lower().startswith("x-"):
                    h.send_header(k, v)
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)
        except (OSError, socket.timeout):
            pass                # client went away mid-write

    def _plain(self, h, status: int, body: bytes,
               retry_after: Optional[str] = None) -> None:
        try:
            h.send_response(status)
            h.send_header("Content-Type", "application/json")
            if retry_after is not None:
                h.send_header("Retry-After", retry_after)
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)
        except (OSError, socket.timeout):
            pass

    def _hang_up(self, h) -> None:
        """Close the client socket abruptly (no HTTP response)."""
        try:
            h.close_connection = True
            h.connection.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            h.connection.close()
        except OSError:
            pass

    # -- the POST path ------------------------------------------------
    def _serve_post(self, h) -> None:
        with self._lock:
            idx = self._idx
            self._idx += 1
            ev = self._sched.get(idx)
            if ev is None and self._burst_left > 0:
                self._burst_left -= 1
                ev = WireEvent("burst503", idx)
            elif ev is not None and ev.kind == "burst503":
                self._burst_left = max(0, int(ev.arg or 1) - 1)
            if ev is not None:
                self.fired.append((ev.kind, idx))
                self.counters["faults"][ev.kind] += 1
            self.counters["requests"] += 1
        kind = ev.kind if ev is not None else None

        clen = int(h.headers.get("Content-Length", "0") or 0)

        if kind == "drop":
            # connection drop mid-body: consume half the upload, reset
            if clen:
                h.rfile.read(max(1, clen // 2))
            self._hang_up(h)
            return
        if kind == "burst503":
            h.rfile.read(clen)
            self._plain(h, 503,
                        b'{"error": "wirechaos: injected 503 burst"}',
                        retry_after="1")
            return
        if kind == "blackhole":
            h.rfile.read(clen)
            time.sleep(ev.arg or 30.0)
            self._hang_up(h)
            return

        body = h.rfile.read(clen)
        headers = self._req_headers(h)

        if kind == "dup":
            # duplicate delivery: the upstream executes twice; relay
            # the SECOND response — dedupe/idempotency must absorb it
            st, raw, hdrs, err = self._post_upstream(h.path, body,
                                                     headers)
            if err:
                self._plain(h, 503,
                            b'{"error": "wirechaos: upstream down"}')
                return
        st, raw, hdrs, err = self._post_upstream(h.path, body, headers)
        if err:
            with self._lock:
                self.counters["upstream_errors"] += 1
            self._plain(h, 503, b'{"error": "wirechaos: upstream down"}')
            return

        if kind == "truncate":
            cut = raw[:max(8, len(raw) // 3)]
            try:
                h.send_response(st)
                for k, v in hdrs:
                    if (k.lower() in _FWD_RESP
                            or k.lower().startswith("x-")):
                        h.send_header(k, v)
                h.send_header("Transfer-Encoding", "chunked")
                h.end_headers()
                h.wfile.write(f"{len(cut):x}\r\n".encode() + cut
                              + b"\r\n")
                h.wfile.flush()
            except (OSError, socket.timeout):
                pass
            self._hang_up(h)    # no terminator: mid-stream death
            return
        if kind == "corrupt" and raw:
            pos = self.rng.randrange(len(raw))
            raw = raw[:pos] + bytes([raw[pos] ^ 0xFF]) + raw[pos + 1:]
        if kind == "trickle":
            total_s = ev.arg or 1.0
            slices = 8
            step = max(1, (len(raw) + slices - 1) // slices) or 1
            try:
                h.send_response(st)
                for k, v in hdrs:
                    if (k.lower() in _FWD_RESP
                            or k.lower().startswith("x-")):
                        h.send_header(k, v)
                h.send_header("Transfer-Encoding", "chunked")
                h.end_headers()
                for i in range(0, max(len(raw), 1), step):
                    piece = raw[i:i + step]
                    if piece:
                        h.wfile.write(f"{len(piece):x}\r\n".encode()
                                      + piece + b"\r\n")
                        h.wfile.flush()
                    time.sleep(total_s / slices)
                h.wfile.write(b"0\r\n\r\n")
            except (OSError, socket.timeout):
                pass
            return

        # fault-free (and corrupt, which is shape-preserving): relay
        # the exact bytes — the gate byte-compares this path
        fake = _FakeResp(st, hdrs)
        self._respond(h, fake, raw)

    def _post_upstream(self, path: str, body: bytes,
                       headers: Dict[str, str]):
        conn = self._conn()
        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            return resp.status, resp.read(), resp.getheaders(), False
        except (OSError, socket.timeout):
            return 0, b"", [], True
        finally:
            conn.close()


class _FakeResp:
    def __init__(self, status: int, headers) -> None:
        self.status = status
        self._headers = headers

    def getheaders(self):
        return self._headers


# ---------------------------------------------------------------------------
# Env-driven install (mirrors chaos.maybe_install_from_env)
# ---------------------------------------------------------------------------

_ENV_PROXIES: List[WireChaosProxy] = []


def maybe_proxy_from_env(edge: str, upstream: str,
                         env: Optional[Dict[str, str]] = None
                         ) -> Optional[WireChaosProxy]:
    """Start a proxy for ``edge`` in front of ``upstream`` when
    ``TPUJOB_WIRE_CHAOS`` schedules faults on that edge; None
    otherwise.  Raises ``ValueError`` on a malformed schedule."""
    env = os.environ if env is None else env
    spec = env.get(WIRE_CHAOS_ENV, "").strip()
    if not spec:
        return None
    sched = parse_schedule(spec)
    if edge not in sched:
        return None
    seed = int(env.get(WIRE_CHAOS_SEED_ENV, "0") or 0)
    proxy = WireChaosProxy(upstream, sched[edge], edge=edge,
                           seed=seed).start()
    _ENV_PROXIES.append(proxy)
    print(f"wirechaos: edge {edge} injured "
          f"({len(sched[edge])} scheduled fault(s), seed {seed}) — "
          f"{proxy.endpoint} -> {upstream}", flush=True)
    return proxy


def wire_endpoint_from_env(edge: str, upstream: str,
                           env: Optional[Dict[str, str]] = None) -> str:
    """Endpoint indirection for callers that only hold a ``host:port``
    string: returns the injured proxy endpoint when the env schedules
    this edge, the upstream unchanged otherwise."""
    if not upstream:
        return upstream
    proxy = maybe_proxy_from_env(edge, upstream, env=env)
    return proxy.endpoint if proxy is not None else upstream


def env_proxies() -> List[WireChaosProxy]:
    return list(_ENV_PROXIES)


def close_env_proxies() -> None:
    while _ENV_PROXIES:
        _ENV_PROXIES.pop().close()


# ---------------------------------------------------------------------------
# Standalone CLI — injure an edge of a live deployment
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="wirechaos: deterministic wire-fault proxy")
    ap.add_argument("edge", choices=EDGES)
    ap.add_argument("upstream", help="host:port to front")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--schedule", default=None,
                    help=f"kind@index[:arg],... (default: the {edge_env()}"
                         " entry for this edge)")
    ap.add_argument("--seed", type=int, default=None)
    args = ap.parse_args(argv)

    if args.schedule is not None:
        events = parse_schedule(f"{args.edge}={args.schedule}"
                                ).get(args.edge, [])
    else:
        spec = os.environ.get(WIRE_CHAOS_ENV, "")
        events = parse_schedule(spec).get(args.edge, []) if spec else []
    seed = (args.seed if args.seed is not None
            else int(os.environ.get(WIRE_CHAOS_SEED_ENV, "0") or 0))
    proxy = WireChaosProxy(args.upstream, events, edge=args.edge,
                           seed=seed, host=args.host, port=args.port)
    print(f"wirechaos proxy [{args.edge}] listening on "
          f"{proxy.endpoint} -> {args.upstream} "
          f"({len(events)} scheduled fault(s), seed {seed})", flush=True)
    try:
        proxy._srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        proxy._srv.server_close()
        print(proxy.metrics_text(), flush=True)
    return 0


def edge_env() -> str:
    return WIRE_CHAOS_ENV


if __name__ == "__main__":
    raise SystemExit(main())
