"""Request-scoped tracing, latency histograms, flight recorder (ISSUE 15).

The serving fleet (PRs 6-14) grew into router -> prefill pool -> decode
replicas with migration, spill/restore and an SLO autoscaler — but its
only telemetry was point-in-time gauges.  This module is the jax-free
observability kit the whole stack wires through:

- **Request spans** — each request carries a trace context (the
  ``X-Tpujob-Trace`` header: ``<trace_id>`` or
  ``<trace_id>-<parent_span_id>``) and accumulates monotonic-clock
  phase spans (:class:`RequestTrace`) at the scheduler's EXISTING
  blocking points: queue wait, admission, prefill slices, handoff
  uploads, decode dispatches, spill/restore, migration, adoption.
  Completed span sets ride response metadata so the router can stitch
  ONE cross-pod timeline per request (:class:`TraceStore`,
  ``/debug/tracez``).  Tracing is strictly additive host bookkeeping:
  it never adds a device sync, and token streams with tracing on are
  byte-identical to tracing off (the dryrun ``serve-trace`` line pins
  it).

- **Histograms** — fixed log-bucket Prometheus histograms
  (:class:`Histogram`, :class:`ServeHistograms`) for the SLO-bearing
  latencies: TTFT, inter-token latency (chunk-granular), e2e, and
  queue wait.  Fixed bounds mean bucket counts FOLD across replicas by
  addition (:func:`fold_latency_hists`) — the router folds scraped
  per-replica histograms fleet-wide, and the SLO autoscaler reads a
  real windowed p95 (:func:`hist_p95`) instead of a point gauge.

- **Flight recorder** — a bounded ring of structured events per pod
  (:class:`FlightRecorder`: admission, preemption, watchdog rebuild,
  NaN quarantine, envelope refusal, migration/adoption outcome, drain
  transitions, chaos injection) that dumps JSON on watchdog restart,
  chaos injection and SIGTERM, and is served at ``/debug/flightrec``.

Everything here is stdlib-only — the router and controller processes
import it without jax.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

# the cross-pod trace context header: "<trace_id>" (the client/router
# minted a trace but no parent span) or "<trace_id>-<parent_span_id>"
TRACE_HEADER = "X-Tpujob-Trace"

# env knob serve.py reads: SERVE_TRACE=1 turns span capture on for a
# replica (histograms and the flight recorder are always on — they are
# metrics, like the gauges)
TRACE_ENV = "SERVE_TRACE"


def trace_enabled(env=None) -> bool:
    env = os.environ if env is None else env
    return env.get(TRACE_ENV, "0") == "1"


def safe_header_value(value, cap: int = 128) -> str:
    """A client-supplied string (request_id) made safe to ECHO in a
    response header: printable ASCII only (CR/LF would split the
    response; non-latin-1 raises inside send_header AFTER the status
    line, truncating an otherwise-good reply), bounded length."""
    return "".join(c if " " <= c <= "~" else "_"
                   for c in str(value))[:cap]


def new_id() -> str:
    """16-hex span/trace id (crypto-strength uniqueness is not the
    point; cross-process collision resistance is)."""
    return os.urandom(8).hex()


def format_trace_header(trace_id: str,
                        parent: Optional[str] = None) -> str:
    return f"{trace_id}-{parent}" if parent else str(trace_id)


def parse_trace_header(value: Optional[str]
                       ) -> Optional[Tuple[str, Optional[str]]]:
    """``(trace_id, parent_span_id | None)`` — or None for an absent /
    unusable header (tracing silently off for that request; a
    malformed header must never 400 a generate)."""
    if not value:
        return None
    value = value.strip()
    if not value:
        return None
    tid, sep, parent = value.partition("-")
    if not tid:
        return None
    return tid, (parent or None)


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------


def make_span(name: str, parent: Optional[str], t0_ms: float,
              dur_ms: float, *, span_id: Optional[str] = None,
              pod: str = "", **attrs) -> Dict[str, Any]:
    """One wire-format span.  ``t0_ms`` is WALL-clock epoch ms (the
    only clock that means anything across pods; durations are measured
    on the monotonic clock and only anchored to wall time once)."""
    span = {"id": span_id or new_id(), "parent": parent, "name": name,
            "t0": round(float(t0_ms), 3), "dur": round(float(dur_ms), 3)}
    if pod:
        span["pod"] = pod
    if attrs:
        span["attrs"] = attrs
    return span


def span_roots(spans: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Spans whose parent is absent from the set (None, or an id the
    set does not contain — the stitched timeline's roots).  A COMPLETE
    stitched tree has exactly one."""
    ids = {s.get("id") for s in spans}
    return [s for s in spans
            if s.get("parent") is None or s.get("parent") not in ids]


class RequestTrace:
    """Per-request span accumulator (host bookkeeping only).

    A root ``request`` span opens at construction; phases land through
    :meth:`add` with MONOTONIC timestamps (wall anchoring happens once,
    here).  The span list is bounded — a 10k-token generation must not
    grow an unbounded decode-dispatch list; overflow increments
    ``dropped`` and the root carries the count.  ``add`` is
    thread-safe: the remote-prefill client and migration workers stamp
    spans off the ring thread."""

    MAX_SPANS = 128

    __slots__ = ("trace_id", "pod", "root_id", "spans", "dropped",
                 "_anchor_mono", "_anchor_wall", "_t0_mono", "_lock",
                 "_closed")

    def __init__(self, trace_id: Optional[str] = None,
                 parent: Optional[str] = None, pod: str = "",
                 request_id: Optional[str] = None) -> None:
        self.trace_id = trace_id or new_id()
        self.pod = pod
        self.root_id = new_id()
        self._anchor_mono = time.monotonic()
        self._anchor_wall = time.time()
        self._t0_mono = self._anchor_mono
        self._lock = threading.Lock()
        self._closed = False
        self.dropped = 0
        root = make_span("request", parent, self._wall_ms(
            self._anchor_mono), 0.0, span_id=self.root_id, pod=pod)
        if request_id is not None:
            root["attrs"] = {"requestId": request_id}
        self.spans: List[Dict[str, Any]] = [root]

    def _wall_ms(self, t_mono: float) -> float:
        return (self._anchor_wall + (t_mono - self._anchor_mono)) * 1e3

    def add(self, name: str, t0_mono: float,
            t1_mono: Optional[float] = None,
            parent: Optional[str] = None, **attrs) -> None:
        """Record one phase span [t0, t1) (monotonic seconds); parent
        defaults to the request root.  Attr names colliding with
        make_span's own fields are dropped rather than crashing the
        capture thread (a span is telemetry, never a fault)."""
        for reserved in ("pod", "span_id"):
            attrs.pop(reserved, None)
        t1 = time.monotonic() if t1_mono is None else t1_mono
        with self._lock:
            if len(self.spans) >= self.MAX_SPANS:
                self.dropped += 1
                return
            self.spans.append(make_span(
                name, parent or self.root_id, self._wall_ms(t0_mono),
                (t1 - t0_mono) * 1e3, pod=self.pod, **attrs))

    def annotate(self, **attrs: Any) -> None:
        """Merge attrs into the ROOT span (workload-shape stamps:
        ``promptLen``/``maxNew``/``prio`` at scheduler submit) so an
        exported span tree alone reconstructs the request the fleet
        served — the replay harness (router/replay.py) rebuilds
        open-loop schedules from exactly these attrs.  None values are
        skipped; telemetry never raises."""
        clean = {k: v for k, v in attrs.items() if v is not None}
        if not clean:
            return
        with self._lock:
            self.spans[0].setdefault("attrs", {}).update(clean)

    def seed(self, spans: Sequence[Dict[str, Any]]) -> None:
        """Graft a PRIOR pod's completed spans (lane migration: the
        origin's spans travel in the envelope meta so the adopter's
        set still stitches into one tree)."""
        with self._lock:
            room = self.MAX_SPANS - len(self.spans)
            take = list(spans)[:max(0, room)]
            self.dropped += len(spans) - len(take)
            self.spans.extend(take)

    def finish(self, error: Optional[str] = None) -> None:
        """Close the root span (idempotent — a request resolves
        exactly once, but error paths can race the loop's sweep)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            root = self.spans[0]
            root["dur"] = round(
                (time.monotonic() - self._t0_mono) * 1e3, 3)
            if error or self.dropped:
                attrs = root.setdefault("attrs", {})
                if error:
                    attrs["error"] = str(error)[:200]
                if self.dropped:
                    attrs["droppedSpans"] = self.dropped

    def to_wire(self) -> Dict[str, Any]:
        """The response-metadata form the router stitches."""
        with self._lock:
            return {"traceId": self.trace_id, "pod": self.pod,
                    "rootId": self.root_id,
                    "spans": [dict(s) for s in self.spans]}


class Tracer:
    """Span-capture switchboard for one serving process: ``None`` on a
    batcher means tracing is OFF and every capture site is one
    attribute check (the zero-cost contract)."""

    def __init__(self, pod: str = "") -> None:
        self.pod = pod

    def begin(self, ctx: Optional[Tuple[str, Optional[str]]] = None,
              request_id: Optional[str] = None) -> RequestTrace:
        tid, parent = ctx if ctx is not None else (None, None)
        return RequestTrace(trace_id=tid, parent=parent, pod=self.pod,
                            request_id=request_id)


# ---------------------------------------------------------------------------
# Histograms
# ---------------------------------------------------------------------------

# Fixed log2 bucket bounds in MILLISECONDS, 1ms..~65s.  FIXED on
# purpose: bucket counts from different replicas fold by plain
# addition only while every exporter agrees on the bounds, and the
# serving latencies of interest (TTFT, ITL, e2e, queue wait) all live
# inside this range.  docs/observability.md is the catalog of record.
BUCKETS_MS: Tuple[float, ...] = tuple(
    float(2 ** i) for i in range(17))        # 1, 2, 4, ... 65536

# the serving histogram families — family key -> metric name
HIST_FAMILIES: Dict[str, str] = {
    "ttft": "tpujob_serve_ttft_ms",
    "itl": "tpujob_serve_itl_ms",
    "e2e": "tpujob_serve_e2e_ms",
    "queueWait": "tpujob_serve_queue_wait_ms",
}

# the rolling window the autoscaler's p95 reads over: long enough to
# smooth a scrape tick, short enough that a resolved burst stops
# breaching the SLO within ~two windows
HIST_WINDOW_S = 60.0


class Histogram:
    """Prometheus-style cumulative histogram with fixed bounds, plus a
    ROLLING-WINDOW view for control decisions.

    The cumulative counts are what ``/metrics`` exposes (standard
    ``_bucket``/``_sum``/``_count`` exposition; monotone, rate()-able).
    A cumulative histogram's quantile is sticky — one slow boot hour
    would pin the p95 forever — so :meth:`p95` reads a two-epoch
    rotating window (last ``window_s``..2x``window_s`` of samples)
    instead: the SLO autoscaler reacts to NOW, not to boot."""

    def __init__(self, name: str,
                 buckets: Sequence[float] = BUCKETS_MS,
                 window_s: float = HIST_WINDOW_S,
                 clock=time.monotonic) -> None:
        self.name = name
        self.bounds = tuple(float(b) for b in buckets)
        self._clock = clock
        self.window_s = float(window_s)
        n = len(self.bounds) + 1          # trailing +Inf bucket
        self._lock = threading.Lock()
        self.counts = [0] * n
        self.sum = 0.0
        self.count = 0
        self._cur = [0] * n
        self._prev = [0] * n
        self._epoch = self._clock()

    def _bucket_of(self, v: float) -> int:
        for i, b in enumerate(self.bounds):
            if v <= b:
                return i
        return len(self.bounds)

    def _rotate_locked(self, now: float) -> None:
        gap = now - self._epoch
        if gap >= 2 * self.window_s:
            # rotation is driven by observe/snapshot calls, so a long
            # quiet gap (idle replica, paused controller polling) must
            # clear BOTH epochs — otherwise the first poll after the
            # gap would report a long-resolved burst as "the last 1-2
            # windows" and spuriously re-trigger the autoscaler's p95
            # floor
            self._prev = [0] * len(self.counts)
            self._cur = [0] * len(self.counts)
            self._epoch = now
        elif gap >= self.window_s:
            # one stale epoch survives as _prev so the window never
            # reads empty right after a rotation
            self._prev = self._cur
            self._cur = [0] * len(self.counts)
            self._epoch = now

    def observe(self, v_ms: float) -> None:
        v = float(v_ms)
        i = self._bucket_of(v)
        now = self._clock()
        with self._lock:
            self._rotate_locked(now)
            self.counts[i] += 1
            self._cur[i] += 1
            self.sum += v
            self.count += 1

    def window_counts(self) -> List[int]:
        """Per-bucket counts over the last 1-2 windows."""
        now = self._clock()
        with self._lock:
            self._rotate_locked(now)
            return [a + b for a, b in zip(self._cur, self._prev)]

    def p95(self) -> Optional[float]:
        return hist_quantile(self.bounds, self.window_counts(), 0.95)

    def snapshot(self) -> Dict[str, Any]:
        """The ``status.serving.latencyHist`` entry: cumulative counts
        for exposition, windowed counts for folding/quantiles."""
        window = self.window_counts()
        with self._lock:
            return {"buckets": list(self.bounds),
                    "counts": list(self.counts),
                    "sum": round(self.sum, 3),
                    "count": self.count,
                    "window": window}

def hist_quantile(bounds: Sequence[float], counts: Sequence[int],
                  q: float) -> Optional[float]:
    """Prometheus ``histogram_quantile``-style estimate from
    PER-BUCKET (non-cumulative) counts: find the bucket the q-rank
    lands in, interpolate linearly inside it.  None with no samples.
    The +Inf bucket reports its lower bound (the standard clamp)."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank:
            if i >= len(bounds):            # +Inf bucket
                return float(bounds[-1])
            hi = float(bounds[i])
            lo = float(bounds[i - 1]) if i else 0.0
            frac = (rank - (cum - c)) / c if c else 1.0
            return lo + (hi - lo) * frac
    return float(bounds[-1])


class ServeHistograms:
    """The serving ring's histogram set (one per
    :data:`HIST_FAMILIES`).  Always on — observing is a few host float
    ops at points the scheduler already timestamps."""

    def __init__(self, clock=time.monotonic) -> None:
        self.ttft = Histogram(HIST_FAMILIES["ttft"], clock=clock)
        self.itl = Histogram(HIST_FAMILIES["itl"], clock=clock)
        self.e2e = Histogram(HIST_FAMILIES["e2e"], clock=clock)
        self.queue_wait = Histogram(HIST_FAMILIES["queueWait"],
                                    clock=clock)

    def families(self) -> Dict[str, Histogram]:
        return {"ttft": self.ttft, "itl": self.itl, "e2e": self.e2e,
                "queueWait": self.queue_wait}

    def snapshot(self) -> Dict[str, Any]:
        return {k: h.snapshot() for k, h in self.families().items()}


def fold_latency_hists(blocks: Sequence[Dict[str, Any]]
                       ) -> Dict[str, Any]:
    """Fold per-replica ``latencyHist`` snapshot blocks into one fleet
    block by per-bucket addition.  Entries whose bucket bounds differ
    from the majority are DROPPED (a mid-rollout mixed fleet must not
    mis-add counts into the wrong bounds)."""
    out: Dict[str, Any] = {}
    for fam in HIST_FAMILIES:
        entries = [b.get(fam) for b in blocks
                   if isinstance(b.get(fam), dict)
                   and b[fam].get("buckets")]
        if not entries:
            continue
        bounds = entries[0]["buckets"]
        entries = [e for e in entries if e["buckets"] == bounds]
        n = len(bounds) + 1

        def fold(key: str) -> List[int]:
            acc = [0] * n
            for e in entries:
                vals = e.get(key)
                if not vals and key == "window":
                    # windowless snapshot (e.g. freshly parsed from
                    # exposition): its cumulative counts ARE its best
                    # window estimate
                    vals = e.get("counts")
                for i in range(min(n, len(vals or []))):
                    acc[i] += int(vals[i])
            return acc

        out[fam] = {"buckets": list(bounds),
                    "counts": fold("counts"),
                    "sum": round(sum(float(e.get("sum", 0.0))
                                     for e in entries), 3),
                    "count": sum(int(e.get("count", 0))
                                 for e in entries),
                    "window": fold("window")}
    return out


def hist_p95(entry: Optional[Dict[str, Any]]) -> Optional[float]:
    """Windowed p95 of one snapshot/folded histogram entry (the
    number the SLO autoscaler compares against the CRD target)."""
    if not isinstance(entry, dict):
        return None
    counts = entry.get("window") or entry.get("counts") or []
    return hist_quantile(entry.get("buckets") or BUCKETS_MS, counts,
                         0.95)


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

FLIGHTREC_DIR_ENV = "TPUJOB_FLIGHTREC_DIR"


class FlightRecorder:
    """Bounded ring of structured events per pod.

    ``record(kind, **detail)`` is cheap host bookkeeping (deque append
    under a lock) at event rates of admissions/preemptions — never in
    a per-token path.  ``dump_file`` writes the whole ring as JSON
    (reason-stamped, newest last) to
    ``$TPUJOB_FLIGHTREC_DIR/tpujob_flightrec_<pod|pid>.json`` — fired
    on watchdog restart, chaos injection and SIGTERM so the last
    moments before a crash/drain survive the pod."""

    def __init__(self, capacity: int = 512, pod: str = "") -> None:
        self.pod = pod or str(os.getpid())
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.dumps = 0
        self.last_dump_path: Optional[str] = None

    def record(self, kind: str, **detail) -> None:
        ev = {"t": round(time.time(), 3), "kind": str(kind)}
        if detail:
            ev.update({k: v for k, v in detail.items()
                       if v is not None})
        with self._lock:
            self._ring.append(ev)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def dump(self, reason: str) -> Dict[str, Any]:
        return {"pod": self.pod, "reason": str(reason),
                "t": round(time.time(), 3), "events": self.events()}

    def default_path(self) -> str:
        d = os.environ.get(FLIGHTREC_DIR_ENV) or tempfile.gettempdir()
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in self.pod)
        return os.path.join(d, f"tpujob_flightrec_{safe}.json")

    def dump_file(self, reason: str,
                  path: Optional[str] = None) -> Optional[str]:
        """Write the dump; returns the path (None on I/O failure — a
        full disk must never take the serving path down with it)."""
        path = path or self.default_path()
        try:
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(self.dump(reason), f)
            os.replace(tmp, path)
        except OSError:
            return None
        self.dumps += 1
        self.last_dump_path = path
        return path


# ---------------------------------------------------------------------------
# Router-side timeline store
# ---------------------------------------------------------------------------


class TraceStore:
    """Bounded LRU of stitched cross-pod timelines, keyed by trace id
    (the router's ``/debug/tracez`` backing store).

    The router creates ONE parentless ``request`` root span per trace
    (:meth:`root`) and parents every proxy attempt under it — so a
    retried request (replica died, lane migrated) stitches into the
    SAME tree instead of spawning a second root.  Replica span sets
    (ridden back on response metadata) land via :meth:`add`."""

    def __init__(self, cap: int = 256) -> None:
        self.cap = int(cap)
        self._lock = threading.Lock()
        self._timelines: "OrderedDict[str, Dict[str, Any]]" = \
            OrderedDict()

    def root(self, trace_id: str, parent: Optional[str] = None,
             request_id: Optional[str] = None) -> Dict[str, Any]:
        """Get-or-create the timeline for ``trace_id``; returns its
        root span (callers parent attempt spans on its id)."""
        with self._lock:
            tl = self._timelines.get(trace_id)
            if tl is None:
                root = make_span("request", parent, time.time() * 1e3,
                                 0.0)
                if request_id is not None:
                    root["attrs"] = {"requestId": request_id}
                tl = {"traceId": trace_id, "requestId": request_id,
                      "spans": [root]}
                self._timelines[trace_id] = tl
                while len(self._timelines) > self.cap:
                    self._timelines.popitem(last=False)
            self._timelines.move_to_end(trace_id)
            return tl["spans"][0]

    MAX_TIMELINE_SPANS = 512

    def add(self, trace_id: str,
            spans: Sequence[Dict[str, Any]]) -> None:
        with self._lock:
            tl = self._timelines.get(trace_id)
            if tl is None:
                return
            room = self.MAX_TIMELINE_SPANS - len(tl["spans"])
            tl["spans"].extend(list(spans)[:max(0, room)])
            # keep the root's duration covering the whole exchange
            root = tl["spans"][0]
            root["dur"] = round(time.time() * 1e3 - root["t0"], 3)
            self._timelines.move_to_end(trace_id)

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            tl = self._timelines.get(trace_id)
            return json.loads(json.dumps(tl)) if tl else None

    def timelines(self) -> List[Dict[str, Any]]:
        with self._lock:
            return json.loads(json.dumps(list(
                self._timelines.values())))


# ---------------------------------------------------------------------------
# Machine-readable export (ISSUE 18): span trees + histogram snapshots
# as JSONL, the replay harness's recorded-trace input format
# ---------------------------------------------------------------------------

# one export line per record; "kind" discriminates
EXPORT_KIND_TIMELINE = "timeline"
EXPORT_KIND_HIST = "hist"


def export_jsonl(timelines: Sequence[Dict[str, Any]],
                 hists: Optional[Dict[str, Any]] = None,
                 pod: str = "") -> str:
    """Serialize stitched timelines (and optionally a
    :meth:`ServeHistograms.snapshot` / :func:`fold_latency_hists`
    block) as JSONL — one self-describing JSON object per line, so a
    replay consumer streams records without loading the whole export,
    and exports CONCATENATE across pods/scrapes by plain file append
    (the property JSON arrays lack, and the reason the format is
    JSONL at all).  Each line carries ``kind``:
    ``timeline`` (one stitched trace: traceId + spans) or ``hist``
    (one histogram snapshot block, ``families`` keyed like
    :data:`HIST_FAMILIES` — the calibration input for the virtual-time
    fleet model)."""
    lines: List[str] = []
    for tl in timelines:
        rec = {"kind": EXPORT_KIND_TIMELINE}
        rec.update(tl)
        lines.append(json.dumps(rec, sort_keys=True))
    if hists:
        rec = {"kind": EXPORT_KIND_HIST, "families": hists}
        if pod:
            rec["pod"] = pod
        lines.append(json.dumps(rec, sort_keys=True))
    return "\n".join(lines) + ("\n" if lines else "")


def parse_jsonl_export(text: str) -> Dict[str, Any]:
    """Parse an :func:`export_jsonl` stream (possibly several exports
    concatenated) back into ``{"timelines": [...], "hists": [...]}``.
    Unknown kinds and malformed lines are SKIPPED, not fatal — a
    replay must tolerate an export truncated by the pod dying
    mid-write, which is precisely when its trace matters most."""
    timelines: List[Dict[str, Any]] = []
    hists: List[Dict[str, Any]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if not isinstance(rec, dict):
            continue
        kind = rec.get("kind")
        if kind == EXPORT_KIND_TIMELINE and rec.get("spans"):
            timelines.append(rec)
        elif kind == EXPORT_KIND_HIST and rec.get("families"):
            hists.append(rec)
    return {"timelines": timelines, "hists": hists}


def read_flightrec_dump(path: str) -> Dict[str, Any]:
    """Read a :meth:`FlightRecorder.dump_file` JSON dump back as a
    dict (``{"pod", "reason", "t", "events"}``) — the OTHER recorded
    workload source replay accepts: ``admit`` events carry arrival
    wall-time, request id and priority, enough to rebuild an open-loop
    arrival schedule when span capture was off.  Raises OSError /
    ValueError on an unreadable or non-dump file — a replay fed a
    wrong path should fail loudly, unlike the in-band telemetry
    paths."""
    with open(path) as f:
        d = json.load(f)
    if not isinstance(d, dict) or "events" not in d:
        raise ValueError(f"{path}: not a flight-recorder dump "
                         "(no 'events' key)")
    return d
