"""Append-only router state journal (ISSUE 20).

The router's exactly-once guarantee lives in two in-memory windows —
the dedupe LRU (``request_id -> (status, body)``) and the migration
table (``request_id -> adopting endpoint``).  Before this journal they
died with the process: a ``kill -9``'d router restarted empty, and a
client retry of an already-served request re-executed it (double
execution), while a retried migration record re-admitted a lane that
already moved.  The journal persists both windows so a restarted
router boots back into the *same* exactly-once window.

Shape: one JSONL file under ``ROUTER_STATE_DIR``.  Appends are a
single ``write()`` of one ``\\n``-terminated line followed by
``fsync`` — a crash can tear at most the final line, and replay
skips any undecodable tail instead of refusing to boot.  Result
bodies are latin-1-escaped JSON strings (bodies are bytes; latin-1
round-trips every byte value).

Compaction: the file grows one line per served request forever while
the in-memory windows are capped LRUs, so once the journal exceeds
``compact_slack`` x the combined caps the router rewrites it from the
live windows (tmp file + ``os.replace`` — atomic, crash at any point
leaves either the old or the new journal, never a torn one).

Counters surface as ``tpujob_router_journal_*`` (docs/observability.md).
"""
from __future__ import annotations

import json
import os
from collections import OrderedDict
from typing import Dict, Optional, Tuple

JOURNAL_NAME = "router_journal.jsonl"


class RouterJournal:
    """Crash-safe persistence for the router's dedupe + migration
    windows.  Not thread-safe on its own — the router calls it under
    its state lock."""

    def __init__(self, state_dir: str, *,
                 compact_slack: int = 4) -> None:
        self.state_dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        self.path = os.path.join(state_dir, JOURNAL_NAME)
        self.compact_slack = max(2, int(compact_slack))
        self.records = 0            # lines in the current file
        self.appends = 0            # appends this process
        self.replayed = 0           # records restored at boot
        self.compactions = 0
        self._fh = None

    # -- appends ------------------------------------------------------
    def _open(self):
        if self._fh is None:
            self._fh = open(self.path, "ab")
        return self._fh

    def _append(self, rec: Dict) -> None:
        fh = self._open()
        fh.write(json.dumps(rec, separators=(",", ":")).encode()
                 + b"\n")
        fh.flush()
        os.fsync(fh.fileno())
        self.records += 1
        self.appends += 1

    def append_result(self, request_id: str, status: int, body: bytes,
                      replica: str = "") -> None:
        self._append({"k": "res", "id": request_id, "st": int(status),
                      "b": body.decode("latin-1"), "rep": replica})

    def append_migration(self, request_id: str, endpoint: str) -> None:
        self._append({"k": "mig", "id": request_id, "ep": endpoint})

    # -- boot replay --------------------------------------------------
    def replay(self) -> Tuple["OrderedDict[str, Tuple[int, bytes]]",
                              Dict[str, str],
                              "OrderedDict[str, str]"]:
        """Read the journal back into (results, result_replica,
        migrations) in append order — last write wins, undecodable
        lines (a torn tail from kill -9 mid-append) are skipped."""
        results: "OrderedDict[str, Tuple[int, bytes]]" = OrderedDict()
        result_replica: Dict[str, str] = {}
        migrations: "OrderedDict[str, str]" = OrderedDict()
        if not os.path.exists(self.path):
            return results, result_replica, migrations
        self.records = 0
        with open(self.path, "rb") as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                    kind = rec["k"]
                    if kind == "res":
                        rid = rec["id"]
                        results.pop(rid, None)
                        results[rid] = (int(rec["st"]),
                                        rec["b"].encode("latin-1"))
                        if rec.get("rep"):
                            result_replica[rid] = rec["rep"]
                    elif kind == "mig":
                        rid = rec["id"]
                        migrations.pop(rid, None)
                        migrations[rid] = rec["ep"]
                    else:
                        continue
                except (ValueError, KeyError, AttributeError):
                    continue        # torn / foreign line
                self.records += 1
        self.replayed = self.records
        return results, result_replica, migrations

    # -- compaction ---------------------------------------------------
    def should_compact(self, live: int) -> bool:
        return self.records > self.compact_slack * max(1, live)

    def compact(self, results: "OrderedDict[str, Tuple[int, bytes]]",
                result_replica: Dict[str, str],
                migrations: "OrderedDict[str, str]") -> None:
        """Rewrite the journal from the live (already capped) windows.
        tmp + ``os.replace`` so a crash mid-compaction leaves a whole
        journal either way."""
        tmp = self.path + ".tmp"
        n = 0
        with open(tmp, "wb") as fh:
            for rid, ep in migrations.items():
                fh.write(json.dumps(
                    {"k": "mig", "id": rid, "ep": ep},
                    separators=(",", ":")).encode() + b"\n")
                n += 1
            for rid, (st, body) in results.items():
                fh.write(json.dumps(
                    {"k": "res", "id": rid, "st": int(st),
                     "b": body.decode("latin-1"),
                     "rep": result_replica.get(rid, "")},
                    separators=(",", ":")).encode() + b"\n")
                n += 1
            fh.flush()
            os.fsync(fh.fileno())
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        os.replace(tmp, self.path)
        self.records = n
        self.compactions += 1

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
