"""Consistent-hash ring over replica endpoints.

Classic Karger ring with virtual nodes: each endpoint owns ``vnodes``
points on a 64-bit circle; a request key maps to the first endpoint
point clockwise from the key's point.  Properties the fleet relies on:

- **stability** — adding/removing one endpoint remaps ~1/N of the key
  population (only the keys whose clockwise walk crossed the changed
  endpoint's points move); every other prefix keeps hitting the replica
  whose radix cache already holds it.  Pinned by tests/test_fleet.py.
- **drain awareness without remapping** — selection takes a ``ready``
  set and walks PAST not-ready endpoints instead of rebuilding the
  ring.  A draining replica (readyz false) sheds its keys to its ring
  successors while it finishes residents; when it comes back the same
  keys return to it, radix cache intact.

Hashing is deliberately process-independent: endpoint points come from
blake2b (str hashing is PYTHONHASHSEED-salted; hashlib is not), and the
request key — the radix prefix chain key, an int — is spread over the
circle with a splitmix64 finalizer (chain keys are well-distributed but
ints must not map to themselves, or small keys would all land at the
circle's origin).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_MASK = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """Finalizer of the splitmix64 PRNG — a cheap, well-mixed 64-bit
    int->int hash (same recipe infer/scheduler.py uses for seed
    folding)."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK
    return (x ^ (x >> 31)) & _MASK


def _endpoint_point(endpoint: str, vnode: int) -> int:
    h = hashlib.blake2b(f"{endpoint}#{vnode}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


class HashRing:
    """``pick(key, ready)`` -> endpoint, or None when nothing is ready."""

    def __init__(self, endpoints: Iterable[str] = (),
                 vnodes: int = 64) -> None:
        self.vnodes = vnodes
        self._points: List[Tuple[int, str]] = []   # sorted (point, ep)
        self._keys: List[int] = []                 # points only (bisect)
        self._endpoints: Dict[str, List[int]] = {}
        for ep in endpoints:
            self.add(ep)

    @property
    def endpoints(self) -> List[str]:
        return sorted(self._endpoints)

    def __contains__(self, endpoint: str) -> bool:
        return endpoint in self._endpoints

    def __len__(self) -> int:
        return len(self._endpoints)

    def add(self, endpoint: str) -> None:
        if endpoint in self._endpoints:
            return
        pts = [_endpoint_point(endpoint, i) for i in range(self.vnodes)]
        self._endpoints[endpoint] = pts
        for p in pts:
            i = bisect.bisect_left(self._keys, p)
            self._keys.insert(i, p)
            self._points.insert(i, (p, endpoint))

    def remove(self, endpoint: str) -> None:
        pts = self._endpoints.pop(endpoint, None)
        if pts is None:
            return
        self._points = [(p, e) for (p, e) in self._points
                        if e != endpoint]
        self._keys = [p for (p, _) in self._points]

    def set_endpoints(self, endpoints: Sequence[str]) -> None:
        """Converge membership to ``endpoints`` (scale up/down): only
        the changed endpoints' points move — survivors keep theirs, so
        the ≤1/N remap bound holds across a whole set update."""
        want = set(endpoints)
        for ep in [e for e in self._endpoints if e not in want]:
            self.remove(ep)
        for ep in endpoints:
            self.add(ep)

    def pick(self, key: int,
             ready: Optional[Iterable[str]] = None) -> Optional[str]:
        """The endpoint owning ``key``: first ring point clockwise from
        the key's circle position whose endpoint is in ``ready``
        (``None`` = every member is eligible).  Walking past not-ready
        members — instead of removing them — keeps the key->endpoint
        map stable across a drain."""
        if not self._points:
            return None
        eligible = set(ready) if ready is not None else None
        if eligible is not None:
            eligible &= set(self._endpoints)
            if not eligible:
                return None
        point = _splitmix64(key & _MASK)
        start = bisect.bisect_right(self._keys, point)
        n = len(self._points)
        for off in range(n):
            _, ep = self._points[(start + off) % n]
            if eligible is None or ep in eligible:
                return ep
        return None
