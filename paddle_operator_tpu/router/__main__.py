"""``python -m paddle_operator_tpu.router`` — run the fleet router."""

from paddle_operator_tpu.router.router import main

if __name__ == "__main__":
    raise SystemExit(main())
