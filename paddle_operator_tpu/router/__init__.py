"""Fleet router — the operator-managed serving fleet's traffic tier.

A jax-free process that fronts N serving replicas (infer/serve.py pods):

- ``hashring``  — consistent-hash ring over replica endpoints, keyed by
                  the radix prefix chain key (utils/radixkey.py — the
                  SAME chain the replicas' paged KV cache uses, so
                  affinity routing and radix hits agree by construction);
- ``router``    — the HTTP proxy: streaming-aware ``/v1/generate``
                  forwarding, drain-aware replica selection from scraped
                  ``tpujob_serve_*`` gauges, idempotent request-id dedupe
                  (exactly-once at the fleet level), and the fleet's own
                  ``/metrics``/``/readyz``/``/statusz``;
- ``simfleet``  — the simulated-fleet harness (N in-process or
                  subprocess rings behind the real router) tests, the
                  dryrun ``serve-fleet`` gate, and ``bench.py
                  measure_fleet`` all drive.  The only module here that
                  may touch jax — ``python -m paddle_operator_tpu.router``
                  never imports it.

Run the router: ``python -m paddle_operator_tpu.router`` (see
``router.main`` for the ROUTER_* env surface).
"""

from paddle_operator_tpu.router.hashring import HashRing  # noqa: F401
from paddle_operator_tpu.router.router import (  # noqa: F401
    FleetRouter,
    aggregate_fleet_serving,
    make_router_server,
    parse_serve_gauges,
)
