"""Simulated serving fleet: N REAL ring servers behind the REAL router.

The fake_api.py pattern applied to the serving fleet: everything above
the pod boundary is the production code path — infer/serve.py HTTP
servers around real continuous-batching rings, the router proxying,
scraping and deduping exactly as deployed — only the pods themselves
are simulated (in-process threads, or subprocesses for honest
multi-core scaling in bench.py).  Tests, the dryrun ``serve-fleet``
gate and ``bench.py measure_fleet`` all drive fleets through this.

This is the one module under router/ that may import jax (the replicas
are real rings); the router process itself (``python -m
paddle_operator_tpu.router``) never imports it.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional

from paddle_operator_tpu.router.router import (
    FleetRouter,
    make_router_server,
)

_CLIENT_DIR = os.path.join(os.path.dirname(__file__), "..", "..",
                           "client")


def _client_module():
    """client/client.py, imported once (it lives outside the package
    tree; repeated sys.path.insert per request would grow sys.path
    without bound under bench load)."""
    if _CLIENT_DIR not in sys.path:
        sys.path.insert(0, _CLIENT_DIR)
    import client as client_cli

    return client_cli


class _Replica:
    def __init__(self, endpoint: str) -> None:
        self.endpoint = endpoint
        self.srv = None            # in-process: ThreadingHTTPServer
        self.proc = None           # subprocess: Popen
        self.thread = None
        self.exit_code: Optional[int] = None
        self.drained = False

    @property
    def batcher(self):
        return self.srv.generator.batcher if self.srv is not None \
            else None


def _tiny_params():
    import jax
    import jax.numpy as jnp

    from paddle_operator_tpu.models.llama import make_model

    model, cfg = make_model("tiny", dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return params, cfg


class SimFleet:
    """``SimFleet(2)`` -> two paged tiny-model rings + a router.

    - ``add_replica()``      scale up: the router routes to it only
      after its /readyz goes true (the scrape loop's admission gate);
    - ``drain_replica(i)``   scale down, the PR 5 way: readiness drops,
      residents finish, stragglers cancel at the budget, the server
      exits "83" (recorded — no real process to kill in-process);
    - ``kill_replica(i)``    unplanned loss: the socket just dies.

    ``affinity=False`` builds the round-robin-ish control (pure
    least-loaded routing) the affinity comparison benches against.
    """

    def __init__(self, n: int = 2, *, affinity: bool = True,
                 block_size: int = 8, slots: int = 2,
                 max_len: int = 64, chunk_tokens: int = 4,
                 prefill_buckets=(16, 32), num_blocks: int = None,
                 hot_queue_depth: int = 4,
                 scrape_interval: float = 0.2,
                 subprocess_replicas: bool = False,
                 host_env: Optional[Dict[str, str]] = None,
                 ring_extra: Optional[Dict[str, Any]] = None,
                 fleet_kv: bool = False,
                 prefill_pool: int = 0,
                 trace: bool = False,
                 state_dir: Optional[str] = None,
                 router_extra: Optional[Dict[str, Any]] = None) -> None:
        self.block_size = block_size
        self.ring_kw: Dict[str, Any] = dict(
            slots=slots, max_len=max_len, chunk_tokens=chunk_tokens,
            prefill_buckets=tuple(prefill_buckets), paged=True,
            block_size=block_size, prefix_cache=True)
        if trace:
            # span capture on every replica ring + timeline stitching
            # in the router (ISSUE 18: the replay harness records
            # fleets with trace=True and exports
            # /debug/tracez?format=jsonl as its workload format)
            self.ring_kw["trace"] = True
        if num_blocks is not None:
            self.ring_kw["num_blocks"] = num_blocks
        # extra ring knobs (ISSUE 12 fleet-KV tests size a host tier
        # with host_cache_blocks=, quant fleets pass kv_quant=, ...)
        self.ring_kw.update(ring_extra or {})
        self.fleet_kv = fleet_kv
        self.subprocess_replicas = subprocess_replicas
        self.host_env = host_env or {}
        self.replicas: List[_Replica] = []
        self._params = self._cfg = None
        if not subprocess_replicas:
            self._params, self._cfg = _tiny_params()
        # cross-host disaggregation (ISSUE 13): N REAL prefill servers
        # (infer/prefill_serve.py) spawned BEFORE the decode replicas
        # — each decode ring boots with a RemotePrefillClient pointed
        # at this fleet's router, exactly the pod wiring
        # (SERVE_PREFILL=disagg + SERVE_PREFILL_REMOTE=1 +
        # SERVE_PREFILL_BROKER=<fleet service>) produces
        self.prefill_servers: List[Any] = []
        self._prefill_exits: List[Optional[int]] = []
        if prefill_pool:
            if subprocess_replicas:
                raise ValueError("prefill_pool needs in-process "
                                 "replicas (the client wires at ring "
                                 "construction)")
            self.ring_kw["prefill_mode"] = "disagg"
            for _ in range(prefill_pool):
                self._spawn_prefill()
        # router FIRST (empty decode membership): replicas constructed
        # below need its address for their remote-prefill broker
        # state_dir (ISSUE 20): a crash-safe journal under the fleet's
        # router, so kill/restart tests can rebuild a SECOND router on
        # the same dedupe window; router_extra passes breaker knobs
        # and friends straight through to FleetRouter
        self.state_dir = state_dir
        self.router = FleetRouter(
            [],
            block_size=block_size,
            affinity_blocks=2 if affinity else 0,
            hot_queue_depth=hot_queue_depth,
            scrape_interval=scrape_interval,
            prefill_endpoints=self.prefill_endpoints(),
            trace=trace or None,
            state_dir=state_dir,
            **(router_extra or {}))
        self.router_srv = make_router_server("127.0.0.1", 0,
                                             self.router)
        # short poll: shutdown() blocks a full poll interval per
        # server, and test fleets tear down three of them
        self._router_thread = threading.Thread(
            target=lambda: self.router_srv.serve_forever(
                poll_interval=0.05), daemon=True)
        self._router_thread.start()
        self.router_url = ("http://127.0.0.1:"
                           f"{self.router_srv.server_address[1]}")
        for _ in range(n):
            self.add_replica(wait_ready=False)
        if self.fleet_kv:
            self.enable_fleet_kv()
        self.wait_ready()

    # -- prefill pool (ISSUE 13) -------------------------------------------

    def prefill_endpoints(self) -> List[str]:
        return [f"127.0.0.1:{s.server_address[1]}"
                for i, s in enumerate(self.prefill_servers)
                if self._prefill_exits[i] is None]

    def _spawn_prefill(self):
        from paddle_operator_tpu.infer.prefill_serve import (
            make_prefill_server,
        )

        srv = make_prefill_server(
            "127.0.0.1", 0, self._params, self._cfg,
            block_size=self.block_size,
            max_len=self.ring_kw["max_len"],
            buckets=self.ring_kw["prefill_buckets"],
            kv_quant=self.ring_kw.get("kv_quant", "none"),
            # sampling rule is part of the handoff fingerprint: a
            # ring_extra top-k/top-p the pool didn't carry would 409
            # every handoff
            top_k=self.ring_kw.get("top_k"),
            top_p=self.ring_kw.get("top_p"),
            job="sim/fleet",
            replica=f"pf{len(self.prefill_servers)}")
        threading.Thread(
            target=lambda: srv.serve_forever(poll_interval=0.05),
            daemon=True).start()
        self.prefill_servers.append(srv)
        self._prefill_exits.append(None)
        return srv

    def add_prefill(self) -> str:
        """Scale the prefill pool up (the autoscaler's join): the
        router routes jobs to it once its scrape sees /readyz true."""
        srv = self._spawn_prefill()
        self.router.set_prefill_endpoints(self.prefill_endpoints())
        return f"127.0.0.1:{srv.server_address[1]}"

    def drain_prefill(self, idx: int, budget_s: float = 30.0) -> None:
        """The prefill pod's drain protocol (docs/fault-tolerance.md):
        /readyz false and new handoffs 503 (the decode side retries
        another pod), in-flight jobs finish and flush, exit 83."""
        import time as _time

        from paddle_operator_tpu.api.types import EXIT_PREEMPTED

        srv = self.prefill_servers[idx]
        srv.frontend.draining = True
        deadline = _time.monotonic() + budget_s
        while srv.frontend.depth() > 0 \
                and _time.monotonic() < deadline:
            _time.sleep(0.02)
        srv.shutdown()
        srv.server_close()      # refuse, don't backlog (drain_replica)
        srv.frontend.close()
        self._prefill_exits[idx] = EXIT_PREEMPTED
        self.router.set_prefill_endpoints(self.prefill_endpoints())

    def enable_fleet_kv(self, *, migrate: bool = True,
                        peer_fetch: bool = True,
                        parked_s: Optional[float] = None) -> None:
        """Wire every LIVE in-process replica with a FleetKVClient
        pointed at this fleet's router (ISSUE 12): drain-by-migration
        + router-brokered parked-lane shed + peer prefix fetch — the
        same wiring serve.py's SERVE_KV_MIGRATE / SERVE_KV_PEER_FETCH
        envs produce in a pod.  Idempotent; call again after
        add_replica()."""
        from paddle_operator_tpu.utils import fleetkv as FK

        broker = f"127.0.0.1:{self.router_srv.server_address[1]}"
        for rep in self.replicas:
            b = rep.batcher
            if b is None or rep.exit_code is not None \
                    or b.pool is None:
                continue
            client = FK.FleetKVClient(broker=broker,
                                      origin=rep.endpoint)
            if migrate:
                b.migrate_out = (
                    lambda c: lambda meta, spill:
                    c.migrate_out(FK.encode_lane(meta, spill)))(client)
                b._migrate_on_drain = True
                if parked_s:
                    b.migrate_parked_s = parked_s
            if peer_fetch and b.pool.host is not None:
                b.peer_fetch = client.fetch_prefix

    # -- replica lifecycle -------------------------------------------------

    def add_replica(self, wait_ready: bool = True) -> str:
        idx = len(self.replicas)
        if self.subprocess_replicas:
            rep = self._spawn_subprocess(idx)
        else:
            rep = self._spawn_inprocess(idx)
        self.replicas.append(rep)
        if hasattr(self, "router"):
            self.router.set_endpoints(
                [r.endpoint for r in self.replicas
                 if r.exit_code is None])
            if wait_ready:
                self.wait_ready()
        return rep.endpoint

    def _spawn_inprocess(self, idx: int) -> _Replica:
        from paddle_operator_tpu.infer.serve import make_server

        ring_kw = dict(self.ring_kw)
        if self.prefill_servers:
            from paddle_operator_tpu.infer.prefill_serve import (
                RemotePrefillClient,
            )

            ring_kw["prefill_client"] = RemotePrefillClient(
                broker="127.0.0.1:"
                       f"{self.router_srv.server_address[1]}")
        srv = make_server("127.0.0.1", 0, self._params, self._cfg,
                          continuous=True, job="sim/fleet",
                          replica=str(idx), **ring_kw)
        rep = _Replica(f"127.0.0.1:{srv.server_address[1]}")
        rep.srv = srv
        rep.thread = threading.Thread(
            target=lambda: srv.serve_forever(poll_interval=0.05),
            daemon=True)
        rep.thread.start()
        return rep

    def _spawn_subprocess(self, idx: int) -> _Replica:
        """A REAL replica process (bench.py: honest multi-core tok/s —
        in-process rings share one GIL for their host-side work)."""
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   TPUJOB_REPLICA_PORT=str(port),
                   TPUJOB_REPLICA_ID=str(idx),
                   SIMFLEET_RING_KW=repr(self.ring_kw),
                   **self.host_env)
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "paddle_operator_tpu.router.simfleet"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        rep = _Replica(f"127.0.0.1:{port}")
        rep.proc = proc
        return rep

    def wait_ready(self, timeout: float = 120.0,
                   n: Optional[int] = None) -> None:
        """Block until ``n`` (default: all live) replicas are routable
        THROUGH the router — i.e. its scrape loop has admitted them."""
        want = n if n is not None else sum(
            1 for r in self.replicas if r.exit_code is None)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ready = sum(1 for st in self.router.replicas.values()
                        if st.ready)
            if ready >= want:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"fleet not ready: want {want}, have "
            f"{sum(1 for st in self.router.replicas.values() if st.ready)}")

    def drain_replica(self, idx: int, budget_s: float = 30.0) -> None:
        """The scale-down protocol, replica side: stop admissions
        (/readyz false, new submits 503), finish residents within the
        budget, exit EXIT_PREEMPTED.  The router's scrape loop observes
        the readiness drop and stops routing here — the same sequence a
        SIGTERM-d pod runs through resilience.ServingDrain."""
        from paddle_operator_tpu.api.types import EXIT_PREEMPTED

        rep = self.replicas[idx]
        if rep.proc is not None:
            import signal

            rep.proc.send_signal(signal.SIGTERM)
            rep.exit_code = rep.proc.wait(timeout=budget_s + 30)
            rep.drained = rep.exit_code == EXIT_PREEMPTED
        else:
            rep.srv.state.draining = True      # /readyz false, 503s
            rep.batcher.drain(budget_s)        # residents finish
            rep.srv.shutdown()
            # server_close() too: shutdown() alone leaves the LISTEN
            # socket open, and connections would sit in the dead
            # server's accept backlog instead of being refused — the
            # router must see a hard refusal to fail over immediately
            rep.srv.server_close()
            rep.exit_code = EXIT_PREEMPTED
            rep.drained = True

    def kill_replica(self, idx: int) -> None:
        rep = self.replicas[idx]
        if rep.proc is not None:
            rep.proc.kill()
            rep.exit_code = rep.proc.wait()
        else:
            rep.srv.shutdown()
            rep.srv.server_close()   # refuse, don't backlog (see drain)
            rep.batcher.close()
            rep.exit_code = 137
        rep.drained = False

    # -- traffic -----------------------------------------------------------

    def post(self, payload: Dict[str, Any], *, deadline_s=None,
             max_retries: int = 8, rng=None):
        """One request through the router with the PRODUCTION client
        retry discipline (client/client.py post_generate — 503 backoff,
        Retry-After, idempotent request_id)."""
        client_cli = _client_module()
        return client_cli.post_generate(
            self.router_url, payload, deadline_s=deadline_s,
            max_retries=max_retries, backoff_base_s=0.05,
            backoff_max_s=0.5, rng=rng)

    def replica_status(self, idx: int) -> Dict[str, Any]:
        with urllib.request.urlopen(
                f"http://{self.replicas[idx].endpoint}/statusz",
                timeout=10) as r:
            import json

            return json.loads(r.read())

    def check_invariants(self) -> None:
        """Per-replica pool invariant (free+mapped+cached==num_blocks)
        on every LIVE in-process replica."""
        for rep in self.replicas:
            b = rep.batcher
            if rep.exit_code is None and b is not None \
                    and b.pool is not None:
                b.pool.check_invariant()

    def close(self) -> None:
        self.router_srv.shutdown()
        self.router_srv.server_close()
        self.router.close()
        for i, rep in enumerate(self.replicas):
            if rep.exit_code is None:
                if rep.proc is not None:
                    rep.proc.kill()
                    rep.proc.wait()
                else:
                    rep.srv.shutdown()
                    rep.srv.server_close()
                    try:
                        rep.batcher.close()
                    except Exception:
                        pass
        for i, srv in enumerate(self.prefill_servers):
            if self._prefill_exits[i] is None:
                srv.shutdown()
                srv.server_close()
                try:
                    srv.frontend.close()
                except Exception:
                    pass


def prefix_workload(n_groups: int, per_group: int, *,
                    prefix_blocks: int = 2, block_size: int = 8,
                    suffix_len: int = 4, vocab: int = 256,
                    seed: int = 0) -> List[List[int]]:
    """``n_groups`` tenants, each with ``per_group`` prompts sharing
    ``prefix_blocks`` full blocks (the shared system prompt the radix
    cache + affinity routing exist for) and a distinct suffix."""
    import numpy as np

    rng = np.random.default_rng(seed)
    prompts = []
    for g in range(n_groups):
        prefix = rng.integers(1, vocab,
                              (prefix_blocks * block_size,)).tolist()
        for _ in range(per_group):
            prompts.append(prefix
                           + rng.integers(1, vocab,
                                          (suffix_len,)).tolist())
    return prompts


def _replica_main() -> int:
    """Subprocess replica entry (``python -m
    paddle_operator_tpu.router.simfleet``): a tiny-model paged ring
    server with the full SIGTERM drain chain — what bench.py's
    subprocess fleets run per replica."""
    import ast

    from paddle_operator_tpu.ft.preemption import PreemptionWatcher
    from paddle_operator_tpu.infer.resilience import ServingDrain
    from paddle_operator_tpu.infer.serve import (
        make_server,
        wire_fleet_kv_from_env,
        wire_kv_store_from_env,
    )

    port = int(os.environ["TPUJOB_REPLICA_PORT"])
    ring_kw = ast.literal_eval(os.environ.get("SIMFLEET_RING_KW",
                                              "{}"))
    params, cfg = _tiny_params()
    srv = make_server("127.0.0.1", port, params, cfg,
                      continuous=True, job="sim/fleet",
                      replica=os.environ.get("TPUJOB_REPLICA_ID", ""),
                      **ring_kw)
    # fleet-level KV (ISSUE 12): the same SERVE_KV_* env contract the
    # real entrypoint honors, so bench subprocess fleets migrate too
    wire_fleet_kv_from_env(srv.generator.batcher, port)
    # durable prefix store (ISSUE 17): same env contract as the real
    # entrypoint, so bench fleets exercise the fleet-restart warm start
    wire_kv_store_from_env(srv.generator.batcher)
    watcher = PreemptionWatcher.install()
    drain = ServingDrain(
        srv, srv.state, batcher=srv.generator.batcher,
        budget_s=float(os.environ.get("SERVE_DRAIN_BUDGET_S", "30")))
    drain.install(watcher)
    srv.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(_replica_main())
