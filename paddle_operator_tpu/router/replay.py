"""Trace-driven fleet replay + virtual-time policy sweeps (ISSUE 18).

The fleet grew a real policy surface — the two-pool SLO autoscaler
(controller/autoscaler.py), QoS preemption budgets (infer/qos.py),
executor shape knobs, the router's spill threshold — and every one of
those constants was tuned by burning real wall-clock on a contended
CPU box.  This module is the sim-then-validate loop the
DistServe/Sarathi lineage used to pick their disaggregation and
chunking points, applied to OUR knobs:

- **Workload layer** — :func:`synthetic_workload` draws seeded
  ShareGPT-shaped open-loop schedules (lognormal prompt/output
  lengths, diurnal + burst arrival envelope, priority/adapter mix);
  :func:`schedule_from_export` / :func:`schedule_from_flightrec`
  rebuild the schedule a REAL fleet served from its recorded
  telemetry (the ISSUE 15 span trees exported as JSONL via
  ``/debug/tracez?format=jsonl``, or a flight-recorder dump).  Either
  way the product is a :class:`Workload`: absolute arrival offsets +
  request shapes, replayable open-loop (arrivals never wait on
  completions — closed-loop replay would hide every queueing
  collapse the autoscaler exists to prevent).

- **Virtual-time model** — :class:`VirtualFleet` is a discrete-event
  simulator whose per-replica service times come from a
  :class:`Calibration` scraped off a short real run's histogram
  families.  The part that makes its sweeps trustworthy: it binds THE
  production control law, never a copy.  ``FleetAutoscaler.observe``
  (imported, not reimplemented) makes every scaling decision on
  virtual gauges; the TTFT/queue-wait quantiles come from the
  production :class:`~paddle_operator_tpu.utils.tracing.Histogram`
  run on the VIRTUAL clock (its ``clock=`` injection point exists for
  exactly this); admission ordering is the production
  :class:`~paddle_operator_tpu.infer.qos.MultiClassQueue`; and a
  sweep point is a production
  :class:`~paddle_operator_tpu.controller.policy.PolicyConfig` —
  tests/test_replay.py pins all four bindings by object identity.

- **Real-ring replay** — :func:`replay_on_simfleet` replays the same
  :class:`Workload` against a REAL simfleet (tiny-model rings behind
  the production router) with the same autoscaler driving real
  ``add_replica``/``drain_replica``, so a sim prediction can be
  checked against a measured run (the ``serve-sim`` dryrun line pins
  the agreement envelope; bench.py ``measure_fleet_sim`` records it).

- **Sweep driver** — :func:`sweep` scores a list of policy points on
  sim-predicted p95 TTFT and pod-seconds; ``make sim`` runs it.  The
  ``up_cooldown_s`` 5.0 -> 2.0 default in controller/policy.py is the
  first constant this loop landed.

Virtual-model assumptions (stated so sweep readers know what the
model does NOT capture): service times are deterministic per-request
(calibrated means — the sim predicts QUEUEING dynamics, not service
jitter); routing is least-loaded (affinity locality shows up only
through the calibrated prefill cost); lane spill/preemption and KV
pressure are not modeled; a booting replica accepts queue work it
serves only after ``boot_s`` (client-retry backlog in the real
fleet).  Everything here is stdlib-only — ``make sim`` never imports
jax.
"""

from __future__ import annotations

import argparse
import heapq
import json
import math
import random
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

# THE production control law and knob surface — imported, never
# copied.  tests/test_replay.py pins these bindings by identity; if a
# refactor renames them, the sim must follow, not fork.
from paddle_operator_tpu.api.types import AutoscaleSpec
from paddle_operator_tpu.controller.autoscaler import FleetAutoscaler
from paddle_operator_tpu.controller.policy import (
    DEFAULT_POLICY,
    PolicyConfig,
)
from paddle_operator_tpu.infer.qos import MultiClassQueue
from paddle_operator_tpu.utils import tracing as TR

# ---------------------------------------------------------------------------
# Workload layer
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SimRequest:
    """One replayable request: WHEN it arrived and what SHAPE it was.
    Token contents are irrelevant to queueing dynamics; real-ring
    replay synthesizes deterministic tokens of the recorded length."""

    t: float                    # arrival offset from trace start (s)
    prompt_len: int
    max_new: int
    priority: int = 0
    adapter: Optional[str] = None


@dataclass
class Workload:
    """An open-loop schedule: requests sorted by arrival offset."""

    requests: List[SimRequest]
    duration_s: float
    source: str = "synthetic"

    def __post_init__(self) -> None:
        # open-loop contract: arrivals are monotone
        self.requests = sorted(self.requests, key=lambda r: r.t)

    def to_jsonl(self) -> str:
        """Deterministic serialization (the seeded-determinism test
        compares these bytes)."""
        head = json.dumps({"kind": "workload", "source": self.source,
                           "durationS": round(self.duration_s, 6),
                           "n": len(self.requests)}, sort_keys=True)
        lines = [head]
        for r in self.requests:
            lines.append(json.dumps(
                {"t": round(r.t, 6), "promptLen": r.prompt_len,
                 "maxNew": r.max_new, "prio": r.priority,
                 **({"adapter": r.adapter} if r.adapter else {})},
                sort_keys=True))
        return "\n".join(lines) + "\n"

    @classmethod
    def from_jsonl(cls, text: str) -> "Workload":
        reqs: List[SimRequest] = []
        duration = 0.0
        source = "file"
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            if d.get("kind") == "workload":
                duration = float(d.get("durationS", 0.0))
                source = str(d.get("source", source))
                continue
            reqs.append(SimRequest(
                t=float(d["t"]), prompt_len=int(d["promptLen"]),
                max_new=int(d["maxNew"]), priority=int(d.get("prio", 0)),
                adapter=d.get("adapter")))
        if not duration and reqs:
            duration = max(r.t for r in reqs)
        return cls(reqs, duration, source=source)


def _lognormal_int(rng: random.Random, median: float, sigma: float,
                   lo: int, hi: int) -> int:
    """ShareGPT-ish length draw: lognormal around ``median`` with log
    stddev ``sigma``, clipped to [lo, hi] (real prompt/output length
    distributions are heavy-tailed, and the tail is what fills lanes
    and queues — a normal draw would under-stress the scheduler)."""
    v = rng.lognormvariate(math.log(max(median, 1.0)), sigma)
    return max(lo, min(hi, int(round(v))))


def synthetic_workload(seed: int = 0, duration_s: float = 60.0,
                       mean_rps: float = 2.0, *,
                       burst_factor: float = 4.0, n_bursts: int = 2,
                       burst_frac: float = 0.12,
                       diurnal_amp: float = 0.3,
                       prompt_median: int = 24, prompt_sigma: float = 0.7,
                       new_median: int = 12, new_sigma: float = 0.6,
                       max_prompt: int = 48, max_new: int = 24,
                       priority_mix: Sequence[float] = (0.25, 0.75),
                       adapter_mix: Optional[Dict[str, float]] = None
                       ) -> Workload:
    """Seeded ShareGPT-shaped open-loop workload.

    Arrivals are a non-homogeneous Poisson process drawn by thinning:
    the base rate rides a diurnal sinusoid (one period over the
    trace, amplitude ``diurnal_amp``) and ``n_bursts`` evenly-spaced
    burst windows (each ``burst_frac`` of the duration at
    ``burst_factor`` x the base rate) — the burst-onset shape the
    autoscaler's up-path is tuned against.  Lengths are lognormal
    (heavy-tailed like real chat traces), priorities/adapters draw
    from the stated mixes.  Same seed -> byte-identical
    :meth:`Workload.to_jsonl` (pinned by test)."""
    rng = random.Random(seed)
    peak = mean_rps * (1.0 + diurnal_amp) * max(burst_factor, 1.0)

    def rate(t: float) -> float:
        r = mean_rps * (1.0 + diurnal_amp
                        * math.sin(2 * math.pi * t / duration_s))
        if n_bursts > 0 and burst_frac > 0:
            spacing = duration_s / n_bursts
            for i in range(n_bursts):
                b0 = spacing * (i + 0.35)
                if b0 <= t < b0 + burst_frac * duration_s:
                    r *= burst_factor
                    break
        return r

    prios = list(range(len(priority_mix)))
    adapters = sorted(adapter_mix) if adapter_mix else []
    aweights = [adapter_mix[a] for a in adapters] if adapter_mix else []
    reqs: List[SimRequest] = []
    t = 0.0
    while True:
        # thinning: draw at the peak rate, keep with prob rate(t)/peak
        t += rng.expovariate(peak)
        if t >= duration_s:
            break
        if rng.random() > rate(t) / peak:
            continue
        adapter = (rng.choices(adapters, aweights)[0]
                   if adapters and rng.random() < sum(aweights)
                   else None)
        reqs.append(SimRequest(
            t=t,
            prompt_len=_lognormal_int(rng, prompt_median, prompt_sigma,
                                      1, max_prompt),
            max_new=_lognormal_int(rng, new_median, new_sigma,
                                   1, max_new),
            priority=rng.choices(prios, list(priority_mix))[0],
            adapter=adapter))
    return Workload(reqs, duration_s, source=f"synthetic:seed={seed}")


def _root_attrs(spans: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Merged attrs of every ``request`` span in one stitched
    timeline: the router's root carries requestId, the replica's root
    carries the workload stamps (promptLen/maxNew/prio) — replay
    needs the union."""
    out: Dict[str, Any] = {}
    for s in spans:
        if s.get("name") == "request" and isinstance(s.get("attrs"),
                                                     dict):
            out.update(s["attrs"])
    return out


def schedule_from_export(export: Any, *, default_prompt_len: int = 16,
                         default_max_new: int = 8) -> Workload:
    """Rebuild the open-loop schedule a real fleet served from its
    ``/debug/tracez?format=jsonl`` export (text, or the dict
    :func:`~paddle_operator_tpu.utils.tracing.parse_jsonl_export`
    returns).  Arrival = each timeline's earliest root ``t0`` (wall
    ms), normalized to offset-from-first; shapes come from the
    scheduler's root-span stamps, with stated defaults when a
    timeline predates the stamps."""
    parsed = (TR.parse_jsonl_export(export) if isinstance(export, str)
              else export)
    rows: List[Dict[str, Any]] = []
    for tl in parsed.get("timelines", []):
        spans = tl.get("spans") or []
        roots = TR.span_roots(spans)
        if not roots:
            continue
        t0 = min(float(s.get("t0", 0.0)) for s in roots)
        attrs = _root_attrs(spans)
        rows.append({"t0": t0, "attrs": attrs})
    if not rows:
        return Workload([], 0.0, source="export")
    base = min(r["t0"] for r in rows)
    reqs = [SimRequest(
        t=(r["t0"] - base) / 1e3,
        prompt_len=int(r["attrs"].get("promptLen",
                                      default_prompt_len)),
        max_new=int(r["attrs"].get("maxNew", default_max_new)),
        priority=int(r["attrs"].get("prio", 0)),
        adapter=r["attrs"].get("adapter")) for r in rows]
    duration = max(r.t for r in reqs)
    return Workload(reqs, duration, source="export")


def schedule_from_flightrec(dump: Any, *, default_prompt_len: int = 16,
                            default_max_new: int = 8) -> Workload:
    """Rebuild a schedule from a flight-recorder dump (path or the
    dict :func:`~paddle_operator_tpu.utils.tracing.read_flightrec_dump`
    returns): ``admit`` events carry wall arrival time and priority —
    the fallback workload source when span capture was off."""
    d = TR.read_flightrec_dump(dump) if isinstance(dump, str) else dump
    admits = [e for e in d.get("events", [])
              if e.get("kind") == "admit"]
    if not admits:
        return Workload([], 0.0, source="flightrec")
    base = min(float(e["t"]) for e in admits)
    reqs = [SimRequest(
        t=float(e["t"]) - base,
        prompt_len=default_prompt_len,
        max_new=default_max_new,
        priority=int(e.get("prio", 0) or 0)) for e in admits]
    duration = max(r.t for r in reqs)
    return Workload(reqs, duration, source="flightrec")


# ---------------------------------------------------------------------------
# Calibration
# ---------------------------------------------------------------------------


@dataclass
class Calibration:
    """Per-replica service-time model, scraped off a short real run.

    The virtual fleet charges each request
    ``prefill_ms_base + prompt_len * prefill_ms_token (+ wire_ms)``
    to first token and ``max_new * itl_ms`` to stream the rest;
    ``boot_s`` is replica boot-to-ready (what the up-cool-down trades
    against); ``promote_ms`` rides requests that migrate/promote (not
    charged in v1's dispatch path, carried for the handoff-aware
    model).  Means, deliberately: the sim predicts queueing dynamics
    under policy changes, and those are driven by load vs capacity,
    not by per-request jitter."""

    prefill_ms_base: float = 1.0
    prefill_ms_token: float = 0.5
    itl_ms: float = 5.0
    wire_ms: float = 1.0
    boot_s: float = 2.0
    promote_ms: float = 0.0

    def prefill_ms(self, prompt_len: int) -> float:
        return (self.prefill_ms_base + self.wire_ms
                + self.prefill_ms_token * max(0, prompt_len))

    def to_dict(self) -> Dict[str, Any]:
        return {k: round(float(getattr(self, k)), 4)
                for k in ("prefill_ms_base", "prefill_ms_token",
                          "itl_ms", "wire_ms", "boot_s", "promote_ms")}

    @classmethod
    def from_hists(cls, families: Dict[str, Any], *,
                   mean_prompt_len: float, boot_s: float = 2.0
                   ) -> "Calibration":
        """Calibrate from one histogram snapshot block (a
        :meth:`ServeHistograms.snapshot` /
        :func:`~paddle_operator_tpu.utils.tracing.fold_latency_hists`
        ``families`` dict, e.g. the ``hist`` record of a JSONL
        export).  Means decompose the families: mean TTFT minus mean
        queue wait is the service component of first-token latency;
        divided across the trace's mean prompt length it yields the
        per-token prefill cost; the ITL family's mean is the decode
        per-token cost directly."""

        def mean(fam: str) -> Optional[float]:
            e = families.get(fam)
            if not isinstance(e, dict) or not e.get("count"):
                return None
            return float(e.get("sum", 0.0)) / float(e["count"])

        ttft = mean("ttft")
        qwait = mean("queueWait") or 0.0
        itl = mean("itl")
        c = cls(boot_s=boot_s)
        if ttft is not None:
            service_ms = max(0.5, ttft - qwait)
            c.prefill_ms_token = max(
                0.01, (service_ms - c.prefill_ms_base - c.wire_ms)
                / max(mean_prompt_len, 1.0))
        if itl is not None and itl > 0:
            c.itl_ms = itl
        return c


# ---------------------------------------------------------------------------
# Virtual-time fleet model
# ---------------------------------------------------------------------------


class _VReplica:
    """One virtual decode replica: ``slots`` lanes, a production
    MultiClassQueue for class-ordered admission, a boot-ready time."""

    __slots__ = ("rid", "slots", "queue", "busy", "ready_at",
                 "draining", "born_at", "died_at")

    def __init__(self, rid: int, slots: int, priorities: int,
                 now: float, boot_s: float) -> None:
        self.rid = rid
        self.slots = slots
        self.queue = MultiClassQueue(priorities)
        self.busy = 0
        self.born_at = now
        self.ready_at = now + boot_s
        self.draining = False
        self.died_at: Optional[float] = None

    def load(self) -> int:
        return self.busy + self.queue.qsize()


@dataclass
class SimResult:
    """One virtual (or real) replay's score card."""

    p95_ttft_ms: Optional[float]
    mean_ttft_ms: Optional[float]
    p95_queue_wait_ms: Optional[float]
    pod_seconds: float
    completed: int
    duration_s: float
    wall_s: float
    speedup: float
    replicas_peak: int
    scale_events: int
    policy: Dict[str, Any] = field(default_factory=dict)
    backend: str = "virtual"

    def to_dict(self) -> Dict[str, Any]:
        d = {"p95TtftMs": self.p95_ttft_ms,
             "meanTtftMs": self.mean_ttft_ms,
             "p95QueueWaitMs": self.p95_queue_wait_ms,
             "podSeconds": round(self.pod_seconds, 3),
             "completed": self.completed,
             "durationS": round(self.duration_s, 3),
             "wallS": round(self.wall_s, 4),
             "speedup": round(self.speedup, 1),
             "replicasPeak": self.replicas_peak,
             "scaleEvents": self.scale_events,
             "backend": self.backend}
        if self.policy:
            d["policy"] = self.policy
        return d


class VirtualFleet:
    """Discrete-event fleet on a virtual clock, run by THE production
    control law.

    Every scaling decision is ``FleetAutoscaler.observe`` on gauges
    the model computes the way the router computes them; the p95 the
    law reads mid-run is the production ``Histogram``'s rolling
    window on the virtual clock.  One run costs milliseconds of wall
    time per minute of trace — the >=20x speedup the sweeps exist
    for."""

    def __init__(self, workload: Workload, calib: Calibration, *,
                 policy: PolicyConfig = DEFAULT_POLICY,
                 ttft_target_ms: float = 250.0,
                 tok_s_per_replica: float = 0.0,
                 min_replicas: int = 1, max_replicas: int = 4,
                 slots: int = 4,
                 control_interval_s: float = 0.5,
                 hist_window_s: float = 10.0) -> None:
        self.workload = workload
        self.calib = calib
        self.policy = policy
        self.slots = max(1, int(slots))
        self.min_replicas = max(1, int(min_replicas))
        self.control_interval_s = float(control_interval_s)
        # the replica pool is modeled as the law's PREFILL pool: its
        # load signals are queue depth and the measured TTFT p95 —
        # exactly what these replicas emit (a simfleet-shaped ring
        # does its own prefill and exports no prefillMsAvg, so both
        # the sim and the real-ring replay run the law's conservative
        # no-service-time branch plus the p95 floor — same inputs,
        # same branch).  The decode pool is off (max 0 = spec stands).
        spec = AutoscaleSpec(
            ttft_target_ms=float(ttft_target_ms),
            tok_s_per_replica=float(tok_s_per_replica),
            min_replicas=1, max_replicas=0,
            prefill_min=self.min_replicas,
            prefill_max=int(max_replicas),
            cooldown_s=policy.cooldown_s,
            up_cooldown_s=policy.up_cooldown_s,
            scale_down_ratio=policy.scale_down_ratio)
        # THE law — the sweep's subject, imported not copied
        self.autoscaler = FleetAutoscaler(spec, policy=policy)
        self.spec = spec
        self._now = 0.0
        # production histograms on the VIRTUAL clock: the law reads
        # the same rolling-window p95 in here as it does in a pod
        clock = lambda: self._now          # noqa: E731
        self.hist_ttft = TR.Histogram("sim_ttft", window_s=hist_window_s,
                                      clock=clock)
        self.hist_qwait = TR.Histogram("sim_queue_wait",
                                       window_s=hist_window_s,
                                       clock=clock)
        self._replicas: List[_VReplica] = []
        # raw TTFT/queue-wait samples for SCORING (exact quantiles):
        # the law keeps reading the production Histogram's log-bucket
        # windowed p95 — same resolution it has in a pod — but sweep
        # scores must resolve sub-bucket differences between policy
        # points, which bucket interpolation flattens
        self._ttft_samples: List[float] = []
        self._qwait_samples: List[float] = []
        self._next_rid = 0
        self._state: Optional[Dict[str, Any]] = None
        self._tok_window: List[Any] = []   # (t, tokens) completions
        self._prefill_ms_obs: List[float] = []
        self._pod_seconds = 0.0
        self._pod_last_t = 0.0
        self._scale_events = 0
        self._peak = 0
        self._seq = 0
        self._heap: List[Any] = []

    # -- event machinery ---------------------------------------------------

    def _push(self, t: float, kind: str, payload: Any = None) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, kind, payload))

    def _advance(self, t: float) -> None:
        live = sum(1 for r in self._replicas if r.died_at is None)
        self._pod_seconds += live * max(0.0, t - self._pod_last_t)
        self._pod_last_t = t
        self._now = t

    # -- fleet plumbing ----------------------------------------------------

    def _boot_replica(self, boot_s: Optional[float] = None) -> None:
        r = _VReplica(self._next_rid, self.slots,
                      self.policy.priorities, self._now,
                      self.calib.boot_s if boot_s is None else boot_s)
        self._next_rid += 1
        self._replicas.append(r)
        self._push(r.ready_at, "ready", r.rid)

    def _live(self) -> List[_VReplica]:
        return [r for r in self._replicas if r.died_at is None]

    def _ready(self) -> List[_VReplica]:
        return [r for r in self._live()
                if r.ready_at <= self._now and not r.draining]

    def _route(self, req: SimRequest) -> None:
        """Least-loaded routing over non-draining replicas (the
        affinity=False control; locality enters through the
        calibrated prefill cost, see module docstring).  Booting
        replicas count — queueing there models the client-retry
        backlog that accumulates against capacity still booting."""
        cands = [r for r in self._live() if not r.draining]
        if not cands:
            self._boot_replica()          # floor: the law never goes
            cands = [self._replicas[-1]]  # below min, but be safe
        tgt = min(cands, key=lambda r: (r.load(), r.rid))
        prio = min(max(req.priority, 0), self.policy.priorities - 1)
        tgt.queue.put_nowait((req, self._now), prio)
        self._kick(tgt)

    def _kick(self, r: _VReplica) -> None:
        """Start queued work on free lanes (production class order)."""
        if r.ready_at > self._now or r.died_at is not None:
            return
        while r.busy < r.slots:
            try:
                req, t_arrive = r.queue.get_nowait()
            except Exception:
                break
            r.busy += 1
            qwait_ms = (self._now - t_arrive) * 1e3
            pre_ms = self.calib.prefill_ms(req.prompt_len)
            if req.adapter:
                pre_ms += self.calib.promote_ms
            self.hist_qwait.observe(qwait_ms)
            self.hist_ttft.observe(qwait_ms + pre_ms)
            self._qwait_samples.append(qwait_ms)
            self._ttft_samples.append(qwait_ms + pre_ms)
            self._prefill_ms_obs.append(pre_ms)
            done = self._now + (pre_ms
                                + req.max_new * self.calib.itl_ms) / 1e3
            self._push(done, "free", (r.rid, req.max_new))
            self._completed += 1

    def _replica_by_id(self, rid: int) -> Optional[_VReplica]:
        for r in self._replicas:
            if r.rid == rid:
                return r
        return None

    # -- gauges + control --------------------------------------------------

    def _gauges(self) -> Dict[str, Any]:
        """The ``status.serving`` block the law reads, computed the
        way the fleet computes it: queue depths summed, tok/s over a
        rolling window, prefill service-time EMA, and the windowed
        histogram p95 (``ttftP95Ms``) — same keys, same meanings."""
        horizon = self._now - 5.0
        self._tok_window = [(t, n) for t, n in self._tok_window
                            if t >= horizon]
        toks = sum(n for _, n in self._tok_window)
        depth = sum(r.queue.qsize() for r in self._live())
        p95 = self.hist_ttft.p95()
        return {
            "queueDepth": depth,
            "prefillQueueDepth": depth,
            "tokensPerSec": toks / 5.0,
            "kvBlocksFree": 1 << 20,      # KV pressure not modeled
            # no prefillMsAvg — see __init__: simfleet-shaped rings
            # export none, and the sim must read what the real side
            # reads so the law takes the same branch in both
            "prefillLanes": self.policy.prefill_lanes,
            "ttftP95Ms": p95 if p95 else None,
        }

    def _control(self) -> None:
        live = self._live()
        ready = [r for r in live
                 if r.ready_at <= self._now and not r.draining]
        draining = any(r.draining for r in live)
        self._state = self.autoscaler.observe(
            self._state, self._gauges(),
            decode_spec=0, prefill_spec=self.min_replicas,
            decode_ready=0, prefill_ready=len(ready),
            decode_draining=False, prefill_draining=draining,
            now=self._now)
        desired = int(self._state["prefillDesired"])
        have = sum(1 for r in live if not r.draining)
        if self._state.get("prefillReason"):
            self._scale_events += 1
        while have < desired:
            self._boot_replica()
            have += 1
        if have > desired and not draining:
            # the law sheds one at a time through a drain; the victim
            # is the least-loaded non-draining replica
            victims = [r for r in live if not r.draining]
            v = min(victims, key=lambda r: (r.load(), -r.rid))
            v.draining = True
            self._maybe_retire(v)
        self._peak = max(self._peak,
                         sum(1 for r in self._live()))

    def _maybe_retire(self, r: _VReplica) -> None:
        if r.draining and r.busy == 0 and r.queue.empty() \
                and r.died_at is None:
            r.died_at = self._now

    # -- the run -----------------------------------------------------------

    def run(self) -> SimResult:
        wall0 = time.perf_counter()
        self._completed = 0
        for _ in range(self.min_replicas):
            self._boot_replica(boot_s=0.0)   # initial fleet is ready
        for req in self.workload.requests:
            self._push(req.t, "arrive", req)
        t = self.control_interval_s
        end_hint = self.workload.duration_s
        while t <= end_hint + self.calib.boot_s + 5.0:
            self._push(t, "control", None)
            t += self.control_interval_s
        while self._heap:
            t, _, kind, payload = heapq.heappop(self._heap)
            self._advance(t)
            if kind == "arrive":
                self._route(payload)
            elif kind == "ready":
                r = self._replica_by_id(payload)
                if r is not None:
                    self._kick(r)
            elif kind == "free":
                rid, toks = payload
                self._tok_window.append((self._now, toks))
                r = self._replica_by_id(rid)
                if r is not None:
                    r.busy -= 1
                    self._kick(r)
                    self._maybe_retire(r)
            elif kind == "control":
                self._control()
        for r in self._live():
            r.died_at = self._now
        wall = max(time.perf_counter() - wall0, 1e-9)
        dur = max(self._now, self.workload.duration_s)
        n = self.hist_ttft.count

        def exact_p95(xs: List[float]) -> Optional[float]:
            if not xs:
                return None
            xs = sorted(xs)
            return round(xs[int(0.95 * (len(xs) - 1))], 3)

        return SimResult(
            p95_ttft_ms=exact_p95(self._ttft_samples),
            mean_ttft_ms=(round(self.hist_ttft.sum / n, 3) if n
                          else None),
            p95_queue_wait_ms=exact_p95(self._qwait_samples),
            pod_seconds=self._pod_seconds,
            completed=self._completed,
            duration_s=dur,
            wall_s=wall,
            speedup=dur / wall,
            replicas_peak=self._peak,
            scale_events=self._scale_events,
            policy=DEFAULT_POLICY.diff(self.policy),
            backend="virtual")


# ---------------------------------------------------------------------------
# Real-ring replay (simfleet + the same law driving real scale actions)
# ---------------------------------------------------------------------------


def _prompt_tokens(req: SimRequest, idx: int, vocab: int = 256
                   ) -> List[int]:
    """Deterministic tokens of the recorded length (content is
    irrelevant to queueing; determinism keeps reruns comparable)."""
    rng = random.Random((idx << 16) ^ req.prompt_len)
    return [1 + rng.randrange(vocab - 1) for _ in range(req.prompt_len)]


def replay_on_simfleet(workload: Workload, *,
                       policy: PolicyConfig = DEFAULT_POLICY,
                       ttft_target_ms: float = 250.0,
                       min_replicas: int = 1, max_replicas: int = 3,
                       time_scale: float = 1.0,
                       control_interval_s: float = 0.25,
                       slots: int = 4, max_len: int = 64,
                       trace: bool = False,
                       fleet_kw: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
    """Replay ``workload`` against a REAL simfleet (tiny-model rings
    behind the production router), with the production autoscaler
    observing the router's live folded gauges and driving real
    ``add_replica`` / ``drain_replica`` — the measured side of every
    sim-vs-real comparison.  ``time_scale`` > 1 compresses the
    schedule (arrival offsets divide by it).  Returns the same score
    keys as :meth:`SimResult.to_dict` plus the export (when
    ``trace=True``) for calibration."""
    import threading
    import urllib.request

    from paddle_operator_tpu.router.simfleet import SimFleet

    # same pool wiring as VirtualFleet: the replica pool rides the
    # law's PREFILL path (queue depth + measured TTFT p95), decode off
    spec = AutoscaleSpec(
        ttft_target_ms=ttft_target_ms, tok_s_per_replica=0.0,
        min_replicas=1, max_replicas=0,
        prefill_min=min_replicas, prefill_max=max_replicas,
        cooldown_s=policy.cooldown_s,
        up_cooldown_s=policy.up_cooldown_s,
        scale_down_ratio=policy.scale_down_ratio)
    law = FleetAutoscaler(spec, policy=policy)
    fleet = SimFleet(n=min_replicas, slots=slots, max_len=max_len,
                     trace=trace, **(fleet_kw or {}))
    stop = threading.Event()
    pod_seconds = [0.0]
    scale_events = [0]
    peak = [min_replicas]
    boot_times: List[float] = []
    pending_boots: List[float] = []
    ready_seen = [min_replicas]
    state: List[Optional[Dict[str, Any]]] = [None]
    drain_lock = threading.Lock()
    draining_flag = [False]

    def live_count() -> int:
        return sum(1 for r in fleet.replicas if r.exit_code is None)

    def control() -> None:
        last = time.monotonic()
        while not stop.is_set():
            time.sleep(control_interval_s)
            now = time.monotonic()
            pod_seconds[0] += live_count() * (now - last)
            last = now
            try:
                serving = fleet.router.statusz()["fleet"]
            except Exception:
                continue
            ready = sum(1 for st in fleet.router.replicas.values()
                        if st.ready)
            state[0] = law.observe(
                state[0], serving, decode_spec=0,
                prefill_spec=min_replicas, decode_ready=0,
                prefill_ready=ready, decode_draining=False,
                prefill_draining=draining_flag[0], now=now)
            desired = int(state[0]["prefillDesired"])
            if state[0].get("prefillReason"):
                scale_events[0] += 1
            # boot-to-ready = add_replica stamp -> the scrape first
            # reporting the new replica ready (what the virtual
            # model's boot_s must reproduce for boot-lag fidelity)
            while pending_boots and ready > ready_seen[0]:
                boot_times.append(now - pending_boots.pop(0))
                ready_seen[0] += 1
            ready_seen[0] = min(ready_seen[0], ready)
            have = live_count()
            while have < desired and not stop.is_set():
                fleet.add_replica(wait_ready=False)
                pending_boots.append(time.monotonic())
                have += 1
            if desired < have and not draining_flag[0]:
                idx = next((i for i in range(len(fleet.replicas) - 1,
                                             -1, -1)
                            if fleet.replicas[i].exit_code is None),
                           None)
                if idx is not None and live_count() > min_replicas:
                    def _drain(i: int) -> None:
                        with drain_lock:
                            draining_flag[0] = True
                            try:
                                fleet.drain_replica(i, budget_s=10.0)
                            except Exception:
                                pass
                            draining_flag[0] = False
                    threading.Thread(target=_drain, args=(idx,),
                                     daemon=True).start()
            peak[0] = max(peak[0], live_count())

    ctrl = threading.Thread(target=control, daemon=True)
    ctrl.start()
    t0 = time.monotonic()
    completed = [0]
    errors = [0]
    posters: List[threading.Thread] = []

    def post_one(req: SimRequest, idx: int) -> None:
        payload = {"tokens": [_prompt_tokens(req, idx)],
                   "max_new_tokens": req.max_new,
                   "priority": req.priority,
                   "request_id": f"replay-{idx}"}
        if req.adapter:
            payload["adapter"] = req.adapter
        try:
            fleet.post(payload, deadline_s=60.0)
            completed[0] += 1
        except Exception:
            errors[0] += 1

    try:
        for idx, req in enumerate(workload.requests):
            target = t0 + req.t / max(time_scale, 1e-9)
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=post_one, args=(req, idx),
                                  daemon=True)
            th.start()
            posters.append(th)
        for th in posters:
            th.join(timeout=120.0)
        # one settle tick so the last completions land in the fold
        time.sleep(max(control_interval_s,
                       fleet.router.scrape_interval) * 2)
        serving = fleet.router.statusz()["fleet"]
        export = None
        if trace:
            with urllib.request.urlopen(
                    fleet.router_url + "/debug/tracez?format=jsonl",
                    timeout=10) as r:
                export = r.read().decode()
        wall = time.monotonic() - t0
        lh = serving.get("latencyHist") or {}

        def fam_stats(fam: str):
            e = lh.get(fam)
            if not isinstance(e, dict):
                return None, None
            p95 = TR.hist_quantile(e.get("buckets") or TR.BUCKETS_MS,
                                   e.get("counts") or [], 0.95)
            cnt = int(e.get("count", 0) or 0)
            mean = (float(e.get("sum", 0.0)) / cnt) if cnt else None
            return p95, mean

        p95_ttft, mean_ttft = fam_stats("ttft")
        p95_qw, _ = fam_stats("queueWait")
        return {
            "p95TtftMs": p95_ttft,
            "meanTtftMs": round(mean_ttft, 3) if mean_ttft else None,
            "p95QueueWaitMs": p95_qw,
            "podSeconds": round(pod_seconds[0], 3),
            "completed": completed[0],
            "errors": errors[0],
            "durationS": round(wall, 3),
            "wallS": round(wall, 3),
            "speedup": 1.0,
            "replicasPeak": peak[0],
            "scaleEvents": scale_events[0],
            "bootSecondsMean": (round(sum(boot_times)
                                      / len(boot_times), 3)
                                if boot_times else None),
            "policy": DEFAULT_POLICY.diff(policy),
            "backend": "simfleet",
            "export": export,
            "serving": serving,
        }
    finally:
        stop.set()
        ctrl.join(timeout=5.0)
        with drain_lock:
            pass                    # let an in-flight drain finish
        fleet.close()


# ---------------------------------------------------------------------------
# Sweep driver
# ---------------------------------------------------------------------------


def sweep(workload: Workload, calib: Calibration,
          points: Sequence[PolicyConfig], *,
          ttft_target_ms: float = 250.0, min_replicas: int = 1,
          max_replicas: int = 4, slots: int = 4,
          log: Optional[Callable[[str], None]] = None
          ) -> List[Dict[str, Any]]:
    """Score each policy point on the virtual fleet: sim-predicted
    p95 TTFT and pod-seconds, one row per point (row 0 should be the
    baseline ``DEFAULT_POLICY`` so diffs read against it)."""
    rows = []
    for pt in points:
        res = VirtualFleet(workload, calib, policy=pt,
                           ttft_target_ms=ttft_target_ms,
                           min_replicas=min_replicas,
                           max_replicas=max_replicas,
                           slots=slots).run()
        row = res.to_dict()
        row["policy"] = DEFAULT_POLICY.diff(pt) or {"baseline": True}
        rows.append(row)
        if log:
            log(f"  {row['policy']}: p95 TTFT "
                f"{row['p95TtftMs']:.1f} ms, "
                f"{row['podSeconds']:.1f} pod-s, "
                f"{row['speedup']:.0f}x realtime")
    return rows


def pick_winner(rows: Sequence[Dict[str, Any]], *,
                pod_seconds_slack: float = 1.10
                ) -> Optional[Dict[str, Any]]:
    """The sweep's verdict: the lowest sim-predicted p95 TTFT whose
    pod-seconds stay within ``pod_seconds_slack`` x the baseline's
    (row 0) — a latency win bought with unbounded capacity is not a
    tuning, it is a bigger fleet."""
    if not rows:
        return None
    base = rows[0]
    budget = float(base["podSeconds"]) * pod_seconds_slack
    ok = [r for r in rows
          if r["p95TtftMs"] is not None
          and float(r["podSeconds"]) <= budget]
    return min(ok, key=lambda r: float(r["p95TtftMs"])) if ok else base


# ---------------------------------------------------------------------------
# tpujob_sim_* metrics (docs/observability.md catalogs these; the
# doc-drift test pins catalog <-> code both directions)
# ---------------------------------------------------------------------------

SIM_METRICS: Dict[str, str] = {
    "tpujob_sim_p95_ttft_ms":
        "sim-predicted p95 TTFT over the replayed workload",
    "tpujob_sim_mean_ttft_ms":
        "sim-predicted mean TTFT over the replayed workload",
    "tpujob_sim_pod_seconds":
        "pod-seconds consumed (integral of live replicas over time)",
    "tpujob_sim_requests_total":
        "requests completed by the replay",
    "tpujob_sim_speedup":
        "virtual-time speedup: trace duration over sim wall-clock",
    "tpujob_sim_replicas_peak":
        "peak live replica count the control law reached",
    "tpujob_sim_scale_events_total":
        "autoscaler decisions (up/down/clamp) taken during the replay",
}


def sim_metrics_text(result: Dict[str, Any]) -> str:
    """Render one replay result as Prometheus-style gauge lines under
    the ``tpujob_sim_*`` names (what ``make sim`` prints and bench
    folds into summary keys)."""
    vals = {
        "tpujob_sim_p95_ttft_ms": result.get("p95TtftMs"),
        "tpujob_sim_mean_ttft_ms": result.get("meanTtftMs"),
        "tpujob_sim_pod_seconds": result.get("podSeconds"),
        "tpujob_sim_requests_total": result.get("completed"),
        "tpujob_sim_speedup": result.get("speedup"),
        "tpujob_sim_replicas_peak": result.get("replicasPeak"),
        "tpujob_sim_scale_events_total": result.get("scaleEvents"),
    }
    lines = []
    for name in sorted(SIM_METRICS):
        v = vals.get(name)
        if v is None:
            continue
        lines.append(f"# HELP {name} {SIM_METRICS[name]}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {float(v)}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# CLI (`make sim`)
# ---------------------------------------------------------------------------


def _default_points(base: PolicyConfig) -> List[PolicyConfig]:
    """The stock sweep grid: the up-path cool-down (how fast capacity
    chases a burst) against the down-path hysteresis — the two knobs
    the bursty envelope is most sensitive to.  Baseline first."""
    pts = [base]
    for ucd in (0.5, 1.0, 2.0, 5.0, 10.0):
        if ucd != base.up_cooldown_s:
            pts.append(base.override(up_cooldown_s=ucd))
    for sdr in (0.3, 0.7):
        pts.append(base.override(scale_down_ratio=sdr))
    return pts


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_operator_tpu.router.replay",
        description="Virtual-time fleet policy sweeps over recorded "
                    "or synthetic traces (ISSUE 18)")
    ap.add_argument("--trace", help="recorded workload: a "
                    "/debug/tracez?format=jsonl export or "
                    "flight-recorder dump path")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--duration", type=float, default=300.0,
                    help="synthetic trace duration (s)")
    ap.add_argument("--rps", type=float, default=2.0)
    ap.add_argument("--burst-factor", type=float, default=6.0)
    # 4-5x the bare service time, the headroom a deployed SLO carries:
    # an un-headroomed target pins the p95 floor above the down
    # hysteresis and the law (correctly) never scales down — sweeps
    # in that regime score every policy identically
    ap.add_argument("--ttft-target-ms", type=float, default=1000.0)
    ap.add_argument("--max-replicas", type=int, default=6)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--json", action="store_true",
                    help="emit the full sweep as JSON")
    args = ap.parse_args(argv)

    if args.trace:
        text = open(args.trace).read()
        if '"kind": "timeline"' in text or '"kind":"timeline"' in text:
            wl = schedule_from_export(text)
            parsed = TR.parse_jsonl_export(text)
            fams = (parsed["hists"][0]["families"]
                    if parsed["hists"] else {})
        else:
            wl = schedule_from_flightrec(args.trace)
            fams = {}
        mean_p = (sum(r.prompt_len for r in wl.requests)
                  / max(len(wl.requests), 1))
        calib = (Calibration.from_hists(fams, mean_prompt_len=mean_p)
                 if fams else Calibration())
        print(f"workload: {wl.source}, {len(wl.requests)} requests "
              f"over {wl.duration_s:.1f}s")
    else:
        wl = synthetic_workload(seed=args.seed,
                                duration_s=args.duration,
                                mean_rps=args.rps,
                                burst_factor=args.burst_factor,
                                n_bursts=3)
        # small-real-model service times: one replica saturates inside
        # the burst windows, so the sweep actually exercises the
        # up-path it exists to tune (the all-idle regime scores every
        # policy identically and teaches nothing)
        calib = Calibration(prefill_ms_token=8.0, itl_ms=30.0,
                            boot_s=4.0)
        print(f"workload: {wl.source}, {len(wl.requests)} requests "
              f"over {wl.duration_s:.1f}s (synthetic)")
    print(f"calibration: {calib.to_dict()}")

    rows = sweep(wl, calib, _default_points(DEFAULT_POLICY),
                 ttft_target_ms=args.ttft_target_ms,
                 max_replicas=args.max_replicas, slots=args.slots,
                 log=print)
    win = pick_winner(rows)
    if args.json:
        print(json.dumps({"rows": rows, "winner": win}, indent=2))
    else:
        print(f"winner: {win['policy']} — p95 TTFT "
              f"{win['p95TtftMs']:.1f} ms at "
              f"{win['podSeconds']:.1f} pod-s")
        print(sim_metrics_text(win), end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
