"""The fleet router process: prefix-affinity routing + streaming proxy.

Jax-free (it runs in its own pod next to the replicas — the operator's
``construct_router_pod``).  One process fronts N serving replicas
(infer/serve.py), each of which already exports everything the router
needs:

- ``/readyz``            — drain-aware readiness (PR 5): false while the
  replica is draining or self-healing, so the router stops routing to a
  scale-down victim the moment its SIGTERM lands, while the victim
  finishes its residents and exits 83;
- ``/metrics``           — the per-pod ``tpujob_serve_*`` gauges
  (utils/observability.serving_gauges); the router scrapes
  ``tpujob_serve_queue_depth`` / ``tpujob_serve_kv_blocks_free`` /
  ``tpujob_serve_tokens_per_sec`` and scores load from them;
- ``/v1/generate``       — the proxied work, streaming or not.

Routing policy (Llumnix / SGLang cache-aware router lineage):

1. **Affinity**: the request's first prefix blocks hash to a radix
   chain key (utils/radixkey.py — the SAME chain the replicas' paged
   cache keys on), and the consistent-hash ring (hashring.py) maps the
   key to a replica.  Requests sharing a system prompt therefore land
   on the replica that already caches its blocks — prefill skipped.
2. **Spillover**: when the affinity target is HOT (scraped queue depth
   at/over ``hot_queue_depth``, or free KV blocks at/under
   ``low_blocks``) the request spills to the least-loaded ready
   replica — ordered by (queue depth, fewest free blocks, slowest
   tok/s) so all three scraped gauges participate.  Cache misses on
   spill are the price of not queueing behind a hot replica.
3. **Drain/scale**: a not-ready replica is walked PAST on the ring
   (keys do not remap); a new replica takes traffic only once its
   ``/readyz`` goes true (scale-up admission gating).

Exactly-once at the fleet level: a replica drain 503s requests it
sheds; the client retries (client/client.py).  The retry carries the
same idempotent ``request_id``, and the router remembers completed
results (bounded LRU) — a retry that raced the original's completion
replays the recorded response instead of generating twice.

Fleet-level KV brokering (ISSUE 12, docs/serving.md "Fleet-level
KV"): ``POST /v1/kv/migrate`` places a draining/parked lane's wire
envelope on the best ready peer (fewest parked lanes, then
least-loaded; origin excluded) and records ``request_id -> adopter``
so the client's retry routes there (``X-Router-Reason: migrated``);
replayed migrations answer from the table without re-forwarding.
``POST /v1/kv/prefix`` forwards a peer-prefix-fetch ask to the
prompt's hashring affinity owner — the same placement rule that put
the prefix's traffic (and therefore its cached blocks) there.  The
router only peeks envelope headers (utils/fleetkv.peek_header) and
relays raw bytes; it stays jax-free.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from paddle_operator_tpu.utils import tracing as TRC
from paddle_operator_tpu.utils.radixkey import prefix_chain_key
from paddle_operator_tpu.router.hashring import HashRing

# gauge name -> camelCase serving-block key (the inverse of
# utils/observability.serving_gauges for the fields the router uses)
_GAUGE_KEYS = {
    "tpujob_serve_queue_depth": "queueDepth",
    "tpujob_serve_kv_blocks_free": "kvBlocksFree",
    "tpujob_serve_tokens_per_sec": "tokensPerSec",
    "tpujob_serve_prefix_hit_rate": "prefixHitRate",
    "tpujob_serve_accept_rate": "acceptRate",
    "tpujob_serve_draining": "draining",
    # fleet-level KV (ISSUE 12): parked lanes + host-tier residency
    # make migration-target choice inspectable (/statusz) and feed the
    # broker's least-loaded-holder ordering
    "tpujob_serve_parked_lanes": "parkedLanes",
    "tpujob_serve_host_cache_blocks": "hostCacheBlocks",
    # prefill pool (ISSUE 13): queue depth + per-job service time —
    # what /v1/prefill forwarding orders candidates by, and what the
    # operator's SLO autoscaler converts a TTFT target into a depth
    # bound with (controller/autoscaler.py)
    "tpujob_serve_prefill_queue_depth": "prefillQueueDepth",
    "tpujob_serve_prefill_ms_avg": "prefillMsAvg",
    # the served-jobs weight for the fleet prefillMsAvg fold — without
    # it a freshly-joined pod's one slow reading counts as much as a
    # seasoned pod's thousands
    "tpujob_serve_prefill_jobs_total": "prefillJobs",
    # prefill-pool throughput (ISSUE 14): batch occupancy + engine
    # lanes feed the autoscaler's prefill denominator (a half-empty
    # batch must not read as a saturated pool); HOL wait p95 surfaces
    # queueing the depth gauge alone can hide
    "tpujob_serve_prefill_lanes": "prefillLanes",
    "tpujob_serve_prefill_batch_occupancy": "prefillBatchOccupancy",
    "tpujob_serve_prefill_hol_wait_ms": "prefillHolWaitMs",
    # live weight swap (ISSUE 19): the generation each replica serves
    # — /statusz shows the mid-roll spread, and the fleet fold splits
    # its token-weighted rates per generation instead of blending
    # old- and new-weights readings into one unlabeled number
    "tpujob_serve_generation": "weightGeneration",
    "tpujob_serve_tp": "servingTp",
}

_GAUGE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{[^}]*\})?\s+"
    r"(?P<value>[-+0-9.eEnaif]+)\s*$")

# tpujob_serve_adapter_loaded{...,adapter="name"} marker gauges
# (ISSUE 10): the per-replica loaded-adapter SET the router's
# adapter-affinity policy reads — scraped from the same /metrics pass
# as the load gauges, no extra endpoint
_ADAPTER_RE = re.compile(
    r'^tpujob_serve_adapter_loaded\{[^}]*adapter="(?P<name>[^"]*)"[^}]*\}'
    r"\s+1(?:\.0)?\s*$")


# latency-histogram exposition lines (ISSUE 15): the per-replica
# _bucket/_sum/_count families utils/observability.histogram_exposition
# renders — the router folds them fleet-wide and derives the windowed
# TTFT p95 the SLO autoscaler consumes
_HIST_RE = re.compile(
    r"^(?P<name>tpujob_serve_(?:ttft|itl|e2e|queue_wait)_ms)_"
    r"(?P<part>bucket|sum|count)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[-+0-9.eE+Inf]+)\s*$")
_LE_RE = re.compile(r'le="(?P<le>[^"]+)"')

# the metric name -> family key map (inverse of tracing.HIST_FAMILIES)
_HIST_KEYS = {name: fam for fam, name in TRC.HIST_FAMILIES.items()}

# the rolling window the router's fleet p95 reads over: wide enough to
# smooth scrape ticks and per-replica windows, narrow enough that a
# resolved burst stops breaching within ~two windows
ROUTER_HIST_WINDOW_S = 120.0


def parse_serve_histograms(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse a replica's histogram exposition into snapshot-shaped
    entries ``{family: {"buckets": [...], "counts": [...per-bucket,
    +Inf last...], "sum": s, "count": n}}`` (the same shape
    ``status.serving.latencyHist`` carries, so one fold —
    tracing.fold_latency_hists — serves both paths).  Cumulative
    ``_bucket`` lines are de-cumulated here."""
    acc: Dict[str, Dict[str, Any]] = {}
    for line in text.splitlines():
        m = _HIST_RE.match(line.strip())
        if m is None:
            continue
        fam = _HIST_KEYS.get(m.group("name"))
        if fam is None:
            continue
        e = acc.setdefault(fam, {"les": [], "sum": 0.0, "count": 0})
        part, raw = m.group("part"), m.group("value")
        if part == "sum":
            e["sum"] = float(raw)
        elif part == "count":
            e["count"] = int(float(raw))
        else:
            le = _LE_RE.search(m.group("labels") or "")
            if le is None:
                continue
            bound = le.group("le")
            e["les"].append((float("inf") if bound == "+Inf"
                             else float(bound), int(float(raw))))
    out: Dict[str, Dict[str, Any]] = {}
    for fam, e in acc.items():
        les = sorted(e["les"])
        if not les:
            continue
        bounds = [b for b, _ in les if b != float("inf")]
        cums = [c for _, c in les]
        counts, prev = [], 0
        for c in cums:
            counts.append(max(0, c - prev))
            prev = c
        if les[-1][0] != float("inf"):
            counts.append(max(0, e["count"] - prev))
        out[fam] = {"buckets": bounds, "counts": counts,
                    "sum": e["sum"], "count": e["count"]}
    return out


def parse_adapter_gauges(text: str) -> set:
    """The adapter names a replica's /metrics declares loaded."""
    out = set()
    for line in text.splitlines():
        m = _ADAPTER_RE.match(line.strip())
        if m:
            out.add(m.group("name"))
    return out


def parse_serve_gauges(text: str) -> Dict[str, float]:
    """Parse prometheus exposition text into {camelCase key: value}
    for the ``tpujob_serve_*`` gauges the router scores on (labels are
    per-pod constant, so they are dropped)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _GAUGE_RE.match(line)
        if not m:
            continue
        key = _GAUGE_KEYS.get(m.group("name"))
        if key is None:
            continue
        try:
            out[key] = float(m.group("value"))
        except ValueError:
            continue
    return out


def aggregate_fleet_serving(replicas: Dict[str, Dict[str, Any]]
                            ) -> Dict[str, Any]:
    """Fold per-replica ``status.serving`` blocks into one fleet block
    (the top-level shape dashboards already read): capacities and
    throughputs SUM; rates average weighted by each replica's served
    tokens (a fresh replica's 0.0 hit rate must not drag the fleet
    number below what the traffic actually experienced); liveness
    folds conservatively (draining if ANY, healthy only if ALL).
    Shared by the router's ``/statusz`` and the reconciler's fleet
    status aggregation — one definition, no drift.

    ROLE-AWARE (ISSUE 13): a prefill-pool replica (``role:
    "prefill"``) never decodes — its ``tokensPerSec`` counts PREFILL
    tokens and its hit/accept rates do not exist.  Folding it into the
    decode sums would inflate fleet tok/s with prompt tokens and (via
    its ``tokensTotal`` weight) drag every token-weighted rate toward
    0.  Prefill blocks therefore aggregate into their OWN keys
    (``prefillTokensPerSec`` / ``prefillMsAvg`` /
    ``prefillReplicasReporting``, and their queue depths fold into the
    fleet ``prefillQueueDepth``); only liveness folds across both
    pools."""
    blocks_all = [b for b in replicas.values() if isinstance(b, dict)]
    agg: Dict[str, Any] = {"replicasReporting": len(blocks_all)}
    if not blocks_all:
        return agg
    blocks = [b for b in blocks_all if b.get("role") != "prefill"]
    prefill = [b for b in blocks_all if b.get("role") == "prefill"]
    for key in ("tokensPerSec", "queueDepth", "kvBlocksFree",
                "tokensTotal", "activeLanes", "kvPoolBytes",
                "hostCacheBlocks", "promotedBlocks", "deadlineExceeded",
                "watchdogRestarts", "quarantinedLanes",
                "prefillQueueDepth",
                # multi-tenant QoS counters (ISSUE 10) — without them
                # the fleet gauges read 0 while replicas preempt
                "preemptedLanes", "parkedLanes", "activeAdapters",
                # fleet-level KV (ISSUE 12): migration and peer-fetch
                # accounting sums across the fleet
                "laneMigrations", "adoptedLanes", "peerPrefixFetches",
                "hostCacheEvictions",
                # durable prefix store (ISSUE 17) — NOTE: replicas
                # sharing one dir: volume each report the full store,
                # so the fleet sum over-counts by the sharing factor;
                # per-replica /metrics stay exact
                "kvStoreBlocks", "kvStoreBytes", "kvStoreEvictions"):
        vals = [b.get(key) for b in blocks if b.get(key) is not None]
        if vals:
            total = sum(float(v) for v in vals)
            agg[key] = round(total, 2) if total % 1 else int(total)
    # per-class queue depth sums element-wise (classes align by index;
    # a ragged fleet pads the shorter lists with 0)
    depths = [b.get("priorityQueueDepth") for b in blocks
              if isinstance(b.get("priorityQueueDepth"), list)]
    if depths:
        width = max(len(d) for d in depths)
        agg["priorityQueueDepth"] = [
            int(sum(float(d[i]) if i < len(d) else 0.0 for d in depths))
            for i in range(width)]
    weights = [max(float(b.get("tokensTotal", 0) or 0), 0.0)
               for b in blocks]
    if not sum(weights):
        weights = [1.0] * len(blocks)   # no traffic yet: plain mean
    for key in ("prefixHitRate", "acceptRate", "hostHitRate",
                "kvStoreHitRate", "chunkedPrefillTokenShare"):
        vals = [(float(b.get(key, 0.0) or 0.0), w)
                for b, w in zip(blocks, weights) if key in b]
        if vals:
            agg[key] = round(sum(v * w for v, w in vals)
                             / (sum(w for _, w in vals) or 1.0), 4)
    # live weight swap (ISSUE 19): a mid-roll fleet serves two weight
    # generations at once — blending their hit/accept rates into ONE
    # unlabeled token-weighted number would attribute the old
    # generation's warmed-cache readings to the new deploy (and the
    # swapped replica's cold restart to the old).  The fold therefore
    # labels the blend: the generation spread + a ``mixedGenerations``
    # flag always ride the block, and mid-roll the same token-weighted
    # rates are ALSO split per generation (``byGeneration``), so
    # dashboards and the bench read honest numbers while the roll is
    # in flight.
    gens = sorted({int(b["weightGeneration"]) for b in blocks
                   if b.get("weightGeneration") is not None})
    if gens:
        agg["generationMin"] = gens[0]
        agg["generationMax"] = gens[-1]
        agg["mixedGenerations"] = len(gens) > 1
        if len(gens) > 1:
            by: Dict[str, Any] = {}
            for g in gens:
                sub = [b for b in blocks
                       if int(b.get("weightGeneration", -1)) == g]
                ws = [max(float(b.get("tokensTotal", 0) or 0), 0.0)
                      for b in sub]
                if not sum(ws):
                    ws = [1.0] * len(sub)
                ent: Dict[str, Any] = {"replicas": len(sub)}
                tps = [float(b.get("tokensPerSec", 0.0) or 0.0)
                       for b in sub if "tokensPerSec" in b]
                if tps:
                    ent["tokensPerSec"] = round(sum(tps), 2)
                for key in ("prefixHitRate", "acceptRate",
                            "hostHitRate", "kvStoreHitRate"):
                    vals = [(float(b.get(key, 0.0) or 0.0), w)
                            for b, w in zip(sub, ws) if key in b]
                    if vals:
                        ent[key] = round(
                            sum(v * w for v, w in vals)
                            / (sum(w for _, w in vals) or 1.0), 4)
                by[str(g)] = ent
            agg["byGeneration"] = by
    tp_vals = [int(b["servingTp"]) for b in blocks
               if b.get("servingTp") is not None]
    if tp_vals:
        # mid-resize the wider degree is the capacity truth, same rule
        # as the prefill-lane fold
        agg["servingTp"] = max(tp_vals)
    # latency histograms (ISSUE 15): fixed-bucket counts FOLD by
    # addition — decode replicas only (prefill pods never emit a TTFT)
    # — and the folded rolling window yields the one number a p95 can
    # honestly be at fleet level (averaging per-replica p95s cannot:
    # quantiles do not average)
    lh = [b.get("latencyHist") for b in blocks
          if isinstance(b.get("latencyHist"), dict)]
    if lh:
        folded = TRC.fold_latency_hists(lh)
        if folded:
            agg["latencyHist"] = folded
            p95 = TRC.hist_p95(folded.get("ttft"))
            if p95 is not None:
                agg["ttftP95Ms"] = round(p95, 3)
    # prefill-pool fold (ISSUE 13): own keys, decode sums untouched
    if prefill:
        agg["prefillReplicasReporting"] = len(prefill)
        agg["prefillTokensPerSec"] = round(
            sum(float(b.get("tokensPerSec", 0.0) or 0.0)
                for b in prefill), 2)
        # the POOL's own depth REPLACES the decode-side sum: a remote
        # handoff in flight is counted by its decode ring
        # (_disagg_waiting) AND by the pod serving it — folding both
        # would read ~2x and the SLO autoscaler would converge the
        # pool at twice the pods the TTFT target needs
        agg["prefillQueueDepth"] = int(sum(
            float(b.get("prefillQueueDepth", 0) or 0)
            for b in prefill))
        ms = [(float(b.get("prefillMsAvg", 0.0) or 0.0),
               max(1.0, float(b.get("prefillJobs", 0) or 0)))
              for b in prefill if b.get("prefillMsAvg")]
        if ms:
            agg["prefillMsAvg"] = round(
                sum(v * w for v, w in ms) / sum(w for _, w in ms), 3)
    # prefill-pool throughput fold (ISSUE 14), role-aware: occupancy
    # and HOL wait come from whichever pods run an engine — prefill
    # pods, or decode pods with the IN-PROCESS engine — weighted by
    # served prefill jobs (a fresh pod's empty batch must not drag
    # the fleet occupancy the autoscaler divides by); lanes folds as
    # the per-pod width (max — pools are homogeneous by construction,
    # and mid-rollout the wider generation is the capacity truth)
    eng = [b for b in blocks_all
           if float(b.get("prefillLanes", 0) or 0) > 0]
    if eng:
        agg["prefillLanes"] = int(max(
            float(b.get("prefillLanes", 0) or 0) for b in eng))
        ws = [max(1.0, float(b.get("prefillJobs",
                                   b.get("tokensTotal", 0)) or 0))
              for b in eng]
        agg["prefillBatchOccupancy"] = round(
            sum(float(b.get("prefillBatchOccupancy", 0.0) or 0.0) * w
                for b, w in zip(eng, ws)) / sum(ws), 4)
        agg["prefillHolWaitMs"] = round(max(
            float(b.get("prefillHolWaitMs", 0.0) or 0.0)
            for b in eng), 3)
    if any("draining" in b for b in blocks_all):
        agg["draining"] = any(bool(b.get("draining"))
                              for b in blocks_all)
    if any("healthy" in b for b in blocks_all):
        agg["healthy"] = all(bool(b.get("healthy", True))
                             for b in blocks_all)
    return agg


@dataclass
class ReplicaState:
    """What the scrape loop knows about one replica."""

    endpoint: str                       # "host:port"
    ready: bool = False
    gauges: Dict[str, float] = field(default_factory=dict)
    adapters: set = field(default_factory=set)   # loaded LoRA adapters
    last_ok: float = 0.0                # monotonic time of last scrape
    consecutive_failures: int = 0
    # circuit breaker (ISSUE 20): POST-path failures trip the breaker
    # even while /readyz keeps answering (a blackholed replica accepts
    # probes and hangs work).  While open (monotonic now <
    # breaker_open_until) the replica is unroutable; after the
    # cooldown ONE request is admitted as the half-open probe
    # (breaker_probe_inflight) — its outcome closes or re-opens.
    breaker_open_until: float = 0.0
    breaker_probe_inflight: bool = False
    # POST-path failure streak for the trip threshold, SEPARATE from
    # consecutive_failures: the scrape loop zeroes that one on every
    # successful /readyz, so a blackholed replica whose probes keep
    # passing would never accumulate to the threshold.  Only a real
    # upstream POST response (breaker_success) clears this.
    breaker_failures: int = 0
    # latency histograms (ISSUE 15): the last parsed snapshot plus a
    # short history of (t, snapshot) pairs — cumulative scraped counts
    # turn into a rolling window by differencing against the oldest
    # retained snapshot (the Prometheus rate() discipline, in-process)
    hists: Dict[str, Any] = field(default_factory=dict)
    hist_hist: Any = field(default_factory=deque)

    def record_hists(self, hists: Dict[str, Any], now: float) -> None:
        if not hists:
            return
        self.hists = hists
        self.hist_hist.append((now, hists))
        # keep the oldest snapshot as the first one at least a window
        # old (entries younger than the window stay so it can slide)
        while (len(self.hist_hist) > 1
               and now - self.hist_hist[1][0]
               >= ROUTER_HIST_WINDOW_S):
            self.hist_hist.popleft()

    def latency_hist_block(self) -> Optional[Dict[str, Any]]:
        """Snapshot-shaped block with ``window`` = the delta against
        the oldest retained scrape (full counts before a baseline
        exists, and on a counter reset — replica restart — where a
        negative delta would lie)."""
        if not self.hists:
            return None
        old = (self.hist_hist[0][1]
               if len(self.hist_hist) >= 2 else None)
        out: Dict[str, Any] = {}
        for fam, e in self.hists.items():
            win = list(e.get("counts") or [])
            oe = (old or {}).get(fam)
            if oe and oe.get("buckets") == e.get("buckets"):
                delta = [c - o for c, o in
                         zip(e["counts"], oe["counts"])]
                if all(v >= 0 for v in delta):
                    win = delta
            out[fam] = dict(e, window=win)
        return out

    @property
    def queue_depth(self) -> float:
        return self.gauges.get("queueDepth", 0.0)

    @property
    def kv_blocks_free(self) -> float:
        return self.gauges.get("kvBlocksFree", 0.0)

    @property
    def tokens_per_sec(self) -> float:
        return self.gauges.get("tokensPerSec", 0.0)

    @property
    def parked_lanes(self) -> float:
        return self.gauges.get("parkedLanes", 0.0)

    def load_rank(self) -> Tuple[float, float, float]:
        """Least-loaded ordering: shortest queue first, then the most
        free KV blocks, then the highest recent throughput (a replica
        already moving tokens clears its queue fastest)."""
        return (self.queue_depth, -self.kv_blocks_free,
                -self.tokens_per_sec)


class FleetRouter:
    """Replica selection + scrape state + dedupe.  The HTTP handler
    (make_router_server) is a thin shell over this object, so tests
    can drive policy without sockets."""

    def __init__(self, endpoints: Optional[List[str]] = None, *,
                 block_size: int = 256, affinity_blocks: int = 2,
                 hot_queue_depth: int = 4, low_blocks: int = 0,
                 scrape_interval: float = 1.0, dedupe_cap: int = 1024,
                 endpoints_file: Optional[str] = None,
                 vnodes: int = 64, retry_after_s: int = 1,
                 upstream_timeout: float = 600.0,
                 prefill_endpoints: Optional[List[str]] = None,
                 prefill_endpoints_file: Optional[str] = None,
                 trace: Optional[bool] = None,
                 kv_store=None,
                 state_dir: Optional[str] = None,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 2.0) -> None:
        self.block_size = block_size
        # durable prefix store (ISSUE 17): with ROUTER_KV_STORE
        # pointing at the fleet's shared store volume, a /v1/kv/prefix
        # ask that finds no hashring owner (or whose owner misses) is
        # served from the store directly — the router re-encodes the
        # entries as a standard prefix envelope stamped with THEIR
        # fingerprint, and the asking replica's check_fingerprint stays
        # the last word.  kvstore is jax-free, so the router stays
        # jax-free.
        self.kv_store = kv_store
        self.affinity_blocks = affinity_blocks
        self.hot_queue_depth = hot_queue_depth
        self.low_blocks = low_blocks
        self.scrape_interval = scrape_interval
        self.retry_after_s = retry_after_s
        self.upstream_timeout = upstream_timeout
        self.endpoints_file = endpoints_file
        self._lock = threading.RLock()
        self.ring = HashRing(vnodes=vnodes)
        self.replicas: Dict[str, ReplicaState] = {}
        # prefill pool (ISSUE 13 cross-host disaggregation): a SECOND
        # scraped directory — prefill pods take /v1/prefill forwards
        # only, never generate traffic, and never join the hashring
        # (they hold no radix cache to be affine to)
        self.prefill_endpoints_file = prefill_endpoints_file
        self.prefill: Dict[str, ReplicaState] = {}
        self.draining = False
        self.inflight_proxies = 0
        # exactly-once dedupe: request_id -> recorded (status, body) for
        # COMPLETED results; _inflight holds ids being proxied right now
        self._results: "OrderedDict[str, Tuple[int, bytes]]" = \
            OrderedDict()
        self._dedupe_cap = dedupe_cap
        self._inflight: set = set()
        # fleet-level KV (ISSUE 12): request_id -> adopting endpoint
        # for brokered lane migrations (bounded LRU); retries with a
        # recorded id route to the adopter, replayed migrations are
        # answered from the table instead of re-forwarded, and ids
        # mid-broker sit in _migr_inflight so a replay race cannot
        # place one lane on two replicas
        self._migrations: "OrderedDict[str, str]" = OrderedDict()
        self._migr_cap = 4096
        self._migr_inflight: set = set()
        # tracing (ISSUE 15): one stitched cross-pod timeline per
        # trace id, served at /debug/tracez.  Stitching activates per
        # request when the inbound X-Tpujob-Trace header is present;
        # ROUTER_TRACE=1 (or trace=True) additionally MINTS a trace
        # for every generate so a fleet can be inspected without
        # client cooperation.
        self.trace_all = (os.environ.get("ROUTER_TRACE", "0") == "1"
                          if trace is None else bool(trace))
        self.traces = TRC.TraceStore(
            cap=int(os.environ.get("ROUTER_TRACE_CAP", "256") or 256))
        # dedupe replays echo the replica that SERVED the recorded
        # result (ISSUE 15 satellite) — parallel to _results, pruned
        # with it
        self._result_replica: Dict[str, str] = {}
        self.counters: Dict[str, float] = {
            "routed_affinity": 0, "routed_spill": 0,
            "routed_least_loaded": 0, "routed_adapter": 0,
            "routed_migrated": 0,
            "dedupe_replays": 0,
            "migrations_brokered": 0, "migration_replays": 0,
            "prefix_forwards": 0,
            # durable store (ISSUE 17): prefix asks served from the
            # store after an owner miss / no-owner
            "store_prefix_serves": 0,
            # prefill pool (ISSUE 13): /v1/prefill forwards placed on
            # a ready prefill pod, and asks that found none ready
            "prefill_jobs_forwarded": 0, "no_ready_prefill": 0,
            "upstream_errors": 0, "no_ready_replica": 0,
            # crash-safe journal (ISSUE 20): appended exactly-once
            # records, records restored at boot, LRU-cap compactions
            "journal_appends": 0, "journal_replayed": 0,
            "journal_compactions": 0,
            # circuit breaker (ISSUE 20): trips (closed -> open),
            # re-opens (failed half-open probe), half-open probes
            # admitted, closes (probe succeeded)
            "breaker_trips": 0, "breaker_reopens": 0,
            "breaker_probes": 0, "breaker_closes": 0,
            # streamed results recorded as already-served terminal
            # markers (the streamed-dedupe fix, ISSUE 20 satellite)
            "stream_results_recorded": 0,
        }
        # circuit breaker config: threshold 0 disables (the bench's
        # timeout-path control)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        # boot warm-up (ISSUE 20): with a live-reloaded endpoints file
        # the directory is EMPTY until the first scrape tick reloads
        # it — a restarted router must not answer /readyz true (and
        # route nothing, or worse, everything least-loaded to a stale
        # member) before that first reload.  Static constructor
        # endpoints ARE the directory, so they warm immediately.
        self._warmed = not (endpoints_file or prefill_endpoints_file)
        # crash-safe journal (ISSUE 20): ROUTER_STATE_DIR persists the
        # dedupe + migration windows so a kill -9'd router restarts
        # into the SAME exactly-once window instead of an empty one
        self._journal = None
        if state_dir:
            from paddle_operator_tpu.router.journal import RouterJournal

            self._journal = RouterJournal(state_dir)
            results, result_replica, migrations = self._journal.replay()
            while len(results) > self._dedupe_cap:
                k, _ = results.popitem(last=False)
                result_replica.pop(k, None)
            self._results = results
            self._result_replica = result_replica
            # re-derive base-id routes exactly as record_migration did
            for rid, ep in migrations.items():
                self._record_migration_locked(rid, ep)
            self.counters["journal_replayed"] = float(
                self._journal.replayed)
        self._stop = threading.Event()
        self._scrape_thread: Optional[threading.Thread] = None
        self._scrape_pool = None        # lazy ThreadPoolExecutor
        if endpoints:
            self.set_endpoints(endpoints)
        if prefill_endpoints:
            self.set_prefill_endpoints(prefill_endpoints)

    # -- membership --------------------------------------------------------

    @staticmethod
    def _norm(endpoint: str) -> str:
        return endpoint.split("://", 1)[-1].strip().rstrip("/")

    def set_endpoints(self, endpoints: List[str]) -> None:
        eps = [self._norm(e) for e in endpoints if e.strip()]
        with self._lock:
            self.ring.set_endpoints(eps)
            for ep in eps:
                self.replicas.setdefault(ep, ReplicaState(ep))
            for ep in [e for e in self.replicas if e not in set(eps)]:
                del self.replicas[ep]

    def endpoints(self) -> List[str]:
        with self._lock:
            return self.ring.endpoints

    def set_prefill_endpoints(self, endpoints: List[str]) -> None:
        eps = [self._norm(e) for e in endpoints if e.strip()]
        with self._lock:
            for ep in eps:
                self.prefill.setdefault(ep, ReplicaState(ep))
            for ep in [e for e in self.prefill if e not in set(eps)]:
                del self.prefill[ep]

    def prefill_pool(self) -> List[str]:
        with self._lock:
            return sorted(self.prefill)

    def _reload_endpoints_file(self) -> None:
        if self.endpoints_file:
            try:
                with open(self.endpoints_file) as f:
                    raw = f.read()
            except OSError:
                raw = ""
            eps = [e for e in re.split(r"[,\s]+", raw) if e]
            if eps and set(map(self._norm, eps)) \
                    != set(self.endpoints()):
                self.set_endpoints(eps)
        if self.prefill_endpoints_file:
            try:
                with open(self.prefill_endpoints_file) as f:
                    raw = f.read()
            except OSError:
                return
            # unlike the decode list, EMPTY is meaningful here: the
            # autoscaler may scale the prefill pool to its minimum and
            # back — stale entries must drop, not linger unroutable
            eps = [e for e in re.split(r"[,\s]+", raw) if e]
            if set(map(self._norm, eps)) != set(self.prefill):
                self.set_prefill_endpoints(eps)

    # -- scraping ----------------------------------------------------------

    def _http_get(self, endpoint: str, path: str,
                  timeout: float = 2.0) -> Tuple[int, bytes]:
        host, _, port = endpoint.rpartition(":")
        conn = HTTPConnection(host, int(port), timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _http_post(self, endpoint: str, path: str, body: bytes,
                   content_type: str = "application/octet-stream",
                   timeout: float = 10.0) -> Tuple[int, bytes]:
        # shared with FleetKVClient (utils/fleetkv.http_post) so the
        # wire's endpoint-parse/timeout semantics cannot drift
        from paddle_operator_tpu.utils.fleetkv import http_post

        return http_post(endpoint, path, body,
                         content_type=content_type, timeout=timeout)

    def scrape_once(self) -> None:
        """One poll of every replica's /readyz + /metrics.  A replica
        is routable only while its LAST readyz probe succeeded — which
        is both the drain shed (victim goes false, traffic stops) and
        the scale-up admission gate (newcomer gets traffic only after
        its first true).  Endpoints probe CONCURRENTLY: a black-holed
        replica costs the pass one probe timeout, not one per position
        behind it — a draining peer's readiness drop must never wait
        on somebody else's dead socket."""
        self._reload_endpoints_file()

        def probe(st: ReplicaState) -> None:
            try:
                code, _ = self._http_get(st.endpoint, "/readyz")
                st.ready = code == 200
                code, body = self._http_get(st.endpoint, "/metrics")
                if code == 200:
                    text = body.decode()
                    st.gauges = parse_serve_gauges(text)
                    st.adapters = parse_adapter_gauges(text)
                    st.record_hists(parse_serve_histograms(text),
                                    time.monotonic())
                st.last_ok = time.monotonic()
                st.consecutive_failures = 0
            except (OSError, socket.timeout, ValueError):
                # ValueError: a malformed endpoint (no port) must cost
                # only ITSELF — freezing other endpoints' readiness at
                # their last value is how dead replicas keep traffic
                st.consecutive_failures += 1
                st.ready = False

        states = [st for ep in self.endpoints()
                  if (st := self.replicas.get(ep)) is not None]
        with self._lock:
            states += list(self.prefill.values())
        if len(states) <= 1:
            for st in states:
                probe(st)
            self._warmed = True
            return
        # reused pool, not per-tick threads: the router scrapes every
        # second for its whole lifetime, and per-endpoint probes are
        # bounded by their own 2s socket timeouts so workers recycle
        if self._scrape_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._scrape_pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="router-probe")
        futures = [self._scrape_pool.submit(probe, st)
                   for st in states]
        for f in futures:
            try:
                f.result(timeout=10)
            except Exception:
                pass   # probe() handles its own errors; belt+braces
        # boot warm-up (ISSUE 20): only now — with the endpoints file
        # reloaded and every member probed once — may /readyz go true
        self._warmed = True

    def start(self) -> None:
        if self._scrape_thread is not None:
            return
        try:
            self.scrape_once()   # prime readiness before serving
        except Exception:
            pass   # a bad config entry must not crash-loop the router

        def loop() -> None:
            while not self._stop.wait(self.scrape_interval):
                try:
                    self.scrape_once()
                except Exception:
                    pass   # scrape must never kill the router

        self._scrape_thread = threading.Thread(
            target=loop, name="router-scrape", daemon=True)
        self._scrape_thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=5)
            self._scrape_thread = None
        if self._scrape_pool is not None:
            self._scrape_pool.shutdown(wait=False)
            self._scrape_pool = None

    # -- selection ---------------------------------------------------------

    def _ready_endpoints(self) -> List[str]:
        now = time.monotonic()
        return [ep for ep, st in self.replicas.items()
                if st.ready and not self._breaker_blocked(st, now)]

    # -- circuit breaker (ISSUE 20) ----------------------------------------

    def _breaker_blocked(self, st: ReplicaState, now: float) -> bool:
        """Passive breaker filter (no side effects — statusz and
        metrics consult it too).  Open pre-cooldown: blocked.  Open
        post-cooldown: one request may pass as the half-open probe;
        while that probe is in flight everyone else stays blocked."""
        if st.breaker_open_until <= 0.0 or self.breaker_threshold <= 0:
            return False
        if now < st.breaker_open_until:
            return True
        return st.breaker_probe_inflight

    def breaker_admit(self, endpoint: str) -> None:
        """Called by the proxy as a request is dispatched: if this
        replica's breaker is half-open, this request IS the probe."""
        st = self.replicas.get(self._norm(endpoint))
        if st is None or st.breaker_open_until <= 0.0:
            return
        with self._lock:
            if (time.monotonic() >= st.breaker_open_until
                    and not st.breaker_probe_inflight):
                st.breaker_probe_inflight = True
                self.counters["breaker_probes"] += 1

    def breaker_success(self, endpoint: str) -> None:
        """An upstream POST produced a response: close the breaker (a
        successful scrape does NOT — a blackholed replica keeps
        answering /readyz while hanging work, so only the work path
        can prove recovery)."""
        st = self.replicas.get(self._norm(endpoint))
        if st is None:
            return
        with self._lock:
            st.breaker_failures = 0   # the streak is CONSECUTIVE
            if st.breaker_open_until > 0.0:
                st.breaker_open_until = 0.0
                st.breaker_probe_inflight = False
                st.consecutive_failures = 0
                self.counters["breaker_closes"] += 1

    def _hot(self, st: ReplicaState) -> bool:
        """Affinity target too loaded to queue behind.  Judged only
        from gauges actually scraped — a replica we have no reading
        for yet is unknown, not starved (its kvBlocksFree "0" would
        otherwise mark every fresh replica hot)."""
        free = st.gauges.get("kvBlocksFree")
        return (st.queue_depth >= self.hot_queue_depth
                or (self.low_blocks > 0 and free is not None
                    and free <= self.low_blocks))

    def mark_unready(self, endpoint: str) -> None:
        """A proxy attempt failed at the socket: stop routing there
        until the scrape loop observes it healthy again (faster than
        waiting a whole scrape interval to shed a dead replica)."""
        st = self.replicas.get(self._norm(endpoint))
        if st is not None:
            with self._lock:
                st.ready = False
                st.consecutive_failures += 1
                if self.breaker_threshold <= 0:
                    st.breaker_probe_inflight = False
                    return
                st.breaker_failures += 1
                was_open = st.breaker_open_until > 0.0
                if (st.breaker_failures >= self.breaker_threshold
                        or was_open):
                    # trip — or RE-open after a failed half-open probe
                    # (the scrape zeroes consecutive_failures on every
                    # passing /readyz, which proves nothing about the
                    # POST path — the trip streak is the breaker's own)
                    st.breaker_open_until = (time.monotonic()
                                             + self.breaker_cooldown_s)
                    self.counters["breaker_reopens" if was_open
                                  else "breaker_trips"] += 1
                st.breaker_probe_inflight = False

    def choose(self, tokens,
               adapter: Optional[str] = None) -> Tuple[Optional[str], str]:
        """Pick the replica for a prompt.  Returns ``(endpoint,
        reason)`` with reason in {"adapter", "affinity", "spill",
        "least_loaded"} — or ``(None, "no_ready_replica")``.

        ``adapter`` (ISSUE 10): prefer the least-loaded READY replica
        whose scraped /metrics declare the adapter loaded — the request
        then needs no runtime load, and that replica's radix cache is
        where the adapter's prefixes live (the chain namespace is
        per-replica state).  No holder -> fall through to the normal
        prefix-affinity/least-loaded policy (the replica will 400 an
        unknown adapter, which the client surfaces — loading is an
        operator action, not a routing side effect)."""
        with self._lock:
            ready = self._ready_endpoints()
            if not ready:
                self.counters["no_ready_replica"] += 1
                return None, "no_ready_replica"
            if adapter is not None:
                holders = [ep for ep in ready
                           if adapter in self.replicas[ep].adapters]
                if holders:
                    ep = min(holders,
                             key=lambda e: self.replicas[e].load_rank())
                    self.counters["routed_adapter"] += 1
                    return ep, "adapter"
            if self.affinity_blocks > 0 and tokens is not None:
                key, _ = prefix_chain_key(tokens, self.block_size,
                                          self.affinity_blocks)
                target = self.ring.pick(key, ready)
            else:
                target = None
            if target is None:
                ep = min(ready,
                         key=lambda e: self.replicas[e].load_rank())
                self.counters["routed_least_loaded"] += 1
                return ep, "least_loaded"
            if self._hot(self.replicas[target]) and len(ready) > 1:
                spill = min(ready,
                            key=lambda e: self.replicas[e].load_rank())
                if spill != target:
                    self.counters["routed_spill"] += 1
                    return spill, "spill"
            self.counters["routed_affinity"] += 1
            return target, "affinity"

    # -- fleet-level KV brokering (ISSUE 12) -------------------------------

    @staticmethod
    def _base_request_id(request_id: str) -> str:
        """The client-level id behind a per-row id: replicas key
        migrations on ``{client_id}/row{i}`` (serve.py's per-row
        submit ids), but the client's retry carries the bare
        ``{client_id}`` — record both so the retry routes to the
        adopter."""
        base, sep, tail = request_id.rpartition("/row")
        return base if sep and tail.isdigit() else request_id

    def migrate_target(self, request_id: Optional[str]
                       ) -> Optional[str]:
        if request_id is None:
            return None
        with self._lock:
            return self._migrations.get(request_id)

    def record_migration(self, request_id: str, endpoint: str) -> None:
        with self._lock:
            self._record_migration_locked(request_id, endpoint)
            if self._journal is not None:
                self._journal.append_migration(request_id, endpoint)
                self.counters["journal_appends"] += 1
                self._maybe_compact_locked()

    def _record_migration_locked(self, request_id: str,
                                 endpoint: str) -> None:
        self._migrations[request_id] = endpoint
        self._migrations.move_to_end(request_id)
        base = self._base_request_id(request_id)
        if base != request_id:
            # FIRST adopter wins the client-level id: a multi-row
            # request whose rows land on different adopters must
            # not have each row's record overwrite the base route
            # (the retry would then miss every earlier adopter's
            # handle and re-generate those rows while the adopted
            # lanes decode orphaned)
            self._migrations.setdefault(base, endpoint)
            self._migrations.move_to_end(base)
        while len(self._migrations) > self._migr_cap:
            self._migrations.popitem(last=False)

    def _maybe_compact_locked(self) -> None:
        """Compact the journal against the live (capped) windows once
        it outgrows them — called under the lock right after an
        append, so the rewrite races nothing."""
        live = len(self._results) + len(self._migrations)
        if self._journal.should_compact(live):
            self._journal.compact(self._results, self._result_replica,
                                  self._migrations)
            self.counters["journal_compactions"] += 1

    def migration_candidates(self, origin: str) -> List[str]:
        """Ready replicas able to adopt a lane, best first: fewest
        parked lanes (a backlog of parked work means no room to host
        more), then the usual least-loaded ordering.  The origin — the
        replica shedding the lane — is excluded."""
        origin = self._norm(origin) if origin else ""
        with self._lock:
            ready = [ep for ep in self._ready_endpoints()
                     if ep != origin]
            return sorted(ready, key=lambda e: (
                self.replicas[e].parked_lanes,
                self.replicas[e].load_rank()))

    def broker_migration(self, envelope: bytes, request_id: str,
                         origin: str) -> Tuple[int, Dict[str, Any]]:
        """Place one lane envelope on the best ready peer.  Returns
        ``(http_status, response_body)``.  A replayed id answers from
        the migration table without forwarding (the lane must never
        run on two replicas); an id mid-broker gets a retriable 503."""
        with self._lock:
            existing = self._migrations.get(request_id)
            if existing is not None:
                self.counters["migration_replays"] += 1
                return 200, {"target": existing, "deduped": True}
            if request_id in self._migr_inflight:
                return 503, {"error": "migration already in flight"}
            self._migr_inflight.add(request_id)
        try:
            for ep in self.migration_candidates(origin):
                try:
                    # the forward must resolve INSIDE the origin's
                    # broker budget (utils/fleetkv timeout ordering) —
                    # a slow-but-successful restore that outlives the
                    # origin's socket would resume the lane locally
                    # AND decode it on the adopter
                    from paddle_operator_tpu.utils.fleetkv import (
                        RESTORE_FORWARD_TIMEOUT_S,
                    )

                    code, _ = self._http_post(
                        ep, "/v1/kv/restore", envelope,
                        timeout=RESTORE_FORWARD_TIMEOUT_S)
                except ConnectionRefusedError:
                    # never reached the peer: safe to try the next
                    self.mark_unready(ep)
                    continue
                except (OSError, socket.timeout):
                    # AMBIGUOUS: the peer may have received (and
                    # adopted) the envelope before the socket died —
                    # forwarding to another candidate could place one
                    # lane on TWO replicas.  Stop here; the origin
                    # keeps the lane (completion-wait fallback), and
                    # a possibly-adopted orphan decays out of the
                    # adopter's bounded handle map unclaimed.
                    self.mark_unready(ep)
                    return 503, {"error": f"adopter {ep} unreachable "
                                          "mid-restore; lane kept at "
                                          "origin"}
                if code == 200:
                    self.record_migration(request_id, ep)
                    with self._lock:
                        self.counters["migrations_brokered"] += 1
                    return 200, {"target": ep}
                # 409/4xx: this peer refused (fingerprint mismatch,
                # adapter absent) — try the next one
            return 503, {"error": "no replica adopted the lane"}
        finally:
            with self._lock:
                self._migr_inflight.discard(request_id)

    # -- prefill pool forwarding (ISSUE 13) --------------------------------

    def prefill_candidates(self) -> List[str]:
        """Ready prefill pods, best first: shortest queue, then
        fastest recent service time — a pod already turning jobs
        around clears its queue soonest.  Prefill is side-effect-free,
        so the caller may walk the WHOLE list on failure (unlike lane
        migration, where an ambiguous hop must stop the walk)."""
        with self._lock:
            ready = [ep for ep, st in self.prefill.items() if st.ready]
            return sorted(ready, key=lambda e: (
                self.prefill[e].gauges.get("prefillQueueDepth", 0.0),
                self.prefill[e].gauges.get("prefillMsAvg", 0.0)))

    def forward_prefill(self, body: bytes
                        ) -> Tuple[int, bytes, Optional[str]]:
        """Place one prefill job on the best ready prefill pod.
        Returns ``(status, response_bytes, pod)``.  Connection
        failures and 503s (draining pod) walk to the next candidate —
        re-running a prefill is always safe; only a deterministic
        4xx/5xx (fingerprint mismatch, bad prompt) relays as-is.  The
        walk is the shared bounded-retry helper (ISSUE 20 satellite)
        with ``honor_retry_after=False``: a candidate walk fails over
        to the next pod immediately instead of waiting out a draining
        pod's Retry-After hint."""
        from paddle_operator_tpu.utils.fleetkv import http_post_retry

        def conn_fail(ep: str) -> None:
            st = self.prefill.get(ep)
            if st is not None:
                st.ready = False

        eps = self.prefill_candidates()
        if eps:
            code, raw, used = http_post_retry(
                eps, "/v1/prefill", body,
                content_type="application/json",
                timeout=self.upstream_timeout,
                max_attempts=len(eps),
                backoff_base_s=0.0, backoff_max_s=0.0,
                honor_retry_after=False,
                on_conn_error=conn_fail)
            if used is not None and code not in (0, 503):
                with self._lock:
                    self.counters["prefill_jobs_forwarded"] += 1
                return code, raw, used
        with self._lock:
            self.counters["no_ready_prefill"] += 1
        return 503, json.dumps(
            {"error": "no ready prefill pod"}).encode(), None

    def prefix_owner(self, tokens, origin: str) -> Optional[str]:
        """The replica whose radix cache most likely holds this
        prompt's prefix: its hashring affinity owner — the SAME
        placement rule that put the prefix there — excluding the
        asking replica."""
        origin = self._norm(origin) if origin else ""
        with self._lock:
            ready = [ep for ep in self._ready_endpoints()
                     if ep != origin]
            if not ready or self.affinity_blocks <= 0:
                return None
            key, _ = prefix_chain_key(tokens, self.block_size,
                                      self.affinity_blocks)
            return self.ring.pick(key, ready)

    # -- dedupe ------------------------------------------------------------

    def dedupe_begin(self, request_id: Optional[str]
                     ) -> Tuple[str, Optional[Tuple[int, bytes]]]:
        """Returns ``("replay", recorded)`` when the id already
        completed, ``("inflight", None)`` when the original is still
        being proxied (the retry should back off and re-ask), or
        ``("new", None)`` after marking the id in-flight."""
        if request_id is None:
            return "new", None
        with self._lock:
            rec = self._results.get(request_id)
            if rec is not None:
                self._results.move_to_end(request_id)
                self.counters["dedupe_replays"] += 1
                return "replay", rec
            if request_id in self._inflight:
                return "inflight", None
            self._inflight.add(request_id)
            return "new", None

    def dedupe_end(self, request_id: Optional[str], status: int,
                   body: Optional[bytes],
                   replica: Optional[str] = None) -> None:
        """Record a completed RESULT (200 ok / 504 deadline partial —
        both resolve the request); 503s and errors are not results, so
        a later retry runs for real.  ``replica`` (ISSUE 15
        satellite): the endpoint that served it, echoed on replay so a
        deduped client can still tell which pod produced its result."""
        if request_id is None:
            return
        with self._lock:
            self._inflight.discard(request_id)
            if body is not None and status in (200, 504):
                self._results[request_id] = (status, body)
                if replica:
                    self._result_replica[request_id] = replica
                while len(self._results) > self._dedupe_cap:
                    k, _ = self._results.popitem(last=False)
                    self._result_replica.pop(k, None)
                if self._journal is not None:
                    self._journal.append_result(request_id, status,
                                                body, replica or "")
                    self.counters["journal_appends"] += 1
                    self._maybe_compact_locked()

    def replay_replica(self, request_id: Optional[str]
                       ) -> Optional[str]:
        if request_id is None:
            return None
        with self._lock:
            return self._result_replica.get(request_id)

    # -- fleet status ------------------------------------------------------

    def ready(self) -> bool:
        # under the lock like choose()/statusz(): the scrape thread's
        # set_endpoints() deletes replica entries mid-scale, and an
        # unlocked iteration here would crash the /readyz handler at
        # exactly the moment kubelet and the admission gate poll it
        with self._lock:
            # _warmed (ISSUE 20): a restarted router with a
            # live-reloaded endpoints file answers ready only after
            # its first full scrape — never on an empty directory
            return (self._warmed and not self.draining
                    and bool(self._ready_endpoints()))

    def statusz(self) -> Dict[str, Any]:
        with self._lock:
            per = {ep: dict(st.gauges, ready=st.ready)
                   for ep, st in self.replicas.items()}
            # prefill blocks join the aggregate under their scraped
            # role marker so the fold stays role-aware; scraped
            # latency histograms (ISSUE 15) ride each block so the
            # fold derives the fleet ttftP95Ms the autoscaler reads
            fleet_in = {}
            for ep, st in self.replicas.items():
                if not st.gauges and not st.hists:
                    continue
                blk: Dict[str, Any] = dict(st.gauges)
                lh = st.latency_hist_block()
                if lh:
                    blk["latencyHist"] = lh
                fleet_in[ep] = blk
            fleet_in.update({ep: dict(st.gauges, role="prefill")
                             for ep, st in self.prefill.items()
                             if st.gauges})
            out = {
                "replicas": per,
                "fleet": aggregate_fleet_serving(fleet_in),
                "router": dict(self.counters,
                               readyReplicas=len(self._ready_endpoints()),
                               endpoints=len(self.replicas),
                               draining=self.draining),
            }
            if self.prefill:
                out["prefill"] = {
                    ep: dict(st.gauges, ready=st.ready)
                    for ep, st in self.prefill.items()}
                out["router"]["readyPrefill"] = sum(
                    1 for st in self.prefill.values() if st.ready)
            return out

    def metrics_text(self) -> str:
        """The fleet's own /metrics: router counters + per-replica
        readiness/load as labeled gauges."""
        with self._lock:
            lines = []
            for name, val in sorted(self.counters.items()):
                lines.append(f"tpujob_router_{name}_total {float(val)}")
            lines.append("tpujob_router_ready_replicas "
                         f"{float(len(self._ready_endpoints()))}")
            lines.append("tpujob_router_endpoints "
                         f"{float(len(self.replicas))}")
            lines.append("tpujob_router_draining "
                         f"{1.0 if self.draining else 0.0}")
            for ep, st in sorted(self.replicas.items()):
                lbl = f'{{replica="{ep}"}}'
                lines.append(f"tpujob_router_replica_ready{lbl} "
                             f"{1.0 if st.ready else 0.0}")
                lines.append(f"tpujob_router_replica_queue_depth{lbl} "
                             f"{st.queue_depth}")
                lines.append(
                    f"tpujob_router_replica_breaker_open{lbl} "
                    f"{1.0 if st.breaker_open_until > 0.0 else 0.0}")
            for ep, st in sorted(self.prefill.items()):
                lbl = f'{{replica="{ep}"}}'
                lines.append(f"tpujob_router_prefill_ready{lbl} "
                             f"{1.0 if st.ready else 0.0}")
                lines.append(
                    f"tpujob_router_prefill_queue_depth{lbl} "
                    f"{st.gauges.get('prefillQueueDepth', 0.0)}")
            # fleet-folded latency histograms (ISSUE 15): the scraped
            # per-replica families summed per bucket under the
            # tpujob_fleet_* names — what a fleet dashboard's
            # histogram_quantile should read, one scrape instead of N.
            # Rendered by THE shared renderer (observability.
            # render_histogram_lines) so the fleet and replica
            # expositions cannot drift format-wise.
            from paddle_operator_tpu.utils.observability import (
                render_histogram_lines,
            )

            lh = [b for st in self.replicas.values()
                  if (b := st.latency_hist_block())]
            folded = TRC.fold_latency_hists(lh) if lh else {}
            for fam, name in sorted(TRC.HIST_FAMILIES.items()):
                e = folded.get(fam)
                if not e:
                    continue
                lines.extend(render_histogram_lines(
                    name.replace("tpujob_serve_", "tpujob_fleet_"),
                    e))
            return "\n".join(lines) + "\n"


def stream_served_body(request_id: Optional[str]) -> bytes:
    """The deterministic "already-served" replay body recorded for a
    COMPLETED streamed request (ISSUE 20 satellite).  Streams are not
    replayable — the router never buffers their bytes — but before
    this marker they were not dedupe-recordable at all, so a client
    retry AFTER a stream completed re-executed the whole generation
    (double execution).  Now the completed stream records this marker
    and the retry gets a terminal JSON answer instead of a re-run; a
    client that still wants output must mint a new request_id."""
    return json.dumps({"done": True, "alreadyServed": True,
                       "stream": True,
                       "requestId": request_id}, sort_keys=True).encode()


class _RouterHandler(BaseHTTPRequestHandler):
    router: FleetRouter    # injected by make_router_server
    protocol_version = "HTTP/1.1"
    timeout = 120

    def log_message(self, *a):
        pass

    def _send(self, code: int, obj: Any, headers=None,
              raw: Optional[bytes] = None) -> None:
        body = raw if raw is not None else json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        r = self.router
        if self.path == "/healthz":
            self._send(200, {"ok": True})
        elif self.path == "/readyz":
            if r.ready():
                self._send(200, {"ready": True,
                                 "replicas": len(r.endpoints())})
            else:
                self._send(503, {"ready": False,
                                 "reason": ("draining" if r.draining
                                            else "no ready replica")},
                           headers={"Retry-After": r.retry_after_s})
        elif self.path == "/statusz":
            self._send(200, r.statusz())
        elif self.path.split("?", 1)[0] == "/debug/tracez":
            # stitched cross-pod timelines (ISSUE 15): newest-last
            # bounded LRU; ?trace_id= narrows to one; ?format=jsonl
            # (ISSUE 18) streams the machine-readable export — span
            # trees plus the fleet-folded histogram snapshot — that
            # router/replay.py consumes as a recorded workload
            query = self.path.partition("?")[2]
            tid = None
            fmt = None
            for part in query.split("&"):
                k, _, v = part.partition("=")
                if k == "trace_id" and v:
                    tid = v
                elif k == "format" and v:
                    fmt = v
            if fmt == "jsonl":
                lh = [b for st in r.replicas.values()
                      if (b := st.latency_hist_block())]
                folded = TRC.fold_latency_hists(lh) if lh else None
                tls = r.traces.timelines()
                if tid is not None:
                    tls = [t for t in tls if t.get("traceId") == tid]
                body = TRC.export_jsonl(tls, hists=folded).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/jsonl")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif tid is not None:
                tl = r.traces.get(tid)
                self._send(200 if tl else 404,
                           tl or {"error": f"no timeline {tid}"})
            else:
                self._send(200, {"timelines": r.traces.timelines()})
        elif self.path == "/metrics":
            body = r.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._send(404, {})

    # -- the proxy ---------------------------------------------------------

    def _kv_migrate(self, body: bytes) -> None:
        """POST /v1/kv/migrate — broker one lane envelope (ISSUE 12):
        peek the header for the request id, place the raw bytes on the
        best ready peer, record id -> adopter so the client's retry
        routes there."""
        from paddle_operator_tpu.utils.fleetkv import (
            EnvelopeError,
            peek_header,
        )

        r = self.router
        if r.draining:
            self._send(503, {"error": "router draining"},
                       headers={"Retry-After": r.retry_after_s})
            return
        try:
            header = peek_header(body)
            rid = (header.get("meta") or {}).get("requestId")
        except EnvelopeError as e:
            self._send(400, {"error": str(e)})
            return
        if not rid:
            self._send(400, {"error": "lane envelope carries no "
                                      "requestId"})
            return
        origin = self.headers.get("X-Migrate-Origin", "")
        code, resp = r.broker_migration(body, str(rid), origin)
        headers = ({"Retry-After": r.retry_after_s}
                   if code == 503 else None)
        self._send(code, resp, headers=headers)

    def _kv_prefix(self, body: bytes) -> None:
        """POST /v1/kv/prefix — forward a prefix-fetch ask to the
        prompt's hashring affinity owner (the replica the placement
        rule sent that prefix's traffic to) and relay its envelope.
        On an owner miss — no owner, unreachable, or a 204 — the
        durable store (ISSUE 17, ``ROUTER_KV_STORE``) is the fallback
        tier: probe it and relay a store-built prefix envelope."""
        r = self.router
        try:
            req = json.loads(body)
            tokens = [int(t) for t in req["tokens"]]
            ns = int(req.get("ns", 0))
        except (ValueError, TypeError, KeyError,
                json.JSONDecodeError) as e:
            self._send(400, {"error": f"bad tokens: {e}"})
            return
        owner = r.prefix_owner(tokens,
                               self.headers.get("X-Migrate-Origin", ""))
        code, raw = 204, b""
        if owner is not None:
            try:
                code, raw = r._http_post(owner, "/v1/kv/prefix", body,
                                         content_type="application/json")
            except (OSError, socket.timeout):
                r.mark_unready(owner)
                code, raw = 204, b""
            with r._lock:
                r.counters["prefix_forwards"] += 1
        if code == 200 and raw:
            self.send_response(200)
            self.send_header("Content-Type",
                             "application/octet-stream")
            self.send_header("Content-Length", str(len(raw)))
            self.send_header("X-Router-Replica", owner)
            self.end_headers()
            self.wfile.write(raw)
            return
        if r.kv_store is not None:
            try:
                store_raw = r.kv_store.fetch_prefix_envelope(
                    tokens, r.block_size, ns=ns)
            except Exception:
                store_raw = None    # a store consult never errors an ask
            if store_raw:
                with r._lock:
                    r.counters["store_prefix_serves"] += 1
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(store_raw)))
                self.send_header("X-Router-Store", "1")
                self.end_headers()
                self.wfile.write(store_raw)
                return
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _prefill_forward(self, body: bytes) -> None:
        """POST /v1/prefill — the prefill-pool half of cross-host
        disaggregation (ISSUE 13): relay one prefill job to the
        least-loaded ready prefill pod and stream its handoff envelope
        back.  The router never parses the envelope; it stays
        jax-free."""
        r = self.router
        if r.draining:
            self._send(503, {"error": "router draining"},
                       headers={"Retry-After": r.retry_after_s})
            return
        # the SIGTERM drain gates on this counter: a forward can hold
        # its upstream for up to upstream_timeout, and shutting the
        # server down mid-relay severs a live handoff (same contract
        # as the generate proxy)
        with r._lock:
            r.inflight_proxies += 1
        try:
            code, raw, ep = r.forward_prefill(body)
        finally:
            with r._lock:
                r.inflight_proxies -= 1
        self.send_response(code)
        self.send_header("Content-Type",
                         "application/octet-stream" if code == 200
                         else "application/json")
        self.send_header("Content-Length", str(len(raw)))
        if ep:
            self.send_header("X-Router-Prefill", ep)
        if code == 503:
            self.send_header("Retry-After", str(r.retry_after_s))
        self.end_headers()
        self.wfile.write(raw)

    def do_POST(self):
        r = self.router
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n) if n else b""
        if self.path == "/v1/prefill":
            return self._prefill_forward(body)
        if self.path == "/v1/kv/migrate":
            return self._kv_migrate(body)
        if self.path == "/v1/kv/prefix":
            return self._kv_prefix(body)
        if self.path != "/v1/generate":
            self._send(404, {})
            return
        retry_hdr = {"Retry-After": r.retry_after_s}
        if r.draining:
            self._send(503, {"error": "router draining"},
                       headers=retry_hdr)
            return
        try:
            req = json.loads(body)
        except json.JSONDecodeError as e:
            self._send(400, {"error": str(e)})
            return
        request_id = req.get("request_id")
        tokens = req.get("tokens") or None
        first_row = tokens[0] if (isinstance(tokens, list) and tokens
                                  and isinstance(tokens[0], list)) \
            else tokens
        # identity echo (ISSUE 15 satellite): EVERY reply names the
        # request — without it a client cannot correlate fleet logs.
        # Sanitized: the id is CLIENT input and send_header does no
        # CR/LF or charset validation (response splitting / a
        # mid-response UnicodeEncodeError on a non-latin-1 id)
        id_hdrs = ({"X-Request-Id": TRC.safe_header_value(request_id)}
                   if request_id is not None else {})
        # trace context (ISSUE 15): honor an inbound header; with
        # ROUTER_TRACE=1 mint one per generate.  One parentless root
        # span per trace lives in the store — every proxy ATTEMPT
        # (retries after a pod death included) parents under it, so a
        # retried request stitches into ONE tree, never two.
        ctx = TRC.parse_trace_header(
            self.headers.get(TRC.TRACE_HEADER))
        if ctx is None and r.trace_all:
            ctx = (TRC.new_id(), None)
        state, recorded = r.dedupe_begin(request_id)
        if state == "replay":
            code, raw = recorded
            hdrs = dict(id_hdrs, **{"X-Router-Dedupe": "replay"})
            rep = r.replay_replica(request_id)
            if rep:
                # the replica that SERVED the recorded result — the
                # adopter after a migration — even on a cache replay
                hdrs["X-Router-Replica"] = rep
            self._send(code, None, raw=raw, headers=hdrs)
            return
        if state == "inflight":
            # the original is still running on some replica; re-running
            # it elsewhere would double-generate.  Tell the retrying
            # client to come back — by then the original has either
            # completed (replayed above) or failed (re-routed fresh).
            self._send(503, {"error": "request in flight"},
                       headers=dict(retry_hdr, **id_hdrs))
            return
        status, result = 0, None
        self.served_replica: Optional[str] = None
        try:
            # fleet-level KV (ISSUE 12): a retry whose lane migrated
            # routes to the ADOPTER — it holds (or is still decoding)
            # the result under this id.  An adopter that has since
            # gone unready falls through to the normal policy (the
            # request re-generates fresh; the original never
            # delivered, so exactly-once delivery holds).
            mt = r.migrate_target(request_id)
            if mt is not None:
                st = r.replicas.get(mt)
                if (st is not None and st.ready
                        and not r._breaker_blocked(st,
                                                   time.monotonic())):
                    with r._lock:
                        r.counters["routed_migrated"] += 1
                    status, result = self._proxy(mt, "migrated", body,
                                                 req, trace=ctx,
                                                 id_hdrs=id_hdrs)
                    return
            try:
                ep, reason = r.choose(first_row,
                                      adapter=req.get("adapter"))
            except (ValueError, TypeError) as e:
                # malformed tokens (non-int elements): the replica
                # would 400 this — so must the router, or the client
                # burns its whole retry budget on a connection reset
                # for a permanently-bad request
                self._send(400, {"error": f"bad tokens: {e}"},
                           headers=id_hdrs)
                return
            if ep is None:
                self._send(503, {"error": "no ready replica"},
                           headers=dict(retry_hdr, **id_hdrs))
                return
            status, result = self._proxy(ep, reason, body, req,
                                         trace=ctx, id_hdrs=id_hdrs)
        finally:
            r.dedupe_end(request_id, status, result,
                         replica=self.served_replica)

    def _proxy(self, endpoint: str, reason: str, body: bytes,
               req: Dict[str, Any], trace=None,
               id_hdrs=None) -> Tuple[int, Optional[bytes]]:
        """Forward to ``endpoint``; returns (status, recordable body) —
        body None for streams/errors (not dedupe-recordable).
        ``trace`` (ISSUE 15): the ``(trace_id, parent)`` context — the
        forward carries ``X-Tpujob-Trace`` with a fresh attempt-span
        id, and the replica's span set (response metadata) stitches
        into the trace's timeline."""
        r = self.router
        host, _, port = endpoint.rpartition(":")
        conn = HTTPConnection(host, int(port),
                              timeout=r.upstream_timeout)
        attempt_id = root_id = None
        t_att_wall = time.time() * 1e3
        t_att0 = time.monotonic()
        if trace is not None:
            tid, parent = trace
            root_id = r.traces.root(tid, parent=parent,
                                    request_id=req.get("request_id")
                                    )["id"]
            attempt_id = TRC.new_id()

        def stitch(status: int, payload: Optional[bytes]) -> None:
            if trace is None:
                return
            spans = [TRC.make_span(
                "proxy", root_id, t_att_wall,
                (time.monotonic() - t_att0) * 1e3,
                span_id=attempt_id, pod="router", replica=endpoint,
                reason=reason, status=status)]
            if payload:
                try:
                    rows = json.loads(payload).get("trace") or []
                    for row in rows:
                        if isinstance(row, dict):
                            spans.extend(row.get("spans") or [])
                except (ValueError, AttributeError):
                    pass        # non-JSON / traceless payload
            r.traces.add(trace[0], spans)
        # under the lock: handler threads race, and the SIGTERM drain
        # gates on this counter reaching zero — a lost update either
        # burns the whole drain budget or truncates a live stream
        with r._lock:
            r.inflight_proxies += 1
        try:
            headers = {"Content-Type": "application/json"}
            hdr = self.headers.get("X-Request-Deadline")
            if hdr:
                headers["X-Request-Deadline"] = hdr
            # QoS class rides through untouched (ISSUE 10) — the body's
            # priority/adapter keys are already forwarded verbatim; the
            # header form must survive the hop too
            phdr = self.headers.get("X-Request-Priority")
            if phdr:
                headers["X-Request-Priority"] = phdr
            if trace is not None:
                # the replica's request root parents under THIS
                # attempt's span — the cross-pod tree by construction
                headers[TRC.TRACE_HEADER] = TRC.format_trace_header(
                    trace[0], attempt_id)
            # circuit breaker (ISSUE 20): if this replica's breaker is
            # half-open, this request is the probe
            r.breaker_admit(endpoint)
            conn.request("POST", "/v1/generate", body=body,
                         headers=headers)
            resp = conn.getresponse()
            self.served_replica = endpoint
            r.breaker_success(endpoint)
            passthrough = dict(id_hdrs or {},
                               **{"X-Router-Replica": endpoint,
                                  "X-Router-Reason": reason})
            ra = resp.getheader("Retry-After")
            if ra is not None:
                passthrough["Retry-After"] = ra
            if req.get("stream") and resp.status == 200:
                # streaming relay: re-chunk upstream NDJSON as it
                # arrives — read1 returns whatever is buffered, so the
                # first token reaches the client without waiting for
                # the full generation
                self.send_response(resp.status)
                self.send_header("Content-Type",
                                 resp.getheader("Content-Type",
                                                "application/x-ndjson"))
                self.send_header("Transfer-Encoding", "chunked")
                for k, v in passthrough.items():
                    self.send_header(k, v)
                self.end_headers()
                # streamed-dedupe fix (ISSUE 20 satellite): the relay
                # now distinguishes UPSTREAM death (stream incomplete —
                # the retry must re-run) from DOWNSTREAM death (the
                # replica finishes the generation regardless — keep
                # draining it, and record the completed stream so the
                # client's inevitable retry replays an already-served
                # marker instead of re-executing)
                upstream_done = False
                downstream_ok = True
                while True:
                    try:
                        chunk = resp.read1(65536)
                    except OSError:
                        break     # upstream died: not a result
                    if not chunk:
                        upstream_done = True
                        break
                    if downstream_ok:
                        try:
                            self.wfile.write(
                                f"{len(chunk):x}\r\n".encode() + chunk
                                + b"\r\n")
                            self.wfile.flush()
                        except OSError:
                            downstream_ok = False
                # the chunked response must still be TERMINATED, or a
                # waiting client hangs on an unfinished stream until
                # its socket timeout (it detects truncation by the
                # missing done event)
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass          # downstream client went away
                stitch(resp.status, None)  # attempt span only: the
                # relay never parses the stream (docs/observability.md)
                if upstream_done:
                    with r._lock:
                        r.counters["stream_results_recorded"] += 1
                    return resp.status, stream_served_body(
                        req.get("request_id"))
                return resp.status, None   # incomplete: retry re-runs
            payload = resp.read()
            stitch(resp.status,
                   payload if resp.status in (200, 504) else None)
            if resp.status == 503:
                # the replica shed us (drain or a live swap raced the
                # scrape tick): mark it down NOW — the client's
                # idempotent retry must re-route to a ready peer, not
                # bounce off the same quiescing replica until the next
                # poll — and bound the retry signal even when the
                # upstream forgot the header
                r.mark_unready(endpoint)
                passthrough.setdefault("Retry-After", r.retry_after_s)
            # the UPSTREAM result is in hand: from here on a failure is
            # the downstream client's socket, not the replica's — it
            # must neither mark the replica unready nor lose the
            # recordable payload (the dedupe window is exactly what
            # makes the client's retry after a response-path death
            # exactly-once)
            try:
                self._send(resp.status, None, raw=payload,
                           headers=passthrough)
            except OSError:
                pass              # client gone; result still recorded
            return resp.status, payload
        except (OSError, socket.timeout):
            # the replica vanished mid-proxy (drain finished, pod gone):
            # mark it down NOW and hand the client the same retryable
            # 503 a draining replica would have sent.  The failed
            # attempt still stitches into the timeline — a
            # retry-after-pod-death trace SHOWS the death.
            self.served_replica = None
            stitch(503, None)
            r.mark_unready(endpoint)
            with r._lock:
                r.counters["upstream_errors"] += 1
            try:
                self._send(503, {"error":
                                 f"replica {endpoint} unreachable"},
                           headers=dict(id_hdrs or {},
                                        **{"Retry-After":
                                           r.retry_after_s}))
            except OSError:
                pass
            return 503, None
        finally:
            with r._lock:
                r.inflight_proxies -= 1
            conn.close()


def make_router_server(host: str, port: int, router: FleetRouter
                       ) -> ThreadingHTTPServer:
    """HTTP shell around a FleetRouter; starts the scrape loop.  The
    returned server carries ``.router`` — close it when shutting the
    server down."""
    handler = type("RouterHandler", (_RouterHandler,),
                   {"router": router})
    srv = ThreadingHTTPServer((host, port), handler)
    srv.router = router
    router.start()
    return srv


def main() -> int:
    """Router entrypoint (the operator's router container runs this).

    Env surface:

    - ``ROUTER_PORT``            listen port (default 8800);
    - ``TPUJOB_SERVE_REPLICAS``  comma list of ``host:port`` replica
      endpoints (the rendezvous ConfigMap carries it);
    - ``ROUTER_ENDPOINTS_FILE``  path re-read every scrape tick — the
      operator mounts the ConfigMap as a volume here, so scale up/down
      reaches a RUNNING router (env vars cannot);
    - ``ROUTER_BLOCK_SIZE``      must match the replicas'
      SERVE_BLOCK_SIZE (affinity keys are block-granular; default 256);
    - ``ROUTER_AFFINITY_BLOCKS`` prefix blocks in the affinity key
      (0 disables affinity -> pure least-loaded; default 2);
    - ``ROUTER_HOT_QUEUE``       scraped queue depth at/over which the
      affinity target is "hot" and requests spill (default 4);
    - ``ROUTER_LOW_BLOCKS``      free-KV-block floor that also marks a
      replica hot (0 disables; default 0);
    - ``ROUTER_SCRAPE_S``        scrape interval seconds (default 1);
    - ``ROUTER_DRAIN_BUDGET_S``  SIGTERM: seconds to let in-flight
      proxies finish before exit (default 10);
    - ``ROUTER_STATE_DIR``       crash-safe journal directory
      (ISSUE 20): dedupe results + migration records are fsync'd
      there and replayed at boot, so a ``kill -9``'d router restarts
      into the same exactly-once window (unset = in-memory only, the
      pre-journal behavior);
    - ``ROUTER_BREAKER_THRESHOLD`` consecutive POST failures that trip
      a replica's circuit breaker (0 disables; default 3);
    - ``ROUTER_BREAKER_COOLDOWN_S`` seconds an open breaker holds
      before admitting one half-open probe request (default 2).

    SIGTERM drains like a replica does (docs/fault-tolerance.md): stop
    admitting (/readyz false, 503 + Retry-After), let in-flight proxies
    finish within the budget, exit EXIT_PREEMPTED so the reconciler
    counts the restart preempted-not-failed."""
    from paddle_operator_tpu.api.types import EXIT_PREEMPTED
    from paddle_operator_tpu.ft.preemption import PreemptionWatcher

    port = int(os.environ.get("ROUTER_PORT", "8800"))
    eps = [e for e in os.environ.get("TPUJOB_SERVE_REPLICAS",
                                     "").split(",") if e.strip()]
    # prefill pool (ISSUE 13): the second scraped directory —
    # TPUJOB_PREFILL_REPLICAS at boot, ROUTER_PREFILL_ENDPOINTS_FILE
    # re-read live (the same ConfigMap volume trick as the decode
    # list) so the SLO autoscaler's pool changes reach a RUNNING
    # router
    peps = [e for e in os.environ.get("TPUJOB_PREFILL_REPLICAS",
                                      "").split(",") if e.strip()]
    # durable prefix store (ISSUE 17): ROUTER_KV_STORE=dir:/path (a
    # shared volume) lets the router answer prefix asks no live
    # replica can — the fallback tier below the hashring owner.  The
    # router never validates fingerprints against a ring (it has
    # none); it relays entries stamped with their OWN fingerprint and
    # the asking replica refuses skew.
    kv_store = None
    store_url = os.environ.get("ROUTER_KV_STORE", "").strip()
    if store_url:
        from paddle_operator_tpu.infer.kvstore import (
            KVBlockStore,
            parse_store_url,
        )

        try:
            kv_store = KVBlockStore(parse_store_url(store_url),
                                    fingerprint=None)
        except (ValueError, OSError) as e:
            print(f"ROUTER_KV_STORE ignored: {e}", flush=True)
    router = FleetRouter(
        eps,
        block_size=int(os.environ.get("ROUTER_BLOCK_SIZE", "256")),
        affinity_blocks=int(os.environ.get("ROUTER_AFFINITY_BLOCKS",
                                           "2")),
        hot_queue_depth=int(os.environ.get("ROUTER_HOT_QUEUE", "4")),
        low_blocks=int(os.environ.get("ROUTER_LOW_BLOCKS", "0")),
        scrape_interval=float(os.environ.get("ROUTER_SCRAPE_S", "1")),
        endpoints_file=os.environ.get("ROUTER_ENDPOINTS_FILE"),
        prefill_endpoints=peps,
        prefill_endpoints_file=os.environ.get(
            "ROUTER_PREFILL_ENDPOINTS_FILE"),
        kv_store=kv_store,
        state_dir=os.environ.get("ROUTER_STATE_DIR") or None,
        breaker_threshold=int(os.environ.get(
            "ROUTER_BREAKER_THRESHOLD", "3")),
        breaker_cooldown_s=float(os.environ.get(
            "ROUTER_BREAKER_COOLDOWN_S", "2")))
    srv = make_router_server("0.0.0.0", port, router)
    print(f"fleet router on :{port} fronting "
          f"{len(router.endpoints())} replica(s) "
          f"(affinity_blocks={router.affinity_blocks}, "
          f"block_size={router.block_size})", flush=True)
    if router._journal is not None:
        print(f"router journal: {router._journal.path} "
              f"({router._journal.replayed} record(s) replayed)",
              flush=True)
    budget = float(os.environ.get("ROUTER_DRAIN_BUDGET_S", "10"))
    code: List[int] = [0]

    def drain() -> None:
        router.draining = True
        deadline = time.monotonic() + budget
        while router.inflight_proxies > 0 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        code[0] = EXIT_PREEMPTED
        srv.shutdown()

    watcher = PreemptionWatcher.install()
    watcher.on_drain(lambda reason: threading.Thread(
        target=drain, daemon=True).start())
    srv.serve_forever()
    router.close()
    return code[0]


if __name__ == "__main__":
    raise SystemExit(main())
