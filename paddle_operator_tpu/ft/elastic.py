"""Topology-elastic resume: a checkpoint saved under mesh A restores onto
mesh B.

What makes a reshape legal (docs/fault-tolerance.md): a checkpoint stores
*global* logical arrays — sharding is metadata, not layout — so any resume
whose TrainState tree (model config + optimizer) is identical can pick a
new mesh; orbax reshards each array to the template's ``NamedSharding`` at
restore time.  The production case is a **dp resize** inside the CRD's
elastic bounds (``worker.requests``/``limits``): dp shards only the batch
dim, so params/opt-state are untouched and the restore is a pure
re-placement.  fsdp/tp resizes work the same way provided every sharded
axis stays divisible by its new mesh factor (tree_shardings raises
otherwise).

Two things do NOT come for free and are handled here:

- **data continuity** — the batch at global step *k* must be the same
  batches regardless of world shape, or resume silently repeats/skips
  data.  :func:`resume_step_for` maps preserved progress (global step ×
  global batch = tokens) to the iterator fast-forward offset; the
  deterministic sources in train/data.py accept ``start_step``.
- **LR-schedule continuity** — when the global batch changes with the
  world size, a per-step schedule would replay or fast-forward the decay.
  :func:`scale_schedule` re-parameterizes it to token-equivalent position
  (plus the linear-scaling LR rule).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from paddle_operator_tpu.train.checkpoint import CheckpointManager, resume_or_init


def resume_step_for(tokens_consumed: int, global_batch: int) -> int:
    """Iterator fast-forward offset: the number of *new-batch* steps whose
    data has already been consumed.  Floor — a partially-consumed batch is
    re-read rather than skipped (repeating a fraction of one batch is
    harmless; skipping data is not)."""
    if global_batch <= 0:
        raise ValueError(f"global_batch must be positive, got {global_batch}")
    return tokens_consumed // global_batch


def scale_schedule(base_schedule: Callable, ref_global_batch: int,
                   global_batch: int, *, scale_lr: bool = True) -> Callable:
    """Wrap a per-step LR schedule defined for ``ref_global_batch`` so a
    run at ``global_batch`` traverses it at the same tokens-per-unit rate.

    ``schedule(count)`` is evaluated at ``count * global_batch /
    ref_global_batch`` — the token-equivalent position — so warmup and
    decay land on the same *data*, not the same step index, across elastic
    resizes.  ``scale_lr`` additionally applies the linear scaling rule
    (LR proportional to global batch), the standard compensation when dp
    shrink halves the batch.  With equal batches this is the identity."""
    if ref_global_batch <= 0 or global_batch <= 0:
        raise ValueError("global batch sizes must be positive")
    ratio = global_batch / ref_global_batch

    def sched(count):
        lr = base_schedule(count * ratio)
        return lr * ratio if scale_lr else lr

    return sched if ratio != 1.0 else base_schedule


def elastic_resume(ckpt: CheckpointManager, init_fn: Callable,
                   state_like: Any = None, *,
                   saved_global_batch: Optional[int] = None,
                   global_batch: Optional[int] = None,
                   goodput=None,
                   logger=None) -> Tuple[Any, bool, Dict[str, Any]]:
    """The restart entry for an elastic gang: restore the newest complete
    checkpoint into the *current* mesh's template (``init_fn``/
    ``state_like`` built against the new mesh — orbax reshards), falling
    back over corrupt steps like :func:`resume_or_init`.

    Returns ``(state, resumed, plan)`` where ``plan`` carries the data
    continuity numbers::

        step             restored global step (0 when fresh)
        tokens_consumed  step × saved_global_batch
        data_start_step  fast-forward offset for the NEW global batch

    ``goodput`` (a :class:`ft.goodput.GoodputTracker`) attributes the
    restore wallclock to the ``restore`` badput bucket."""
    import contextlib

    phase = (goodput.phase("restore") if goodput is not None
             else contextlib.nullcontext())
    with phase:
        state, resumed = resume_or_init(ckpt, init_fn, state_like,
                                        logger=logger)
    step = int(state.step) if resumed else 0
    sgb = saved_global_batch or global_batch or 0
    ngb = global_batch or saved_global_batch or 0
    tokens = step * sgb
    plan: Dict[str, Any] = {
        "step": step,
        "tokens_consumed": tokens,
        "data_start_step": (resume_step_for(tokens, ngb) if ngb else step),
    }
    if resumed and logger is not None:
        logger.info(
            f"elastic resume: step={step} tokens={tokens} "
            f"global_batch {sgb}->{ngb} "
            f"data_start_step={plan['data_start_step']}")
    return state, resumed, plan
