"""Goodput accounting: how much of the wallclock actually trained.

On preemptible capacity the interesting number is not step time but the
fraction of elapsed time that produced retained progress.  The breakdown
used here (the Google "goodput" formulation):

    goodput_ratio = productive_seconds / wallclock_seconds

with badput buckets:

    init       process start → first step (compile, mesh bring-up)
    restore    checkpoint restore on a restarted/rescaled gang
    lost_work  steps that ran before a kill but were after the last
               durable checkpoint — re-done after resume
    other      everything unattributed (data stalls between phases,
               teardown, eval)

The tracker is workload-side (ticked by train/trainer.fit); its snapshot
is published into ``TPUJob.status.goodput`` and surfaced two ways by the
control plane: per-job ``tpujob_goodput_*`` gauges on the manager's
``/metrics`` endpoint (controller/manager.py) and a ``Goodput`` job-status
condition (controller/reconciler.py).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, Optional


class GoodputTracker:
    """Wallclock ledger: productive step time vs attributed badput.

    Usage::

        tracker = GoodputTracker()
        with tracker.phase("init"):
            state = create_state(...)
        with tracker.phase("restore"):
            state, resumed = resume_or_init(...)
        fit(..., goodput=tracker)          # ticks per completed step
        tracker.record_lost_steps(lost, step_time)   # after a resume
    """

    def __init__(self, clock=time.perf_counter) -> None:
        self._clock = clock
        self._start = clock()
        self._productive = 0.0
        self._steps = 0
        self._badput: Dict[str, float] = {
            "init": 0.0, "restore": 0.0, "lost_work": 0.0,
        }
        self._last: Optional[float] = None
        self._lock = threading.Lock()

    # -- recording ---------------------------------------------------------

    @contextlib.contextmanager
    def phase(self, name: str):
        """Attribute the enclosed wallclock to badput bucket ``name``.
        Also disarms the step clock: a tick after the phase must not
        accrue the phase's interval (already badput) into productive
        time — that would double-count it and inflate the ratio."""
        t0 = self._clock()
        try:
            yield self
        finally:
            with self._lock:
                self._badput[name] = (self._badput.get(name, 0.0)
                                      + self._clock() - t0)
                self._last = None

    def tick(self) -> None:
        """Mark a completed training step.  The first tick only arms the
        clock (time before it belongs to init/restore); each later tick
        adds the inter-tick interval to productive time."""
        now = self._clock()
        with self._lock:
            if self._last is not None:
                self._productive += now - self._last
                self._steps += 1
            self._last = now

    def pause(self) -> None:
        """Disarm the step clock (e.g. around eval): the gap until the
        next tick is not counted productive."""
        with self._lock:
            self._last = None

    def record_lost_work(self, seconds: float) -> None:
        """Attribute re-done work: wallclock of the steps a predecessor
        process ran past its last durable checkpoint."""
        with self._lock:
            self._badput["lost_work"] += max(0.0, seconds)

    def record_lost_steps(self, steps: int, step_time: float) -> None:
        self.record_lost_work(steps * step_time)

    # -- reading -----------------------------------------------------------

    @property
    def wallclock_seconds(self) -> float:
        return self._clock() - self._start

    @property
    def productive_seconds(self) -> float:
        with self._lock:
            return self._productive

    @property
    def steps(self) -> int:
        with self._lock:
            return self._steps

    def badput(self) -> Dict[str, float]:
        """Badput breakdown, including the residual ``other`` bucket (so
        the buckets + productive always sum to wallclock)."""
        wall = self.wallclock_seconds
        with self._lock:
            out = dict(self._badput)
            attributed = self._productive + sum(out.values())
        out["other"] = max(0.0, wall - attributed)
        return out

    @property
    def goodput_ratio(self) -> float:
        wall = self.wallclock_seconds
        return self.productive_seconds / wall if wall > 0 else 0.0

    # -- export ------------------------------------------------------------

    def to_status(self) -> Dict[str, Any]:
        """The ``TPUJob.status.goodput`` block (camelCase, rounded — this
        rides the CRD through the apiserver)."""
        return {
            "ratio": round(self.goodput_ratio, 4),
            "productiveSeconds": round(self.productive_seconds, 3),
            "wallclockSeconds": round(self.wallclock_seconds, 3),
            "steps": self.steps,
            "badput": {k: round(v, 3) for k, v in self.badput().items()},
        }


def goodput_gauges(status_goodput: Dict[str, Any],
                   job: str) -> Dict[str, float]:
    """Prometheus gauge lines for one job's published goodput block —
    shared by the manager's metrics export so names can't drift from the
    docs.  ``job`` is ``namespace/name``."""
    lbl = f'{{job="{job}"}}'
    out = {
        f"tpujob_goodput_ratio{lbl}": float(status_goodput.get("ratio", 0.0)),
        f"tpujob_goodput_productive_seconds{lbl}":
            float(status_goodput.get("productiveSeconds", 0.0)),
        f"tpujob_goodput_wallclock_seconds{lbl}":
            float(status_goodput.get("wallclockSeconds", 0.0)),
    }
    for kind, secs in (status_goodput.get("badput") or {}).items():
        out[f'tpujob_badput_seconds{{job="{job}",kind="{kind}"}}'] = \
            float(secs)
    return out


def goodput_condition(status_goodput: Dict[str, Any], now: str) -> Dict[str, Any]:
    """The ``Goodput`` job-status condition derived from a published
    goodput block (set by the reconciler's status sync)."""
    ratio = float(status_goodput.get("ratio", 0.0))
    return {
        "type": "Goodput",
        "status": "True" if ratio >= 0.5 else "False",
        "reason": "Measured",
        "message": f"goodput {ratio:.2%} of wallclock",
        "lastTransitionTime": now,
    }
