"""Fault-tolerance runtime: the workload side of the recovery contract.

The controller half of fault tolerance has existed since the seed (gang
restart on pod failure, elastic rescale, checkpoint-path injection —
controller/reconciler.py); this package closes the loop from inside the
trainer:

- :mod:`ft.preemption` — SIGTERM / maintenance-notice drain: finish the
  in-flight step, force a durable checkpoint, exit ``EXIT_PREEMPTED`` so
  the controller restarts the gang without burning the failure budget.
- :mod:`ft.elastic` — topology-elastic resume: restore a checkpoint saved
  under mesh A onto mesh B (dp resize within the CRD's elastic bounds),
  with deterministic data fast-forward and LR-schedule continuity.
- :mod:`ft.goodput` — productive-time vs wallclock accounting with a
  badput breakdown (init / restore / lost work), exported through the
  manager's ``/metrics`` endpoint and a job-status condition.

Exports resolve lazily (module ``__getattr__``): ``ft.goodput`` and
``ft.preemption`` are stdlib-only, and the CONTROL PLANE imports
``ft.goodput`` on every metrics pass — an eager ``ft.elastic`` import
here would drag jax/orbax into the previously ML-stack-free controller
image (and its multi-second import into the reconcile loop).
"""

_EXPORTS = {
    "elastic_resume": "paddle_operator_tpu.ft.elastic",
    "resume_step_for": "paddle_operator_tpu.ft.elastic",
    "scale_schedule": "paddle_operator_tpu.ft.elastic",
    "GoodputTracker": "paddle_operator_tpu.ft.goodput",
    "EXIT_PREEMPTED": "paddle_operator_tpu.ft.preemption",
    "PreemptionWatcher": "paddle_operator_tpu.ft.preemption",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
