"""Preemption drain: turn SIGTERM / maintenance notices into a clean exit.

On spot/preemptible capacity the node gives the pod a grace window
(kubelet SIGTERM on pod deletion; GKE additionally surfaces upcoming TPU
maintenance through a notice file).  Without a handler the trainer dies
mid-step and the whole interval since the last periodic checkpoint is
lost work.  With this watcher the fit loop (train/trainer.py) finishes
the in-flight step, forces a durable checkpoint (``save(force=True)`` +
``wait()``) and the process exits ``EXIT_PREEMPTED`` — a code the
reconciler recognizes as *capacity loss, not program failure*, so the
gang restarts without consuming ``spec.maxRestarts``
(controller/builders.py get_job_phase, controller/reconciler.py).

The exit-code contract (docs/fault-tolerance.md):

    0               clean completion
    EXIT_PREEMPTED  drain completed; checkpoint durable; restart me
    anything else   program failure; consumes the restart budget

SERVING pods speak the same contract (infer/resilience.py
ServingDrain): their drain is "stop admissions (503 + Retry-After),
finish in-flight lanes within the budget, flush partials" instead of
"finish the step, force a checkpoint" — but the exit code, and the
reconciler's preempted-not-failed accounting, are identical.  A second
SIGTERM during a serving drain means the grace period is nearly up:
immediate best-effort exit, still EXIT_PREEMPTED.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Callable, Iterable, Optional

# Also defined (as the cross-layer contract constant) in api/types.py; the
# two must agree — tests/test_ft_preemption.py pins them together.
EXIT_PREEMPTED = 83

# Env var naming the maintenance-notice file a node agent touches ahead of
# TPU maintenance / spot reclaim (GKE: the maintenance-event metadata is
# mirrored to a file by the node watcher DaemonSet).
NOTICE_FILE_ENV = "TPUJOB_PREEMPTION_NOTICE_FILE"


class PreemptionWatcher:
    """One flag, two sources: unix signals and a maintenance-notice file.

    Usage in a trainer::

        watcher = PreemptionWatcher.install()
        state, history = fit(..., preemption=watcher)
        if watcher.draining:
            raise SystemExit(EXIT_PREEMPTED)

    ``install()`` must run on the main thread (CPython delivers signals
    there).  The watcher chains any previously-installed handler so it
    composes with frameworks that hook SIGTERM themselves.
    """

    def __init__(self) -> None:
        self._event = threading.Event()
        self.reason: str = ""
        self._prev: dict = {}
        self._poll_thread: Optional[threading.Thread] = None
        self._poll_stop = threading.Event()
        self._callbacks: list = []

    # -- state -------------------------------------------------------------

    @property
    def draining(self) -> bool:
        """True once a preemption notice arrived; the fit loop checks this
        at every step boundary."""
        return self._event.is_set()

    def trigger(self, reason: str = "manual") -> None:
        """Mark the process as draining (also the test hook)."""
        if not self._event.is_set():
            self.reason = reason
            self._event.set()
            for cb in self._callbacks:
                try:
                    cb(reason)
                except Exception:
                    pass

    def on_drain(self, cb: Callable[[str], None]) -> None:
        """Register a callback fired once when the drain starts (e.g. to
        stamp the goodput tracker or log)."""
        self._callbacks.append(cb)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    # -- installation ------------------------------------------------------

    @classmethod
    def install(cls, signals: Iterable[int] = (signal.SIGTERM,),
                notice_file: Optional[str] = None,
                poll_interval: float = 1.0) -> "PreemptionWatcher":
        """Install handlers and (when a notice file is configured) start
        the poll thread.  ``notice_file`` defaults to
        ``$TPUJOB_PREEMPTION_NOTICE_FILE``; no file, no poller."""
        w = cls()
        for sig in signals:
            prev = signal.signal(sig, w._make_handler(sig))
            w._prev[sig] = prev
        notice_file = notice_file or os.environ.get(NOTICE_FILE_ENV, "")
        if notice_file:
            w.watch_file(notice_file, poll_interval)
        return w

    def _make_handler(self, sig: int):
        def handler(signum, frame):
            self.trigger(f"signal:{signal.Signals(signum).name}")
            prev = self._prev.get(sig)
            if callable(prev):
                prev(signum, frame)
        return handler

    def watch_file(self, path: str, poll_interval: float = 1.0) -> None:
        """Poll ``path``; its appearance (or pre-existence) triggers the
        drain with the file's first line as the reason."""

        def read_line() -> str:
            try:
                with open(path) as f:
                    return f.readline().strip()
            except OSError:
                return ""

        def poll() -> None:
            while not self._poll_stop.is_set():
                if os.path.exists(path):
                    line = read_line()
                    if not line:
                        # create->write is not atomic: the poller can
                        # catch the file mid-write and read an empty
                        # first line — give the writer one poll tick
                        # before triggering with a bare reason
                        self._poll_stop.wait(poll_interval)
                        line = read_line()
                    self.trigger(f"notice-file:{line}" if line
                                 else "notice-file")
                    return
                self._poll_stop.wait(poll_interval)

        self._poll_thread = threading.Thread(target=poll, daemon=True,
                                             name="preemption-notice")
        self._poll_thread.start()

    def uninstall(self) -> None:
        """Restore previous signal handlers and stop the file poller
        (test hygiene; production processes exit instead)."""
        self._poll_stop.set()
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev if prev is not None
                              else signal.SIG_DFL)
            except (ValueError, TypeError):
                pass  # not on the main thread / handler not restorable
        self._prev.clear()


def inject_preemption(batches, at_step: int, watcher: PreemptionWatcher,
                      *, signal_self: bool = False):
    """Test/bench harness shared by bench.py, the dryrun gate, and the
    drain tests: pass ``batches`` through, raising the preemption flag
    just before yielding batch index ``at_step`` — so the step consuming
    that batch is the "in-flight" step the drain must finish.
    ``signal_self`` delivers a real SIGTERM to this process (the watcher
    must be installed) instead of flipping the flag directly."""
    for k, b in enumerate(batches):
        if k == at_step:
            if signal_self:
                os.kill(os.getpid(), signal.SIGTERM)
            else:
                watcher.trigger("injected")
        yield b


def drain_checkpoint(checkpoint, state, step: int) -> bool:
    """The durable-checkpoint half of the drain sequence: force a save at
    ``step`` and block until it is on storage.  Returns True when a
    checkpoint manager was active (the exit code should then be
    ``EXIT_PREEMPTED``; without one the work is simply lost)."""
    if checkpoint is None or not getattr(checkpoint, "enabled", False):
        return False
    if step not in checkpoint.all_steps() and \
            checkpoint.latest_step() != step:
        try:
            checkpoint.save(step, state, force=True)
        except ValueError:
            # the loop's interval save of this very step is still in
            # flight (orbax tracks scheduled steps before they commit);
            # the wait below makes it durable either way
            pass
    checkpoint.wait()
    return True
