"""Multi-tenant QoS for the serving ring: priority classes, preemptive
lane spill, and many-adapter (LoRA) serving (ISSUE 10).

Three pieces, consumed by ``infer/scheduler.py`` / ``infer/executor.py``:

- **Priority classes** (:class:`MultiClassQueue`, :class:`QoSConfig`):
  ``submit(priority=)`` / HTTP ``X-Request-Priority`` order admission in
  class-then-FIFO order (class 0 is the most urgent).  Each class gets
  its OWN bounded queue — a priority-1 flood saturating its bound must
  never backpressure a priority-0 request (that is the whole point).
  When a more urgent request would queue behind a full ring, the
  scheduler PREEMPTS the least urgent resident lane at its next chunk
  boundary: the lane spills to host byte-exactly
  (``RingExecutor.spill_lane`` — the ISSUE 8 primitive built for this),
  its blocks free for the preemptor, and the victim re-admits later
  through ``restore_lane`` with a BIT-IDENTICAL resumed stream.
  :class:`PreemptionBudget` bounds preemption density (and a per-request
  cap bounds how often one victim can be bounced) so priority inversion
  fixes cannot degenerate into spill thrash.

- **Many-adapter serving** (:class:`AdapterRegistry`): LoRA-style
  low-rank deltas (S-LoRA lineage: many fine-tunes batched off ONE base
  param set).  Adapters live in fixed-capacity stacked device arrays
  ``[L, capacity + 1, ...]`` (slot 0 is the all-zero base — a lane with
  adapter id 0 computes byte-identically to the adapterless ring, since
  ``x @ 0 @ 0`` is an exact zero), so load/evict never changes compiled
  shapes.  The decode step gathers each lane's ``(A, B)`` pair by its
  per-lane adapter id and fuses the delta matmul into the same compiled
  program — mixed-adapter batches run in ONE dispatch
  (:func:`lora_qkv` is the shared math, applied at every q/k/v
  projection site in decode/executor/paged/speculative).

- **Cache correctness across tenants**: an adapter changes wk/wv, so
  its KV is NOT the base model's — the paged radix cache namespaces
  chain keys by the adapter's load generation
  (:meth:`AdapterRegistry.ns_of` -> ``PagedCacheManager.admit(ns=)``),
  so prefix reuse happens within an adapter and never across, and an
  evict+reload at the same slot can never hit the dead adapter's
  blocks.

Spec decode: the draft stays base-only by design, so a speculative ring
refuses per-request adapters cleanly (``submit(adapter=)`` raises) —
priorities and preemption still fully apply (spill/restore captures the
draft lane too).
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import queue as _queue

import numpy as np

from paddle_operator_tpu.controller.policy import (
    DEFAULT_POLICY as _POLICY,
    PolicyConfig,
)

MAX_PRIORITIES = 8

# adapter names become Prometheus label values and routing keys — keep
# them to a charset that needs no escaping anywhere downstream
_ADAPTER_NAME_RE = re.compile(r"^[A-Za-z0-9_.\-]{1,64}$")


class AdapterInUse(ValueError):
    """Raised by :meth:`AdapterRegistry.evict`/:meth:`load` when the
    target adapter is still serving resident/parked/queued lanes — a
    typed signal so the HTTP surface can 409 exactly (substring
    matching on messages misclassifies)."""


@dataclass
class QoSConfig:
    """Knobs for the multi-tenant scheduler (env surface in
    infer/serve.py: ``SERVE_PRIORITIES`` / ``SERVE_PREEMPT*``).

    - ``priorities``: number of classes (class 0 most urgent).  1 turns
      the whole subsystem into the single-FIFO ring.
    - ``default_priority``: class for unannotated requests; ``None``
      resolves to the LEAST urgent class — priorities are opt-in
      boosts, so legacy traffic keeps today's behavior exactly.
    - ``preempt``: allow lane spill for waiting more-urgent work
      (paged rings only — the spill rides the block pool).
    - ``max_preempts_per_request``: one victim is never bounced more
      than this many times (starvation guard).
    - ``preempt_budget`` / ``preempt_window_s``: at most ``budget``
      preemptions per rolling window (anti-thrash: a pathological
      priority mix degrades to FIFO, never to spill churn).

    Defaults come from the shared policy surface
    (controller/policy.py, ISSUE 18) — the replay simulator sweeps
    these budgets as ``PolicyConfig`` fields, so the numbers a sweep
    scores ARE the numbers this config defaults to.
    """

    priorities: int = _POLICY.priorities
    default_priority: Optional[int] = None
    preempt: bool = True
    max_preempts_per_request: int = _POLICY.max_preempts_per_request
    preempt_budget: int = _POLICY.preempt_budget
    preempt_window_s: float = _POLICY.preempt_window_s

    def __post_init__(self) -> None:
        if not 1 <= self.priorities <= MAX_PRIORITIES:
            raise ValueError(f"priorities must be in [1, {MAX_PRIORITIES}]"
                             f" (got {self.priorities})")
        if self.default_priority is None:
            self.default_priority = self.priorities - 1
        if not 0 <= self.default_priority < self.priorities:
            raise ValueError(
                f"default_priority {self.default_priority} outside "
                f"[0, {self.priorities})")

    @classmethod
    def from_policy(cls, policy: PolicyConfig,
                    **overrides: Any) -> "QoSConfig":
        """Bind the QoS budgets a :class:`PolicyConfig` names — the
        constructor the scheduler's default path and the replay
        simulator share, so a swept sweep point configures the REAL
        admission machinery, not a parallel copy of its knobs."""
        kw: Dict[str, Any] = dict(
            priorities=policy.priorities,
            max_preempts_per_request=policy.max_preempts_per_request,
            preempt_budget=policy.preempt_budget,
            preempt_window_s=policy.preempt_window_s,
        )
        kw.update(overrides)
        return cls(**kw)

    @classmethod
    def from_env(cls) -> "QoSConfig":
        import os

        return cls(
            priorities=int(os.environ.get(
                "SERVE_PRIORITIES", str(_POLICY.priorities))),
            preempt=os.environ.get("SERVE_PREEMPT", "1") == "1",
            max_preempts_per_request=int(os.environ.get(
                "SERVE_PREEMPT_MAX_PER_REQ",
                str(_POLICY.max_preempts_per_request))),
            preempt_budget=int(os.environ.get(
                "SERVE_PREEMPT_BUDGET", str(_POLICY.preempt_budget))),
            preempt_window_s=float(os.environ.get(
                "SERVE_PREEMPT_WINDOW_S",
                str(_POLICY.preempt_window_s))),
        )


class MultiClassQueue:
    """Thread-safe per-class bounded FIFO with class-order pops.

    The API mirrors the slice of ``queue.Queue`` the scheduler used
    (``put_nowait``/``get_nowait``/``qsize``/``empty``/``full``) with a
    class argument where it matters.  The bound is PER CLASS: a flood
    in one class sheds ITS OWN overflow (QueueFull upstream) while the
    other classes keep their full admission budget — shared-bound
    backpressure would let a batch tenant starve the express class at
    the front door, before priority scheduling could even look at it.
    ``maxsize`` 0 = unbounded, like queue.Queue."""

    def __init__(self, n_classes: int, maxsize: int = 0) -> None:
        if n_classes < 1:
            raise ValueError("n_classes must be >= 1")
        self.n_classes = n_classes
        self.maxsize = int(maxsize)
        self._qs: List[deque] = [deque() for _ in range(n_classes)]
        self._lock = threading.Lock()
        # wakes blocked put(timeout=) callers the moment ANY class
        # drains — busy-polling would charge each blocked submitter up
        # to a full tick of avoidable latency per freed slot
        self._not_full = threading.Condition(self._lock)

    def _check_class(self, prio: int) -> int:
        prio = int(prio)
        if not 0 <= prio < self.n_classes:
            raise ValueError(f"priority {prio} outside "
                             f"[0, {self.n_classes})")
        return prio

    def put_nowait(self, item: Any, prio: int) -> None:
        prio = self._check_class(prio)
        with self._lock:
            if self.maxsize and len(self._qs[prio]) >= self.maxsize:
                raise _queue.Full
            self._qs[prio].append(item)

    def put(self, item: Any, prio: int,
            timeout: Optional[float] = None) -> None:
        """Blocking put: wait up to ``timeout`` for class ``prio`` to
        have room (condition-based — wakes the instant a slot frees,
        like queue.Queue), then raise queue.Full.  The scheduler's
        submit keeps its short ticks so close()/drain() can interrupt
        a blocked submitter between waits."""
        prio = self._check_class(prio)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._not_full:
            while self.maxsize and len(self._qs[prio]) >= self.maxsize:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise _queue.Full
                self._not_full.wait(remaining)
            self._qs[prio].append(item)

    def get_nowait(self) -> Any:
        """Pop the oldest item of the MOST urgent non-empty class."""
        with self._lock:
            for q in self._qs:
                if q:
                    item = q.popleft()
                    self._not_full.notify_all()
                    return item
        raise _queue.Empty

    def peek_class(self) -> Optional[int]:
        """Most urgent non-empty class (None when empty)."""
        with self._lock:
            for c, q in enumerate(self._qs):
                if q:
                    return c
        return None

    def full(self, prio: int) -> bool:
        prio = self._check_class(prio)
        if not self.maxsize:
            return False
        with self._lock:
            return len(self._qs[prio]) >= self.maxsize

    def qsize(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._qs)

    def qsize_by_class(self) -> List[int]:
        with self._lock:
            return [len(q) for q in self._qs]

    def empty(self) -> bool:
        return self.qsize() == 0

    def items(self) -> List[Any]:
        """Snapshot of every queued item (all classes) — e.g. the
        adapter-evict guard must see requests that resolved their
        adapter slot at submit but have not been admitted yet."""
        with self._lock:
            return [item for q in self._qs for item in q]


class PreemptionBudget:
    """Rolling-window preemption counter (the anti-thrash budget): at
    most ``budget`` spends per ``window_s``.  Deliberately simple —
    preemption is a rare corrective action, and when the mix is so
    adversarial that the budget pins, degrading to in-order admission
    is the safe behavior (the spill/restore cycle itself costs a block
    upload per bounce)."""

    def __init__(self, budget: int, window_s: float,
                 clock=time.monotonic) -> None:
        self.budget = int(budget)
        self.window_s = float(window_s)
        self._clock = clock
        self._spends: deque = deque()

    def _trim(self) -> None:
        now = self._clock()
        while self._spends and now - self._spends[0] >= self.window_s:
            self._spends.popleft()

    def ok(self) -> bool:
        self._trim()
        return len(self._spends) < self.budget

    def spend(self) -> None:
        self._trim()
        self._spends.append(self._clock())


# ---------------------------------------------------------------------------
# Many-adapter (LoRA) serving
# ---------------------------------------------------------------------------

# projections the low-rank deltas target: the attention inputs (classic
# LoRA).  wo is deliberately NOT in the set: the TP-sharded pallas path
# applies wo inside its shard_map region where the pre-projection
# activation is not exposed, and q/k/v deltas apply identically on
# every attention backend.
LORA_PROJS = ("wq", "wk", "wv")


def _proj_dims(cfg) -> Dict[str, Tuple[int, int]]:
    return {
        "wq": (cfg.dim, cfg.n_heads * cfg.head_dim),
        "wk": (cfg.dim, cfg.n_kv_heads * cfg.head_dim),
        "wv": (cfg.dim, cfg.n_kv_heads * cfg.head_dim),
    }


def stable_name_seed(name: str) -> int:
    """Deterministic cross-process seed for a bare adapter name:
    ``hash(str)`` is PYTHONHASHSEED-salted (the radixkey/hashring trap
    all over again), so two fleet replicas booting ``SERVE_ADAPTERS=x``
    would synthesize DIFFERENT smoke adapters and the router would
    treat them as interchangeable holders.  A digest is stable
    everywhere."""
    import hashlib

    return int.from_bytes(
        hashlib.blake2b(name.encode(), digest_size=4).digest(),
        "little") & 0x7FFFFFFF


def make_random_adapter(cfg, rank: int, seed: int,
                        scale: float = 0.5) -> Dict[str, Any]:
    """Synthesize a deterministic random LoRA delta (smoke mode — the
    serving analogue of serve.py's fresh-init draft): per-projection
    ``A [L, dim, r]`` / ``B [L, r, out]`` f32 numpy arrays.  ``scale``
    is large enough that distinct adapters produce distinct token
    streams on a tiny model, which is what the parity tests need."""
    rng = np.random.default_rng(seed)
    out = {}
    for proj, (din, dout) in _proj_dims(cfg).items():
        a = rng.standard_normal((cfg.n_layers, din, rank)).astype(
            np.float32) * (scale / np.sqrt(din))
        b = rng.standard_normal((cfg.n_layers, rank, dout)).astype(
            np.float32) * (scale / np.sqrt(rank))
        out[proj] = {"a": a, "b": b}
    return out


def load_adapter_file(cfg, path: str, rank: int) -> Dict[str, Any]:
    """Load a LoRA delta from an ``.npz`` with keys ``{proj}_a``
    [L, dim, r] / ``{proj}_b`` [L, r, out] per projection in
    :data:`LORA_PROJS`.  A file rank SMALLER than the registry rank
    zero-pads (exact — padded rank columns contribute 0); larger
    raises."""
    import numpy as _np

    data = _np.load(path)
    dims = _proj_dims(cfg)
    out = {}
    for proj, (din, dout) in dims.items():
        a = _np.asarray(data[f"{proj}_a"], _np.float32)
        b = _np.asarray(data[f"{proj}_b"], _np.float32)
        if a.shape[0] != cfg.n_layers or a.shape[1] != din \
                or b.shape[2] != dout or a.shape[2] != b.shape[1]:
            raise ValueError(
                f"{path}: {proj} shapes {a.shape}/{b.shape} do not fit "
                f"[L={cfg.n_layers}, {din}, r]/[L, r, {dout}]")
        r = a.shape[2]
        if r > rank:
            raise ValueError(f"{path}: {proj} rank {r} exceeds the "
                             f"registry rank {rank}")
        if r < rank:
            a = _np.pad(a, ((0, 0), (0, 0), (0, rank - r)))
            b = _np.pad(b, ((0, 0), (0, rank - r), (0, 0)))
        out[proj] = {"a": a, "b": b}
    return out


class AdapterRegistry:
    """Fixed-capacity pool of LoRA adapters served off one base model.

    Device layout: per projection, stacked ``a [L, capacity+1, dim, r]``
    and ``b [L, capacity+1, r, out]`` f32 arrays whose index 0 is the
    all-zero BASE adapter.  Shapes are static, so load/evict (an
    ``.at[:, idx].set``) never invalidates a compiled program; the
    arrays are passed to every dispatch as traced operands, so updates
    reach the ring without recompiles.

    ``ns_of(idx)`` is the radix-cache namespace: a fresh token minted
    at every load, so a prefix cached under one adapter can never be
    hit by a DIFFERENT adapter later loaded into the same slot (the KV
    bytes differ — wk/wv carry the delta)."""

    def __init__(self, cfg, capacity: int = 8, rank: int = 8) -> None:
        import jax.numpy as jnp

        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if rank < 1:
            raise ValueError("rank must be >= 1")
        self.cfg = cfg
        self.capacity = int(capacity)
        self.rank = int(rank)
        self._lock = threading.Lock()
        self._by_name: Dict[str, int] = {}
        self._by_idx: Dict[int, str] = {}
        self._ns: Dict[int, int] = {}           # idx -> load generation
        self._gen = 0
        self._dev: Dict[str, Dict[str, Any]] = {}
        for proj, (din, dout) in _proj_dims(cfg).items():
            self._dev[proj] = {
                "a": jnp.zeros((cfg.n_layers, self.capacity + 1, din,
                                self.rank), jnp.float32),
                "b": jnp.zeros((cfg.n_layers, self.capacity + 1,
                                self.rank, dout), jnp.float32),
            }

    def __len__(self) -> int:
        return len(self._by_name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._by_name)

    def resolve(self, name: str) -> int:
        with self._lock:
            idx = self._by_name.get(name)
        if idx is None:
            raise ValueError(f"unknown adapter {name!r} (loaded: "
                             f"{sorted(self._by_name) or 'none'})")
        return idx

    def resolve_ns(self, name: str) -> Tuple[int, int]:
        """Atomically resolve ``name`` to ``(slot, namespace)`` under
        ONE lock acquisition — a concurrent evict between a resolve()
        and an ns_of() would otherwise surface as a raw KeyError
        instead of the ValueError every other adapter failure maps
        to."""
        with self._lock:
            idx = self._by_name.get(name)
            if idx is None:
                raise ValueError(
                    f"unknown adapter {name!r} (loaded: "
                    f"{sorted(self._by_name) or 'none'})")
            return idx, self._ns[idx]

    def ns_of(self, idx: int) -> int:
        """Radix-cache namespace token for adapter slot ``idx`` (0 for
        the base model — namespace 0 IS today's unsalted chain, so
        adapterless serving keys byte-identically)."""
        if idx == 0:
            return 0
        with self._lock:
            return self._ns[idx]

    def arrays(self) -> Dict[str, Dict[str, Any]]:
        """The stacked device arrays, passed as a traced operand pytree
        to every adapter-aware compiled program."""
        return self._dev

    def load(self, name: str, deltas: Optional[Dict[str, Any]] = None,
             *, seed: Optional[int] = None, in_use=frozenset()) -> int:
        """Install (or replace) adapter ``name``; returns its slot
        index.  ``deltas``: :func:`load_adapter_file`-shaped dict; with
        ``deltas=None`` a deterministic random adapter is synthesized
        from ``seed`` (smoke mode).  Raises when the pool is full —
        evict first; capacity is the compiled-shape contract."""
        import jax.numpy as jnp

        if not _ADAPTER_NAME_RE.match(name or ""):
            raise ValueError(
                f"adapter name {name!r} must match [A-Za-z0-9_.-]{{1,64}}"
                " (it becomes a Prometheus label value and routing key)")
        if deltas is None:
            deltas = make_random_adapter(
                self.cfg, self.rank, seed if seed is not None
                else stable_name_seed(name))
        with self._lock:
            idx = self._by_name.get(name)
            if idx is not None and idx in in_use:
                # REPLACING a live adapter would mix old-delta KV with
                # new-delta decode math mid-stream for its lanes — the
                # same hazard evict guards against
                raise AdapterInUse(
                    f"adapter {name!r} is serving resident lanes; drain "
                    "them before replacing it")
            if idx is None:
                used = set(self._by_idx)
                idx = next((i for i in range(1, self.capacity + 1)
                            if i not in used), None)
                if idx is None:
                    raise ValueError(
                        f"adapter pool full ({self.capacity}); evict one "
                        "before loading another")
            # validate EVERY projection before the first device write:
            # a replace that raises mid-loop would leave a live adapter
            # half-overwritten — new wq with old wk/wv, a silent
            # corrupted mixture no oracle matches
            staged = {}
            for proj in LORA_PROJS:
                a = jnp.asarray(deltas[proj]["a"], jnp.float32)
                b = jnp.asarray(deltas[proj]["b"], jnp.float32)
                want_a = self._dev[proj]["a"].shape[2:]
                want_b = self._dev[proj]["b"].shape[2:]
                if a.shape[2] != self.rank:
                    raise ValueError(
                        f"adapter {name!r} rank {a.shape[2]} != registry "
                        f"rank {self.rank}")
                if (a.shape[0], a.shape[1:]) != (self.cfg.n_layers,
                                                 want_a) \
                        or (b.shape[0], b.shape[1:]) != (
                            self.cfg.n_layers, want_b):
                    raise ValueError(
                        f"adapter {name!r} {proj} shapes {a.shape}/"
                        f"{b.shape} do not fit [L, *{want_a}]/"
                        f"[L, *{want_b}]")
                staged[proj] = (a, b)
            for proj, (a, b) in staged.items():
                self._dev[proj]["a"] = \
                    self._dev[proj]["a"].at[:, idx].set(a)
                self._dev[proj]["b"] = \
                    self._dev[proj]["b"].at[:, idx].set(b)
            self._by_name[name] = idx
            self._by_idx[idx] = name
            self._gen += 1
            self._ns[idx] = self._gen
            return idx

    def evict(self, name: str, in_use=frozenset()) -> None:
        """Remove adapter ``name`` (its slot zeroes and becomes
        loadable).  ``in_use``: adapter idxs with resident/parked lanes
        — evicting one of those would serve garbage deltas to a live
        request, so it refuses."""
        import jax.numpy as jnp

        with self._lock:
            idx = self._by_name.get(name)
            if idx is None:
                raise ValueError(f"unknown adapter {name!r}")
            if idx in in_use:
                raise AdapterInUse(
                    f"adapter {name!r} is serving resident lanes; drain "
                    "them before evicting")
            for proj in LORA_PROJS:
                self._dev[proj]["a"] = \
                    self._dev[proj]["a"].at[:, idx].set(0.0)
                self._dev[proj]["b"] = \
                    self._dev[proj]["b"].at[:, idx].set(0.0)
            del self._by_name[name]
            del self._by_idx[idx]
            self._ns.pop(idx, None)

    @classmethod
    def from_env(cls, cfg) -> Optional["AdapterRegistry"]:
        """Build from ``SERVE_ADAPTERS`` (comma list of ``name``,
        ``name:path.npz`` or ``name:seed:<int>`` entries;
        ``SERVE_ADAPTER_RANK``/``SERVE_MAX_ADAPTERS`` size the pool).
        Unset/empty -> None: the ring stays byte-identical to the
        adapterless build."""
        import os

        raw = os.environ.get("SERVE_ADAPTERS", "").strip()
        if not raw:
            return None
        rank = int(os.environ.get("SERVE_ADAPTER_RANK", "8"))
        cap = int(os.environ.get("SERVE_MAX_ADAPTERS", "8"))
        reg = cls(cfg, capacity=cap, rank=rank)
        for entry in raw.split(","):
            entry = entry.strip()
            if not entry:
                continue
            name, _, src = entry.partition(":")
            if not src:
                reg.load(name)
            elif src.startswith("seed:"):
                reg.load(name, seed=int(src[len("seed:"):]))
            else:
                reg.load(name, load_adapter_file(cfg, src, rank))
        return reg


def lora_qkv(h, adp_l, aid, q, k, v, dtype):
    """THE shared adapter-delta rule, applied at every q/k/v projection
    site (decode._qkv, executor._qkv_ring, speculative._layer_multi*,
    and through them every admission insert and the resident step), so
    prefill KV and decode KV can never be computed under different
    adapter math.

    ``h`` [B, T, D] is the post-norm activation the base projections
    consumed; ``adp_l`` is ONE layer's stacked arrays (the [L, ...]
    stacks ride the layer scan as xs and arrive here layer-sliced);
    ``aid`` [B] int32 gathers each lane's (A, B) pair — the batched
    gather + adapter matmul that lets a MIXED-adapter batch run in one
    compiled program.  f32 compute, cast to the ring dtype at the add;
    adapter slot 0 is all-zero, so an aid-0 lane's delta is an exact
    zero and its stream is bit-identical to the adapterless ring."""
    import jax.numpy as jnp

    hf = h.astype(jnp.float32)
    out = []
    for proj, base in zip(LORA_PROJS, (q, k, v)):
        a = jnp.take(adp_l[proj]["a"], aid, axis=0)     # [B, D, r]
        b = jnp.take(adp_l[proj]["b"], aid, axis=0)     # [B, r, O]
        t = jnp.einsum("btd,bdr->btr", hf, a)
        delta = jnp.einsum("btr,bro->bto", t, b)
        out.append(base + delta.astype(dtype))
    return tuple(out)
