"""Weight-only int8 (int4 stretch) quantization for decoding.

Decode is HBM-bandwidth-bound: every step streams every weight.  Storing
the matmul kernels as int8 with per-output-channel f32 scales halves the
bytes streamed (activations and accumulation stay in the compute dtype —
"weight-only" quantization, the standard serving recipe).  Norm scales
and the embedding table stay full precision (tiny / gather-shaped).

``quantize_params`` maps the trained param tree to the same tree shape
with each targeted ``kernel`` leaf replaced by ``{"q": int8, "s": f32}``;
infer/decode.py's matmul helper consumes either form, so all decode entry
points (prefill / decode_step / generate / serve) work unchanged on
quantized params.  Accuracy is config-dependent; tests bound the logit
error on the tiny model.

Because the codes+scales live INSIDE the params pytree — which is already
a trailing operand of every compiled dispatch (step fns are traced over
``params``, the same way LoRA deltas ride ``*lora_args``) — a serving
process without quantization traces programs byte-identical to one built
before this module existed.  There is no quant flag threaded through the
executors: the leaf type IS the dispatch.

**Quantize-at-load, not a new checkpoint format.**  Serving quantizes the
bf16/f32 checkpoint after restore (``serve.py`` / ``prefill_serve.py``
under ``SERVE_WEIGHT_QUANT`` / ``SERVE_DRAFT_QUANT``).  Rounding is
round-half-even (``jnp.round`` is banker's rounding), which makes
quantize→dequant→quantize bit-stable: re-quantizing the dequantized tree
reproduces the codes and scales exactly, so a process restarted from a
dequantized snapshot serves identical logits.

**Skip list.**  The serving path (``skip=SERVING_SKIP``) keeps
embeddings (gather-shaped — int8 buys nothing on a one-row gather),
``lm_head`` (the logit matmul sets the sampling distribution; int8 error
there moves tokens directly instead of being absorbed by later layers),
and norm scales (tiny) in bf16.  The legacy no-kwargs call keeps the
original target set (lm_head included) for bench comparability.

**What bounds the speedup** (measured, one v5e chip via axon, jax 0.9,
dim-2048/L8/ffn-8192 model in bf16 serving dtype, greedy decode,
steady-state ms/token via bench.py's two-length differencing — relay RTT
and prefill cancel; e2e tok/s ratios are smaller because RTT is common):

    batch  8: int8 ~1.4-1.5x over bf16   batch 32: ~1.1x   batch 64: ~1.1x

not the ~2x the byte count suggests, because the int8→bf16 dequant feeding
the MXU caps the weight stream at ~220 GB/s of int8 bytes while the plain
bf16 stream runs ~340-400 GB/s (isolated-dot measurements) — past batch 8
the dot is dequant/MXU-bound, not HBM-bound.  Alternatives measured and
rejected on the same hardware:

- a pallas dequant-in-register kernel (int8 tiles HBM→VMEM, convert on
  the way into the MXU): ties bf16 on an isolated [8,2048]x[2048,8192]
  dot (83 vs 84 us) but LOSES to XLA's fused astype-then-dot inside the
  full decode step (2463 vs 2919 tok/s at batch 8);
- a native int8xint8 ``dot_general`` with dynamic activation quant
  (w8a8): 2x slower than bf16 (159 vs 75 us on the isolated dot) — the
  MXU path here gains nothing from int8 operands;
- scale folded as f32 after an f32 dot: within noise of astype-then-dot.

At batch 64 the dot is MXU-compute-bound and int8 buys nothing.  int4
(``mode="int4"``, absmax/7 scales, ``jnp.int4`` codes) halves the code
bytes again but the 4-bit grid is coarse enough that it is draft-model
territory — spec verify absorbs draft drift as accept-rate, so the
quality floor there is latency, not correctness.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

# matmul kernels worth quantizing: attention + (dense or MoE) FFN + head
_TARGETS = re.compile(
    r"(attn/(wq|wk|wv|wo)/kernel"
    r"|mlp/w[123]/kernel"
    r"|moe/w[12]"
    r"|lm_head/kernel)$")

# Serving skip list (ISSUE 16): leaves that stay bf16 when quantizing for
# the serving fleet.  Embeddings are gather-shaped (one row read per
# token — quantizing saves resident HBM, not streamed bytes, and decode
# streams), lm_head errors land directly on the sampling distribution,
# norms are tiny.  Matched as substrings of the '/'-joined leaf path.
SERVING_SKIP = ("embed", "lm_head", "norm")

#: Recognized quantization modes → (max code magnitude, code dtype).
_MODES = {
    "int8": (127.0, jnp.int8),
    "int4": (7.0, jnp.int4),
}


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)


def quantize_leaf(w: jax.Array, mode: str = "int8") -> Dict[str, jax.Array]:
    """[..., in, out] kernel -> integer codes with per-out-channel scales
    (absmax over the contraction dim).  ``jnp.round`` is round-half-even,
    so re-quantizing the dequantized leaf is bit-stable.  Scale/round
    math runs in f32 even for bf16 checkpoints (no-op for f32 trees)."""
    qmax, qdtype = _MODES[mode]
    w = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / qmax
    s = jnp.where(s == 0, 1.0, s)
    q = jnp.clip(jnp.round(w / s), -qmax, qmax).astype(qdtype)
    return {"q": q, "s": s.astype(jnp.float32)}


def dequantize_leaf(leaf, dtype) -> jax.Array:
    if isinstance(leaf, dict) and "q" in leaf:
        return (leaf["q"].astype(dtype) * leaf["s"].astype(dtype))
    return leaf.astype(dtype)


def serving_params(params: Dict[str, Any], dtype) -> Dict[str, Any]:
    """Cast float leaves to the serving/compute dtype (normally bf16).

    Training keeps f32 master params (train/trainer.py); serving them
    directly would stream 4 bytes/param from HBM in the decode hot loop —
    decode._mm converts at use, so storage dtype IS the streamed dtype.
    Every serving entry point (bench, infer/serve.py) should cast once
    up front.  Integer leaves (e.g. already-quantized int8) pass through."""
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, params)


def quantize_params(params: Dict[str, Any],
                    cfg: Any = None,
                    *,
                    mode: str = "int8",
                    skip: Optional[Iterable[str]] = None) -> Dict[str, Any]:
    """Return the params tree with the decode-relevant matmul kernels
    replaced by codes+scale pairs (everything else untouched).

    ``quantize_params(params)`` is the legacy form: int8, original target
    set (lm_head included).  The serving path passes ``cfg`` (reserved
    for per-config target tuning; unused today beyond documentation) and
    ``skip=SERVING_SKIP`` so embeddings/lm_head/norms stay bf16 — no new
    checkpoint format, quantization happens after restore.  ``mode`` is
    ``"int8"`` or ``"int4"``.  Scale leaves are ``{"s"}`` f32 planes with
    the contraction dim collapsed to 1; ``shard_params_for_serving``
    replicates them under TP (replicate_indivisible)."""
    del cfg  # target set is path-driven; cfg reserved for future tuning
    if mode not in _MODES:
        raise ValueError(
            f"unknown weight quant mode {mode!r} (want one of "
            f"{sorted(_MODES)})")
    skip_pats = tuple(skip) if skip is not None else ()
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = params
    quantized = {}
    for path, leaf in flat:
        p = _path_str(path)
        if not _TARGETS.search(p):
            continue
        if any(re.search(pat, p) for pat in skip_pats):
            continue
        quantized[p] = quantize_leaf(leaf, mode)

    def rebuild(tree, prefix=""):
        if not isinstance(tree, dict):
            return tree
        return {k: (quantized[f"{prefix}{k}"]
                    if f"{prefix}{k}" in quantized
                    else rebuild(v, f"{prefix}{k}/"))
                for k, v in tree.items()}

    return rebuild(out)


def weight_quant_mode(params: Dict[str, Any]) -> str:
    """Detect the quantization mode of a params tree from its leaves:
    "int8" / "int4" when any quantized code leaf is present, else "none".
    Detection (not a threaded flag) keeps serving_status truthful about
    the tree actually dispatched."""
    mode = "none"
    for leaf in jax.tree_util.tree_leaves(params):
        dt = getattr(leaf, "dtype", None)
        if dt == jnp.int4:
            return "int4"
        if dt == jnp.int8:
            mode = "int8"
    return mode


def param_bytes(params: Dict[str, Any]) -> int:
    """Total HBM bytes of a params tree — pure shape arithmetic (no
    device sync), the weight-side sibling of executor.pool_bytes().
    int4 codes count 1 byte each (jax stores sub-byte dtypes unpacked
    on most backends; we report the conservative resident figure)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        shape = getattr(leaf, "shape", None)
        dt = getattr(leaf, "dtype", None)
        if shape is None or dt is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * max(1, dt.itemsize)
    return total
