"""Weight-only int8 quantization for decoding.

Decode is HBM-bandwidth-bound: every step streams every weight.  Storing
the matmul kernels as int8 with per-output-channel f32 scales halves the
bytes streamed (activations and accumulation stay in the compute dtype —
"weight-only" quantization, the standard serving recipe).  Norm scales
and the embedding table stay full precision (tiny / gather-shaped).

``quantize_params`` maps the trained param tree to the same tree shape
with each targeted ``kernel`` leaf replaced by ``{"q": int8, "s": f32}``;
infer/decode.py's matmul helper consumes either form, so all decode entry
points (prefill / decode_step / generate / serve) work unchanged on
quantized params.  Accuracy is config-dependent; tests bound the logit
error on the tiny model.

**What bounds the speedup** (measured, one v5e chip via axon, jax 0.9,
dim-2048/L8/ffn-8192 model in bf16 serving dtype, greedy decode,
steady-state ms/token via bench.py's two-length differencing — relay RTT
and prefill cancel; e2e tok/s ratios are smaller because RTT is common):

    batch  8: int8 ~1.4-1.5x over bf16   batch 32: ~1.1x   batch 64: ~1.1x

not the ~2x the byte count suggests, because the int8→bf16 dequant feeding
the MXU caps the weight stream at ~220 GB/s of int8 bytes while the plain
bf16 stream runs ~340-400 GB/s (isolated-dot measurements) — past batch 8
the dot is dequant/MXU-bound, not HBM-bound.  Alternatives measured and
rejected on the same hardware:

- a pallas dequant-in-register kernel (int8 tiles HBM→VMEM, convert on
  the way into the MXU): ties bf16 on an isolated [8,2048]x[2048,8192]
  dot (83 vs 84 us) but LOSES to XLA's fused astype-then-dot inside the
  full decode step (2463 vs 2919 tok/s at batch 8);
- a native int8xint8 ``dot_general`` with dynamic activation quant
  (w8a8): 2x slower than bf16 (159 vs 75 us on the isolated dot) — the
  MXU path here gains nothing from int8 operands;
- scale folded as f32 after an f32 dot: within noise of astype-then-dot.

At batch 64 the dot is MXU-compute-bound and int8 buys nothing.
"""

from __future__ import annotations

import re
from typing import Any, Dict

import jax
import jax.numpy as jnp

# matmul kernels worth quantizing: attention + (dense or MoE) FFN + head
_TARGETS = re.compile(
    r"(attn/(wq|wk|wv|wo)/kernel"
    r"|mlp/w[123]/kernel"
    r"|moe/w[12]"
    r"|lm_head/kernel)$")


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)


def quantize_leaf(w: jax.Array) -> Dict[str, jax.Array]:
    """[..., in, out] kernel -> int8 with per-out-channel scales
    (absmax over the contraction dim)."""
    s = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 127.0
    s = jnp.where(s == 0, 1.0, s)
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s.astype(jnp.float32)}


def dequantize_leaf(leaf, dtype) -> jax.Array:
    if isinstance(leaf, dict) and "q" in leaf:
        return (leaf["q"].astype(dtype) * leaf["s"].astype(dtype))
    return leaf.astype(dtype)


def serving_params(params: Dict[str, Any], dtype) -> Dict[str, Any]:
    """Cast float leaves to the serving/compute dtype (normally bf16).

    Training keeps f32 master params (train/trainer.py); serving them
    directly would stream 4 bytes/param from HBM in the decode hot loop —
    decode._mm converts at use, so storage dtype IS the streamed dtype.
    Every serving entry point (bench, infer/serve.py) should cast once
    up front.  Integer leaves (e.g. already-quantized int8) pass through."""
    return jax.tree.map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, params)


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Return the params tree with the decode-relevant matmul kernels
    replaced by int8+scale pairs (everything else untouched)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = params
    quantized = {}
    for path, leaf in flat:
        if _TARGETS.search(_path_str(path)):
            quantized[_path_str(path)] = quantize_leaf(leaf)

    def rebuild(tree, prefix=""):
        if not isinstance(tree, dict):
            return tree
        return {k: (quantized[f"{prefix}{k}"]
                    if f"{prefix}{k}" in quantized
                    else rebuild(v, f"{prefix}{k}/"))
                for k, v in tree.items()}

    return rebuild(out)
