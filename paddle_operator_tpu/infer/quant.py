"""Weight-only int8 quantization for decoding.

Decode is HBM-bandwidth-bound: every step streams every weight.  Storing
the matmul kernels as int8 with per-output-channel f32 scales halves the
bytes streamed (activations and accumulation stay in the compute dtype —
"weight-only" quantization, the standard serving recipe).  Norm scales
and the embedding table stay full precision (tiny / gather-shaped).

``quantize_params`` maps the trained param tree to the same tree shape
with each targeted ``kernel`` leaf replaced by ``{"q": int8, "s": f32}``;
infer/decode.py's matmul helper consumes either form, so all decode entry
points (prefill / decode_step / generate / serve) work unchanged on
quantized params.  Accuracy is config-dependent; tests bound the logit
error on the tiny model.
"""

from __future__ import annotations

import re
from typing import Any, Dict

import jax
import jax.numpy as jnp

# matmul kernels worth quantizing: attention + (dense or MoE) FFN + head
_TARGETS = re.compile(
    r"(attn/(wq|wk|wv|wo)/kernel"
    r"|mlp/w[123]/kernel"
    r"|moe/w[12]"
    r"|lm_head/kernel)$")


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)


def quantize_leaf(w: jax.Array) -> Dict[str, jax.Array]:
    """[..., in, out] kernel -> int8 with per-out-channel scales
    (absmax over the contraction dim)."""
    s = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 127.0
    s = jnp.where(s == 0, 1.0, s)
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s.astype(jnp.float32)}


def dequantize_leaf(leaf, dtype) -> jax.Array:
    if isinstance(leaf, dict) and "q" in leaf:
        return (leaf["q"].astype(dtype) * leaf["s"].astype(dtype))
    return leaf.astype(dtype)


def quantize_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Return the params tree with the decode-relevant matmul kernels
    replaced by int8+scale pairs (everything else untouched)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = params
    quantized = {}
    for path, leaf in flat:
        if _TARGETS.search(_path_str(path)):
            quantized[_path_str(path)] = quantize_leaf(leaf)

    def rebuild(tree, prefix=""):
        if not isinstance(tree, dict):
            return tree
        return {k: (quantized[f"{prefix}{k}"]
                    if f"{prefix}{k}" in quantized
                    else rebuild(v, f"{prefix}{k}/"))
                for k, v in tree.items()}

    return rebuild(out)
