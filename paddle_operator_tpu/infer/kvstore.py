"""Durable prefix store — the persistent KV tier below host/peer cache
(ISSUE 17).

The cache hierarchy above this module is HBM -> host RAM
(``paged.HostCacheTier``) -> live peers (fleet prefix fetch), and every
byte of it dies with the fleet: a full deploy, a scale-to-zero, or a
rolling restart re-prefills the entire shared-prompt corpus from
scratch.  Mooncake and AttentionStore both put the KV of long-lived
shared prefixes in a disaggregated persistent store below DRAM; we
already have the two ingredients they had to invent — a
self-describing, CRC'd, fingerprint-refusing wire envelope
(``utils/fleetkv.py``) and a process-stable radix chain key
(``utils/radixkey.py``) — so this store is a new tier speaking an
EXISTING protocol, not a new protocol.

One store entry is one demoted block payload, wrapped in a fleetkv
envelope of kind ``"kvblock"`` whose meta carries the chain key, the
namespace, the raw token chunk (so a hash collision is caught by the
same equality check the radix walk uses) and the ring fingerprint.
Everything the envelope already refuses — truncation, CRC mismatch,
version skew, fingerprint skew — the store refuses too, wholesale, and
garbage-collects the offending file: a store can never poison a ring.

Write path: the host tier's overflow drops (previously a silent
discard) are offered to a BACKGROUND writer thread through a bounded
drop-oldest queue — the ring thread never blocks on disk.  Files land
via write-tmp+rename, so a crash mid-write leaves only a ``*.tmp``
orphan that readers never see (the janitor sweeps it).

Read path: the submit-thread probe order becomes peer -> store; a
store hit is queued through the exact ``import_host_blocks`` -> host
tier -> batched promote scatter path a peer fetch uses, so a store hit
is bit-identical to a cold prefill by the same construction the
host/peer tiers already pin.

Lifecycle: a janitor pass applies TTL (last-touch mtime) then a size
budget (LRU by mtime), and ``python -m paddle_operator_tpu.infer.kvstore``
runs the same pass offline against a shared volume.

This module must stay import-light (NO jax): the fleet router is a
jax-free process and consults the store directly on a peer miss when
``ROUTER_KV_STORE`` points at a shared ``dir:`` volume.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_operator_tpu.utils import fleetkv as FK
from paddle_operator_tpu.utils.radixkey import chain_key

KIND = "kvblock"
_SUFFIX = ".tpkv"
# a *.tmp older than this is a torn write from a dead process — the
# janitor may reclaim it (a LIVE writer renames within milliseconds)
TMP_REAP_S = 300.0

_tmp_seq = itertools.count()


def parse_store_url(url: str) -> "DirBackend":
    """``SERVE_KV_STORE`` / ``ROUTER_KV_STORE`` value -> backend.
    ``dir:/path`` is the local-disk (or shared-volume) backend; the
    scheme prefix exists so an object-store backend can be a second
    implementation of the same small interface behind a new scheme."""
    url = url.strip()
    scheme, _, rest = url.partition(":")
    if scheme == "dir" and rest:
        return DirBackend(rest)
    raise ValueError(
        f"unsupported KV store url {url!r} (expected dir:/path)")


class DirBackend:
    """Directory-per-namespace block files, one fleetkv envelope each.

    The interface the store needs from any backend is deliberately
    small — ``put`` (atomic), ``get``, ``exists``, ``touch``,
    ``delete``, ``entries`` (size + last-touch listing for the
    janitor), ``sweep_tmp`` — so an object-store backend is a second
    impl of the same methods, not a rewrite of the store."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _ns_dir(self, ns: int) -> str:
        return os.path.join(self.root, f"ns{int(ns)}")

    def path(self, ns: int, key: int) -> str:
        # chain keys are arbitrary-width Python ints and may be
        # NEGATIVE (hash of a tuple) — encode the sign explicitly,
        # hex for compactness
        k = int(key)
        sign = "n" if k < 0 else "p"
        return os.path.join(self._ns_dir(ns),
                            f"{sign}{abs(k):x}{_SUFFIX}")

    def put(self, ns: int, key: int, blob: bytes) -> None:
        """Atomic publish: write a sibling ``*.tmp``, fsync, rename.
        A reader can never observe a torn entry — it sees the old
        file, the new file, or nothing."""
        final = self.path(ns, key)
        os.makedirs(os.path.dirname(final), exist_ok=True)
        tmp = f"{final}.{os.getpid()}.{next(_tmp_seq)}.tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)

    def get(self, ns: int, key: int) -> Optional[bytes]:
        try:
            with open(self.path(ns, key), "rb") as f:
                return f.read()
        except (FileNotFoundError, NotADirectoryError, IsADirectoryError):
            return None

    def exists(self, ns: int, key: int) -> bool:
        return os.path.isfile(self.path(ns, key))

    def touch(self, ns: int, key: int) -> None:
        """Stamp last-touch time — the janitor's LRU/TTL clock."""
        try:
            os.utime(self.path(ns, key), None)
        except OSError:
            pass

    def delete(self, ns: int, key: int) -> None:
        try:
            os.remove(self.path(ns, key))
        except OSError:
            pass

    def entries(self) -> List[Tuple[str, int, float]]:
        """Every published entry as ``(path, size, last_touch)``."""
        out: List[Tuple[str, int, float]] = []
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                if not fn.endswith(_SUFFIX):
                    continue
                p = os.path.join(dirpath, fn)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                out.append((p, int(st.st_size), float(st.st_mtime)))
        return out

    def sweep_tmp(self, max_age_s: float = TMP_REAP_S) -> int:
        """Reap torn-write ``*.tmp`` orphans older than ``max_age_s``."""
        now = time.time()
        reaped = 0
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                if not fn.endswith(".tmp"):
                    continue
                p = os.path.join(dirpath, fn)
                try:
                    if now - os.stat(p).st_mtime >= max_age_s:
                        os.remove(p)
                        reaped += 1
                except OSError:
                    continue
        return reaped


class KVBlockStore:
    """The durable tier: a backend + a background writer + a janitor.

    ``fingerprint`` is the owning ring's geometry dict
    (``ContinuousBatcher._fingerprint()``); ``None`` means a ring-less
    consumer (the router), which requires fetched entries to agree
    with EACH OTHER and stamps their fingerprint onto the prefix
    envelope it relays — the receiving replica's own
    ``check_fingerprint`` stays the last word."""

    def __init__(self, backend: DirBackend,
                 fingerprint: Optional[Dict[str, Any]] = None,
                 ttl_s: float = 0.0, budget_mb: int = 0,
                 queue_len: int = 256) -> None:
        self.backend = backend
        self.fingerprint = fingerprint
        self.ttl_s = float(ttl_s)
        self.budget_mb = int(budget_mb)
        self._q: "deque[Tuple[int, int, Tuple[int, ...], Dict[str, Any]]]" \
            = deque()
        self._q_max = max(1, int(queue_len))
        self._busy = False
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._writer: Optional[threading.Thread] = None
        self.stats = {
            # write side: payloads persisted, offers shed by the
            # bounded queue (drop-oldest backpressure), bytes written
            "puts": 0, "put_drops": 0, "bytes_written": 0,
            # read side: fetch calls, fetch calls that returned >= 1
            # block, blocks returned, entries refused+GC'd (corrupt /
            # truncated / fingerprint-skewed)
            "probes": 0, "hits": 0, "blocks_fetched": 0, "refused": 0,
            # lifecycle: janitor removals (TTL + budget LRU)
            "evicted": 0,
        }

    # -- write path (ring thread -> writer thread) --------------------------

    def offer(self, key: int, chunk: Sequence[int],
              payload: Dict[str, Any], ns: int = 0) -> None:
        """Queue one demoted payload for persistence.  NEVER blocks:
        on backpressure the OLDEST queued offer is shed (it was the
        coldest — it aged out of the host tier first)."""
        if self._q_max and len(self._q) >= self._q_max:
            try:
                self._q.popleft()
                self.stats["put_drops"] += 1
            except IndexError:
                pass
        self._q.append((int(ns), int(key),
                        tuple(int(t) for t in chunk), payload))
        if self._writer is None:
            self._writer = threading.Thread(
                target=self._writer_loop, daemon=True, name="kvstore-writer")
            self._writer.start()
        self._wake.set()

    def _writer_loop(self) -> None:
        while not self._stop.is_set():
            try:
                ns, key, chunk, payload = self._q.popleft()
            except IndexError:
                self._busy = False
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            self._busy = True
            try:
                self._persist_one(ns, key, chunk, payload)
            except Exception:
                pass    # persistence is an optimization, never a fault

    def _persist_one(self, ns: int, key: int, chunk: Tuple[int, ...],
                     payload: Dict[str, Any]) -> None:
        if self.backend.exists(ns, key):
            # same chain key = same immutable bytes under the same
            # fingerprint: refresh the LRU stamp instead of rewriting
            self.backend.touch(ns, key)
            return
        blob = FK.encode_envelope(KIND, {
            "key": int(key), "ns": int(ns),
            "chunk": [int(t) for t in chunk],
            "fingerprint": self.fingerprint,
        }, {name: np.asarray(a) for name, a in payload.items()})
        self.backend.put(ns, key, blob)
        self.stats["puts"] += 1
        self.stats["bytes_written"] += len(blob)

    def flush(self, timeout: float = 10.0) -> bool:
        """Drain the writer queue (tests / bench teardown)."""
        deadline = time.monotonic() + timeout
        while (self._q or self._busy) and time.monotonic() < deadline:
            self._wake.set()
            time.sleep(0.005)
        return not self._q and not self._busy

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._writer is not None:
            self._writer.join(timeout=2.0)

    # -- read path ----------------------------------------------------------

    def _decode_one(self, ns: int, key: int, chunk: Tuple[int, ...],
                    blob: bytes, want_fp: Optional[Dict[str, Any]]
                    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """One entry's bytes -> ``(meta, payload)``, or EnvelopeError.
        On top of decode_envelope's magic/CRC/manifest checks: kind,
        key/ns/chunk identity (the radix equality check, so a file
        placed under the wrong name can never serve the wrong tokens),
        k/v presence, and the fingerprint."""
        kind, meta, arrays = FK.decode_envelope(blob)
        if kind != KIND:
            raise FK.EnvelopeError(
                f"expected a {KIND} envelope, got {kind!r}")
        if (int(meta.get("key", 0)) != int(key)
                or int(meta.get("ns", -1)) != int(ns)
                or [int(t) for t in meta.get("chunk", ())] != list(chunk)):
            raise FK.EnvelopeError(
                "store entry identity mismatch (key/ns/chunk disagree "
                "with its chain position) — refusing")
        if "k" not in arrays or "v" not in arrays:
            raise FK.EnvelopeError("store entry missing k/v payload")
        if want_fp is not None:
            FK.check_fingerprint(meta, want_fp)
        return meta, arrays

    def fetch(self, tokens: Sequence[int], block_size: int, ns: int = 0,
              skip: int = 0) -> Tuple[List[List[int]], List[int],
                                      List[Dict[str, Any]],
                                      Optional[Dict[str, Any]]]:
        """Probe the store for the prompt's chain: returns
        ``(chunks, block_idx, payloads, fingerprint)`` shaped exactly
        like ``PagedCacheManager.export_host_chain`` output (chunks =
        EVERY full block's tokens from the chain start, so the
        importer can recompute parent keys) plus the entries'
        fingerprint (what a ring-less router stamps on the relay
        envelope).  ``skip`` = leading blocks the caller already
        covers locally; probing stops at the first miss past it
        (deeper blocks would be parent-gapped and unreachable).

        A refused entry (corrupt, truncated, skewed) is deleted —
        GC'd, never promoted — and ends the probe.  Adapter
        namespaces abstain: their chain salts are per-load
        per-replica, so a persisted entry could never be re-keyed."""
        self.stats["probes"] += 1
        empty: Tuple[List[List[int]], List[int], List[Dict[str, Any]],
                     Optional[Dict[str, Any]]] = ([], [], [], None)
        if ns:
            return empty
        bs = int(block_size)
        toks = [int(t) for t in tokens]
        n_full = len(toks) // bs
        if n_full == 0:
            return empty
        chunks: List[List[int]] = []
        keys: List[int] = []
        key: Optional[int] = None
        for j in range(n_full):
            chunk = tuple(toks[j * bs:(j + 1) * bs])
            key = chain_key(key, chunk)
            chunks.append(list(chunk))
            keys.append(key)
        block_idx: List[int] = []
        payloads: List[Dict[str, Any]] = []
        fp: Optional[Dict[str, Any]] = self.fingerprint
        for j in range(max(0, int(skip)), n_full):
            blob = self.backend.get(ns, keys[j])
            if blob is None:
                break
            try:
                meta, payload = self._decode_one(
                    ns, keys[j], tuple(chunks[j]), blob, fp)
            except FK.EnvelopeError:
                self.backend.delete(ns, keys[j])
                self.stats["refused"] += 1
                break
            if fp is None:
                # ring-less consumer: later entries must agree with
                # the first (one coherent chain on the relay envelope)
                fp = meta.get("fingerprint")
            self.backend.touch(ns, keys[j])
            block_idx.append(j)
            payloads.append(payload)
        if block_idx:
            self.stats["hits"] += 1
            self.stats["blocks_fetched"] += len(block_idx)
        return chunks, block_idx, payloads, fp

    def fetch_prefix_envelope(self, tokens: Sequence[int],
                              block_size: int,
                              ns: int = 0) -> Optional[bytes]:
        """The router-side consult: probe + re-encode as a standard
        PREFIX envelope (the same wire shape a peer export produces),
        stamped with the entries' own fingerprint — the receiving
        replica's ``check_fingerprint`` is the final gate.  Returns
        ``None`` on a clean miss."""
        chunks, idx, payloads, fp = self.fetch(tokens, block_size, ns=ns)
        if not idx:
            return None
        return FK.encode_prefix({"fingerprint": fp}, chunks, idx,
                                payloads)

    def delete(self, key: int, ns: int = 0) -> None:
        """Drop one entry (quarantine scrub of a store-resident chain)."""
        self.backend.delete(ns, key)

    # -- lifecycle ----------------------------------------------------------

    def usage(self) -> Tuple[int, int]:
        """``(blocks, bytes)`` currently resident — the
        kvStoreBlocks/kvStoreBytes status keys."""
        ents = self.backend.entries()
        return len(ents), sum(sz for _, sz, _ in ents)

    def hit_rate(self) -> float:
        """Share of store probes that returned >= 1 block — the
        kvStoreHitRate status key."""
        p = self.stats["probes"]
        return round(self.stats["hits"] / p, 4) if p else 0.0

    def evictions(self) -> int:
        return self.stats["evicted"]

    def janitor(self, now: Optional[float] = None) -> Dict[str, int]:
        """One lifecycle pass: reap torn-write tmp orphans, expire
        entries past the TTL (last-touch), then enforce the size
        budget LRU-oldest-first.  Idempotent and safe against
        concurrent readers/writers — a remove racing a touch loses
        nothing but one warm entry."""
        now = time.time() if now is None else float(now)
        reaped_tmp = self.backend.sweep_tmp()
        expired = 0
        ents = self.backend.entries()
        if self.ttl_s > 0:
            live: List[Tuple[str, int, float]] = []
            for p, sz, mt in ents:
                if now - mt >= self.ttl_s:
                    try:
                        os.remove(p)
                        expired += 1
                    except OSError:
                        pass
                else:
                    live.append((p, sz, mt))
            ents = live
        budget_evicted = 0
        if self.budget_mb > 0:
            budget = self.budget_mb * (1 << 20)
            total = sum(sz for _, sz, _ in ents)
            for p, sz, _mt in sorted(ents, key=lambda e: e[2]):
                if total <= budget:
                    break
                try:
                    os.remove(p)
                    total -= sz
                    budget_evicted += 1
                except OSError:
                    pass
        self.stats["evicted"] += expired + budget_evicted
        return {"tmp_reaped": reaped_tmp, "expired": expired,
                "budget_evicted": budget_evicted}


def _janitor_main(argv: Optional[List[str]] = None) -> int:
    """Offline GC against a (shared-volume) store directory:
    ``python -m paddle_operator_tpu.infer.kvstore dir:/path --ttl-s ...
    --budget-mb ... [--interval-s N]`` — one pass by default, a
    long-running janitor sidecar with ``--interval-s``.  (The tier-1
    preflight orphan sweep pgreps this module name.)"""
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m paddle_operator_tpu.infer.kvstore",
        description="durable prefix store janitor (TTL + size budget)")
    p.add_argument("store", help="store url, e.g. dir:/var/kvstore")
    p.add_argument("--ttl-s", type=float, default=0.0,
                   help="expire entries idle longer than this (0 = off)")
    p.add_argument("--budget-mb", type=int, default=0,
                   help="LRU-evict down to this size (0 = unbounded)")
    p.add_argument("--interval-s", type=float, default=0.0,
                   help="loop every N seconds (0 = one pass and exit)")
    args = p.parse_args(argv)
    store = KVBlockStore(parse_store_url(args.store),
                         ttl_s=args.ttl_s, budget_mb=args.budget_mb)
    while True:
        out = store.janitor()
        blocks, nbytes = store.usage()
        print(f"kvstore janitor: {out} now {blocks} blocks "
              f"{nbytes} bytes", flush=True)
        if args.interval_s <= 0:
            return 0
        time.sleep(args.interval_s)


if __name__ == "__main__":
    raise SystemExit(_janitor_main())
