"""Speculative decoding: draft-model propose + chunked target verify.

Decode at low batch is memory-bandwidth-bound (BENCH_r05: HBM util
0.23-0.31 on the XLA path at batch 1-8) — every generated token streams
the full weight set for ONE matmul-vector's worth of compute.
Speculative decoding (Leviathan et al., "Fast Inference from
Transformers via Speculative Decoding"; Chen et al., "Accelerating
Large Language Model Decoding with Speculative Sampling") converts that
idle bandwidth into tokens: a small DRAFT model proposes K tokens
autoregressively (cheap — its weight stream is a fraction of the
target's), then the TARGET model scores all K+1 positions in ONE
chunked forward (the same weight stream a single decode step pays) and
accepts the longest prefix consistent with its own distribution.  Per
accepted token the target streams its weights 1/(a+1) times.

Design, in this codebase's terms:

- **Draft propose** rides the existing single-token ring step
  (infer/batcher.py ``_ring_forward`` — per-lane positions, pallas
  kernel on TPU) for K+1 ticks: the last tick's logits are discarded
  but its cache write appends d_K's KV, so ANY accept length can rewind
  without a gap (the standard "feed the last draft too" trick).
- **Chunked verify** is one multi-token forward at per-lane offsets
  (:func:`_multi_forward`) — the prefill math of infer/decode.py
  ``_layer`` generalized to a per-lane position vector, reusing the
  cache-append layout the ring path established.  XLA einsum attention:
  T = K+1 is a handful of rows, the weight stream dominates.
- **Acceptance**: exact greedy equality at temperature 0 (output is
  BIT-IDENTICAL to autoregressive ``decode.generate`` — pinned by
  tests and the dryrun ``serve-spec`` gate), and textbook rejection
  sampling (accept d_i with prob min(1, p/q); on rejection sample the
  normalized residual max(0, p-q)) for temperature > 0, which preserves
  the target distribution exactly in expectation.
- **Cache rollback is a write-index rewind, no copy**: rejected
  positions' K/V rows simply stay behind the rewound per-lane ``pos``;
  the causal/fill mask never attends past ``pos`` and later writes
  overwrite them — the same invariant idle ring lanes already rely on.
- **No divergent compiles**: one jitted round serves every accept
  pattern; per-lane accept lengths land in a ``pos`` vector, and the
  greedy/sampled rules are computed side by side and selected per lane
  by ``temp > 0`` (the ``_sample_tokens`` discipline).

Capacity: a round starting at position p writes verify rows p..p+K, so
callers must leave ``spec_k - 1`` positions of headroom past
prompt+max_new_tokens (speculative_generate grows its allocation;
ContinuousBatcher.submit enforces it against max_len).

Fault tolerance (infer/resilience.py): the spec round is just another
resident dispatch to the batcher's host loop, so request deadlines,
the dispatch watchdog, and ring self-healing all apply unchanged — a
heal rebuilds BOTH caches (target + draft) and re-admits queued work.
The one exception is ``nan_check``: the per-lane isfinite fold is a
chunk-step output the spec round does not produce, so the batcher
rejects the combination up front rather than silently not checking.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from paddle_operator_tpu.infer import decode as D
from paddle_operator_tpu.models.llama import LlamaConfig, rope_frequencies


def check_draft_compat(cfg: LlamaConfig, draft_cfg: LlamaConfig) -> None:
    """The one hard compatibility invariant: only TOKEN IDS cross
    between draft and target, so they must share a tokenizer.  Raises a
    clear error on vocab mismatch (everything else — depth, width,
    head counts — may differ freely; ``LlamaConfig.draft()`` builds a
    compatible config)."""
    if cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError(
            f"draft/target vocab mismatch: draft vocab_size="
            f"{draft_cfg.vocab_size} vs target {cfg.vocab_size} — "
            "speculative decoding exchanges token ids between the two "
            "models, so they must share one tokenizer")


# ---------------------------------------------------------------------------
# Device side: multi-token verify forward at per-lane positions
# ---------------------------------------------------------------------------


def _write_rows(cache_l: jax.Array, kv: jax.Array,
                pos: jax.Array) -> jax.Array:
    """[B, H, S, D] cache layer <- [B, H, T, D] new rows at per-lane
    start positions ``pos``.  Unrolled per lane (static slot count) for
    the same reason as batcher._write_lane_stacked: a vmapped update
    over ragged positions lowers to a scatter that copies the carry."""
    for lane in range(kv.shape[0]):
        cache_l = jax.lax.dynamic_update_slice(
            cache_l, kv[lane][None], (lane, 0, pos[lane], 0))
    return cache_l


def _proj_qkv(cfg: LlamaConfig, lp: Dict[str, Any], x: jax.Array,
              lora=None) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shared multi-token projection block: norm -> q/k/v (+ the
    per-row LoRA delta when ``lora=(adp_l, aid)`` — qos.lora_qkv, the
    same rule every other projection site applies), reshaped to
    [B, T, H, D] pre-RoPE."""
    b, t, _ = x.shape
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = D._rms(x, lp["attn_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    q = D._mm(h, lp["attn"]["wq"]["kernel"], cfg.dtype)
    k = D._mm(h, lp["attn"]["wk"]["kernel"], cfg.dtype)
    v = D._mm(h, lp["attn"]["wv"]["kernel"], cfg.dtype)
    if lora is not None:
        from paddle_operator_tpu.infer.qos import lora_qkv

        q, k, v = lora_qkv(h, lora[0], lora[1], q, k, v, cfg.dtype)
    return (q.reshape(b, t, hq, d), k.reshape(b, t, hkv, d),
            v.reshape(b, t, hkv, d))


def _layer_multi(cfg: LlamaConfig, lp: Dict[str, Any], x: jax.Array,
                 cos: jax.Array, sin: jax.Array, k_cache: jax.Array,
                 v_cache: jax.Array, pos: jax.Array, lora=None
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decoder layer over [B, T] new tokens starting at PER-LANE
    offsets ``pos`` [B] — decode._layer's math with the scalar position
    generalized to a vector (and batcher._layer_step's with one token
    generalized to T).  Row (b, j) sits at absolute position pos[b]+j
    and attends cache cols [0, pos[b]+j]."""
    b, t, _ = x.shape
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    q, k, v = _proj_qkv(cfg, lp, x, lora)
    abs_pos = pos[:, None] + jnp.arange(t)[None, :]          # [B, T]
    cos_b = cos[abs_pos][:, :, None, :]                      # [B, T, 1, d/2]
    sin_b = sin[abs_pos][:, :, None, :]

    def rot(u):
        u1, u2 = jnp.split(u.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate(
            [u1 * cos_b - u2 * sin_b, u2 * cos_b + u1 * sin_b],
            axis=-1).astype(u.dtype)

    q, k = rot(q), rot(k)
    k_cache = _write_rows(k_cache, k.transpose(0, 2, 1, 3), pos)
    v_cache = _write_rows(v_cache, v.transpose(0, 2, 1, 3), pos)

    n_rep = hq // hkv
    s = k_cache.shape[2]
    qg = q.reshape(b, t, hkv, n_rep, d)
    scores = jnp.einsum("bthrd,bhsd->bthrs", qg, k_cache,
                        preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(d))
    mask = jnp.arange(s)[None, None, :] <= abs_pos[:, :, None]  # [B, T, S]
    scores = jnp.where(mask[:, :, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bthrs,bhsd->bthrd", probs.astype(cfg.dtype),
                     v_cache, preferred_element_type=jnp.float32)
    out = out.reshape(b, t, hq * d).astype(cfg.dtype)
    return D._finish_layer(cfg, lp, x, out), k_cache, v_cache


def _multi_forward(cfg: LlamaConfig, params: Dict[str, Any],
                   toks: jax.Array, cache: Dict[str, jax.Array],
                   mesh=None, head: bool = True, lora=None
                   ) -> Tuple[Optional[jax.Array], Dict[str, jax.Array]]:
    """[B, T] new tokens at per-lane cache['pos'] -> ([B, T, vocab]
    logits, advanced cache).  The chunked-verify forward: every einsum
    is the ring path's, so under a serving mesh the whole thing rides
    GSPMD off the param/cache shardings (T is a handful of rows — the
    pallas single-query kernel has nothing to win here).

    ``head=False`` skips the final norm + lm head and returns
    ``(None, cache)`` — an INTERMEDIATE chunked-prefill slice
    (executor.make_prefill_chunk) only appends KV, and head logits
    over a whole slice are the biggest tensor in the prefill path."""
    pos = cache["pos"]
    adp, aid = lora if lora is not None else (None, None)
    x = params["tok_embed"]["embedding"].astype(cfg.dtype)[toks]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                cfg.rope_theta)

    def body(x, layer_in):
        if adp is not None:
            lp, adp_l, k_c, v_c = layer_in
            lo = (adp_l, aid)
        else:
            lp, k_c, v_c = layer_in
            lo = None
        y, k_c, v_c = _layer_multi(cfg, lp, x, cos, sin, k_c, v_c, pos,
                                   lora=lo)
        return y, (k_c, v_c)

    xs = ((params["layers"], adp, cache["k"], cache["v"])
          if adp is not None
          else (params["layers"], cache["k"], cache["v"]))
    x, (k_new, v_new) = jax.lax.scan(body, x, xs)
    new_cache = {"k": k_new, "v": v_new, "pos": pos + toks.shape[1]}
    if not head:
        return None, new_cache
    x = D._rms(x, params["final_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    logits = D._mm(x, params["lm_head"]["kernel"],
                   cfg.dtype).astype(jnp.float32)
    return logits, new_cache


def _layer_multi_paged(cfg: LlamaConfig, lp: Dict[str, Any], x: jax.Array,
                       cos: jax.Array, sin: jax.Array, k_pool: jax.Array,
                       v_pool: jax.Array, li: jax.Array, table: jax.Array,
                       pos: jax.Array, limit: Optional[jax.Array],
                       lora=None, aligned: bool = False
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """:func:`_layer_multi` over the PAGED pool (infer/paged.py): new
    rows land in whatever pool block the lane's table maps for their
    absolute position (rows past ``limit`` route to the trash block —
    suffix-prefill pads), and the attention walks the table through the
    gathered lane view.  Same einsum/mask sequence as the contiguous
    verify, so greedy paged-vs-contiguous streams stay bit-identical.

    ``aligned=True`` (callers that guarantee block-aligned ``pos`` and
    a block-multiple row count — the N-lane prefill engine's slice
    programs): writes go whole-block (``_write_blocks_paged``) instead
    of per-row, collapsing the traced write-op count by
    ``block_size``x — at production slice widths the per-row unroll is
    pathological to compile, not just to run."""
    from paddle_operator_tpu.infer.paged import (
        _gather_lane_view,
        _write_blocks_paged,
        _write_rows_paged,
    )

    b, t, _ = x.shape
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    q, k, v = _proj_qkv(cfg, lp, x, lora)
    abs_pos = pos[:, None] + jnp.arange(t)[None, :]          # [B, T]
    cos_b = cos[abs_pos][:, :, None, :]
    sin_b = sin[abs_pos][:, :, None, :]

    def rot(u):
        u1, u2 = jnp.split(u.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate(
            [u1 * cos_b - u2 * sin_b, u2 * cos_b + u1 * sin_b],
            axis=-1).astype(u.dtype)

    q, k = rot(q), rot(k)
    block_size = k_pool.shape[3]
    write = _write_blocks_paged if aligned else _write_rows_paged
    k_pool = write(k_pool, k.transpose(0, 2, 1, 3), li, table, pos,
                   block_size, limit)
    v_pool = write(v_pool, v.transpose(0, 2, 1, 3), li, table, pos,
                   block_size, limit)
    k_view = _gather_lane_view(k_pool, table, li)
    v_view = _gather_lane_view(v_pool, table, li)

    n_rep = hq // hkv
    s = k_view.shape[2]
    qg = q.reshape(b, t, hkv, n_rep, d)
    scores = jnp.einsum("bthrd,bhsd->bthrs", qg, k_view,
                        preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(d))
    mask = jnp.arange(s)[None, None, :] <= abs_pos[:, :, None]  # [B, T, S]
    scores = jnp.where(mask[:, :, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bthrs,bhsd->bthrd", probs.astype(cfg.dtype),
                     v_view, preferred_element_type=jnp.float32)
    out = out.reshape(b, t, hq * d).astype(cfg.dtype)
    return D._finish_layer(cfg, lp, x, out), k_pool, v_pool


def _layer_multi_paged_quant(cfg: LlamaConfig, lp: Dict[str, Any],
                             x: jax.Array, cos: jax.Array, sin: jax.Array,
                             kc: jax.Array, vc: jax.Array, ks: jax.Array,
                             vs: jax.Array, kt: jax.Array, vt: jax.Array,
                             li: jax.Array, table: jax.Array,
                             pos: jax.Array, limit: Optional[jax.Array],
                             lane_mask: Optional[jax.Array], lora=None):
    """:func:`_layer_multi_paged` over the QUANTIZED pool
    (SERVE_KV_QUANT=int8): each new row accumulates EXACT in the lane's
    bf16 staging tail; a row completing its block quantizes the whole
    tail block into the int8 pool — codes + one scale, computed once
    from the full block (the reason the tail exists: per-token
    requantization would re-derive the scale T times and perturb
    already-written rows every step).  Rows that are pads (``p >=
    limit``) or belong to masked lanes (``lane_mask``) redirect to the
    TRASH tail row (index B) — a pad row writing the lane's real tail
    would clobber live rows when the pad span wraps the block.  The
    attention reads the dequantizing gather view: full blocks from the
    pool, the write-frontier block from the tail."""
    from paddle_operator_tpu.infer.paged import (
        _gather_lane_view_quant,
        quantize_kv,
    )

    b, t, _ = x.shape
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    q, k, v = _proj_qkv(cfg, lp, x, lora)
    abs_pos = pos[:, None] + jnp.arange(t)[None, :]          # [B, T]
    cos_b = cos[abs_pos][:, :, None, :]
    sin_b = sin[abs_pos][:, :, None, :]

    def rot(u):
        u1, u2 = jnp.split(u.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate(
            [u1 * cos_b - u2 * sin_b, u2 * cos_b + u1 * sin_b],
            axis=-1).astype(u.dtype)

    q, k = rot(q), rot(k)
    bs = kc.shape[3]
    kh = k.transpose(0, 2, 1, 3)                             # [B, H, T, D]
    vh = v.transpose(0, 2, 1, 3)
    trash_row = kt.shape[1] - 1
    for lane in range(b):
        for j in range(t):
            p = pos[lane] + j
            real = None
            if limit is not None:
                real = p < limit[lane]
            if lane_mask is not None:
                real = (lane_mask[lane] if real is None
                        else real & lane_mask[lane])
            row = (lane if real is None
                   else jnp.where(real, lane, trash_row))
            kt = jax.lax.dynamic_update_slice(
                kt, kh[lane, :, j][None, None, :, None, :],
                (li, row, 0, p % bs, 0))
            vt = jax.lax.dynamic_update_slice(
                vt, vh[lane, :, j][None, None, :, None, :],
                (li, row, 0, p % bs, 0))
            complete = (p + 1) % bs == 0
            if real is not None:
                complete = complete & real
            dst = table[lane, p // bs]

            # block-completion commit behind a cond: only the
            # 1-in-bs completing row pays the two tile quantizes +
            # pool writes (same rationale as paged._write_token_quant)
            def _commit(st, row=row, dst=dst, kt=kt, vt=vt):
                kc, vc, ks, vs = st
                ktile = jax.lax.dynamic_slice(
                    kt, (li, row, 0, 0, 0), (1, 1, hkv, bs, d))
                kcodes, kscale = quantize_kv(ktile)
                kc = jax.lax.dynamic_update_slice(kc, kcodes,
                                                  (li, dst, 0, 0, 0))
                ks = jax.lax.dynamic_update_slice(ks, kscale,
                                                  (li, dst, 0))
                vtile = jax.lax.dynamic_slice(
                    vt, (li, row, 0, 0, 0), (1, 1, hkv, bs, d))
                vcodes, vscale = quantize_kv(vtile)
                vc = jax.lax.dynamic_update_slice(vc, vcodes,
                                                  (li, dst, 0, 0, 0))
                vs = jax.lax.dynamic_update_slice(vs, vscale,
                                                  (li, dst, 0))
                return kc, vc, ks, vs

            kc, vc, ks, vs = jax.lax.cond(complete, _commit,
                                          lambda st: st,
                                          (kc, vc, ks, vs))

    # per-lane write-frontier block: the last REAL row written (pads
    # never advance the tail), floor 0 for fully-masked lanes
    lim_eff = limit if limit is not None else pos + t
    wb = jnp.maximum(jnp.minimum(pos + t, lim_eff) - 1, 0) // bs
    k_view = _gather_lane_view_quant(kc, ks, kt, table, li, wb)
    v_view = _gather_lane_view_quant(vc, vs, vt, table, li, wb)

    n_rep = hq // hkv
    s = k_view.shape[2]
    qg = q.reshape(b, t, hkv, n_rep, d)
    scores = jnp.einsum("bthrd,bhsd->bthrs", qg, k_view,
                        preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(d))
    mask = jnp.arange(s)[None, None, :] <= abs_pos[:, :, None]  # [B, T, S]
    scores = jnp.where(mask[:, :, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bthrs,bhsd->bthrd", probs.astype(cfg.dtype),
                     v_view, preferred_element_type=jnp.float32)
    out = out.reshape(b, t, hq * d).astype(cfg.dtype)
    return D._finish_layer(cfg, lp, x, out), kc, vc, ks, vs, kt, vt


def _multi_forward_paged(cfg: LlamaConfig, params: Dict[str, Any],
                         toks: jax.Array, cache: Dict[str, jax.Array],
                         table: jax.Array,
                         limit: Optional[jax.Array] = None,
                         mesh=None, head: bool = True,
                         quant: bool = False,
                         lane_mask: Optional[jax.Array] = None,
                         lora=None, aligned: bool = False
                         ) -> Tuple[Optional[jax.Array],
                                    Dict[str, jax.Array]]:
    """:func:`_multi_forward` with the target cache PAGED: the
    chunked-verify (and paged suffix-prefill) forward whose writes and
    attention walk the block table.  ``table`` [B, M] int32;
    ``limit`` [B] (optional) bounds real rows per lane — pads beyond it
    write to the trash block.  The pools ride the layer scan as carry
    (block ids are dynamic).  ``head=False``: KV append only, logits
    None (intermediate chunked-prefill slices,
    paged.make_paged_prefill_chunk).

    ``quant=True``: the cache is the int8 codes+scales+tails dict and
    the per-lane staging tails ride the carry too; ``lane_mask`` [B]
    (the spec round's ``active``) additionally redirects masked lanes'
    writes to the trash tail — their tail rows may be live prefill
    state (see :func:`_layer_multi_paged_quant`).

    ``aligned=True`` (bf16 only — the quant tail protocol is
    inherently per-row): block-aligned whole-block writes, see
    :func:`_layer_multi_paged`."""
    pos = cache["pos"]
    adp, aid = lora if lora is not None else (None, None)
    x = params["tok_embed"]["embedding"].astype(cfg.dtype)[toks]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                cfg.rope_theta)
    xs = ((params["layers"], adp, jnp.arange(cfg.n_layers))
          if adp is not None
          else (params["layers"], jnp.arange(cfg.n_layers)))

    def _unpack(layer_in):
        if adp is not None:
            lp, adp_l, li = layer_in
            return lp, li, (adp_l, aid)
        lp, li = layer_in
        return lp, li, None

    if quant:
        def body_q(carry, layer_in):
            x, kc, vc, ks, vs, kt, vt = carry
            lp, li, lo = _unpack(layer_in)
            y, kc, vc, ks, vs, kt, vt = _layer_multi_paged_quant(
                cfg, lp, x, cos, sin, kc, vc, ks, vs, kt, vt, li,
                table, pos, limit, lane_mask, lora=lo)
            return (y, kc, vc, ks, vs, kt, vt), ()

        (x, k_new, v_new, ks_new, vs_new, kt_new, vt_new), _ = \
            jax.lax.scan(
                body_q,
                (x, cache["k"], cache["v"], cache["ks"], cache["vs"],
                 cache["kt"], cache["vt"]), xs)
        new_cache = {"k": k_new, "v": v_new, "ks": ks_new, "vs": vs_new,
                     "kt": kt_new, "vt": vt_new,
                     "pos": pos + toks.shape[1]}
    else:
        def body(carry, layer_in):
            x, kc, vc = carry
            lp, li, lo = _unpack(layer_in)
            y, kc, vc = _layer_multi_paged(cfg, lp, x, cos, sin, kc, vc,
                                           li, table, pos, limit,
                                           lora=lo, aligned=aligned)
            return (y, kc, vc), ()

        (x, k_new, v_new), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]), xs)
        new_cache = {"k": k_new, "v": v_new, "pos": pos + toks.shape[1]}
    if not head:
        return None, new_cache
    x = D._rms(x, params["final_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    logits = D._mm(x, params["lm_head"]["kernel"],
                   cfg.dtype).astype(jnp.float32)
    return logits, new_cache


# ---------------------------------------------------------------------------
# The speculative round: propose K, verify K+1, commit a+1, rewind
# ---------------------------------------------------------------------------


def make_spec_round_fn(cfg: LlamaConfig, dcfg: LlamaConfig, spec_k: int,
                       top_k: Optional[int] = None,
                       top_p: Optional[float] = None, mesh=None,
                       paged: bool = False, quant: bool = False):
    """One jitted speculative round over ring-style caches (per-lane
    ``pos`` vectors), BOTH caches donated.

    ``round(params, dparams, tcache, dcache, tok [B], temp [B],
    keys [B,2], active [B]) -> (tcache', dcache', tok', committed
    [spec_k+1, B], n_commit [B])``

    ``tok`` is the per-lane carry token — committed but not yet in
    either cache.  ``committed[:n_commit[b], b]`` are lane b's newly
    committed tokens this round (accepted drafts then the
    correction/bonus token); inactive lanes freeze their output
    (n_commit 0, tok unchanged; their pos is zeroed — retired-lane
    hygiene) so the compiled program is one shape for every
    arrival/accept pattern.

    ``paged=True``: the TARGET cache is the paged block pool
    (infer/paged.py) — the round signature gains the block table after
    the caches (``round(params, dparams, tcache, dcache, table, ...)``)
    and the verify forward walks it (:func:`_multi_forward_paged`).
    The DRAFT cache stays a contiguous ring either way: its propose
    loop keeps the fast contiguous write path and pays no paging.

    ``quant=True`` (with ``paged``): the target pool is the int8
    codes+scales+tails dict.  The one spec-specific wrinkle is the
    ROLLBACK: the verify wrote K+1 rows through the staging tail, so a
    rewind that crosses back over a completed block boundary leaves the
    tail holding a NEWER block than the lane's write frontier — the
    round re-seeds such lanes' tails by dequantizing the frontier block
    from the pool (its rows below the rewound pos are exactly the
    committed ones; rows above sit behind the fill mask and are
    overwritten before they become attendable, the standard rollback
    invariant).  Lanes whose frontier block never completed keep their
    live tail untouched."""
    _round = _build_spec_round(cfg, dcfg, spec_k, top_k, top_p, mesh,
                               paged, quant)

    if paged:
        def round_fn(params, dparams, tcache, dcache, table, tok, temp,
                     keys, active):
            return _round(params, dparams, tcache, dcache, tok, temp,
                          keys, active, table)
    else:
        def round_fn(params, dparams, tcache, dcache, tok, temp, keys,
                     active):
            return _round(params, dparams, tcache, dcache, tok, temp,
                          keys, active, None)

    return jax.jit(round_fn, donate_argnums=(2, 3))


def _build_spec_round(cfg, dcfg, spec_k, top_k, top_p, mesh, paged,
                      quant):
    """The RAW (un-jitted) speculative round body behind
    :func:`make_spec_round_fn` — extracted so the megastep
    (:func:`make_spec_megastep`) can scan it N times inside one
    compiled program.  The op sequence is exactly what the jitted
    1-round program traced before the extraction; nothing about the
    round changed."""
    from paddle_operator_tpu.infer.executor import _ring_forward

    kk = spec_k

    def _round(params, dparams, tcache, dcache, tok, temp, keys, active,
               table):
        b = tok.shape[0]
        tpos0, dpos0 = tcache["pos"], dcache["pos"]
        # decoupled sampling streams: draft draws, acceptance uniforms
        # and residual draws must not reuse each other's bits
        dkeys = jax.vmap(lambda u: jax.random.fold_in(u, 1))(keys)
        akeys = jax.vmap(lambda u: jax.random.fold_in(u, 2))(keys)
        rkeys = jax.vmap(lambda u: jax.random.fold_in(u, 3))(keys)

        def draft_tick(carry, _):
            dc, tk = carry
            p0 = dc["pos"]
            logits, dc = _ring_forward(dcfg, dparams, tk, dc, mesh=mesh)
            greedy = logits.argmax(-1).astype(jnp.int32)
            filt = D._filter_logits(
                logits / jnp.maximum(temp, 1e-6)[:, None], top_k, top_p)
            qdist = jax.nn.softmax(filt, axis=-1)            # [B, V] f32
            sub = jax.vmap(jax.random.fold_in)(dkeys, p0)
            drawn = jax.vmap(
                lambda u, l: jax.random.categorical(u, l))(sub, filt)
            nxt = jnp.where(temp > 0, drawn.astype(jnp.int32), greedy)
            return (dc, nxt), (nxt, qdist)

        # K+1 ticks: K proposals, plus one extra feed whose logits are
        # discarded but whose cache write appends d_K's KV — the rewind
        # then has no gap at full acceptance (module docstring)
        (dcache2, _), (ds, qdists) = jax.lax.scan(
            draft_tick, (dcache, tok), None, length=kk + 1)
        drafts = ds[:kk].T                                   # [B, K]
        q = jnp.transpose(qdists[:kk], (1, 0, 2))            # [B, K, V]

        seq = jnp.concatenate([tok[:, None], drafts], axis=1)  # [B, K+1]
        if paged and quant:
            # quantized target pool: masked lanes' verify rows redirect
            # to the trash tail (their tail rows may be live prefill
            # state a resident dispatch must not clobber)
            tlogits, tcache2 = _multi_forward_paged(
                cfg, params, seq, tcache, table, mesh=mesh, quant=True,
                lane_mask=active)
        elif paged:
            # paged target: the verify forward walks the block table —
            # writes land in pool blocks, attention gathers the lane
            # view (or streams table-mapped blocks on the kernel path)
            tlogits, tcache2 = _multi_forward_paged(cfg, params, seq,
                                                    tcache, table,
                                                    mesh=mesh)
        else:
            tlogits, tcache2 = _multi_forward(cfg, params, seq, tcache,
                                              mesh=mesh)
        tgt = tlogits.argmax(-1).astype(jnp.int32)           # [B, K+1]

        # greedy rule: accept while the draft equals the target argmax
        accept_g = drafts == tgt[:, :kk]
        # sampled rule: accept d_i with prob min(1, p(d_i)/q(d_i))
        tfilt = D._filter_logits(
            tlogits / jnp.maximum(temp, 1e-6)[:, None, None], top_k, top_p)
        pdist = jax.nn.softmax(tfilt, axis=-1)               # [B, K+1, V]
        p_tok = jnp.take_along_axis(
            pdist[:, :kk], drafts[..., None], -1)[..., 0]    # [B, K]
        q_tok = jnp.take_along_axis(q, drafts[..., None], -1)[..., 0]
        sub_a = jax.vmap(jax.random.fold_in)(akeys, tpos0)
        u = jax.vmap(lambda s_: jax.random.uniform(s_, (kk,)))(sub_a)
        accept_s = u * q_tok < p_tok
        accept = jnp.where(temp[:, None] > 0, accept_s, accept_g)
        # longest accepted prefix per lane, 0..K
        a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)

        # the token after the accepted prefix: at a < K the correction
        # (greedy: target argmax; sampled: the normalized residual
        # max(0, p - q)), at a == K the bonus from the target's K-th
        # distribution — the same gather covers both (q padded with 0)
        nxt_g = jnp.take_along_axis(tgt, a[:, None], 1)[:, 0]
        q_pad = jnp.concatenate([q, jnp.zeros_like(q[:, :1])], axis=1)
        pd_a = jnp.take_along_axis(pdist, a[:, None, None], 1)[:, 0]
        qd_a = jnp.take_along_axis(q_pad, a[:, None, None], 1)[:, 0]
        resid = jnp.clip(pd_a - qd_a, 0.0, None)
        rs = resid.sum(-1, keepdims=True)
        resid = jnp.where(rs > 0, resid, pd_a)   # numerically-empty residual
        sub_r = jax.vmap(jax.random.fold_in)(rkeys, tpos0)
        nxt_s = jax.vmap(
            lambda s_, r: jax.random.categorical(s_, jnp.log(r)))(
            sub_r, resid).astype(jnp.int32)
        nxt = jnp.where(temp > 0, nxt_s, nxt_g)

        n_commit = jnp.where(active, a + 1, 0)
        drafts_pad = jnp.concatenate(
            [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)  # [B, K+1]
        idx = jnp.arange(kk + 1)[None, :]
        committed = jnp.where(
            idx < a[:, None], drafts_pad,
            jnp.where(idx == a[:, None], nxt[:, None], 0))
        tok_out = jnp.where(active, nxt, tok)
        # ROLLBACK: monotone write-index rewind — both caches advanced
        # spec_k+1 rows, committed only a+1; rejected rows stay behind
        # pos, never attended, overwritten by later writes.  Inactive
        # (retired/free) lanes get their position ZEROED rather than
        # frozen: serving_status must never see a stale fill position,
        # and under paging their writes route to the trash block via
        # the zeroed table row regardless.
        tcache2["pos"] = jnp.where(active, tpos0 + a + 1, 0)
        dcache2["pos"] = jnp.where(active, dpos0 + a + 1, 0)
        if paged and quant:
            # tail resync across a block-crossing rewind (docstring):
            # re-seed the tail from the pool's frontier block for lanes
            # whose rewound write block was completed+quantized by the
            # verify; inactive lanes keep their (possibly live-prefill)
            # tails untouched
            from paddle_operator_tpu.infer.paged import dequantize_kv

            bs_q = tcache2["k"].shape[3]
            wb_after = (tpos0 + kk) // bs_q
            wb_new = tcache2["pos"] // bs_q
            need = active & (wb_new < wb_after)

            # behind a cond: a rewind crosses a completed block only
            # ~spec_k/block_size of rounds (and only on partial
            # accepts) — the two pool gathers + dequants + full-tail
            # rewrites must not tax every spec round
            def _resync(tails):
                kt, vt = tails
                blks = jnp.take_along_axis(table, wb_new[:, None],
                                           axis=1)[:, 0]       # [B]
                deqk = dequantize_kv(
                    jnp.take(tcache2["k"], blks, axis=1),
                    jnp.take(tcache2["ks"], blks, axis=1),
                    kt.dtype)                           # [L, B, H, bs, D]
                deqv = dequantize_kv(
                    jnp.take(tcache2["v"], blks, axis=1),
                    jnp.take(tcache2["vs"], blks, axis=1),
                    vt.dtype)
                sel = need[None, :, None, None, None]
                kt = kt.at[:, :b].set(jnp.where(sel, deqk, kt[:, :b]))
                vt = vt.at[:, :b].set(jnp.where(sel, deqv, vt[:, :b]))
                return kt, vt

            tcache2["kt"], tcache2["vt"] = jax.lax.cond(
                need.any(), _resync, lambda t: t,
                (tcache2["kt"], tcache2["vt"]))
        return tcache2, dcache2, tok_out, committed.T, n_commit

    return _round


def make_spec_megastep(cfg: LlamaConfig, dcfg: LlamaConfig, spec_k: int,
                       n_steps: int, top_k: Optional[int] = None,
                       top_p: Optional[float] = None, mesh=None,
                       paged: bool = False, quant: bool = False):
    """N fused SPECULATIVE rounds in one compiled dispatch (ISSUE 11):
    the raw round body (:func:`_build_spec_round`) scanned ``n_steps``
    times with the host's between-round decisions — eos inside a
    committed block, token budget, step budget — carried on device
    (executor._mega_advance over each round's committed tokens).  A
    lane that finishes mid-megastep free-runs masked: under paging its
    verify writes go through an effective table whose row is replaced
    by the trash block, its draft writes land past its frozen draft
    frontier (the rows a rollback already leaves there), and both
    positions are restored from the pre-round snapshot each boundary —
    so a lane frozen by its STEP budget resumes bit-identically later.

    ``mega(params, dparams, tcache, dcache[, table], tok, temp, keys,
    active, eos, left, steps) -> (tcache', dcache', tok',
    committed [n, K+1, B], raw [n, B], counts [n, B])``

    ``raw[r, b]`` is the round's device commit count (the oracle's
    acceptance-telemetry number; 0 for dead rounds), ``counts[r, b]``
    the rows of ``committed[r, :, b]`` the host consumes (eos/budget
    truncated — scheduler._consume's walk, precomputed)."""
    from paddle_operator_tpu.infer.executor import _mega_continue
    from paddle_operator_tpu.infer.paged import TRASH_BLOCK

    _round = _build_spec_round(cfg, dcfg, spec_k, top_k, top_p, mesh,
                               paged, quant)

    def _mega(params, dparams, tcache, dcache, tok, temp, keys, active,
              eos, left, steps, table):

        def outer(carry, _):
            tcache, dcache, tok, live, lleft, lsteps = carry
            tp0, dp0 = tcache["pos"], dcache["pos"]
            tbl_eff = (jnp.where(live[:, None], table, TRASH_BLOCK)
                       if paged else None)
            tcache, dcache, tok, committed, n_commit = _round(
                params, dparams, tcache, dcache, tok, temp, keys, live,
                tbl_eff)
            count, live2, left2, lsteps2 = _mega_continue(
                committed, n_commit, live, lleft, lsteps, eos)
            # frozen/dead lanes keep the positions their last consumed
            # token earned (the round zeroed them via the active mask)
            tcache["pos"] = jnp.where(live, tcache["pos"], tp0)
            dcache["pos"] = jnp.where(live, dcache["pos"], dp0)
            return ((tcache, dcache, tok, live2, left2, lsteps2),
                    (committed, n_commit, count))

        live0 = active & (left > 0) & (steps > 0)
        (tcache, dcache, tok, _, _, _), (committed, raws, counts) = \
            jax.lax.scan(outer, (tcache, dcache, tok, live0, left, steps),
                         None, length=n_steps)
        return tcache, dcache, tok, committed, raws, counts

    if paged:
        def mega(params, dparams, tcache, dcache, table, tok, temp,
                 keys, active, eos, left, steps):
            return _mega(params, dparams, tcache, dcache, tok, temp,
                         keys, active, eos, left, steps, table)
    else:
        def mega(params, dparams, tcache, dcache, tok, temp, keys,
                 active, eos, left, steps):
            return _mega(params, dparams, tcache, dcache, tok, temp,
                         keys, active, eos, left, steps, None)

    return jax.jit(mega, donate_argnums=(2, 3))


@functools.lru_cache(maxsize=16)
def _cached_round_fn(cfg, dcfg, spec_k, top_k, top_p, mesh):
    """Round programs keyed by (configs, K, filters, mesh) so repeated
    speculative_generate calls (bench sweeps, tests) reuse compiles."""
    return make_spec_round_fn(cfg, dcfg, spec_k, top_k, top_p, mesh=mesh)


@functools.lru_cache(maxsize=16)
def _cached_prefill(cfg, alloc_len, mesh):
    return jax.jit(lambda p, t: D.prefill(p, cfg, t, alloc_len, mesh=mesh))


# ---------------------------------------------------------------------------
# Host side: the standalone generate loop
# ---------------------------------------------------------------------------


def speculative_generate(params: Dict[str, Any],
                         draft_params: Dict[str, Any],
                         cfg: LlamaConfig, draft_cfg: LlamaConfig,
                         prompt: jax.Array, *, max_new_tokens: int,
                         spec_k: int = 4, temperature: float = 0.0,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None,
                         key: Optional[jax.Array] = None,
                         max_len: Optional[int] = None,
                         eos_token: Optional[int] = None, mesh=None,
                         return_stats: bool = False):
    """Speculative counterpart of decode.generate: prompt [B, S] ->
    [B, S + max_new_tokens].  At temperature 0 the output is exactly
    token-identical to ``decode.generate`` (greedy acceptance only ever
    commits tokens the target itself would have produced); at
    temperature > 0 rejection sampling preserves the target
    distribution (streams differ from generate's — distributional, not
    bitwise, equivalence).  Host-driven: rounds commit a data-dependent
    1..spec_k+1 tokens each, so the loop runs until every lane has its
    budget (lanes that finish early freeze via the active mask).

    ``mesh`` (make_serving_mesh): BOTH param trees must be laid out
    with decode.shard_params_for_serving; the draft's single-token
    steps and the chunked verify ride the same tp axis.

    ``return_stats``: also return {"accept_rate", "accepted",
    "drafted", "rounds", "spec_k"} — the serving acceptance telemetry.
    """
    check_draft_compat(cfg, draft_cfg)
    if spec_k < 1:
        raise ValueError(f"spec_k must be >= 1 (got {spec_k})")
    b, s = prompt.shape
    cache_len = max_len or cfg.max_seq_len
    need = s + max_new_tokens
    if need > cache_len:
        raise ValueError(f"prompt ({s}) + max_new_tokens "
                         f"({max_new_tokens}) = {need} exceeds the cache "
                         f"({cache_len} positions)")
    # a verify round may write spec_k rows past the last committed
    # token; grow the allocation within the RoPE table and fail clearly
    # when it cannot fit
    alloc_len = min(cfg.max_seq_len, cache_len + spec_k)
    if need + spec_k - 1 > D.cache_alloc_len(alloc_len):
        raise ValueError(
            f"speculative decoding needs {spec_k - 1} positions of cache "
            f"headroom past prompt+max_new_tokens ({need}) but the RoPE "
            f"table caps the allocation at {alloc_len} "
            f"(cfg.max_seq_len={cfg.max_seq_len}); lower spec_k or "
            f"max_new_tokens")
    if alloc_len > draft_cfg.max_seq_len:
        raise ValueError(
            f"draft max_seq_len ({draft_cfg.max_seq_len}) is smaller than "
            f"the serving context ({alloc_len}); derive the draft with "
            f"cfg.draft() to inherit the target's RoPE table")
    if key is None:
        key = jax.random.PRNGKey(0)
    keys = jax.random.split(key, b)

    logits, tc = _cached_prefill(cfg, alloc_len, mesh)(params, prompt)
    _, dc = _cached_prefill(draft_cfg, alloc_len, mesh)(draft_params,
                                                        prompt)
    # two distinct pos buffers: the round donates BOTH caches, and a
    # shared array would be donated twice
    tcache = {"k": tc["k"], "v": tc["v"],
              "pos": jnp.full((b,), s, jnp.int32)}
    dcache = {"k": dc["k"], "v": dc["v"],
              "pos": jnp.full((b,), s, jnp.int32)}

    temp_vec = jnp.full((b,), float(temperature), jnp.float32)
    if temperature <= 0:
        tok = logits.argmax(-1).astype(jnp.int32)
    else:
        filt = D._filter_logits(logits / temperature, top_k, top_p)
        tok = jax.vmap(lambda u, l: jax.random.categorical(u, l))(
            jax.vmap(lambda u: jax.random.fold_in(u, 0))(keys),
            filt).astype(jnp.int32)

    out = [[] for _ in range(b)]
    done = [False] * b
    first = np.asarray(tok)
    for i in range(b):
        t0 = int(first[i])
        out[i].append(t0)
        if eos_token is not None and t0 == eos_token:
            done[i] = True

    round_fn = _cached_round_fn(cfg, draft_cfg, spec_k, top_k, top_p, mesh)
    accepted = drafted = rounds = 0
    while True:
        act = [not done[i] and len(out[i]) < max_new_tokens
               for i in range(b)]
        if not any(act):
            break
        tcache, dcache, tok, committed, n_commit = round_fn(
            params, draft_params, tcache, dcache, tok, temp_vec, keys,
            jnp.asarray(act))
        committed = np.asarray(committed)             # [K+1, B]
        n_commit = np.asarray(n_commit)
        rounds += 1
        for i in range(b):
            if not act[i]:
                continue
            n = int(n_commit[i])
            drafted += spec_k
            accepted += n - 1
            for t in committed[:n, i]:
                if len(out[i]) >= max_new_tokens:
                    break
                out[i].append(int(t))
                if eos_token is not None and int(t) == eos_token:
                    done[i] = True
                    break

    # finished lanes keep emitting eos for their remaining positions —
    # decode.generate's static-shape eos semantics
    pad = eos_token if eos_token is not None else 0
    res = np.full((b, s + max_new_tokens), pad, np.int32)
    res[:, :s] = np.asarray(prompt)
    for i in range(b):
        res[i, s:s + len(out[i])] = out[i]
    tokens = jnp.asarray(res, prompt.dtype)
    if return_stats:
        stats = {
            "accept_rate": round(accepted / drafted, 4) if drafted else 0.0,
            "accepted": accepted, "drafted": drafted,
            "rounds": rounds, "spec_k": spec_k,
        }
        return tokens, stats
    return tokens
