"""Deterministic chaos harness for the serving ring.

Fault-tolerance code that is only exercised by real outages is dead
code with a pager attached.  This module injects the failures
infer/resilience.py exists to absorb — at DETERMINISTIC points, so the
chaos suite (tests/test_resilience.py, the dryrun ``serve-chaos`` gate,
``make chaos``, bench.py ``measure_resilience``) reproduces bit-for-bit
run over run:

- faults fire at **dispatch indices**, not wall-clock times: the ring's
  dispatch counter is the injector's clock, so a schedule means the
  same thing on a fast TPU and a slow CI box;
- the only randomness (picking a victim lane when the schedule names
  none) comes from a **seeded** ``random.Random``.

Schedule syntax (also the ``TPUJOB_CHAOS`` env var)::

    kind@index[:arg][,kind@index[:arg]...]

    dispatch_fail@5          raise from the compiled dispatch #5
    dispatch_hang@9:2.5      sleep 2.5s inside dispatch #9 (stall)
    nan_lane@12:1            poison lane 1's KV with NaN before #12
    client_drop@7            cancel a resident request before #7
    pool_oom@3:2             next 2 pool allocations raise NoFreeBlocks

The injector wraps the executor's PLAN REPLAYER in place
(:meth:`ChaosInjector.install` — RingExecutor.replay, the one seam
every resident decode dispatch passes through, 1-step or fused
megastep), so admission, consume bookkeeping, and the self-healing
machinery all run their REAL code — only the device dispatch lies.
One replay == one dispatch index, whatever SERVE_MEGASTEP is.

This plane covers RING faults only.  The fleet's WIRE faults —
connection drops, truncation, corruption, duplicate delivery,
blackholes on the client/router/broker/prefill edges — are the
sibling plane, ``utils/wirechaos.py``, driven by the same grammar
under ``TPUJOB_WIRE_CHAOS`` (faults fire at per-edge REQUEST indices,
the wire's analogue of the dispatch counter).
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

CHAOS_ENV = "TPUJOB_CHAOS"
CHAOS_SEED_ENV = "TPUJOB_CHAOS_SEED"

KINDS = ("dispatch_fail", "dispatch_hang", "nan_lane", "client_drop",
         "pool_oom")


@dataclass
class ChaosEvent:
    kind: str
    at: int                        # dispatch index the event fires before
    arg: Optional[float] = None    # hang seconds / lane / alloc count


def parse_schedule(spec: str) -> List[ChaosEvent]:
    """``"dispatch_fail@5,nan_lane@12:1"`` -> events.  Raises ValueError
    on unknown kinds or malformed entries — a typo'd chaos schedule
    silently injecting nothing would fake a green gate."""
    events: List[ChaosEvent] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "@" not in part:
            raise ValueError(f"chaos entry {part!r}: expected kind@index")
        kind, rest = part.split("@", 1)
        if kind not in KINDS:
            raise ValueError(f"chaos kind {kind!r} not in {KINDS}")
        arg: Optional[float] = None
        if ":" in rest:
            rest, argstr = rest.split(":", 1)
            arg = float(argstr)
        events.append(ChaosEvent(kind, int(rest), arg))
    return events


class ChaosInjector:
    """Wraps a ContinuousBatcher's resident dispatch with a seeded
    fault schedule.  ``fired`` records (kind, dispatch_index) in firing
    order — the determinism assertion tests pin."""

    def __init__(self, schedule, seed: int = 0) -> None:
        if isinstance(schedule, str):
            schedule = parse_schedule(schedule)
        self.events: Dict[int, List[ChaosEvent]] = {}
        for ev in schedule:
            self.events.setdefault(ev.at, []).append(ev)
        self.rng = random.Random(seed)
        self.dispatches = 0
        self.fired: List[tuple] = []
        self.batcher: Any = None

    # -- wiring ------------------------------------------------------------

    def install(self, batcher) -> "ChaosInjector":
        """Replace the executor's plan replayer
        (RingExecutor.replay) with the faulting wrapper — the ONE path
        every resident dispatch takes (ISSUE 11), so the schedule means
        the same thing on a 1-step and an N-step ring.  Call BEFORE
        submitting work; the wrapper survives ring rebuilds
        (self-healing rebuilds state, not the executor object)."""
        self.batcher = batcher
        batcher.executor.replay = self._wrap(batcher.executor.replay)
        return self

    def _wrap(self, real):
        def step(*args):
            idx = self.dispatches
            self.dispatches += 1
            for ev in self.events.get(idx, ()):
                self._apply(ev, idx, args)
            return real(*args)

        return step

    # -- faults ------------------------------------------------------------

    def _apply(self, ev: ChaosEvent, idx: int, args) -> None:
        self.fired.append((ev.kind, idx))
        # flight recorder (ISSUE 15): every injected fault lands in
        # the pod's event ring AND forces a dump — the chaos suite
        # asserts the dump NAMES the injected fault, which is exactly
        # the property a real incident's post-mortem needs.  Recorded
        # BEFORE the fault fires: dispatch_fail raises out of this
        # frame.
        fr = getattr(self.batcher, "flightrec", None)
        if fr is not None:
            fr.record("chaos_injected", fault=ev.kind, dispatch=idx,
                      arg=ev.arg)
            fr.dump_file(f"chaos:{ev.kind}")
        if ev.kind == "dispatch_fail":
            raise RuntimeError(
                f"chaos: injected dispatch failure @ dispatch {idx}")
        if ev.kind == "dispatch_hang":
            time.sleep(ev.arg if ev.arg is not None else 1.0)
            return
        if ev.kind == "pool_oom":
            pool = getattr(self.batcher, "pool", None)
            if pool is not None:
                pool.chaos_fail_allocs += int(ev.arg or 1)
            return
        if ev.kind == "client_drop":
            slot = self._victim(ev)
            if slot is not None:
                req = self.batcher.lane[slot]
                if req is not None:
                    req.cancel()
            return
        if ev.kind == "nan_lane":
            slot = self._victim(ev)
            if slot is not None:
                self._poison(slot)

    def _victim(self, ev: ChaosEvent) -> Optional[int]:
        """The schedule's lane, or a seeded pick among resident lanes
        (None when the ring is idle — the event is recorded but a fault
        with no victim is a no-op)."""
        if ev.arg is not None:
            return int(ev.arg)
        active = [i for i, r in enumerate(self.batcher.lane)
                  if r is not None]
        if not active:
            return None
        return self.rng.choice(active)

    def _poison(self, slot: int) -> None:
        """Write NaN into lane ``slot``'s K cache so its next logits go
        non-finite.  Lanes are attention-independent, so ONLY this
        lane's stream is poisoned — the quarantine path must fail one
        request and leave every other stream bit-identical.  Runs on
        the ring thread (inside the wrapped dispatch), so mutating
        ``batcher.cache`` is ordered with the real dispatches."""
        import numpy as np

        b = self.batcher
        if getattr(b, "paged", False):
            if getattr(b.executor, "quant", False):
                # int8 codes cannot hold a NaN (the cast would just
                # produce a finite garbage value) — poison the lane's
                # PRIVATE bf16 staging tail (write-frontier reads)
                # AND the scale planes of its private mapped blocks.
                # The tail alone has a washout hole: injected at
                # pos % bs == 0 the fresh tail row is fully
                # overwritten by real writes before any offset
                # becomes attendable and the fault silently vanishes
                # (~1/block_size of injections).  A NaN SCALE makes
                # every dequantized read of a committed block
                # non-finite; the frontier block's scale is
                # overwritten at its commit, so set every private
                # block (any committed one triggers).  The quarantine
                # scrub resets both (scales -> sentinel, tail -> 0).
                b.cache["kt"] = b.cache["kt"].at[:, slot].set(np.nan)
                pool = b.pool
                row = pool.table[slot]
                for j in range(pool.mapped_count[slot]):
                    blk = int(row[j])
                    if pool.ref[blk] == 1 and blk not in pool.by_block:
                        b.cache["ks"] = b.cache["ks"].at[:, blk].set(
                            np.nan)
                return
            # poison one PRIVATE (refcount-1, uncached) mapped block —
            # a shared prefix block would poison other lanes' streams
            pool = b.pool
            row = pool.table[slot]
            for j in range(pool.mapped_count[slot]):
                blk = int(row[j])
                if pool.ref[blk] == 1 and blk not in pool.by_block:
                    b.cache["k"] = b.cache["k"].at[:, blk].set(np.nan)
                    return
            return
        b.cache["k"] = b.cache["k"].at[:, slot].set(np.nan)


def maybe_install_from_env(batcher, env=None) -> Optional[ChaosInjector]:
    """serve.py hook: ``TPUJOB_CHAOS`` set -> install the injector on
    the live server's ring (smoke-testing a deployment's resilience
    end-to-end); unset -> no-op."""
    env = os.environ if env is None else env
    spec = env.get(CHAOS_ENV, "")
    if not spec:
        return None
    seed = int(env.get(CHAOS_SEED_ENV, "0"))
    return ChaosInjector(spec, seed=seed).install(batcher)
