"""Live-swap driver CLI (ISSUE 19) — the deploy tool's view of one
replica's ``/v1/swap``.

    python -m paddle_operator_tpu.infer.swapctl \
        --url http://127.0.0.1:9000 [--checkpoint /path] [--tp 2] \
        [--generation 3] [--weight-quant int8] [--timeout-s 120]

POSTs the swap request, prints the post-swap summary JSON on stdout,
and exits 0 on success.  A 503 (the ring is draining/rebuilding or
never reached a quiesced boundary) retries with backoff up to
``--retries``; a 4xx is terminal — the request itself is wrong.
``--wait-generation N`` instead polls ``/statusz`` until the replica
reports ``weightGeneration >= N`` (the fleet roll's convergence probe,
usable standalone after an out-of-band swap).

Runs as a SUBPROCESS of the serve-swap dryrun gate and of
``bench.measure_weight_swap`` — the tier-1 preflight pgrep names this
module so a wedged driver from a previous session fails the timed run
loudly instead of skewing it.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional


def post_swap(url: str, body: Dict[str, Any], *,
              timeout_s: float = 180.0) -> Dict[str, Any]:
    """One ``/v1/swap`` POST; returns the parsed summary.  Raises
    ``urllib.error.HTTPError`` on non-200 (the caller decides whether
    the status is retriable)."""
    req = urllib.request.Request(
        url.rstrip("/") + "/v1/swap",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return json.loads(r.read())


def poll_generation(url: str, generation: int, *,
                    timeout_s: float = 120.0,
                    interval_s: float = 0.2) -> Optional[Dict[str, Any]]:
    """Poll ``/statusz`` until ``weightGeneration >= generation``;
    returns the converged status block, or None on timeout."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                    url.rstrip("/") + "/statusz", timeout=10) as r:
                st = json.loads(r.read())
            if int(st.get("weightGeneration", -1)) >= int(generation):
                return st
        except (urllib.error.URLError, OSError, ValueError):
            pass                    # replica mid-flip: keep polling
        time.sleep(interval_s)
    return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="drive one replica's live weight swap")
    ap.add_argument("--url", required=True,
                    help="replica base URL (http://host:port)")
    ap.add_argument("--checkpoint", default=None,
                    help="checkpoint path to swap to (omitted: the "
                    "replica rebuilds from its retained boot base)")
    ap.add_argument("--draft-checkpoint", default=None)
    ap.add_argument("--tp", type=int, default=None,
                    help="target tensor-parallel degree (elastic "
                    "resize); omitted keeps the mesh")
    ap.add_argument("--generation", type=int, default=None,
                    help="explicit target generation (omitted: +1)")
    ap.add_argument("--weight-quant", default=None,
                    choices=["none", "int8", "int4"])
    ap.add_argument("--draft-quant", default=None,
                    choices=["none", "int8", "int4"])
    ap.add_argument("--timeout-s", type=float, default=120.0)
    ap.add_argument("--retries", type=int, default=5,
                    help="503 retries (draining/boundary-timeout)")
    ap.add_argument("--wait-generation", type=int, default=None,
                    help="poll /statusz for this generation instead "
                    "of posting a swap")
    args = ap.parse_args(argv)

    if args.wait_generation is not None:
        st = poll_generation(args.url, args.wait_generation,
                             timeout_s=args.timeout_s)
        if st is None:
            print(json.dumps({"error": "generation wait timed out"}),
                  file=sys.stderr)
            return 1
        print(json.dumps({"weightGeneration": st["weightGeneration"],
                          "servingTp": st.get("servingTp")}))
        return 0

    body: Dict[str, Any] = {"timeout_s": args.timeout_s}
    for k, v in (("checkpoint", args.checkpoint),
                 ("draft_checkpoint", args.draft_checkpoint),
                 ("tp", args.tp), ("generation", args.generation),
                 ("weight_quant", args.weight_quant),
                 ("draft_quant", args.draft_quant)):
        if v is not None:
            body[k] = v
    backoff = 0.5
    for attempt in range(args.retries + 1):
        try:
            res = post_swap(args.url, body,
                            timeout_s=args.timeout_s + 60)
            print(json.dumps(res))
            return 0
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:300]
            if e.code == 503 and attempt < args.retries:
                # replica draining / boundary timeout: retriable
                time.sleep(backoff)
                backoff = min(backoff * 2, 8.0)
                continue
            print(json.dumps({"error": f"HTTP {e.code}: {detail}"}),
                  file=sys.stderr)
            return 1
        except (urllib.error.URLError, OSError) as e:
            if attempt < args.retries:
                time.sleep(backoff)
                backoff = min(backoff * 2, 8.0)
                continue
            print(json.dumps({"error": str(e)}), file=sys.stderr)
            return 1
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
