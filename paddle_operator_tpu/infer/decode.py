"""Autoregressive KV-cache decoding for the LLaMA family.

The reference delegates ALL model execution to user containers; a complete
framework also needs the serving-shaped path.  TPU-native design:

- **Static shapes throughout**: the KV cache is a fixed-size ring of
  ``[L, B, H_kv, max_len, D]`` arrays and the generation loop is a
  ``lax.scan`` over ``max_new_tokens`` — one compile serves any
  prompt/continuation length ≤ max_len (no shape-polymorphic retraces).
- **Pure functions over the trained param tree**: decode consumes the
  exact pytree ``train/trainer.py`` optimizes (scanned ``layers`` layout),
  so a checkpoint restored by ``train/checkpoint.py`` serves directly.
  The layer math mirrors ``models/llama.py`` (RMSNorm → GQA attention
  with the split-halves RoPE → SwiGLU); equivalence is pinned by
  tests/test_decode.py, which asserts decode logits match the training
  forward position-for-position.
- Prefill processes the whole prompt in one pass (MXU-friendly [B, S]
  matmuls + causal mask against the cache); the step loop then decodes
  one token per scan tick with single-query attention over the cache.

MoE configs decode with exact no-drop top-1 routing (the training layer's
capacity buffer is a static-shape device whose drops are an
approximation; inference computes the conditional model directly).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_operator_tpu.models.llama import LlamaConfig, rope_frequencies


# ---------------------------------------------------------------------------
# Mesh-sharded serving (tensor parallel over heads/ffn/vocab)
# ---------------------------------------------------------------------------


def mesh_tp(mesh) -> int:
    """Size of the mesh's ``tp`` axis (1 for no mesh) — the one axis the
    serving path shards over (parallel/mesh.py make_serving_mesh)."""
    if mesh is None:
        return 1
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get("tp", 1)


def shard_params_for_serving(params: Dict[str, Any], cfg: LlamaConfig,
                             mesh) -> Dict[str, Any]:
    """Lay the serving param tree onto ``mesh``: the training partition
    table (models/llama.py partition_patterns — heads/mlp/vocab → tp)
    applied with indivisible axes replicated, which covers weight-only
    int8 scale leaves whose contraction dim collapsed to 1.  Works on
    raw bf16/f32 trees and quantize_params output alike."""
    from paddle_operator_tpu.models.llama import partition_patterns
    from paddle_operator_tpu.parallel.sharding import tree_shardings

    return jax.device_put(
        params, tree_shardings(params, mesh, partition_patterns(cfg),
                               replicate_indivisible=True))


def _use_sharded_kernel(cfg: LlamaConfig, mesh, attn_impl: str) -> bool:
    """THE kernel-eligibility rule for tp>1 meshes, shared by
    decode._forward and batcher._ring_forward: the pallas kernel enters
    a sharded mesh only through shard_map (sharded_decode_attention)
    and only when whole GQA groups split; everything else serves
    through the GSPMD einsum path."""
    return (mesh is not None and mesh_tp(mesh) > 1
            and attn_impl != "xla"
            and cfg.decode_tp_compatible(mesh_tp(mesh)))


def alloc_kv_buffer(cfg: LlamaConfig, shape, mesh) -> jax.Array:
    """One KV cache buffer (decode scalar cache or ring cache — they
    differ only in the batch/lane dim), sharded over the kv-head axis
    when the serving mesh can split it: every cache shard lives with
    the wk/wv shard that fills it.  Indivisible kv heads leave the
    buffer replicated — the GSPMD einsum fallback handles it.  Callers
    allocate k and v separately: the jitted steps donate them as
    distinct buffers."""
    buf = jnp.zeros(shape, cfg.dtype)
    if (mesh is not None and mesh_tp(mesh) > 1
            and cfg.n_kv_heads % mesh_tp(mesh) == 0):
        from paddle_operator_tpu.parallel.sharding import kv_cache_sharding

        buf = jax.device_put(buf, kv_cache_sharding(mesh))
    return buf


def _rms(x: jax.Array, scale: jax.Array, eps: float, dtype) -> jax.Array:
    """models/llama.py RMSNorm math, f32 internals."""
    xf = x.astype(jnp.float32)
    norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (norm * scale.astype(jnp.float32)).astype(dtype)


def _mm(x: jax.Array, kernel_leaf, dtype) -> jax.Array:
    """x @ kernel for a raw or weight-only-int8 kernel leaf
    (infer/quant.py): the convert-then-dot form lets XLA fuse the
    dequant into the dot's weight stream (measured fastest — see the
    "what bounds int8" note in infer/quant.py; a hand-written pallas
    dequant-in-register kernel LOST to this lowering at model level).
    The per-output-channel scale applies after the matmul (valid because
    the scale is constant along the contraction dim)."""
    if isinstance(kernel_leaf, dict) and "q" in kernel_leaf:
        out = x @ kernel_leaf["q"].astype(dtype)
        return out * kernel_leaf["s"][..., 0, :].astype(dtype)
    return x @ kernel_leaf.astype(dtype)


def _rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
          pos: jax.Array) -> jax.Array:
    """Split-halves RoPE at dynamic offset ``pos`` (mirrors
    models/llama.py apply_rope, which slices at a static offset)."""
    t = x.shape[1]
    cos = jax.lax.dynamic_slice_in_dim(cos, pos, t)[None, :, None, :]
    sin = jax.lax.dynamic_slice_in_dim(sin, pos, t)[None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


def cache_alloc_len(max_len: int) -> int:
    """Allocation length for a KV cache of logical capacity ``max_len``:
    rounded up to a whole number of pallas key blocks
    (ops/decode_attention.py DEFAULT_BLOCK_K) so the kernel never has to
    shrink its block to divide an odd length — S=2240 would force
    64-wide blocks whose per-cell overhead measured 4x slower than
    256-wide.  Padding is dead weight only to the einsum path (it reads
    the full allocation), bounded at +255 positions — noise next to the
    weight stream at short caches and <12% of cache bytes beyond 2k.
    Lengths within one block stay exact (tiny test caches)."""
    from paddle_operator_tpu.ops.decode_attention import DEFAULT_BLOCK_K

    if max_len <= DEFAULT_BLOCK_K:
        return max_len
    return -(-max_len // DEFAULT_BLOCK_K) * DEFAULT_BLOCK_K


def init_cache(cfg: LlamaConfig, batch: int,
               max_len: Optional[int] = None,
               mesh=None) -> Dict[str, jax.Array]:
    """Fixed-size KV cache: k/v [L, B, H_kv, alloc, D] in compute
    dtype, plus the fill position (scalar int32).  Head-major layout:
    per-head rows are contiguous, which is what both the XLA attention
    einsums and the pallas decode kernel (ops/decode_attention.py) want
    as their DMA/contraction unit — token-major measured 0.64x on the
    kernel from per-head strided relayouts.  The allocation is
    block-aligned (:func:`cache_alloc_len`); positions past the LOGICAL
    ``max_len`` are never written or attended (the fill mask covers
    them), so the RoPE bound below checks the requested capacity, not
    the padded allocation.  max_len may not exceed cfg.max_seq_len:
    positions past the RoPE table would silently clamp (dynamic_slice
    semantics) and corrupt the rotary phases."""
    max_len = max_len or cfg.max_seq_len
    if max_len > cfg.max_seq_len:
        raise ValueError(f"cache max_len {max_len} exceeds the RoPE table "
                         f"(cfg.max_seq_len={cfg.max_seq_len})")
    alloc = cache_alloc_len(max_len)
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, alloc, cfg.head_dim)
    return {
        "k": alloc_kv_buffer(cfg, shape, mesh),
        "v": alloc_kv_buffer(cfg, shape, mesh),
        "pos": jnp.zeros((), jnp.int32),
    }


def _qkv(cfg: LlamaConfig, lp: Dict[str, Any], x: jax.Array,
         cos: jax.Array, sin: jax.Array, pos: jax.Array,
         lora=None) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pre-attention half of a decoder layer: RMSNorm -> q/k/v
    projections -> RoPE at offset ``pos``.  Shapes [B, T, H, D].

    ``lora`` (ISSUE 10 many-adapter serving): ``(adp_l, aid)`` — one
    layer's stacked LoRA arrays + per-row adapter ids; the low-rank
    delta adds to the projection outputs BEFORE RoPE (qos.lora_qkv),
    so adapter KV enters the cache exactly as a merged-weight forward
    would produce it."""
    b, t, _ = x.shape
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = _rms(x, lp["attn_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    q = _mm(h, lp["attn"]["wq"]["kernel"], cfg.dtype)
    k = _mm(h, lp["attn"]["wk"]["kernel"], cfg.dtype)
    v = _mm(h, lp["attn"]["wv"]["kernel"], cfg.dtype)
    if lora is not None:
        from paddle_operator_tpu.infer.qos import lora_qkv

        q, k, v = lora_qkv(h, lora[0], lora[1], q, k, v, cfg.dtype)
    q = q.reshape(b, t, hq, d)
    k = k.reshape(b, t, hkv, d)
    v = v.reshape(b, t, hkv, d)
    return _rope(q, cos, sin, pos), _rope(k, cos, sin, pos), v


def _ffn_residual(cfg: LlamaConfig, lp: Dict[str, Any],
                  x: jax.Array) -> jax.Array:
    """The FFN half of a decoder layer: norm -> (SwiGLU or MoE) -> +x.
    Split out of :func:`_finish_layer` because the TP-sharded kernel
    path applies the output projection INSIDE its shard_map region
    (attention out is head-sharded there; the wo contraction + psum is
    the Megatron row-parallel reduction) and re-enters GSPMD here."""
    n = _rms(x, lp["mlp_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    if cfg.n_experts > 0:
        ffn = _moe_ffn(cfg, lp["moe"], n)
    else:
        gate = _mm(n, lp["mlp"]["w1"]["kernel"], cfg.dtype)
        up = _mm(n, lp["mlp"]["w3"]["kernel"], cfg.dtype)
        ffn = _mm(jax.nn.silu(gate) * up, lp["mlp"]["w2"]["kernel"],
                  cfg.dtype)
    return x + ffn


def _finish_layer(cfg: LlamaConfig, lp: Dict[str, Any], x: jax.Array,
                  out: jax.Array) -> jax.Array:
    """Post-attention half: output projection + residual, then the
    (dense SwiGLU or MoE) FFN + residual."""
    x = x + _mm(out, lp["attn"]["wo"]["kernel"], cfg.dtype)
    return _ffn_residual(cfg, lp, x)


def _layer(cfg: LlamaConfig, lp: Dict[str, Any], x: jax.Array,
           cos: jax.Array, sin: jax.Array, k_cache: jax.Array,
           v_cache: jax.Array, pos: jax.Array, lora=None
           ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decoder layer over [B, T] new positions starting at ``pos``,
    attending to the cache's [0, pos+T), with the XLA einsum attention.
    Returns (y, k_cache', v_cache').  lp is ONE layer's param subtree
    (unstacked); caches are head-major [B, H_kv, S, D] (init_cache).
    The pallas decode path does NOT go through here — it keeps the
    caches stacked (see _forward) so the kernel reads them copy-free."""
    b, t, _ = x.shape
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    q, k, v = _qkv(cfg, lp, x, cos, sin, pos, lora=lora)

    # [B, T, H, D] -> head-major [B, H, T, D] rows into the cache
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k.transpose(0, 2, 1, 3), (0, 0, pos, 0))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v.transpose(0, 2, 1, 3), (0, 0, pos, 0))

    # GQA: group query heads onto kv heads; single-query (or prefill-
    # block) attention against the cache with a causal+fill mask.  The
    # einsums read the cache in its storage dtype and accumulate in f32
    # (preferred_element_type) — upcasting the cache itself would
    # stream a full f32 copy of it from HBM every step, doubling the
    # bandwidth of the decode hot loop.
    n_rep = hq // hkv
    max_len = k_cache.shape[2]
    qg = q.reshape(b, t, hkv, n_rep, d)
    # scores [B, T, Hkv, n_rep, max_len]; rows may attend cache cols
    # up to their own absolute position (causal + fill mask in one)
    scores = jnp.einsum("bthrd,bhsd->bthrs", qg, k_cache,
                        preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(d))
    cols = jnp.arange(max_len)                           # [S]
    rows = pos + jnp.arange(t)                           # [T]
    mask = cols[None, :] <= rows[:, None]                # [T, S]
    scores = jnp.where(mask[None, :, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bthrs,bhsd->bthrd", probs.astype(cfg.dtype),
                     v_cache, preferred_element_type=jnp.float32)
    out = out.reshape(b, t, hq * d).astype(cfg.dtype)
    return _finish_layer(cfg, lp, x, out), k_cache, v_cache


def _moe_ffn(cfg: LlamaConfig, mp: Dict[str, Any],
             n: jax.Array) -> jax.Array:
    """Top-k MoE FFN at inference: exact conditional computation with NO
    capacity dropping (the capacity buffer of models/moe.py is a
    training-time static-shape device; drops are its approximation, not
    the model).  Experts run under lax.scan so peak memory is one
    expert's activations, then each token combines its top-k experts'
    outputs — raw Switch gate at k=1, GShard-renormalized gates at
    k>1, mirroring the training layer's routing rule."""
    from paddle_operator_tpu.models.moe import route_top_k

    b, t, d = n.shape
    kk = cfg.moe_top_k
    tokens = n.reshape(b * t, d)
    probs = jax.nn.softmax(
        tokens.astype(jnp.float32)
        @ mp["router"]["kernel"].astype(jnp.float32), axis=-1)
    gates, topi = route_top_k(probs, kk)                    # [T, k]

    def one_expert(_, w):
        w1_e, w2_e = w
        h = jax.nn.gelu(_mm(tokens, w1_e, cfg.dtype))
        return None, _mm(h, w2_e, cfg.dtype)                # [T, D]

    _, outs = jax.lax.scan(one_expert, None,
                           (mp["w1"], mp["w2"]))            # [E, T, D]
    sel = jnp.sum(jax.nn.one_hot(topi, cfg.n_experts, dtype=jnp.float32)
                  * gates[:, :, None], axis=1)              # [T, E]
    out = jnp.einsum("te,etd->td", sel.astype(cfg.dtype), outs)
    return out.reshape(b, t, d)


def _forward(cfg: LlamaConfig, params: Dict[str, Any], tokens: jax.Array,
             cache: Dict[str, jax.Array], *, last_only: bool = False,
             mesh=None, lora=None
             ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """[B, T] new tokens at cache['pos'] -> ([B, T, vocab] logits,
    advanced cache).  Layers run under lax.scan over the stacked params
    (the same ``layers`` layout nn.scan trains).

    ``last_only``: apply the norm + lm head to the final position only
    (logits [B, 1, vocab]) — prefill needs just the next-token logits,
    and head logits over a whole long prompt are the biggest tensor in
    the decode path ([B, S, V] f32 — gigabytes at real vocab sizes).

    ``mesh``: a serving mesh with a tp axis (make_serving_mesh) makes
    the whole forward tensor-parallel: the einsum/matmul structure rides
    GSPMD off the param/cache shardings, and the pallas kernel enters
    through its own shard_map with a per-layer wo psum
    (sharded_decode_attention).  Configs the kernel cannot split
    (decode_tp_compatible) fall back to the GSPMD einsum path whole.

    ``lora``: ``(adp, aid)`` — stacked [L, ...] adapter arrays riding
    the layer scan as xs, per-row adapter ids (infer/qos.py)."""
    pos = cache["pos"]
    adp, aid = lora if lora is not None else (None, None)
    x = params["tok_embed"]["embedding"].astype(cfg.dtype)[tokens]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                cfg.rope_theta)

    attn_impl = cfg.resolved_decode_attn()
    tp = mesh_tp(mesh)
    use_sharded = _use_sharded_kernel(cfg, mesh, attn_impl)
    if tp > 1 and not use_sharded:
        attn_impl = "xla"   # kernel can't split whole GQA groups: GSPMD
    if tokens.shape[1] == 1 and use_sharded:
        # TP-sharded kernel: same stacked-cache scan as below, but the
        # attention + output projection run inside one manual region per
        # layer (ops/decode_attention.py sharded_decode_attention)
        from paddle_operator_tpu.ops.decode_attention import (
            sharded_decode_attention,
        )

        b = x.shape[0]

        def body(carry, layer_in):
            x, kc, vc = carry
            if adp is not None:
                lp, adp_l, li = layer_in
                lo = (adp_l, aid)
            else:
                lp, li = layer_in
                lo = None
            q, k, v = _qkv(cfg, lp, x, cos, sin, pos, lora=lo)
            kc = jax.lax.dynamic_update_slice(
                kc, k.transpose(0, 2, 1, 3)[None], (li, 0, 0, pos, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, v.transpose(0, 2, 1, 3)[None], (li, 0, 0, pos, 0))
            proj = sharded_decode_attention(
                mesh, q[:, 0], kc, vc, jnp.broadcast_to(pos + 1, (b,)),
                lp["attn"]["wo"]["kernel"], layer=li,
                interpret=(attn_impl == "pallas-interpret"),
                compute_dtype=cfg.dtype)
            x = x + proj[:, None].astype(cfg.dtype)
            return (_ffn_residual(cfg, lp, x), kc, vc), ()

        xs = ((params["layers"], adp, jnp.arange(cfg.n_layers))
              if adp is not None
              else (params["layers"], jnp.arange(cfg.n_layers)))
        (x, k_new, v_new), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]), xs)
    elif tokens.shape[1] == 1 and attn_impl != "xla":
        # pallas decode path: the caches stay STACKED [L, B, H, S, D]
        # and flow as scan CARRY, with the layer index steering the
        # kernel's block index map.  Scanning them as xs (the einsum
        # structure below) would slice each layer out first, and a
        # dynamic-slice that feeds a pallas custom-call must be
        # materialized by XLA — a per-layer copy of the layer's whole
        # cache, measured +170us/layer at b8.
        from paddle_operator_tpu.ops.decode_attention import decode_attention

        b = x.shape[0]
        hq, d = cfg.n_heads, cfg.head_dim

        def body(carry, layer_in):
            x, kc, vc = carry
            if adp is not None:
                lp, adp_l, li = layer_in
                lo = (adp_l, aid)
            else:
                lp, li = layer_in
                lo = None
            q, k, v = _qkv(cfg, lp, x, cos, sin, pos, lora=lo)
            kc = jax.lax.dynamic_update_slice(
                kc, k.transpose(0, 2, 1, 3)[None], (li, 0, 0, pos, 0))
            vc = jax.lax.dynamic_update_slice(
                vc, v.transpose(0, 2, 1, 3)[None], (li, 0, 0, pos, 0))
            out = decode_attention(
                q[:, 0], kc, vc, jnp.broadcast_to(pos + 1, (b,)),
                layer=li, interpret=(attn_impl == "pallas-interpret"))
            out = out.reshape(b, 1, hq * d).astype(cfg.dtype)
            return (_finish_layer(cfg, lp, x, out), kc, vc), ()

        xs = ((params["layers"], adp, jnp.arange(cfg.n_layers))
              if adp is not None
              else (params["layers"], jnp.arange(cfg.n_layers)))
        (x, k_new, v_new), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]), xs)
    else:
        def body(x, layer_in):
            if adp is not None:
                lp, adp_l, k_c, v_c = layer_in
                lo = (adp_l, aid)
            else:
                lp, k_c, v_c = layer_in
                lo = None
            y, k_c, v_c = _layer(cfg, lp, x, cos, sin, k_c, v_c, pos,
                                 lora=lo)
            return y, (k_c, v_c)

        xs = ((params["layers"], adp, cache["k"], cache["v"])
              if adp is not None
              else (params["layers"], cache["k"], cache["v"]))
        x, (k_new, v_new) = jax.lax.scan(body, x, xs)
    if last_only:
        x = x[:, -1:]
    x = _rms(x, params["final_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    logits = _mm(x, params["lm_head"]["kernel"],
                 cfg.dtype).astype(jnp.float32)
    new_cache = {"k": k_new, "v": v_new,
                 "pos": pos + tokens.shape[1]}
    return logits, new_cache


def prefill(params: Dict[str, Any], cfg: LlamaConfig, tokens: jax.Array,
            max_len: Optional[int] = None, mesh=None
            ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Process the whole prompt [B, S] in one pass.  Returns
    ([B, vocab] last-position logits, filled cache)."""
    cache_len = max_len or cfg.max_seq_len
    if tokens.shape[1] > cache_len:
        raise ValueError(f"prompt length {tokens.shape[1]} exceeds the "
                         f"cache ({cache_len} positions)")
    cache = init_cache(cfg, tokens.shape[0], max_len, mesh=mesh)
    logits, cache = _forward(cfg, params, tokens, cache, last_only=True,
                             mesh=mesh)
    return logits[:, 0], cache


def paged_prefill(params: Dict[str, Any], cfg: LlamaConfig,
                  tokens: jax.Array, pool_cache: Dict[str, jax.Array],
                  table_row: jax.Array, *, block_size: Optional[int] = None,
                  mesh=None, quant: bool = False,
                  prompt_len: Optional[jax.Array] = None, lora=None):
    """Prefill a whole [1, bucket] prompt and write its KV into the
    PAGED block pool (infer/paged.py) as block-aligned chunks at the
    lane's ``table_row`` entries — the cold-admission half of paged
    serving.  The forward itself is exactly :func:`prefill`'s (same
    compiled ops — what keeps the paged ring's first token
    bit-identical to the contiguous ring's); only the destination
    changes: block ``j`` of the lane cache lands in pool block
    ``table_row[j]``, pad blocks land wherever the table maps them
    (the trash block when unmapped — exactness-with-padding,
    block-granular).  Returns ([1, bucket, vocab] logits — the caller
    samples at ``prompt_len - 1`` — and the pool cache with this
    lane's position untouched (the caller's insert sets it).

    ``quant=True`` (needs ``prompt_len``, traced): whole blocks
    quantize ONCE on the way into the int8 pool
    (ops/decode_attention.py scatter_prefill_blocks_quant), and the
    prompt's partial last block is returned as exact bf16 tail tiles
    ``(logits, cache', tail_k, tail_v)`` [L, 1, H, bs, D] for the
    caller's insert to splice into the lane's staging tail — the one
    block whose scale cannot be final yet."""
    from paddle_operator_tpu.infer.paged import _scatter_prompt_blocks

    bs = block_size or pool_cache["k"].shape[3]
    lane = init_cache(cfg, 1, tokens.shape[1])
    logits, lane = _forward(cfg, params, tokens, lane, mesh=mesh,
                            lora=lora)
    if not quant:
        k = _scatter_prompt_blocks(pool_cache["k"], lane["k"], table_row,
                                   bs)
        v = _scatter_prompt_blocks(pool_cache["v"], lane["v"], table_row,
                                   bs)
        return logits, {"k": k, "v": v, "pos": pool_cache["pos"]}
    from paddle_operator_tpu.ops.decode_attention import (
        scatter_prefill_blocks_quant,
    )

    if prompt_len is None:
        raise ValueError("quant paged_prefill needs prompt_len for the "
                         "staging-tail slice")
    k, ks = scatter_prefill_blocks_quant(
        pool_cache["k"], pool_cache["ks"], lane["k"], table_row, bs)
    v, vs = scatter_prefill_blocks_quant(
        pool_cache["v"], pool_cache["vs"], lane["v"], table_row, bs)
    # the write-frontier block's exact rows: [start, start + bs) of the
    # lane cache.  The lane alloc need not be a block multiple, and
    # dynamic_slice CLAMPS an out-of-range start backwards — which
    # would hand back rows of the PREVIOUS block at the wrong tail
    # offsets (positions start+o would attend K/V of start-pad+o) —
    # so pad the time axis up to a block multiple first.  The one
    # remaining clamp (block-aligned prompt filling the whole padded
    # alloc, start == padded len) is harmless: decode then begins a
    # FRESH block and every stale tail row sits behind the fill mask.
    L, _, h, t_alloc, dd = lane["k"].shape
    pad = -t_alloc % bs
    lane_k, lane_v = lane["k"], lane["v"]
    if pad:
        widths = ((0, 0), (0, 0), (0, 0), (0, pad), (0, 0))
        lane_k = jnp.pad(lane_k, widths)
        lane_v = jnp.pad(lane_v, widths)
    start = (prompt_len // bs) * bs
    tail_k = jax.lax.dynamic_slice(lane_k, (0, 0, 0, start, 0),
                                   (L, 1, h, bs, dd))
    tail_v = jax.lax.dynamic_slice(lane_v, (0, 0, 0, start, 0),
                                   (L, 1, h, bs, dd))
    cache = {"k": k, "v": v, "ks": ks, "vs": vs, "kt": pool_cache["kt"],
             "vt": pool_cache["vt"], "pos": pool_cache["pos"]}
    return logits, cache, tail_k, tail_v


def decode_step(params: Dict[str, Any], cfg: LlamaConfig,
                token: jax.Array, cache: Dict[str, jax.Array],
                mesh=None) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One token [B] -> next-position logits [B, vocab] + advanced cache."""
    logits, cache = _forward(cfg, params, token[:, None], cache, mesh=mesh)
    return logits[:, 0], cache


def make_decode_fn(cfg: LlamaConfig, mesh=None):
    """Jitted single-token step with the cache DONATED: driving
    decode_step yourself (serving loops, speculative drafts) without
    donation would copy the whole KV cache every step — for a 7B-shaped
    cache that is gigabytes of HBM traffic per token.  Inside
    :func:`generate` the scan already keeps the cache on-device, so this
    matters only for host-driven loops.

    Returns ``step(params, token [B], cache) -> (logits [B, V], cache)``;
    the passed cache buffer is consumed."""

    def step(params, token, cache):
        logits, cache = _forward(cfg, params, token[:, None], cache,
                                 mesh=mesh)
        return logits[:, 0], cache

    return jax.jit(step, donate_argnums=(2,))


def _filter_logits(logits: jax.Array, top_k: Optional[int],
                   top_p: Optional[float]) -> jax.Array:
    """Standard sampling filters, static-shaped: top-k keeps the k highest
    logits; top-p (nucleus) keeps the smallest set of tokens whose
    probability mass reaches p.  Filtered entries go to -inf."""
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens until the cumulative mass FIRST exceeds p (the
        # token crossing the threshold is kept — standard nucleus rule)
        keep_sorted = cum - probs < top_p
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def generate(params: Dict[str, Any], cfg: LlamaConfig, prompt: jax.Array,
             *, max_new_tokens: int, temperature: float = 0.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             key: Optional[jax.Array] = None,
             max_len: Optional[int] = None,
             eos_token: Optional[int] = None, mesh=None) -> jax.Array:
    """Greedy (temperature=0) or temperature sampling, with optional
    top-k / nucleus (top-p) filtering.  prompt [B, S] ->
    [B, S + max_new_tokens].  jit-friendly: the step loop is a lax.scan
    with static trip count (shapes never depend on when sequences stop).
    With ``eos_token``, a sequence that emits it keeps emitting eos for
    its remaining positions (the scan still runs max_new_tokens ticks —
    static shapes beat early exit on TPU).

    ``mesh`` (make_serving_mesh) serves tensor-parallel: params must be
    laid out with :func:`shard_params_for_serving`; output tokens are
    identical to the single-device path (same math, head-sharded)."""
    if temperature > 0 and key is None:
        key = jax.random.PRNGKey(0)
    need = prompt.shape[1] + max_new_tokens
    cache_len = max_len or cfg.max_seq_len
    if need > cache_len:
        raise ValueError(f"prompt ({prompt.shape[1]}) + max_new_tokens "
                         f"({max_new_tokens}) = {need} exceeds the cache "
                         f"({cache_len} positions)")

    logits, cache = prefill(params, cfg, prompt, max_len, mesh=mesh)
    done0 = jnp.zeros((prompt.shape[0],), bool)

    def sample(logits, k):
        if temperature <= 0:
            return logits.argmax(-1).astype(prompt.dtype)
        logits = _filter_logits(logits / temperature, top_k, top_p)
        return jax.random.categorical(k, logits).astype(prompt.dtype)

    def step(carry, k):
        logits, cache, done = carry
        tok = sample(logits, k)
        if eos_token is not None:
            tok = jnp.where(done, jnp.asarray(eos_token, tok.dtype), tok)
            done = done | (tok == eos_token)
        logits, cache = decode_step(params, cfg, tok, cache, mesh=mesh)
        return (logits, cache, done), tok

    keys = (jax.random.split(key, max_new_tokens) if temperature > 0
            else jnp.zeros((max_new_tokens, 2), jnp.uint32))
    (_, _, _), toks = jax.lax.scan(step, (logits, cache, done0), keys)
    return jnp.concatenate([prompt, toks.T], axis=1)


def speculative_generate(params, draft_params, cfg: LlamaConfig,
                         draft_cfg: LlamaConfig, prompt: jax.Array, **kw):
    """Draft-propose + chunked-verify counterpart of :func:`generate`:
    a small draft model (``LlamaConfig.draft()``) proposes ``spec_k``
    tokens per round and the target verifies all of them in one
    multi-token forward — token-identical to :func:`generate` at
    temperature 0, distribution-preserving (rejection sampling) above.
    Implementation and the full contract live in infer/speculative.py;
    this re-export keeps the serving entrypoints in one module."""
    from paddle_operator_tpu.infer.speculative import (
        speculative_generate as _impl,
    )

    return _impl(params, draft_params, cfg, draft_cfg, prompt, **kw)
