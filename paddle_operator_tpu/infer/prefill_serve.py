"""Standalone prefill server — the cross-host half of disaggregation.

ISSUE 6 shipped DistServe-style disaggregated prefill IN-PROCESS: a
:class:`~paddle_operator_tpu.infer.executor.PrefillExecutor` thread with
its own block pool, handing completed prompts to the decode ring by
device-to-device block copy (``paged.make_pool_transfer`` — whose
docstring explicitly reserved "a DCN-crossing variant would replace
only this op").  This module is that variant (ISSUE 13): the SAME
``PrefillExecutor`` wrapped in its own HTTP process, so prefill
capacity scales in its OWN pods, independently of decode — the
DistServe argument realized at the pod level.

Protocol (one round-trip, prefill is side-effect-free so retries are
always safe):

    POST /v1/prefill   {"tokens": [...], "temperature": t, "seed": s,
                        "fingerprint": {...}, "requestId": "..."}
    -> 200  application/octet-stream: a fleetkv HANDOFF envelope
            (utils/fleetkv.encode_handoff — dtype/shape manifest +
            CRC + fingerprint; the decode side refuses WHOLESALE on
            any mismatch)
    -> 409  fingerprint mismatch (mixed fleet config — never serve
            bytes the decode pool would misinterpret)
    -> 503  draining / overloaded: the decode side retries another
            pod (a draining prefill pod REFUSES handoffs; in-flight
            jobs finish and their responses complete)

The decode replica's :class:`RemotePrefillClient` plugs into the ring
scheduler exactly where the in-process executor sits (same
``submit(req, slot)`` / ``results`` queue contract), POSTs on worker
threads (never the ring thread), and posts host payloads the scheduler
lands through the PR 8 promote scatter — so remote-disagg output is
greedy-bit-identical to in-process disagg (dryrun ``serve-xdisagg``).

Drain (docs/fault-tolerance.md): SIGTERM flips /readyz false and new
prefills 503; in-flight jobs finish and flush their responses inside
the budget; exit EXIT_PREEMPTED=83 — the reconciler counts the pod
preempted, not failed.  "Prefill pods drain by finishing/refusing
handoffs."
"""

from __future__ import annotations

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from paddle_operator_tpu.utils import tracing as TRC

# One whole-prompt forward per job, bounded by model size — generous
# enough for a cold 7B 2k-token prefill on real chips, small enough
# that a wedged pod sheds its waiters onto healthy peers.
PREFILL_TIMEOUT_S = 120.0


def handoff_fingerprint(cfg, *, block_size: int, kv_quant: str,
                        top_k: Optional[int],
                        top_p: Optional[float],
                        wquant: str = "none",
                        generation: int = 0) -> Dict[str, Any]:
    """The geometry + sampling rule a handoff envelope must match.
    Narrower than the lane-migration fingerprint on purpose: spec
    depth is absent (the DRAFT lane prefills decode-side at attach —
    the snapshot is target KV only) and tp is absent (host bytes
    re-shard through the promote scatter).  top-k/top-p ARE included:
    the prefill pod samples the FIRST token, so a sampling-rule skew
    would silently break bit-identity with the in-process path.
    ``wquant`` (ISSUE 16) is the WEIGHT quant mode: handed-off KV is a
    function of the weights that produced it, so a bf16 prefill pod
    feeding an int8 decode ring would silently break token-identity
    with the in-process cold path — refuse the mixed fleet instead.
    ``generation`` (ISSUE 19) is the WEIGHT generation for the same
    reason: during a fleet rolling swap a prefill pod still on
    checkpoint r must not feed KV into a decode ring already on r+1 —
    the mismatch 409s and the decode side falls back/retries until
    the pool rolls."""
    return {"layers": int(cfg.n_layers),
            "kvHeads": int(cfg.n_kv_heads),
            "headDim": int(cfg.head_dim),
            "blockSize": int(block_size),
            "quant": kv_quant,
            "wquant": wquant,
            "gen": int(generation),
            "topK": top_k, "topP": top_p}


class _Job:
    """The request shim the PrefillExecutor thread reads (it only
    touches prompt/dev_prompt/temperature/seed/adapter_idx and the
    done/_cancel lifecycle flags).  ``wants_frames`` (ISSUE 14
    streamed handoff): the matcher routes the engine's block-group
    frame items into ``frames`` for the chunked HTTP response;
    without it frames are dropped and only the terminal result
    lands."""

    __slots__ = ("prompt", "temperature", "seed", "adapter_idx",
                 "done", "_cancel", "dev_prompt", "result", "error",
                 "t0", "accounted", "wants_frames", "frames")

    def __init__(self, prompt: Sequence[int], temperature: float,
                 seed: int, wants_frames: bool = False) -> None:
        import jax.numpy as jnp

        self.prompt = [int(t) for t in prompt]
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.adapter_idx = 0
        self.done = threading.Event()
        self._cancel = False
        self.dev_prompt = jnp.asarray(
            np.asarray(self.prompt, np.int32)[None, :])
        self.result: Optional[Tuple[Any, ...]] = None
        self.error: Optional[Exception] = None
        self.t0 = time.monotonic()
        # exactly-once depth accounting (under the frontend lock): a
        # timed-out job may be dropped by the executor while QUEUED
        # (no result ever posted) or may still finish and post one —
        # whichever side settles first decrements, the other skips
        self.accounted = False
        self.wants_frames = bool(wants_frames)
        self.frames: Optional["queue.Queue[tuple]"] = (
            queue.Queue() if wants_frames else None)


class PrefillFrontend:
    """The jax half of the prefill server: one PrefillExecutor plus a
    matcher thread that resolves per-job events from its results
    queue, and the snapshot -> host-bytes conversion the wire needs.
    Kept separate from the HTTP shell so tests (and the dryrun gate)
    can drive it in-process."""

    def __init__(self, params: Any, cfg, *, block_size: int,
                 max_len: int, buckets: Tuple[int, ...] = (),
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None, mesh=None,
                 kv_quant: str = "none", lanes: int = 1,
                 prefill_chunk: int = 64,
                 prefix_blocks: int = 0,
                 generation: int = 0) -> None:
        from paddle_operator_tpu.infer import decode as D
        from paddle_operator_tpu.infer import executor as X

        from paddle_operator_tpu.infer import quant as Q

        if mesh is not None and D.mesh_tp(mesh) > 1:
            params = D.shard_params_for_serving(params, cfg, mesh)
        self.cfg = cfg
        self.block_size = int(block_size)
        self.kv_quant = kv_quant
        # detected, not configured: the leaf types of the tree actually
        # dispatched decide the fingerprint (matches the decode side)
        self.wquant = Q.weight_quant_mode(params)
        # weight generation (ISSUE 19): rides the handoff fingerprint
        # so a rolling fleet swap 409s cross-generation handoffs
        self.generation = int(generation)
        self.quant = kv_quant == "int8"
        self.top_k, self.top_p = top_k, top_p
        self.lanes = max(1, int(lanes))
        # the N-lane engine always produces frame items (streaming
        # clients consume them; the matcher drops them for jobs that
        # did not ask) — the 1-lane oracle engine never does
        self.exec = X.PrefillExecutor(
            params, cfg, max_len=max_len, block_size=self.block_size,
            buckets=tuple(buckets) or (max_len,), top_k=top_k,
            top_p=top_p, mesh=mesh, kv_quant=kv_quant,
            lanes=self.lanes, prefill_chunk=prefill_chunk,
            stream=self.lanes > 1, prefix_blocks=prefix_blocks)
        self.draining = False
        self._lock = threading.Lock()
        self._depth = 0
        self.stats = {"jobs": 0, "prompt_tokens": 0, "errors": 0,
                      "refused": 0}
        # rolling per-job wall EMA — the gauge the SLO autoscaler
        # converts a TTFT target into a queue-depth bound with
        self.prefill_ms_avg = 0.0
        # flight recorder (ISSUE 15): the prefill pod's own bounded
        # event ring — refusals, per-job errors, drain transitions —
        # served at /debug/flightrec and dumped on SIGTERM
        import os as _os

        self.flightrec = TRC.FlightRecorder(
            pod=_os.environ.get("TPUJOB_REPLICA_ID", ""))
        self._t_start = time.monotonic()
        self._stop = threading.Event()
        self._matcher = threading.Thread(target=self._match_loop,
                                         daemon=True,
                                         name="prefill-match")
        self._matcher.start()

    def fingerprint(self) -> Dict[str, Any]:
        return handoff_fingerprint(
            self.cfg, block_size=self.block_size,
            kv_quant=self.kv_quant, top_k=self.top_k, top_p=self.top_p,
            wquant=self.wquant, generation=self.generation)

    def depth(self) -> int:
        with self._lock:
            return self._depth

    def _match_loop(self) -> None:
        results = self.exec.results
        while not self._stop.is_set():
            try:
                item = results.get(timeout=0.05)
            except queue.Empty:
                continue
            if isinstance(item[0], str):
                # N-lane engine protocol (ISSUE 14): frames route to
                # streaming jobs; the terminal item completes the job
                kind = item[0]
                job = item[1]
                if kind == "frame":
                    if job.wants_frames and not job.done.is_set():
                        job.frames.put(item)
                    continue
                # ("final", job, slot, snap, lane, j0, n_blocks,
                #  first, t_done)
                job.result = (item[3], item[4], item[5], item[6],
                              int(np.asarray(item[7])), item[8])
                if job.wants_frames:
                    job.frames.put(item)
                self._settle(job)
                continue
            job = item[0]
            if len(item) == 3:
                job.error = item[2]
                if job.wants_frames:
                    job.frames.put(("error", job, item[2]))
            else:
                _, _, snap, n_blocks, first = item
                job.result = (snap, None, 0, n_blocks,
                              int(np.asarray(first)), time.monotonic())
            self._settle(job)

    def _settle(self, job: "_Job") -> None:
        ms = (time.monotonic() - job.t0) * 1e3
        with self._lock:
            if not job.accounted:
                job.accounted = True
                self._depth -= 1
                self.prefill_ms_avg = (
                    ms if not self.prefill_ms_avg
                    else 0.8 * self.prefill_ms_avg + 0.2 * ms)
        job.done.set()

    def _block_ids(self, lane: Optional[int], j0: int,
                   j1: int) -> np.ndarray:
        """Pool block ids backing a job's blocks [j0, j1): the 1-lane
        engine's fixed identity rows 1..M, or lane ``lane``'s identity
        rows on the N-lane engine."""
        if lane is None:
            return np.arange(1 + j0, 1 + j1)
        return self.exec.tables[lane][j0:j1]

    def _host_blocks(self, snap, lane: Optional[int], j0: int,
                     j1: int) -> Dict[str, np.ndarray]:
        """Snapshot -> host bytes for blocks [j0, j1).  jax arrays are
        immutable, so this read races nothing even while the engine
        writes fresh pool versions."""
        ids = self._block_ids(lane, j0, j1)
        arrays: Dict[str, np.ndarray] = {
            "k": np.asarray(snap["k"])[:, ids],
            "v": np.asarray(snap["v"])[:, ids],
        }
        if self.quant:
            arrays["ks"] = np.asarray(snap["ks"])[:, ids]
            arrays["vs"] = np.asarray(snap["vs"])[:, ids]
        return arrays

    def _submit(self, tokens: Sequence[int], temperature: float,
                seed: int, wants_frames: bool = False) -> "_Job":
        job = _Job(tokens, temperature, seed,
                   wants_frames=wants_frames)
        with self._lock:
            self._depth += 1
        self.exec.submit(job, 0)
        return job

    def _timeout(self, job: "_Job", timeout: float) -> None:
        job._cancel = True      # dropped at the executor if queued
        # a QUEUED cancelled job never posts a result, so the
        # matcher never sees it — settle the depth here (the
        # ``accounted`` flag keeps a mid-flight job that still
        # finishes from decrementing twice)
        with self._lock:
            if not job.accounted:
                job.accounted = True
                self._depth -= 1
        raise TimeoutError(
            f"prefill did not finish within {timeout}s")

    def prefill(self, tokens: Sequence[int], temperature: float,
                seed: int,
                timeout: float = PREFILL_TIMEOUT_S) -> bytes:
        """Run one whole-prompt prefill and return its HANDOFF
        envelope.  Raises on executor failure or timeout — the HTTP
        shell maps those to error responses, and the decode side
        fails (or retries) that one request."""
        from paddle_operator_tpu.utils import fleetkv as FK

        job = self._submit(tokens, temperature, seed)
        if not job.done.wait(timeout):
            self.flightrec.record("prefill_timeout",
                                  tokens=len(job.prompt))
            self._timeout(job, timeout)
        if job.error is not None:
            with self._lock:
                self.stats["errors"] += 1
            self.flightrec.record("prefill_error",
                                  error=str(job.error)[:200])
            raise job.error
        snap, lane, _, n_blocks, first, _ = job.result
        arrays = self._host_blocks(snap, lane, 0, n_blocks)
        if self.quant:
            # the prompt's partial last block lives EXACT in the
            # engine lane's staging-tail row — it lands in the decode
            # tail row ``slot`` at attach
            trow = 0 if lane is None else lane
            arrays["kt"] = np.asarray(snap["kt"])[:, trow:trow + 1]
            arrays["vt"] = np.asarray(snap["vt"])[:, trow:trow + 1]
        with self._lock:
            self.stats["jobs"] += 1
            self.stats["prompt_tokens"] += len(job.prompt)
        meta = {"first": first, "promptLen": len(job.prompt),
                "nBlocks": int(n_blocks),
                "fingerprint": self.fingerprint()}
        return FK.encode_handoff(meta, arrays)

    def prefill_stream(self, tokens: Sequence[int], temperature: float,
                       seed: int, timeout: float = PREFILL_TIMEOUT_S):
        """STREAMED prefill (ISSUE 14): yield length-prefixed wire
        frames — completed block groups as they finish, then the
        terminal frame (remaining blocks + staging tail + first token
        + fingerprint) — so the decode side's upload and the wire
        transfer overlap the remaining prefill compute.  Raises
        TimeoutError/executor errors BEFORE the first yield (mapped to
        HTTP statuses); after the first frame the handler can only
        drop the connection, which the client refuses wholesale."""
        from paddle_operator_tpu.utils import fleetkv as FK

        job = self._submit(tokens, temperature, seed,
                           wants_frames=self.lanes > 1)
        if job.frames is None:
            # 1-lane oracle engine: no frames exist — one terminal
            # frame carries the whole handoff (a valid 1-frame stream)
            buf = None
            if not job.done.wait(timeout):
                self._timeout(job, timeout)
            if job.error is not None:
                with self._lock:
                    self.stats["errors"] += 1
                raise job.error
            snap, lane, _, n_blocks, first, t_done = job.result
            arrays = self._host_blocks(snap, lane, 0, n_blocks)
            if self.quant:
                arrays["kt"] = np.asarray(snap["kt"])[:, 0:1]
                arrays["vt"] = np.asarray(snap["vt"])[:, 0:1]
            with self._lock:
                self.stats["jobs"] += 1
                self.stats["prompt_tokens"] += len(job.prompt)
            yield FK.encode_handoff_final(
                {"seq": 0, "nFrames": 1, "j0": 0, "first": first,
                 "promptLen": len(job.prompt), "nBlocks": int(n_blocks),
                 "fingerprint": self.fingerprint(),
                 "tDone": t_done}, arrays)
            return
        deadline = time.monotonic() + timeout
        seq = 0
        while True:
            try:
                item = job.frames.get(
                    timeout=max(0.0, deadline - time.monotonic()))
            except queue.Empty:
                self._timeout(job, timeout)
            if item[0] == "error":
                with self._lock:
                    self.stats["errors"] += 1
                raise item[2]
            if item[0] == "frame":
                _, _, _, snap, lane, j0, j1 = item
                yield FK.encode_handoff_frame(
                    seq, j0, self._host_blocks(snap, lane, j0, j1))
                seq += 1
                continue
            # terminal
            snap, lane, j0, n_blocks, first, t_done = job.result
            arrays = self._host_blocks(snap, lane, j0, n_blocks)
            if self.quant:
                arrays["kt"] = np.asarray(snap["kt"])[:, lane:lane + 1]
                arrays["vt"] = np.asarray(snap["vt"])[:, lane:lane + 1]
            with self._lock:
                self.stats["jobs"] += 1
                self.stats["prompt_tokens"] += len(job.prompt)
            yield FK.encode_handoff_final(
                {"seq": seq, "nFrames": seq + 1, "j0": int(j0),
                 "first": int(first), "promptLen": len(job.prompt),
                 "nBlocks": int(n_blocks),
                 "fingerprint": self.fingerprint(),
                 "tDone": float(t_done)}, arrays)
            return

    def serving_status(self) -> Dict[str, Any]:
        """The prefill pod's status block.  ``role: "prefill"`` is the
        marker ``aggregate_fleet_serving`` keys on so a pool that
        never decodes cannot skew the fleet's token-weighted tok/s or
        hit-rate aggregates; ``tokensPerSec`` here is PREFILL
        tokens/s (folded into the fleet's ``prefillTokensPerSec``)."""
        elapsed = max(1e-9, time.monotonic() - self._t_start)
        with self._lock:
            return {
                "role": "prefill",
                "prefillQueueDepth": self._depth,
                "prefillMsAvg": round(self.prefill_ms_avg, 3),
                "tokensPerSec": round(
                    self.stats["prompt_tokens"] / elapsed, 2),
                "tokensTotal": self.stats["prompt_tokens"],
                "prefillJobs": self.stats["jobs"],
                "prefillErrors": self.stats["errors"],
                "refusedHandoffs": self.stats["refused"],
                # prefill-pool throughput (ISSUE 14): engine width,
                # batch occupancy EMA (busy lanes / N per iteration)
                # and head-of-line wait p95 — what the SLO autoscaler
                # divides by so a half-empty batch never reads as a
                # saturated pool
                "prefillLanes": self.lanes,
                "prefillBatchOccupancy": self.exec.batch_occupancy(),
                "prefillHolWaitMs": self.exec.hol_wait_ms_p95(),
                "prefillPrefixHits": self.exec.prefix_hits,
                "draining": self.draining,
            }

    def metrics_text(self, job: str, replica: str) -> str:
        """Prometheus exposition for the router's scrape — reuses the
        fleet gauge NAMES (queue depth under mode="remote", tok/s,
        draining) plus the prefill-only service-time gauge, so one
        scrape parser serves both pools."""
        st = self.serving_status()
        rep = f',replica="{replica}"' if replica else ""
        lbl = f'{{job="{job}"{rep}}}'
        lines = [
            (f'tpujob_serve_prefill_queue_depth{{job="{job}"{rep},'
             f'mode="remote"}} {float(st["prefillQueueDepth"])}'),
            f'tpujob_serve_prefill_ms_avg{lbl} '
            f'{float(st["prefillMsAvg"])}',
            f'tpujob_serve_prefill_jobs_total{lbl} '
            f'{float(st["prefillJobs"])}',
            f'tpujob_serve_tokens_per_sec{lbl} '
            f'{float(st["tokensPerSec"])}',
            # prefill-pool throughput gauges (ISSUE 14) — the router
            # scrapes these into /statusz and the autoscaler's prefill
            # denominator reads occupancy + lanes
            f'tpujob_serve_prefill_lanes{lbl} '
            f'{float(st["prefillLanes"])}',
            f'tpujob_serve_prefill_batch_occupancy{lbl} '
            f'{float(st["prefillBatchOccupancy"])}',
            f'tpujob_serve_prefill_hol_wait_ms{lbl} '
            f'{float(st["prefillHolWaitMs"])}',
            f'tpujob_serve_draining{lbl} '
            f'{1.0 if st["draining"] else 0.0}',
        ]
        return "\n".join(lines) + "\n"

    def close(self) -> None:
        self._stop.set()
        self.exec.close()
        self._matcher.join(timeout=10)


class _PrefillHandler(BaseHTTPRequestHandler):
    frontend: PrefillFrontend    # injected
    job_key = "local"
    replica_id = ""
    protocol_version = "HTTP/1.1"
    timeout = 120

    def log_message(self, *a):
        pass

    def _send_json(self, code: int, obj, headers=None) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        fe = self.frontend
        if self.path == "/healthz":
            self._send_json(200, {"ok": True})
        elif self.path == "/readyz":
            if fe.draining:
                self._send_json(503, {"ready": False,
                                      "reason": "draining"},
                                headers={"Retry-After": 5})
            else:
                self._send_json(200, {"ready": True})
        elif self.path == "/statusz":
            st = fe.serving_status()
            if self.replica_id:
                st["replica"] = self.replica_id
            self._send_json(200, st)
        elif self.path == "/metrics":
            body = fe.metrics_text(self.job_key,
                                   self.replica_id).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/debug/flightrec":
            # the prefill pod's event ring (ISSUE 15) — same contract
            # as the decode replicas' endpoint
            self._send_json(200, fe.flightrec.dump("debug_endpoint"))
        else:
            self._send_json(404, {})

    def do_POST(self):
        from paddle_operator_tpu.utils.fleetkv import (
            EnvelopeError,
            check_fingerprint,
        )

        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n) if n else b""
        if self.path != "/v1/prefill":
            self._send_json(404, {})
            return
        fe = self.frontend
        if fe.draining:
            # refusing handoffs IS the prefill pod's drain protocol:
            # the decode side retries another pod, and the in-flight
            # jobs below this point finish and flush
            with fe._lock:
                fe.stats["refused"] += 1
            fe.flightrec.record("handoff_refused", reason="draining")
            self._send_json(503, {"error": "draining"},
                            headers={"Retry-After": 2})
            return
        try:
            req = json.loads(body)
            tokens = [int(t) for t in req["tokens"]]
            if not tokens:
                raise ValueError("empty prompt")
            theirs = req.get("fingerprint")
            if theirs is not None:
                check_fingerprint({"fingerprint": theirs},
                                  fe.fingerprint())
        except EnvelopeError as e:
            self._send_json(409, {"error": str(e)})
            return
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            self._send_json(400, {"error": str(e)})
            return
        if req.get("stream"):
            return self._stream_prefill(fe, req, tokens)
        try:
            buf = fe.prefill(tokens,
                             float(req.get("temperature", 0.0)),
                             int(req.get("seed", 0)))
        except TimeoutError as e:
            # overload (a backlogged pod), not a per-prompt defect:
            # 503 like draining so the decode side / router walks to
            # the next candidate instead of hard-failing the request
            self._send_json(503, {"error": str(e)},
                            headers={"Retry-After": 2})
            return
        except Exception as e:      # noqa: BLE001 — isolate per job
            # a deterministic per-prompt failure (bucket overflow,
            # compile error): NOT retriable — the decode side fails
            # that one request instead of hammering every pod
            self._send_json(500, {"error": str(e)})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(buf)))
        self.end_headers()
        self.wfile.write(buf)

    def _stream_prefill(self, fe, req, tokens) -> None:
        """``"stream": true`` (ISSUE 14): chunked transfer of
        length-prefixed handoff frames as block groups complete — the
        decode side uploads each frame while this pod still computes
        the rest of the prompt.  Errors BEFORE the first frame map to
        HTTP statuses exactly like the monolithic path; after it the
        only honest signal is dropping the connection, which the
        receiver refuses wholesale (per-frame CRC + the terminal
        frame's count make any partial stream unusable by
        construction)."""
        gen = fe.prefill_stream(tokens,
                                float(req.get("temperature", 0.0)),
                                int(req.get("seed", 0)))
        try:
            first_frame = next(gen)
        except TimeoutError as e:
            self._send_json(503, {"error": str(e)},
                            headers={"Retry-After": 2})
            return
        except StopIteration:
            self._send_json(500, {"error": "empty handoff stream"})
            return
        except Exception as e:      # noqa: BLE001
            self._send_json(500, {"error": str(e)})
            return
        try:
            self.send_response(200)
            self.send_header("Content-Type",
                             "application/octet-stream")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def emit(wire: bytes) -> None:
                self.wfile.write(f"{len(wire):x}\r\n".encode() + wire
                                 + b"\r\n")
                self.wfile.flush()

            emit(first_frame)
            for wire in gen:
                emit(wire)
            self.wfile.write(b"0\r\n\r\n")
        except OSError:
            return      # client gone mid-stream: nothing to say
        except Exception:   # noqa: BLE001 — engine died mid-stream
            # drop the connection: the receiver sees a truncated
            # frame and refuses the whole stream
            try:
                self.wfile.flush()
            except OSError:
                pass
            self.close_connection = True


def make_prefill_server(host: str, port: int, params: Any, cfg, *,
                        block_size: int = 256,
                        max_len: Optional[int] = None,
                        buckets: Tuple[int, ...] = (),
                        top_k: Optional[int] = None,
                        top_p: Optional[float] = None, mesh=None,
                        kv_quant: str = "none", job: str = "local",
                        replica: str = "", lanes: int = 1,
                        prefill_chunk: int = 64,
                        prefix_blocks: int = 0,
                        generation: int = 0) -> ThreadingHTTPServer:
    """HTTP shell around a PrefillFrontend.  The returned server
    carries ``.frontend`` — close it when tearing down."""
    fe = PrefillFrontend(params, cfg, block_size=block_size,
                         max_len=max_len or cfg.max_seq_len,
                         buckets=buckets, top_k=top_k, top_p=top_p,
                         mesh=mesh, kv_quant=kv_quant, lanes=lanes,
                         prefill_chunk=prefill_chunk,
                         prefix_blocks=prefix_blocks,
                         generation=generation)
    handler = type("PrefillHandler", (_PrefillHandler,),
                   {"frontend": fe, "job_key": job,
                    "replica_id": replica})
    srv = ThreadingHTTPServer((host, port), handler)
    srv.frontend = fe
    return srv


# ---------------------------------------------------------------------------
# Decode-side client: the network stand-in for the in-process executor
# ---------------------------------------------------------------------------


class RemotePrefillClient:
    """The decode replica's prefill-pool client — a drop-in for the
    in-process :class:`PrefillExecutor` at the scheduler seam (same
    ``submit(req, slot)`` / ``results`` queue contract, marked
    ``remote = True`` so the handoff drain lands host payloads through
    the promote scatter instead of the device-to-device copy).

    POSTs run on worker threads, never the ring thread.  ``broker``
    (the fleet router, which forwards ``/v1/prefill`` to the
    least-loaded ready prefill pod) is preferred; static ``peers``
    are the router-less fallback.  Prefill is SIDE-EFFECT-FREE, so —
    unlike lane migration — every failure mode retries freely:
    connection errors and 503s (draining pod) walk to the next
    attempt, and only a deterministic 4xx/5xx fails the request.
    Exhausted attempts post a retriable error: the request 503s and
    the client's fleet-level retry re-routes it."""

    remote = True

    def __init__(self, broker: str = "", peers: Sequence[str] = (), *,
                 timeout: float = PREFILL_TIMEOUT_S, workers: int = 2,
                 max_attempts: int = 4,
                 backoff_s: float = 0.2,
                 stream: bool = False) -> None:
        self.broker = broker.strip().rstrip("/")
        self.peers = [p.strip() for p in peers if p.strip()]
        if not self.broker and not self.peers:
            raise ValueError("remote prefill needs a broker or peers")
        self.timeout = timeout
        self.max_attempts = max_attempts
        self.backoff_s = backoff_s
        # streamed handoff (ISSUE 14): frames post to the scheduler as
        # they arrive off the wire, so the promote upload overlaps the
        # pod's remaining prefill compute AND the DCN transfer
        self.stream = bool(stream)
        # the ring's handoff fingerprint — stamped by the scheduler at
        # construction (it owns cfg/block_size/quant/top-k/top-p)
        self.fingerprint: Optional[Dict[str, Any]] = None
        self.jobs: "queue.Queue[tuple]" = queue.Queue()
        self.results: "queue.Queue[tuple]" = queue.Queue()
        self.stats = {"posted": 0, "retries": 0, "failed": 0,
                      # streams refused WHOLESALE: mid-stream pod
                      # death, truncated / CRC-bad / out-of-order
                      # frames (each walked to the next candidate)
                      "refused_streams": 0}
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"remote-prefill-{i}")
            for i in range(max(1, int(workers)))]
        for t in self._threads:
            t.start()

    def submit(self, req, slot: int) -> None:
        self.jobs.put((req, slot))

    def _targets(self) -> list:
        if self.broker:
            return [self.broker] * self.max_attempts
        reps = -(-self.max_attempts // len(self.peers))
        return (self.peers * reps)[:self.max_attempts]

    def _worker(self) -> None:
        from paddle_operator_tpu.infer.resilience import RetriableError
        from paddle_operator_tpu.utils import fleetkv as FK

        while not self._stop.is_set():
            try:
                req, slot = self.jobs.get(timeout=0.05)
            except queue.Empty:
                continue
            if req.done.is_set() or req._cancel:
                continue            # resolved while queued: drop
            body = json.dumps({
                "tokens": [int(t) for t in req.prompt],
                "temperature": float(req.temperature),
                "seed": int(req.seed),
                "requestId": getattr(req, "request_id", None),
                "fingerprint": self.fingerprint,
                "stream": self.stream,
            }).encode()
            outcome = None
            t_wire0 = time.monotonic()
            if self.stream:
                for i, ep in enumerate(self._targets()):
                    if req.done.is_set() or req._cancel:
                        break       # late resolution: stop POSTing
                    if i:
                        self.stats["retries"] += 1
                        # shared fleet backoff law (ISSUE 20
                        # satellite) — jittered exponential, same as
                        # the non-stream path below
                        time.sleep(FK.backoff_delay(
                            i - 1, base_s=self.backoff_s, max_s=1.0))
                    res = self._stream_attempt(ep, body, req, slot)
                    if res == "next":
                        continue
                    if res == "done":
                        self._wire_span(req, t_wire0, ep, i,
                                        stream=True)
                    outcome = res
                    break
            else:
                # the whole walk — conn errors, 503 (draining pod) and
                # 409 (fingerprint mismatch mid rolling swap, an
                # already-rolled peer may match) retry to the next
                # candidate with jittered backoff, Retry-After honored
                # — is the shared bounded-retry helper (ISSUE 20
                # satellite); prefill is side-effect-free so retrying
                # freely is always safe
                attempts = [0]

                def _on_retry(ep, i):
                    attempts[0] = i + 1
                    self.stats["retries"] += 1

                code, raw, used = FK.http_post_retry(
                    [self.broker] if self.broker else self.peers,
                    "/v1/prefill", body,
                    content_type="application/json",
                    timeout=self.timeout,
                    max_attempts=self.max_attempts,
                    backoff_base_s=self.backoff_s, backoff_max_s=1.0,
                    retry_statuses=(503, 409),
                    on_retry=_on_retry,
                    abort=lambda: req.done.is_set() or req._cancel)
                if used is not None and code not in (0, 503, 409):
                    if code != 200:
                        try:
                            msg = json.loads(raw).get("error",
                                                      raw[:120])
                        except Exception:
                            msg = raw[:120]
                        outcome = (req, slot, RuntimeError(
                            f"remote prefill rejected ({code}): "
                            f"{msg}"))
                    else:
                        try:
                            meta, arrays = FK.decode_handoff(raw)
                            if self.fingerprint is not None:
                                FK.check_fingerprint(meta,
                                                     self.fingerprint)
                            self.stats["posted"] += 1
                            self._wire_span(req, t_wire0, used,
                                            attempts[0], stream=False)
                            outcome = (req, slot, arrays,
                                       int(meta["nBlocks"]),
                                       int(meta["first"]))
                        except FK.EnvelopeError as e:
                            outcome = (req, slot, e)
            if outcome == "done":
                continue    # streamed final already posted
            if outcome is None:
                self.stats["failed"] += 1
                outcome = (req, slot, RetriableError(
                    "no prefill pod accepted the handoff "
                    f"({self.max_attempts} attempts); retry"))
            self.results.put(outcome)

    @staticmethod
    def _wire_span(req, t0: float, ep: str, attempts: int,
                   stream: bool) -> None:
        """Remote-handoff wire span (ISSUE 15): POST -> decoded
        envelope (streamed: first frame -> terminal frame), stamped
        from this worker thread onto the request's trace — the
        RequestTrace is thread-safe for exactly this.  Covers pod
        queue + prefill compute + the DCN transfer; the pod's own
        ``prefillMsAvg`` gauge splits out the compute share."""
        tr = getattr(req, "trace", None)
        if tr is not None:
            # NB: "pod" is make_span's own field (the POSTING pod);
            # the serving prefill pod rides as the target attr
            tr.add("remote_prefill", t0, target=ep,
                   attempts=attempts + 1, stream=stream)

    def _stream_attempt(self, ep: str, body: bytes, req, slot: int):
        """One STREAMED prefill attempt against ``ep``: frames post to
        the scheduler AS THEY ARRIVE (the decode upload overlaps both
        the wire and the pod's remaining compute); the terminal frame
        posts the remainder + first token.  Returns ``"done"`` (final
        posted), ``"next"`` (retry another candidate — 503, connection
        failure, mid-stream death, or a truncated/CRC-bad/out-of-order
        frame, all refused WHOLESALE; prefill is side-effect-free and
        already-uploaded frames are idempotently overwritten by the
        retry), or a terminal error outcome tuple (deterministic
        rejection)."""
        import json as _json

        from http.client import HTTPConnection, HTTPException

        from paddle_operator_tpu.utils import fleetkv as FK

        host, _, port = ep.rpartition(":")
        conn = HTTPConnection(host, int(port), timeout=self.timeout)
        streaming = False       # past the 200: failures = broken stream
        try:
            # Connection: close — one stream per connection, and the
            # server tears it down cleanly after the terminal frame
            # (a lingering keep-alive would just log a reset when
            # this side closes)
            conn.request("POST", "/v1/prefill", body=body,
                         headers={"Content-Type": "application/json",
                                  "Connection": "close"})
            resp = conn.getresponse()
            if resp.status in (503, 409):
                # 503: draining / backlogged pod.  409: weight-
                # generation fingerprint mismatch mid rolling swap
                # (ISSUE 19) — an already-rolled peer may match.
                resp.read()
                return "next"
            if resp.status != 200:
                raw = resp.read()
                try:
                    msg = _json.loads(raw).get("error", raw[:120])
                except Exception:   # noqa: BLE001
                    msg = raw[:120]
                return (req, slot, RuntimeError(
                    f"remote prefill rejected ({resp.status}): {msg}"))
            streaming = True
            seq = 0
            while True:
                buf = FK.read_wire_frame(resp.read)
                if buf is None:
                    raise FK.EnvelopeError(
                        "handoff stream ended before its terminal "
                        "frame")
                kind, meta, arrays = FK.decode_handoff_frame(buf, seq)
                if kind == FK.FRAME_KIND:
                    width = arrays["k"].shape[1]
                    self.results.put(
                        ("frame", req, slot, arrays, None,
                         int(meta["j0"]), int(meta["j0"]) + width))
                    seq += 1
                    continue
                if self.fingerprint is not None:
                    FK.check_fingerprint(meta, self.fingerprint)
                self.stats["posted"] += 1
                self.results.put(
                    ("final", req, slot, arrays, None,
                     int(meta["j0"]), int(meta["nBlocks"]),
                     int(meta["first"]), time.monotonic()))
                return "done"
        except FK.EnvelopeError:
            self.stats["refused_streams"] += 1
            return "next"
        except (OSError, ValueError, HTTPException):
            # connection refused/reset, or the pod died mid-chunk
            # (IncompleteRead) — a started stream refuses WHOLESALE
            # either way; retry elsewhere
            if streaming:
                self.stats["refused_streams"] += 1
            return "next"
        finally:
            conn.close()

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)


def remote_prefill_client_from_env() -> Optional[RemotePrefillClient]:
    """serve.py wiring: SERVE_PREFILL_REMOTE=1 (with
    SERVE_PREFILL=disagg) moves cold prefills to the prefill POOL —
    SERVE_PREFILL_BROKER names the router (it forwards to the
    least-loaded ready prefill pod), SERVE_PREFILL_PEERS is the
    router-less static list.  Returns None when remote prefill is
    off."""
    import os

    if os.environ.get("SERVE_PREFILL_REMOTE", "0") != "1":
        return None
    broker = os.environ.get("SERVE_PREFILL_BROKER", "")
    peers = [p for p in os.environ.get("SERVE_PREFILL_PEERS",
                                       "").split(",") if p.strip()]
    if not broker and not peers:
        print("SERVE_PREFILL_REMOTE=1 ignored: set "
              "SERVE_PREFILL_BROKER or SERVE_PREFILL_PEERS",
              flush=True)
        return None
    # wire chaos (ISSUE 20): with TPUJOB_WIRE_CHAOS scheduling faults
    # on the decode->prefill edge, the broker/peer endpoints are
    # replaced by an injured in-process proxy — the env contract that
    # lets a chaos run injure THIS edge without touching either pod
    from paddle_operator_tpu.utils import wirechaos as WC

    broker = WC.wire_endpoint_from_env("decode-prefill", broker)
    peers = [WC.wire_endpoint_from_env("decode-prefill", p)
             for p in peers]
    # SERVE_PREFILL_STREAM=1 (ISSUE 14): consume the pool's chunked
    # handoff frames, uploading each block group while the pod still
    # prefills the rest — long-prompt TTFT ≈ last chunk + attach
    return RemotePrefillClient(
        broker=broker, peers=peers,
        stream=os.environ.get("SERVE_PREFILL_STREAM", "0") == "1")


def main() -> int:
    """Prefill-pod entrypoint (``python -m
    paddle_operator_tpu.infer.prefill_serve``): restore params exactly
    as serve.py does, serve /v1/prefill on TPUJOB_PORT, drain on
    SIGTERM by refusing new handoffs and finishing in-flight jobs,
    exit EXIT_PREEMPTED."""
    import os

    import jax
    import jax.numpy as jnp

    from paddle_operator_tpu.api.types import EXIT_PREEMPTED
    from paddle_operator_tpu.ft.preemption import PreemptionWatcher
    from paddle_operator_tpu.infer.quant import serving_params
    from paddle_operator_tpu.launch.launcher import JobEnv
    from paddle_operator_tpu.models.llama import make_model
    from paddle_operator_tpu.train import trainer as T
    from paddle_operator_tpu.train.checkpoint import (
        CheckpointManager,
        resume_or_init,
    )

    env = JobEnv.from_env()
    model, cfg = make_model(os.environ.get("MODEL_PRESET", "7b"))
    opt = T.make_optimizer()

    def init():
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]
        return T.TrainState(step=jnp.zeros((), jnp.int32),
                            params=params, opt_state=opt.init(params))

    ckpt = CheckpointManager()
    state, resumed = resume_or_init(ckpt, init)
    params = serving_params(state.params, cfg.dtype)
    # SERVE_WEIGHT_QUANT=int8|int4: match the decode fleet's weight
    # quantization — handed-off KV is a function of the weights that
    # produced it, so a mixed fleet breaks token-identity with the
    # in-process cold path.  builders.py derives this pod's env from
    # the serving container, so the knob arrives automatically; the
    # handoff fingerprint refuses skew regardless.
    wq = os.environ.get("SERVE_WEIGHT_QUANT", "none") or "none"
    if wq != "none":
        from paddle_operator_tpu.infer.quant import (
            SERVING_SKIP,
            quantize_params,
        )

        params = quantize_params(params, cfg, mode=wq, skip=SERVING_SKIP)
    mesh = None
    tp = int(os.environ.get("SERVE_TP", "1"))
    if tp > 1:
        from paddle_operator_tpu.parallel.mesh import make_serving_mesh

        mesh = make_serving_mesh(tp)
    max_len = int(os.environ.get("SERVE_MAX_LEN", "0")) \
        or cfg.max_seq_len
    kv_quant = os.environ.get("SERVE_KV_QUANT", "none")
    # ISSUE 14: SERVE_PREFILL_LANES widens the pool into an N-lane
    # batched, chunk-interleaved engine (1 keeps the monolithic
    # oracle); SERVE_PREFILL_CHUNK is the interleave slice width;
    # SERVE_PREFILL_PREFIX_BLOCKS caps the pod's own radix prefix
    # cache (0 disables; engine-only)
    lanes = int(os.environ.get("SERVE_PREFILL_LANES", "1") or 1)
    srv = make_prefill_server(
        "0.0.0.0", env.port, params, cfg,
        block_size=int(os.environ.get("SERVE_BLOCK_SIZE", "256")),
        max_len=max_len, kv_quant=kv_quant, mesh=mesh,
        job=os.environ.get("TPUJOB_NAME", "local"),
        replica=os.environ.get("TPUJOB_REPLICA_ID", ""),
        lanes=lanes,
        prefill_chunk=int(os.environ.get("SERVE_PREFILL_CHUNK",
                                         "64") or 64),
        prefix_blocks=int(os.environ.get(
            "SERVE_PREFILL_PREFIX_BLOCKS", "256") or 0),
        generation=int(os.environ.get("SERVE_GENERATION", "0") or 0))
    print(f"prefill pool {os.environ.get('MODEL_PRESET', '7b')} "
          f"(resumed={resumed}, tp={tp}, kv_quant={kv_quant}, "
          f"weight_quant={wq}, "
          f"lanes={lanes}, max_len={max_len}) on :{env.port}",
          flush=True)
    budget = float(os.environ.get("SERVE_DRAIN_BUDGET_S", "30"))
    code = [0]

    def drain(reason: str) -> None:
        fe = srv.frontend
        fe.flightrec.record("drain_start", reason=str(reason))
        fe.flightrec.dump_file("sigterm")
        fe.draining = True          # /readyz false, new prefills 503
        deadline = time.monotonic() + budget
        while fe.depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.05)        # in-flight jobs finish + flush
        # a short grace so finished jobs' responses leave the socket
        time.sleep(0.2)
        code[0] = EXIT_PREEMPTED
        srv.shutdown()

    watcher = PreemptionWatcher.install()
    watcher.on_drain(lambda reason: threading.Thread(
        target=drain, args=(reason,), daemon=True).start())
    srv.serve_forever()
    srv.frontend.close()
    return code[0]


if __name__ == "__main__":
    raise SystemExit(main())
