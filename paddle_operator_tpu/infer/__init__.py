"""Inference: decode loops, paged KV cache, serving, durable KV store.

Exports are resolved lazily (PEP 562) so that jax-free submodules —
``infer.kvstore``, which the router process imports to consult the
durable prefix store — can be loaded without dragging in the jax-backed
decode stack via this package ``__init__``.
"""

_DECODE_EXPORTS = (
    "decode_step",
    "generate",
    "init_cache",
    "make_decode_fn",
    "prefill",
    "speculative_generate",
)

__all__ = list(_DECODE_EXPORTS)


def __getattr__(name):
    if name in _DECODE_EXPORTS:
        from paddle_operator_tpu.infer import decode

        return getattr(decode, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
