from paddle_operator_tpu.infer.decode import (  # noqa: F401
    decode_step,
    generate,
    init_cache,
    make_decode_fn,
    prefill,
    speculative_generate,
)
