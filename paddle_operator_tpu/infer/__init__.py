from paddle_operator_tpu.infer.decode import (  # noqa: F401
    generate,
    init_cache,
    prefill,
)
