"""Serving-path fault tolerance: deadlines, drain, dispatch watchdog.

The operator's whole pitch (PAPER.md) is lifecycle robustness — drain,
restart accounting, status conditions — and PR 2 built exactly that for
TRAINING (ft/preemption.py: SIGTERM -> finish the step -> durable
checkpoint -> ``EXIT_PREEMPTED``).  The serving ring had none of it: a
stuck compiled dispatch hung every lane forever, SIGTERM killed
in-flight requests silently, and one slow client could pin a lane (and
its paged KV blocks) to its full token budget.  This module is the
serving half of the same contract, in the crash-only spirit of Candea &
Fox: traffic degrades by shedding INDIVIDUAL requests (a deadline
partial, a retriable 503) instead of losing the ring, and when the ring
itself is sick it is rebuilt from scratch — never patched in place.

Pieces (all host-side; nothing here imports jax):

- **Typed failure surface** — :class:`ShuttingDown` /
  :class:`RetriableError` (503 + ``Retry-After``: the request was fine,
  the server was not), :class:`DeadlineExceeded` (504: the budget ran
  out; partial tokens are still delivered), :class:`LaneQuarantined`
  (the lane's numerics went non-finite; one request fails, the ring
  survives).
- :class:`RingResilience` — the knobs (watchdog thresholds, restart
  budget, backoff, NaN check), env-constructable for serve.py.
- :class:`DispatchWatchdog` — a monitor thread that times every
  blocking device interaction against N x rolling-p95 and fires a
  stall callback when one wedges, so waiting clients get fast 503s
  even while the host thread is still stuck inside XLA.
- :class:`RestartBudget` — exponential backoff with a hard cap; when
  the cap is spent the ring stops self-healing and flips ``/healthz``
  unhealthy so the orchestrator replaces the pod (crash-only again).
- :class:`ServingDrain` — SIGTERM -> stop admissions (503 +
  ``Retry-After``) -> finish in-flight lanes within a drain budget ->
  flush partials -> exit ``EXIT_PREEMPTED`` so the reconciler's
  preempted-not-failed accounting (controller/builders.py
  is_pod_preempted) covers serving pods exactly like trainers.  A
  second SIGTERM means the platform is out of patience: immediate
  best-effort flush and exit.

The deterministic fault injector that exercises every one of these
paths lives in infer/chaos.py; tests/test_resilience.py and the dryrun
``serve-chaos`` gate pin the behavior.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

# Serving pods exit with the SAME code trainers drain to — the
# reconciler already counts it as capacity loss, not program failure.
from paddle_operator_tpu.ft.preemption import EXIT_PREEMPTED  # noqa: F401


# ---------------------------------------------------------------------------
# Failure surface
# ---------------------------------------------------------------------------


class ShuttingDown(RuntimeError):
    """The server is draining (SIGTERM) or closed: the request was
    never started and is safe to retry elsewhere.  serve.py maps it to
    503 + ``Retry-After``."""


class RetriableError(RuntimeError):
    """The ring failed underneath this request (dispatch fault, stall,
    self-healing rebuild) — nothing was wrong with the request; retry
    it.  serve.py maps it to 503 + ``Retry-After``."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline expired before generation finished.  The
    request still RESOLVES (with the tokens produced so far — the
    504-style partial); this type only appears when a caller asks why
    the stream stopped short."""


class LaneQuarantined(RetriableError):
    """This lane's logits went non-finite (NaN/inf) — its request is
    failed and the lane retired (blocks scrubbed + freed) WITHOUT
    touching the other lanes.  Retriable: re-admission re-prefills from
    clean state, and transient numerics (a cosmic-rayed HBM row, a bad
    chip) often do not reproduce."""


class LaneMigrated(RetriableError):
    """This lane migrated to a peer replica (ISSUE 12 fleet-level KV:
    drain-by-migration or parked-lane shed).  The peer resumes the
    stream bit-identically from the spilled bytes; the client's retry
    — same idempotent ``request_id``, through the router — lands on
    the adopter and collects the FULL result (the router's migration
    table pins the id to the adopter before this error is ever
    raised).  serve.py maps it to 503 + ``Retry-After`` like every
    retriable."""


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------


@dataclass
class RingResilience:
    """Fault-tolerance knobs for one ContinuousBatcher.

    Passing an instance turns self-healing ON: ring-level dispatch
    failures fail the in-flight requests retriably and rebuild the ring
    (fresh cache/pool, queued work re-admitted) behind exponential
    backoff, up to ``max_restarts``; exhausting the budget flips the
    batcher unhealthy (``/healthz``) instead of looping forever.
    Without one the batcher keeps its legacy die-on-error behavior.
    """

    # stall threshold: max(stall_floor_s, stall_factor * rolling-p95 of
    # recent dispatch/consume waits).  The floor must comfortably clear
    # a first-dispatch XLA compile (tens of seconds on CPU).
    watchdog: bool = True
    stall_factor: float = 8.0
    stall_floor_s: float = 60.0
    # a stall that ALSO exceeds hard_stall_factor x the threshold is a
    # wedged device: the process cannot recover itself (the host thread
    # is stuck inside XLA), so healthz flips and the pod gets replaced
    hard_stall_factor: float = 4.0
    poll_s: float = 0.05
    # self-healing budget: restarts are cheap but not free (every
    # resident request fails retriably), and a ring that needs them
    # continuously is broken hardware — stop and let k8s replace the
    # pod.  The budget REFILLS after restart_window_s without another
    # restart (crash-loop-backoff style): it bounds restart DENSITY,
    # not lifetime count — a long-lived pod healing one transient fault
    # a week must not die on the max_restarts-th week.
    max_restarts: int = 3
    restart_window_s: float = 300.0
    backoff_base_s: float = 0.25
    backoff_max_s: float = 10.0
    # per-dispatch isfinite fold over the chunk's logits: quarantines a
    # NaN-producing lane (fail ONE request, never the ring).  Off by
    # default — it adds a [slots] bool output to the resident program.
    nan_check: bool = False

    @classmethod
    def from_env(cls, env=None) -> "RingResilience":
        """serve.py construction: SERVE_WATCHDOG_FACTOR/FLOOR,
        SERVE_MAX_RESTARTS, SERVE_NAN_CHECK (docs/serving.md)."""
        env = os.environ if env is None else env
        return cls(
            watchdog=env.get("SERVE_WATCHDOG", "1") == "1",
            stall_factor=float(env.get("SERVE_WATCHDOG_FACTOR", "8")),
            stall_floor_s=float(env.get("SERVE_WATCHDOG_FLOOR_S", "60")),
            max_restarts=int(env.get("SERVE_MAX_RESTARTS", "3")),
            restart_window_s=float(env.get("SERVE_RESTART_WINDOW_S",
                                           "300")),
            nan_check=env.get("SERVE_NAN_CHECK", "0") == "1",
        )


# ---------------------------------------------------------------------------
# Rolling quantile + watchdog
# ---------------------------------------------------------------------------


class RollingQuantile:
    """Nearest-rank quantile over the last ``window`` samples — the
    rolling p95 the stall threshold scales from.  Tiny windows and rare
    updates: a sorted copy per query is cheaper than a tree."""

    def __init__(self, q: float = 0.95, window: int = 64) -> None:
        self.q = q
        self.window = window
        self._xs: List[float] = []
        self._lock = threading.Lock()

    def add(self, x: float) -> None:
        with self._lock:
            self._xs.append(float(x))
            if len(self._xs) > self.window:
                del self._xs[0]

    def value(self) -> Optional[float]:
        with self._lock:
            if not self._xs:
                return None
            xs = sorted(self._xs)
        return xs[min(len(xs) - 1, int(round(self.q * (len(xs) - 1))))]


class DispatchWatchdog:
    """Times every blocking device interaction of one ring against
    ``max(floor, factor * rolling-p95)``.

    The ring thread brackets each region (``begin()``/``end()`` or the
    ``watch()`` context manager); a daemon monitor thread polls the
    in-flight region and fires ``on_stall(elapsed)`` ONCE when it
    crosses the threshold — while the ring thread is still stuck, which
    is the point: clients get their retriable 503s immediately instead
    of after the wedge resolves (if it ever does).  A region that also
    crosses ``hard_stall_factor x threshold`` fires ``on_hard_stall``:
    the host thread is unrecoverably stuck inside the runtime and only
    a pod replacement clears it.
    """

    def __init__(self, cfg: RingResilience,
                 on_stall: Callable[[float], None],
                 on_hard_stall: Optional[Callable[[float], None]] = None
                 ) -> None:
        self.cfg = cfg
        self._on_stall = on_stall
        self._on_hard = on_hard_stall
        self._p95 = RollingQuantile(0.95)
        self._lock = threading.Lock()
        self._start: Optional[float] = None
        # megastep awareness (ISSUE 11): a region covering N fused ring
        # iterations legitimately takes ~N x a 1-step one.  Samples are
        # NORMALIZED to per-iteration time at end() and the threshold
        # multiplies back by the in-flight region's scale — so the p95
        # stays meaningful across N changes and enabling SERVE_MEGASTEP
        # cannot trip spurious stall rebuilds.  scale 1 (the default)
        # is byte-identical to the pre-megastep watchdog.
        self._scale = 1.0
        self._gen = 0                 # region id, so a stall fires once
        self._stalled_gen = -1
        self._hard_gen = -1
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._monitor, daemon=True,
                                        name="dispatch-watchdog")
        self._thread.start()

    # -- ring-thread side --------------------------------------------------

    def begin(self, scale: float = 1.0) -> None:
        """``scale``: how many fused ring iterations this region covers
        (SERVE_MEGASTEP; 1 for ordinary dispatches)."""
        with self._lock:
            self._gen += 1
            self._start = time.monotonic()
            self._scale = max(1.0, float(scale))

    def end(self) -> None:
        with self._lock:
            if self._start is None:
                return
            dur = time.monotonic() - self._start
            # a region already DECLARED stalled must not feed the p95:
            # one 100s wedge would drag the threshold to factor x 100s
            # and blind the watchdog to every later stall
            if self._gen != self._stalled_gen:
                self._p95.add(dur / self._scale)   # per-iteration time
            self._start = None

    class _Watch:
        def __init__(self, wd):
            self._wd = wd

        def __enter__(self):
            self._wd.begin()

        def __exit__(self, *exc):
            self._wd.end()
            return False

    def watch(self) -> "DispatchWatchdog._Watch":
        return self._Watch(self)

    # -- monitor side ------------------------------------------------------

    def threshold(self) -> float:
        """Stall threshold for the IN-FLIGHT region: the factor term
        scales with the region's fused iteration count (its per-
        iteration p95 budget x N); the floor stays absolute — it
        guards first-dispatch compiles, which do not scale with N."""
        with self._lock:
            scale = self._scale
        p95 = self._p95.value()
        if p95 is None:
            return self.cfg.stall_floor_s
        return max(self.cfg.stall_floor_s,
                   scale * self.cfg.stall_factor * p95)

    def _monitor(self) -> None:
        while not self._stop.wait(self.cfg.poll_s):
            with self._lock:
                start, gen = self._start, self._gen
                stalled, hard = self._stalled_gen, self._hard_gen
            if start is None:
                continue
            elapsed = time.monotonic() - start
            thr = self.threshold()
            if elapsed > thr and gen != stalled:
                with self._lock:
                    self._stalled_gen = gen
                try:
                    self._on_stall(elapsed)
                except Exception:
                    pass
            if (self._on_hard is not None
                    and elapsed > thr * self.cfg.hard_stall_factor
                    and gen != hard):
                with self._lock:
                    self._hard_gen = gen
                try:
                    self._on_hard(elapsed)
                except Exception:
                    pass

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


class RestartBudget:
    """Exponential backoff with a restart-density cap.

    ``spend()`` returns the backoff seconds to sleep before the rebuild
    (0.25s, 0.5s, 1s, ... capped) — callers check :attr:`exhausted`
    FIRST; an exhausted budget means the ring stops self-healing and
    the pod's /healthz flips so the orchestrator replaces it.  A quiet
    ``restart_window_s`` since the last restart refills the budget (and
    resets the backoff ladder): the cap is on restarts-per-window, not
    per-lifetime, so transient faults weeks apart never kill a healthy
    long-lived pod.  ``clock`` is injectable for tests."""

    def __init__(self, cfg: RingResilience, clock=time.monotonic) -> None:
        self.cfg = cfg
        self.used = 0
        self._clock = clock
        self._last: Optional[float] = None

    def _refill(self) -> None:
        if (self._last is not None and self.used
                and self._clock() - self._last
                >= self.cfg.restart_window_s):
            self.used = 0

    @property
    def exhausted(self) -> bool:
        self._refill()
        return self.used >= self.cfg.max_restarts

    def spend(self) -> float:
        self._refill()
        backoff = min(self.cfg.backoff_max_s,
                      self.cfg.backoff_base_s * (2 ** self.used))
        self.used += 1
        self._last = self._clock()
        return backoff


# ---------------------------------------------------------------------------
# SIGTERM drain for the server
# ---------------------------------------------------------------------------


class ServerState:
    """Shared readiness flags between the HTTP handler threads and the
    drain/ring machinery (plain attrs; writes are single-word stores
    under the GIL)."""

    def __init__(self) -> None:
        self.draining = False
        # seconds the 503 Retry-After advertises while draining — long
        # enough for the replacement pod to come up behind the Service
        self.retry_after_s = 5


class ServingDrain:
    """The serving half of the ft/preemption.py drain contract.

    First SIGTERM (via a :class:`~paddle_operator_tpu.ft.preemption.
    PreemptionWatcher` this object chains onto): stop admissions (every
    new request gets 503 + ``Retry-After``), let resident lanes finish
    within ``budget_s``, cancel stragglers at the budget (their callers
    receive the tokens produced so far — partials are flushed, not
    dropped), shut the HTTP server down, exit ``EXIT_PREEMPTED`` so the
    reconciler restarts the pod without burning ``maxRestarts``.

    Second SIGTERM: the platform's grace period is nearly up — cancel
    everything best-effort and exit ``EXIT_PREEMPTED`` NOW (partials
    flush at the next chunk boundary if one lands, and are lost
    otherwise; an undrained kill would have lost them anyway).

    ``exit_fn`` is injectable for tests (production: ``os._exit`` —
    serve_forever holds the main thread, a SystemExit from a drain
    thread would be swallowed)."""

    def __init__(self, server, state: ServerState, *,
                 batcher=None, budget_s: float = 30.0,
                 handler_grace_s: float = 2.0,
                 exit_fn: Optional[Callable[[int], None]] = None) -> None:
        self.server = server
        self.state = state
        self.batcher = batcher
        self.budget_s = budget_s
        self.handler_grace_s = handler_grace_s
        self._exit = exit_fn or (lambda code: os._exit(code))
        self._signals = 0
        self._prev = None
        self._started = threading.Event()
        self.done = threading.Event()     # drain ran to completion

    # -- wiring ------------------------------------------------------------

    def install(self, watcher, sig: int = signal.SIGTERM) -> None:
        """Chain onto an installed PreemptionWatcher: its on_drain
        callback starts the drain (so notice-file triggers work too),
        and our own handler in FRONT of it counts repeat signals for
        the immediate-exit escalation.  Must run on the main thread
        (CPython signal rule), before ``serve_forever``."""
        watcher.on_drain(lambda reason: self.start_async(reason))
        self._prev = signal.signal(sig, self._handler)

    def _handler(self, signum, frame) -> None:
        self._signals += 1
        if self._signals >= 2:
            self.hard_exit()
            return
        prev = self._prev
        if callable(prev):
            prev(signum, frame)       # the watcher's handler -> trigger

    # -- the sequence ------------------------------------------------------

    def start_async(self, reason: str = "signal") -> None:
        """Run the drain on its own thread — the signal handler (or the
        watcher's trigger) must return immediately."""
        if self._started.is_set():
            return
        threading.Thread(target=self.run, args=(reason,), daemon=True,
                         name="serving-drain").start()

    def run(self, reason: str = "manual") -> None:
        """The drain sequence, callable directly from tests."""
        if self._started.is_set():
            return
        self._started.set()
        self.state.draining = True
        # flight recorder (ISSUE 15): persist the pod's event ring the
        # moment the drain starts — the process exits at the end of
        # this sequence, and the dump is the post-mortem record of the
        # final moments (the batcher's own drain appends drain_start/
        # drain_done events on top)
        fr = getattr(self.batcher, "flightrec", None)
        if fr is not None:
            fr.record("sigterm", reason=str(reason))
            fr.dump_file("sigterm")
        try:
            if self.batcher is not None:
                self.batcher.drain(self.budget_s)
                if fr is not None:
                    # re-dump with the drain's own events appended —
                    # the early dump above covered a crash mid-drain
                    fr.dump_file("sigterm")
            try:
                self.server.shutdown()
            except Exception:
                pass
            # the batcher just RESOLVED the last requests, but their
            # HTTP handler threads may still be writing the partial
            # responses — shutdown() only stops the accept loop.  Give
            # them a bounded beat before the exit below kills the
            # process mid-write, or "partials flushed" would be a lie
            # exactly at the finish line.
            threads = getattr(self.server, "_threads", None)
            deadline = time.monotonic() + self.handler_grace_s
            if threads is None:
                time.sleep(min(0.2, self.handler_grace_s))
            else:
                while (any(t.is_alive() for t in list(threads))
                       and time.monotonic() < deadline):
                    time.sleep(0.05)
        finally:
            self.done.set()
            # inside the finally ON PURPOSE: if the drain itself raised,
            # dying WITHOUT the exit would leave a pod serving only 503s
            # until the kubelet SIGKILLs it — exit 137, a budget-burning
            # "program failure" instead of the preemption this was
            self._exit(EXIT_PREEMPTED)

    def hard_exit(self) -> None:
        """Second-signal semantics: immediate exit, partials flushed
        best-effort (cancel marks every lane; whatever the ring already
        emitted has been delivered to result()/stream() consumers)."""
        self.state.draining = True
        if self.batcher is not None:
            try:
                self.batcher.abort(ShuttingDown(
                    "server killed (second SIGTERM)"))
            except Exception:
                pass
            fr = getattr(self.batcher, "flightrec", None)
            if fr is not None:
                fr.dump_file("second_sigterm")
        self.done.set()
        self._exit(EXIT_PREEMPTED)
