"""Minimal generation server — the deployable face of the infer layer.

Runs in a worker pod (or anywhere with the params): loads a checkpoint
through the same ``TPUJOB_CHECKPOINT_PATH`` contract training uses, jits
:func:`infer.decode.generate`, and serves JSON over stdlib HTTP (the same
transport discipline as the ps/ and heter/ tiers — no web framework).

    POST /v1/generate
      {"tokens": [[...], ...], "max_new_tokens": N,
       "temperature": 0.7, "top_k": 40, "top_p": 0.9, "eos_token": 2}
    -> {"tokens": [[...], ...]}   (prompt + continuation per row)

Two modes:

- **batch mode** (:class:`Generator`): each distinct (batch,
  prompt-length, options) combination jits once (bounded LRU) and whole
  batches run synchronously — exact, simple, but staggered requests
  serialize behind each other.
- **continuous mode** (``make_server(..., continuous=True)``): requests
  are admitted into a fixed ring of decode lanes sharing ONE resident
  compiled step (infer/batcher.py) — staggered concurrent requests
  decode side by side, lanes recycle on eos/budget, and the compile set
  is fixed regardless of arrival pattern.  Per-request knobs:
  max_new_tokens, temperature, seed, eos_token; top-k/top-p are
  server-global statics of the resident program.  With
  ``SERVE_SPEC_K > 0`` the ring decodes SPECULATIVELY (docs/serving.md):
  a draft model proposes K tokens per round, the target verifies them
  in one chunked forward, and every response carries its measured
  ``accept_rate``.  With ``SERVE_PAGED=1`` the ring's KV lives in a
  block pool with radix prefix reuse (infer/paged.py): requests
  sharing a cached prompt prefix skip its prefill entirely, and the
  ``status.serving`` block gains ``prefixHitRate``/``kvBlocksFree``
  for the manager's /metrics gauges.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp

from paddle_operator_tpu.infer import decode as D
from paddle_operator_tpu.models.llama import LlamaConfig


class Generator:
    """Jit-per-(shape, options) wrapper around decode.generate.

    The compile cache is a bounded LRU: a long-lived server facing
    clients with varied shapes must not grow jitted programs (and XLA
    compile state) without limit.  Evicted entries simply recompile on
    next use."""

    MAX_CACHED = 32

    def __init__(self, params: Any, cfg: LlamaConfig,
                 max_cached: int = MAX_CACHED, mesh=None) -> None:
        # mesh (make_serving_mesh): TP-sharded batch serving — params
        # laid out once, every jitted generate compiles sharded
        self.mesh = mesh
        if mesh is not None and D.mesh_tp(mesh) > 1:
            params = D.shard_params_for_serving(params, cfg, mesh)
        self.params = params
        self.cfg = cfg
        self._fns: "OrderedDict[tuple, Any]" = OrderedDict()
        self._max_cached = max_cached
        self._lock = threading.Lock()

    def __call__(self, tokens: np.ndarray, *, max_new_tokens: int,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_token: Optional[int] = None,
                 seed: int = 0) -> np.ndarray:
        key = (tokens.shape, max_new_tokens, temperature, top_k, top_p,
               eos_token)
        with self._lock:
            fn = self._fns.get(key)
            if fn is None:
                fn = jax.jit(lambda p, t, k: D.generate(
                    p, self.cfg, t, max_new_tokens=max_new_tokens,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    eos_token=eos_token, key=k, mesh=self.mesh))
                self._fns[key] = fn
                while len(self._fns) > self._max_cached:
                    self._fns.popitem(last=False)
            else:
                self._fns.move_to_end(key)
        out = fn(self.params, jnp.asarray(tokens, jnp.int32),
                 jax.random.PRNGKey(seed))
        return np.asarray(out)


class ContinuousGenerator:
    """Adapter giving the decode ring the Generator call surface: rows
    of one HTTP request become independent ring requests (they may land
    in different decode waves), and the call blocks until all rows
    finish.  Concurrent HTTP threads interleave in the ring — that is
    the point."""

    def __init__(self, params: Any, cfg: LlamaConfig, **ring_kw) -> None:
        from paddle_operator_tpu.infer.batcher import ContinuousBatcher

        self.batcher = ContinuousBatcher(params, cfg, **ring_kw)
        self.cfg = cfg
        # fleet-level KV (ISSUE 12): lanes adopted from peers, keyed by
        # the migrated request's idempotent row id — the client's retry
        # (routed here by the router's migration table) collects the
        # result instead of re-generating.  Bounded: an unclaimed
        # handle is dropped oldest-first (its client gave up).
        self.adopted: "OrderedDict[str, Any]" = OrderedDict()
        self._adopted_lock = threading.Lock()

    ADOPTED_CAP = 512

    def adopt_envelope(self, buf: bytes) -> str:
        """Decode + adopt one migrated-lane envelope; returns the
        adopted request id.  Raises fleetkv.EnvelopeError on any
        validation failure (the handler maps it to 409)."""
        from paddle_operator_tpu.utils import fleetkv as FK

        meta, spill = FK.decode_lane(buf)
        rid = meta.get("requestId")
        if not rid:
            raise FK.EnvelopeError(
                "lane envelope carries no requestId — the result "
                "would be unretrievable")
        handle = self.batcher.adopt(meta, spill)
        with self._adopted_lock:
            old = self.adopted.pop(rid, None)
            if old is not None:
                old.cancel()    # replayed migration: one runner only
            self.adopted[rid] = handle
            while len(self.adopted) > self.ADOPTED_CAP:
                _, stale = self.adopted.popitem(last=False)
                stale.cancel()
        return rid

    def take_adopted(self, rid: Optional[str]):
        if rid is None:
            return None
        with self._adopted_lock:
            return self.adopted.pop(rid, None)

    def __call__(self, tokens: np.ndarray, *, max_new_tokens: int,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_token: Optional[int] = None,
                 seed: int = 0) -> list:
        rows, _, _, _ = self.generate_rows(
            tokens, max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_token=eos_token, seed=seed)
        return rows

    def generate_rows(self, tokens, *, max_new_tokens: int,
                      temperature: float = 0.0,
                      top_k: Optional[int] = None,
                      top_p: Optional[float] = None,
                      eos_token: Optional[int] = None, seed: int = 0,
                      request_id: Optional[str] = None,
                      deadline_s: Optional[float] = None,
                      priority: Optional[int] = None,
                      adapter: Optional[str] = None,
                      trace_ctx=None):
        """Rows + per-row speculative accept rates (None entries when
        the ring is not speculative) + per-row deadline-exceeded flags
        (a flagged row carries the PARTIAL tokens produced before its
        ``deadline_s`` budget ran out — the handler's 504-style
        response) + per-row span sets (ISSUE 15 — None entries when
        tracing is off; the router stitches them into one cross-pod
        timeline).  ``request_id`` (the client's, or the handler's
        fallback) is threaded into ``submit`` per row so capacity
        rejections name the offender; ``trace_ctx`` is the parsed
        ``X-Tpujob-Trace`` context every row traces under."""
        if (top_k, top_p) != (self.batcher._top_k, self.batcher._top_p) \
                and (top_k is not None or top_p is not None):
            raise ValueError(
                "top_k/top_p are fixed per continuous server "
                f"(configured: top_k={self.batcher._top_k} "
                f"top_p={self.batcher._top_p})")
        reqs = []
        try:
            for i, row in enumerate(tokens):
                rid_row = (f"{request_id}/row{i}"
                           if request_id is not None else None)
                # fleet-level KV (ISSUE 12): a row whose lane migrated
                # HERE is already decoding (or done) — collect it
                # instead of re-generating; rows without an adopted
                # lane submit as always
                handle = self.take_adopted(rid_row)
                if handle is None:
                    handle = self.batcher.submit(
                        row, max_new_tokens=max_new_tokens,
                        temperature=temperature, seed=seed + i,
                        eos_token=eos_token, deadline_s=deadline_s,
                        priority=priority, adapter=adapter,
                        request_id=rid_row, trace_ctx=trace_ctx)
                reqs.append(handle)
            # ragged rows: sequences stop at eos, no rectangular array
            rows = [r.result(timeout=600) for r in reqs]
        except Exception:
            # a later row's submit rejected (QueueFull) or a result
            # timed out: the already-submitted rows have no consumer —
            # without the cancel they would decode to their full budgets
            # and amplify exactly the overload that shed them
            for r in reqs:
                r.cancel()
            raise
        return (rows, [r.accept_rate for r in reqs],
                [r.deadline_exceeded for r in reqs],
                [getattr(r, "trace", None) for r in reqs])

    def close(self) -> None:
        self.batcher.close()


def _load_swap_checkpoint(path: str, cfg) -> Any:
    """Restore a TRAINING checkpoint's params for serving — the same
    restore + dtype-convert the entrypoint runs at boot — from the
    ``/v1/swap`` handler thread (ISSUE 19): the expensive half of a
    live swap happens HERE, off the ring loop, while the old
    generation keeps serving.  Raises when nothing restores (a swap
    must never silently flip to fresh-init weights)."""
    from paddle_operator_tpu.infer.quant import serving_params
    from paddle_operator_tpu.models.llama import Llama
    from paddle_operator_tpu.train import trainer as T
    from paddle_operator_tpu.train.checkpoint import (
        CheckpointManager,
        resume_or_init,
    )

    model = Llama(cfg)
    opt = T.make_optimizer()

    def init():
        p = model.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, 8), jnp.int32))["params"]
        return T.TrainState(step=jnp.zeros((), jnp.int32), params=p,
                            opt_state=opt.init(p))

    state, resumed = resume_or_init(CheckpointManager(path), init)
    if not resumed:
        raise ValueError(f"no checkpoint restorable at {path}")
    return serving_params(state.params, cfg.dtype)


class _Handler(BaseHTTPRequestHandler):
    generator: Generator  # injected
    state = None          # injected resilience.ServerState
    # fleet identity (make_server job=/replica=): labels the per-pod
    # /metrics gauges the fleet router scrapes for load scoring
    job_key = "local"
    replica_id = ""
    # chunked transfer (the streaming path) requires HTTP/1.1; plain
    # responses carry Content-Length so keep-alive stays correct, and
    # the socket timeout reaps idle/half-dead keep-alive connections
    # that would otherwise pin a server thread forever
    protocol_version = "HTTP/1.1"
    timeout = 120

    def log_message(self, *a):
        pass

    def _send(self, code: int, obj, headers=None) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _batcher(self):
        return getattr(self.generator, "batcher", None)

    def do_GET(self):
        # liveness vs readiness split (docs/serving.md resilience):
        # /healthz answers "should this pod be REPLACED" — 200 while
        # the process is up and the ring has not permanently died
        # (watchdog restart budget exhausted / wedged dispatch);
        # /readyz answers "should this pod take TRAFFIC" — also false
        # while merely draining or mid-self-heal, states /healthz must
        # NOT report (a restart would turn a 30s drain into lost work).
        if self.path == "/healthz":
            b = self._batcher()
            if b is not None and not b.healthy:
                self._send(503, {"ok": False, "reason": "ring dead"})
            else:
                self._send(200, {"ok": True})
        elif self.path == "/readyz":
            b = self._batcher()
            draining = bool(self.state and self.state.draining)
            ready = not draining and (b is None or b.accepting)
            if ready:
                self._send(200, {"ready": True})
            else:
                self._send(503, {
                    "ready": False,
                    "reason": ("draining" if draining else "ring"),
                }, headers={"Retry-After":
                            self.state.retry_after_s if self.state else 5})
        elif self.path == "/v1/adapters":
            # adapter registry surface (ISSUE 10): the loaded set, the
            # pool's capacity/rank contract, and which are serving
            b = self._batcher()
            reg = getattr(b, "adapters", None) if b is not None else None
            if reg is None:
                self._send(200, {"adapters": [], "capacity": 0})
            else:
                self._send(200, {"adapters": reg.names(),
                                 "capacity": reg.capacity,
                                 "rank": reg.rank})
        elif self.path == "/statusz":
            # the serving_status block as JSON — what a fleet replica
            # publishes toward status.serving, self-served for
            # debugging and for harnesses that want the raw block
            b = self._batcher()
            st = b.serving_status() if b is not None else {}
            if self.replica_id:
                st["replica"] = self.replica_id
            self._send(200, st)
        elif self.path == "/metrics":
            # per-pod prometheus gauges (the SAME names the manager
            # exports fleet-wide): the router scrapes
            # tpujob_serve_queue_depth / kv_blocks_free /
            # tokens_per_sec from here to score replica load — plus
            # the latency histograms (ISSUE 15) it folds fleet-wide
            from paddle_operator_tpu.utils.observability import (
                histogram_exposition,
                serving_gauges,
            )

            b = self._batcher()
            st = b.serving_status() if b is not None else {}
            gauges = serving_gauges(st, self.job_key,
                                    replica=self.replica_id or None)
            text = "".join(f"{k} {v}\n"
                           for k, v in sorted(gauges.items()))
            text += histogram_exposition(st.get("latencyHist"),
                                         self.job_key,
                                         self.replica_id or None)
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/debug/flightrec":
            # the pod's bounded event ring (ISSUE 15) — the same JSON
            # a watchdog-restart/chaos/SIGTERM dump writes to disk
            b = self._batcher()
            fr = getattr(b, "flightrec", None) if b is not None else None
            self._send(200, fr.dump("debug_endpoint") if fr is not None
                       else {"events": []})
        else:
            self._send(404, {})

    def _stream_generate(self, req, trace_ctx=None,
                         id_hdrs=None) -> None:
        """``"stream": true`` (continuous mode, single row): emit
        newline-delimited JSON events as the ring produces tokens —
        {"token": t} per generated token, then {"done": true, "tokens":
        [full sequence]}.  Chunked transfer; tokens arrive in
        chunk-sized bursts (the ring's decode granularity).  On a
        tracing ring the done event carries the span set (the router's
        streaming relay does not parse the stream, so streamed
        timelines stitch client-side; docs/observability.md)."""
        gen = self.generator
        if not isinstance(gen, ContinuousGenerator):
            raise ValueError("streaming requires the continuous server "
                             "(SERVE_CONTINUOUS=1)")
        if ((req.get("top_k"), req.get("top_p"))
                != (gen.batcher._top_k, gen.batcher._top_p)
                and (req.get("top_k") is not None
                     or req.get("top_p") is not None)):
            raise ValueError(
                "top_k/top_p are fixed per continuous server "
                f"(configured: top_k={gen.batcher._top_k} "
                f"top_p={gen.batcher._top_p})")
        tokens = np.asarray(req["tokens"], np.int32)
        if tokens.ndim != 2 or tokens.shape[0] != 1:
            raise ValueError("streaming takes tokens [1, seq]")
        prio = req.get("priority")
        handle = gen.batcher.submit(
            tokens[0], max_new_tokens=int(req.get("max_new_tokens", 32)),
            temperature=float(req.get("temperature", 0.0)),
            seed=int(req.get("seed", 0)), eos_token=req.get("eos_token"),
            stream=True, request_id=req.get("request_id"),
            deadline_s=req.get("deadline_s"),
            priority=int(prio) if prio is not None else None,
            adapter=req.get("adapter"), trace_ctx=trace_ctx)

        def emit(obj) -> None:
            body = json.dumps(obj).encode() + b"\n"
            self.wfile.write(f"{len(body):x}\r\n".encode() + body
                             + b"\r\n")
            self.wfile.flush()

        # everything from the first socket write onward sits inside the
        # try: a disconnect raising in send_response/end_headers must
        # still reach the finally's cancel, or the abandoned request
        # holds its decode lane to the full token budget
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            for k, v in (id_hdrs or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            for tok in handle.stream(timeout=600):
                emit({"token": tok})
            done_ev = {"done": True, "tokens": handle.result(timeout=5)}
            if handle.accept_rate is not None:   # speculative ring
                done_ev["accept_rate"] = handle.accept_rate
            if handle.deadline_exceeded:         # 504-style partial
                done_ev["deadline_exceeded"] = True
            if getattr(handle, "trace", None) is not None:
                done_ev["trace"] = handle.trace.to_wire()
            emit(done_ev)
            self.wfile.write(b"0\r\n\r\n")
        except OSError:
            return   # client disconnected mid-stream: nothing to say
        except Exception as e:
            try:
                emit({"error": str(e)})
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass
        finally:
            # on ANY abandoning exit (disconnect, stream timeout, …) the
            # ring must stop decoding for this request — without the
            # cancel a few abandoned long streams would occupy all
            # decode lanes to their full max_new_tokens budget.  A no-op
            # when the generation already finished.
            handle.cancel()

    def _adapters_admin(self, body: bytes) -> None:
        """POST /v1/adapters — runtime load/evict on the serve surface
        (ISSUE 10): ``{"load": {"name": ..., "path"?: ..., "seed"?: ...}}``
        installs (path: .npz deltas; seed/neither: deterministic random
        smoke adapter), ``{"evict": "name"}`` removes — refused with 409
        while a resident or parked lane is still serving it."""
        b = self._batcher()
        reg = getattr(b, "adapters", None) if b is not None else None
        if reg is None:
            self._send(400, {"error": "no adapter registry (set "
                                      "SERVE_ADAPTERS to enable)"})
            return
        from paddle_operator_tpu.infer.qos import AdapterInUse

        def lanes_in_use():
            # resident + parked + QUEUED: a queued request already
            # resolved its adapter slot at submit — evicting/replacing
            # (and a later load reusing the slot) would serve it
            # another tenant's deltas
            in_use = {r.adapter_idx for r in b.lane if r is not None}
            in_use |= {pk.req.adapter_idx for pk in b._parked}
            in_use |= {r.adapter_idx for r in b._pending.items()}
            return in_use

        try:
            req = json.loads(body)
            if "load" in req:
                spec = req["load"]
                name = spec["name"]
                if spec.get("path"):
                    from paddle_operator_tpu.infer.qos import (
                        load_adapter_file,
                    )

                    deltas = load_adapter_file(b.cfg, spec["path"],
                                               reg.rank)
                    idx = reg.load(name, deltas,
                                   in_use=lanes_in_use())
                else:
                    idx = reg.load(name, seed=spec.get("seed"),
                                   in_use=lanes_in_use())
                self._send(200, {"loaded": name, "slot": idx})
            elif "evict" in req:
                reg.evict(req["evict"], in_use=lanes_in_use())
                self._send(200, {"evicted": req["evict"]})
            else:
                raise ValueError("body must carry 'load' or 'evict'")
        except AdapterInUse as e:
            self._send(409, {"error": str(e)})
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            self._send(400, {"error": str(e)})
        except OSError as e:
            self._send(400, {"error": f"adapter file: {e}"})

    def _kv_restore(self, body: bytes) -> None:
        """POST /v1/kv/restore — adopt a migrated lane (ISSUE 12).
        The body is a fleetkv LANE envelope; a valid one parks the
        lane for restore at the next loop boundary and the client's
        request_id-keyed retry collects the result.  Any validation
        failure refuses the WHOLE envelope: 409 tells the origin to
        keep the lane (completion-wait fallback)."""
        from paddle_operator_tpu.infer.resilience import ShuttingDown
        from paddle_operator_tpu.utils.fleetkv import EnvelopeError

        gen = self.generator
        if not isinstance(gen, ContinuousGenerator):
            self._send(400, {"error": "lane adoption requires the "
                                      "continuous server"})
            return
        if self.state is not None and self.state.draining:
            self._send(503, {"error": "draining"},
                       headers={"Retry-After":
                                self.state.retry_after_s})
            return
        try:
            rid = gen.adopt_envelope(body)
            self._send(200, {"adopted": rid})
        except ShuttingDown as e:
            self._send(503, {"error": str(e)})
        except EnvelopeError as e:
            # flight recorder (ISSUE 15): a refused envelope (CRC,
            # fingerprint skew, truncation) is exactly the event fleet
            # debugging needs a durable record of
            fr = getattr(self._batcher(), "flightrec", None)
            if fr is not None:
                fr.record("envelope_refused", error=str(e)[:200])
            self._send(409, {"error": str(e)})
        except Exception as e:      # noqa: BLE001 — refuse, never crash
            self._send(400, {"error": str(e)})

    def _kv_prefix(self, body: bytes) -> None:
        """POST /v1/kv/prefix — export demoted blocks of a prompt's
        radix chain (ISSUE 12 peer prefix fetch).  200 + a PREFIX
        envelope when the host tier holds any of the chain; 204
        otherwise.  The radix is ring-thread state and this runs on a
        handler thread: any racy surprise degrades to 204 (the
        requester re-prefills cold, exactly as without the fetch)."""
        b = self._batcher()
        try:
            req = json.loads(body)
            tokens = [int(t) for t in req["tokens"]]
            ns = int(req.get("ns", 0))
            if (b is None or b.pool is None or ns != 0
                    or b.pool.host is None):
                raise LookupError
            chunks, idx, payloads = b.pool.export_host_chain(tokens,
                                                             ns=0)
            if not idx:
                raise LookupError
            from paddle_operator_tpu.utils import fleetkv as FK

            # materialize lazily-demoted device slices to numpy HERE
            # (jax arrays are immutable — a concurrent read is safe)
            payloads = [{k: np.asarray(v) for k, v in p.items()}
                        for p in payloads]
            buf = FK.encode_prefix({"fingerprint": b._fingerprint()},
                                   chunks, idx, payloads)
        except Exception:       # noqa: BLE001 — nothing to export
            self.send_response(204)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(buf)))
        self.end_headers()
        self.wfile.write(buf)

    def _swap(self, body: bytes) -> None:
        """POST /v1/swap — live weight swap / elastic TP resize
        (ISSUE 19, docs/serving.md "Live model lifecycle").  Body keys
        (all optional): ``checkpoint`` (path; omitted = rebuild from
        the retained boot base — the TP-resize / quant-flip shape),
        ``draft_checkpoint`` (spec rings), ``tp`` (target degree;
        omitted = keep the mesh), ``generation`` (explicit; omitted =
        bump by one), ``weight_quant`` / ``draft_quant``
        (none|int8|int4; omitted = keep the serving mode),
        ``timeout_s``.  The checkpoint load + quantize runs on THIS
        handler thread while the old generation keeps serving; only
        the quiesce-flip-restore runs on the ring loop.  Responses:
        200 + post-swap summary, 409 a swap is already in flight,
        503 + Retry-After the ring cannot swap right now (draining /
        rebuilding / never reached a boundary — retry)."""
        from paddle_operator_tpu.infer.resilience import (
            RetriableError,
            ShuttingDown,
        )

        b = self._batcher()
        if b is None:
            self._send(400, {"error": "live swap requires the "
                             "continuous ring (SERVE_CONTINUOUS=1)"})
            return
        retry_hdr = {"Retry-After":
                     self.state.retry_after_s if self.state else 5}
        try:
            req = json.loads(body) if body else {}
            base = getattr(self.server, "swap_base", None)
            cfg = getattr(self.generator, "cfg", None)
            ckpt = req.get("checkpoint")
            if ckpt:
                params = _load_swap_checkpoint(ckpt, cfg)
            elif base is not None:
                params = base["params"]
            else:
                raise ValueError(
                    "no 'checkpoint' given and no retained base "
                    "(SERVE_SWAP_RETAIN=0) — nothing to swap to")
            wq = req.get("weight_quant")
            if wq is None:
                wq = (base or {}).get("weight_quant", "none")
            wq = wq or "none"
            if wq != "none":
                from paddle_operator_tpu.infer.quant import (
                    SERVING_SKIP,
                    quantize_params,
                )

                params = quantize_params(params, cfg, mode=wq,
                                         skip=SERVING_SKIP)
            dparams = None
            if getattr(b, "spec_k", 0) > 0:
                dck = req.get("draft_checkpoint")
                if dck:
                    dparams = _load_swap_checkpoint(dck, b.draft_cfg)
                elif base is not None \
                        and base.get("draft_params") is not None:
                    dparams = base["draft_params"]
                else:
                    raise ValueError(
                        "speculative ring: a swap needs "
                        "'draft_checkpoint' or a retained draft base")
                dwq = req.get("draft_quant")
                if dwq is None:
                    dwq = (base or {}).get("draft_quant", "none")
                if (dwq or "none") != "none":
                    from paddle_operator_tpu.infer.quant import (
                        SERVING_SKIP,
                        quantize_params,
                    )

                    dparams = quantize_params(dparams, b.draft_cfg,
                                              mode=dwq,
                                              skip=SERVING_SKIP)
            kw = {}
            tp = req.get("tp")
            if tp is not None and int(tp) != b.serving_tp():
                if int(tp) > 1:
                    from paddle_operator_tpu.parallel.mesh import (
                        make_serving_mesh,
                    )

                    kw["mesh"] = make_serving_mesh(int(tp))
                else:
                    kw["mesh"] = None
            if req.get("generation") is not None:
                kw["generation"] = int(req["generation"])
            res = b.swap_weights(
                params, draft_params=dparams,
                timeout=float(req.get("timeout_s", 120.0)), **kw)
            self._send(200, res)
        except (ShuttingDown, RetriableError) as e:
            self._send(503, {"error": str(e)}, headers=retry_hdr)
        except ValueError as e:
            already = "already in flight" in str(e)
            self._send(409 if already else 400, {"error": str(e)})
        except (KeyError, TypeError, json.JSONDecodeError) as e:
            self._send(400, {"error": str(e)})
        except Exception as e:     # noqa: BLE001 — refuse, never crash
            self._send(503, {"error": str(e)}, headers=retry_hdr)

    def do_POST(self):
        from paddle_operator_tpu.infer.resilience import (
            RetriableError,
            ShuttingDown,
        )

        # drain the body before ANY response: under HTTP/1.1 keep-alive
        # an unread body would be parsed as the next request's start line
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n) if n else b""
        if self.path == "/v1/kv/restore":
            return self._kv_restore(body)
        if self.path == "/v1/kv/prefix":
            return self._kv_prefix(body)
        if self.path == "/v1/adapters":
            return self._adapters_admin(body)
        if self.path == "/v1/swap":
            return self._swap(body)
        if self.path != "/v1/generate":
            self._send(404, {})
            return
        retry_hdr = {"Retry-After":
                     self.state.retry_after_s if self.state else 5}
        if self.state is not None and self.state.draining:
            # SIGTERM drain: admissions stop FIRST — clients get an
            # explicit retry signal while resident lanes finish
            self._send(503, {"error": "server draining"},
                       headers=retry_hdr)
            return
        try:
            req = json.loads(body)
            # per-request deadline: the X-Request-Deadline header
            # (seconds, the load-balancer convention) or the body's
            # deadline_s — whichever is set; an expired request resolves
            # with the tokens produced so far and a 504-style marker
            # instead of pinning its lane
            deadline_s = req.get("deadline_s")
            hdr = self.headers.get("X-Request-Deadline")
            if deadline_s is None and hdr is not None:
                deadline_s = float(hdr)
            # QoS class (ISSUE 10): the X-Request-Priority header (the
            # router forwards it verbatim) or the body's ``priority``
            # — body wins when both are set, like deadline_s.  0 is
            # the most urgent class; unannotated requests get the
            # server's default (least urgent) class.
            priority = req.get("priority")
            phdr = self.headers.get("X-Request-Priority")
            if priority is None and phdr is not None:
                priority = int(phdr)
            # trace context (ISSUE 15): the router (or a client)
            # propagates X-Tpujob-Trace; on a SERVE_TRACE=1 ring every
            # row traces under it and the span sets ride the response
            # so the router can stitch one cross-pod timeline
            from paddle_operator_tpu.utils import tracing as _TR

            trace_ctx = _TR.parse_trace_header(
                self.headers.get(_TR.TRACE_HEADER))
            # fleet-debugging identity (ISSUE 15 satellite): every
            # generate reply names its request and serving replica.
            # The id is CLIENT input — sanitize before echoing it into
            # a header (CR/LF would split the response; non-latin-1
            # raises inside send_header after the status line)
            id_hdrs = {}
            if req.get("request_id") is not None:
                id_hdrs["X-Request-Id"] = _TR.safe_header_value(
                    req.get("request_id"))
            if self.replica_id:
                id_hdrs["X-Tpujob-Replica"] = self.replica_id
            if req.get("stream"):
                if deadline_s is not None:
                    req["deadline_s"] = float(deadline_s)
                if priority is not None:
                    req["priority"] = int(priority)
                return self._stream_generate(req, trace_ctx=trace_ctx,
                                             id_hdrs=id_hdrs)
            tokens = np.asarray(req["tokens"], np.int32)
            if tokens.ndim != 2:
                raise ValueError("tokens must be [batch, seq]")
            opts = dict(
                max_new_tokens=int(req.get("max_new_tokens", 32)),
                temperature=float(req.get("temperature", 0.0)),
                top_k=req.get("top_k"),
                top_p=req.get("top_p"),
                eos_token=req.get("eos_token"),
                seed=int(req.get("seed", 0)))
            gen = self.generator
            if isinstance(gen, ContinuousGenerator):
                # request_id (client-supplied) flows into submit so
                # validation errors in multi-request logs name their row
                rows, rates, expired, traces = gen.generate_rows(
                    tokens, request_id=req.get("request_id"),
                    deadline_s=(float(deadline_s)
                                if deadline_s is not None else None),
                    priority=(int(priority)
                              if priority is not None else None),
                    adapter=req.get("adapter"),
                    trace_ctx=trace_ctx,
                    **opts)
                resp = {"tokens": rows}
                if getattr(gen.batcher, "spec_k", 0) > 0:
                    # speculative ring: acceptance rides every response
                    resp["accept_rate"] = rates
                if any(t is not None for t in traces):
                    # per-row span sets (ISSUE 15): response metadata
                    # only — the token payload is untouched, so traced
                    # streams stay byte-identical to untraced ones
                    resp["trace"] = [t.to_wire() if t is not None
                                     else None for t in traces]
                if any(expired):
                    # deadline partials: 504 when EVERY row ran out
                    # (the whole request missed its budget), 200 with
                    # per-row flags on a mixed batch — either way the
                    # partial tokens are delivered, never dropped
                    resp["deadline_exceeded"] = expired
                    self._send(504 if all(expired) else 200, resp,
                               headers=id_hdrs)
                    return
                self._send(200, resp, headers=id_hdrs)
                return
            out = gen(tokens, **opts)
            out = out if isinstance(out, list) else out.tolist()
            self._send(200, {"tokens": out}, headers=id_hdrs)
        except (ShuttingDown, RetriableError) as e:
            # the request was fine, the server was not: an explicit
            # retry signal (drain shed, watchdog rebuild in progress)
            self._send(503, {"error": str(e)}, headers=retry_hdr)
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            self._send(400, {"error": str(e)})
        except Exception as e:
            # server-side failure (dead decode ring, generation timeout):
            # 503 tells clients to retry/fail over, not to blame their
            # request
            self._send(503, {"error": str(e)})


def make_server(host: str, port: int, params: Any, cfg: LlamaConfig,
                *, continuous: bool = False, mesh=None,
                job: str = "local", replica: str = "",
                **ring_kw) -> ThreadingHTTPServer:
    """``continuous=True`` serves through the decode ring
    (infer/batcher.py; ``ring_kw``: slots, max_len, chunk_tokens,
    prefill_buckets, top_k, top_p).  ``mesh`` (make_serving_mesh)
    makes either mode tensor-parallel — the ring's resident programs
    and the batch generator's jits compile sharded, token streams
    unchanged.  The returned server carries ``.generator`` — call its
    ``close()`` when tearing a continuous server down to stop the ring
    thread."""
    from paddle_operator_tpu.infer.resilience import ServerState

    gen = (ContinuousGenerator(params, cfg, mesh=mesh, **ring_kw)
           if continuous else Generator(params, cfg, mesh=mesh))
    state = ServerState()
    handler = type("Handler", (_Handler,),
                   {"generator": gen, "state": state,
                    "job_key": job, "replica_id": replica})
    srv = ThreadingHTTPServer((host, port), handler)
    srv.generator = gen
    # readiness/drain flags shared with the handler threads; a
    # resilience.ServingDrain flips state.draining on SIGTERM
    srv.state = state
    return srv


def wire_fleet_kv_from_env(batcher, port: int) -> None:
    """Fleet-level KV client wiring (ISSUE 12, docs/serving.md
    "Fleet-level KV"): ``SERVE_KV_MIGRATE=1`` drains by MIGRATION
    (residents spill + POST to a peer instead of waiting out
    completions; completion-wait stays the fallback for lanes no peer
    takes), ``SERVE_KV_PEER_FETCH=1`` asks the fleet for demoted
    prefix blocks on a local radix miss.  ``SERVE_KV_BROKER`` names
    the router (it picks adopters + dedupes replayed migrations);
    ``SERVE_KV_PEERS`` is the router-less static peer list.
    ``SERVE_MIGRATE_PARKED_S`` additionally sheds preemption-parked
    lanes to idle peers OUTSIDE a drain.  Everything here requires
    the paged ring (spills are block-granular); peer fetch further
    needs the host tier (imports land there and promote through the
    host-hit path).  Shared by the real entrypoint and the simfleet
    subprocess replicas."""
    import os

    kv_migrate = os.environ.get("SERVE_KV_MIGRATE", "0") == "1"
    kv_fetch = os.environ.get("SERVE_KV_PEER_FETCH", "0") == "1"
    if not (kv_migrate or kv_fetch):
        return
    if batcher.pool is None:
        print("SERVE_KV_MIGRATE/SERVE_KV_PEER_FETCH ignored: "
              "fleet-level KV requires the paged ring (SERVE_PAGED=1)",
              flush=True)
        return
    from paddle_operator_tpu.utils import fleetkv as FK

    # wire chaos (ISSUE 20): TPUJOB_WIRE_CHAOS scheduling faults on
    # the replica->broker edge swaps the broker endpoint for an
    # injured in-process proxy — migrations/prefix fetches then cross
    # a deterministically faulty wire without touching the router
    from paddle_operator_tpu.utils import wirechaos as WC

    origin = f"{os.environ.get('POD_IP', '127.0.0.1')}:{port}"
    kv_client = FK.FleetKVClient(
        broker=WC.wire_endpoint_from_env(
            "replica-broker", os.environ.get("SERVE_KV_BROKER", "")),
        peers=os.environ.get("SERVE_KV_PEERS", "").split(","),
        origin=origin)
    if kv_migrate:
        batcher.migrate_out = lambda meta, spill: \
            kv_client.migrate_out(FK.encode_lane(meta, spill))
        batcher._migrate_on_drain = True
        parked_s = float(os.environ.get("SERVE_MIGRATE_PARKED_S",
                                        "0") or 0)
        if parked_s > 0:
            batcher.migrate_parked_s = parked_s
    if kv_fetch:
        if batcher.pool.host is None:
            print("SERVE_KV_PEER_FETCH ignored: peer payloads import "
                  "through the host tier — set "
                  "SERVE_HOST_CACHE_BLOCKS/_MB", flush=True)
        else:
            batcher.peer_fetch = kv_client.fetch_prefix


def wire_kv_store_from_env(batcher) -> None:
    """Durable prefix store wiring (ISSUE 17, docs/serving.md "Durable
    prefix store"): ``SERVE_KV_STORE=dir:/path`` attaches the
    persistent tier below host/peer — host-tier overflow drops persist
    through a background writer instead of silently discarding, and the
    submit-thread probe order becomes peer -> store.  Lifecycle knobs:
    ``SERVE_KV_STORE_TTL_S`` (expire idle entries),
    ``SERVE_KV_STORE_BUDGET_MB`` (LRU size budget),
    ``SERVE_KV_STORE_JANITOR_S`` (in-process janitor period; 0 leaves
    lifecycle to the offline ``python -m
    paddle_operator_tpu.infer.kvstore`` pass — the shared-volume
    deployment shape), ``SERVE_KV_STORE_QUEUE`` (writer queue bound,
    drop-oldest).  Requires the paged ring + host tier (spills come
    from the tier; hits land through it); unset is byte-identical to
    the store-less ring."""
    import os
    import threading

    url = os.environ.get("SERVE_KV_STORE", "").strip()
    if not url:
        return
    if batcher.pool is None or batcher.pool.host is None:
        print("SERVE_KV_STORE ignored: the durable store spills from "
              "and promotes through the host tier — set SERVE_PAGED=1 "
              "and SERVE_HOST_CACHE_BLOCKS/_MB", flush=True)
        return
    from paddle_operator_tpu.infer import kvstore as KVS

    try:
        backend = KVS.parse_store_url(url)
    except (ValueError, OSError) as e:
        print(f"SERVE_KV_STORE ignored: {e}", flush=True)
        return
    store = KVS.KVBlockStore(
        backend, fingerprint=batcher._fingerprint(),
        ttl_s=float(os.environ.get("SERVE_KV_STORE_TTL_S", "0") or 0),
        budget_mb=int(os.environ.get("SERVE_KV_STORE_BUDGET_MB", "0")
                      or 0),
        queue_len=int(os.environ.get("SERVE_KV_STORE_QUEUE", "256")
                      or 256))
    batcher.attach_kv_store(store)
    janitor_s = float(os.environ.get("SERVE_KV_STORE_JANITOR_S", "0")
                      or 0)
    if janitor_s > 0:
        def _janitor_loop():
            while not batcher._stop.wait(janitor_s):
                try:
                    store.janitor()
                except OSError:
                    pass

        threading.Thread(target=_janitor_loop, daemon=True,
                         name="kvstore-janitor").start()
    print(f"durable KV store attached: {url} "
          f"(ttl_s={store.ttl_s}, budget_mb={store.budget_mb}, "
          f"janitor_s={janitor_s})", flush=True)


def main() -> int:
    """Serving entrypoint: restore params from TPUJOB_CHECKPOINT_PATH
    (fresh init if none — smoke mode) and serve on TPUJOB_PORT."""
    import os

    from paddle_operator_tpu.launch.launcher import JobEnv
    from paddle_operator_tpu.models.llama import Llama, make_model
    from paddle_operator_tpu.train import trainer as T
    from paddle_operator_tpu.train.checkpoint import (
        CheckpointManager,
        resume_or_init,
    )

    env = JobEnv.from_env()
    model, cfg = make_model(os.environ.get("MODEL_PRESET", "7b"))
    opt = T.make_optimizer()

    def init():
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]
        # full TrainState structure so a TRAINING checkpoint restores
        # cleanly; only params are served
        return T.TrainState(step=jnp.zeros((), jnp.int32), params=params,
                            opt_state=opt.init(params))

    ckpt = CheckpointManager()   # TPUJOB_CHECKPOINT_PATH
    state, resumed = resume_or_init(ckpt, init)
    from paddle_operator_tpu.infer.quant import serving_params

    # training checkpoints hold f32 master params; serving them unconverted
    # would stream double the weight bytes every decode step
    params = serving_params(state.params, cfg.dtype)
    if os.environ.get("QUANTIZE", "") == "int8":
        from paddle_operator_tpu.infer.quant import quantize_params

        params = quantize_params(params)   # ~1.4-1.5x decode at batch 8
    # SERVE_WEIGHT_QUANT=int8|int4 (docs/serving.md "Quantized
    # weights"): quantize the TARGET model's matmul kernels at load —
    # per-output-channel absmax codes + f32 scale planes replacing the
    # kernel leaves, dequant fused at the matmul sites, with the serving
    # skip list (embeddings / lm_head / norms stay bf16).  The codes
    # ride the params dispatch operand, so bf16-default processes trace
    # byte-identical programs.  SERVE_DRAFT_QUANT (below, spec rings
    # only) is the safe proving ground: quantize the draft first.
    wq = os.environ.get("SERVE_WEIGHT_QUANT", "none") or "none"
    # live swap (ISSUE 19): retain a HOST copy of the pre-quant serving
    # base so a checkpoint-less /v1/swap — a TP resize or a quant-mode
    # flip — can rebuild from it without a checkpoint round-trip.
    # Host RAM, not HBM; SERVE_SWAP_RETAIN=0 opts out (swaps then
    # require a 'checkpoint' in the body).
    swap_base = None
    if os.environ.get("SERVE_SWAP_RETAIN", "1") == "1":
        swap_base = {"params": jax.device_get(params),
                     "weight_quant": wq}
    if wq != "none":
        from paddle_operator_tpu.infer.quant import (
            SERVING_SKIP,
            quantize_params,
        )

        params = quantize_params(params, cfg, mode=wq, skip=SERVING_SKIP)
    # opt-in: continuous mode fixes top_k/top_p server-side, so flipping
    # it on by default would 400 existing clients that pass them
    continuous = os.environ.get("SERVE_CONTINUOUS", "0") == "1"
    ring_kw = {}
    spec_k = int(os.environ.get("SERVE_SPEC_K", "0"))
    if continuous:
        from paddle_operator_tpu.infer.resilience import RingResilience

        ring_kw = {"slots": int(os.environ.get("SERVE_SLOTS", "8")),
                   "chunk_tokens": int(os.environ.get("SERVE_CHUNK", "8")),
                   "max_queue": int(os.environ.get("SERVE_MAX_QUEUE",
                                                   "0")),
                   # self-healing on by default for deployed rings:
                   # dispatch faults shed the resident requests (503)
                   # and rebuild instead of wedging every lane forever
                   "resilience": RingResilience.from_env()}
        if os.environ.get("SERVE_MAX_LEN"):
            ring_kw["max_len"] = int(os.environ["SERVE_MAX_LEN"])
        # SERVE_GENERATION (ISSUE 19): the weight generation this
        # replica boots serving (operator-injected from
        # spec.serving.generation) — the fleet roll's convergence
        # signal; /v1/swap bumps it live
        ring_kw["generation"] = int(
            os.environ.get("SERVE_GENERATION", "0") or 0)
        # SERVE_PAGED=1: block-pool KV cache + radix prefix reuse
        # (infer/paged.py; docs/serving.md has the layout/eviction/CoW
        # story).  SERVE_BLOCK_SIZE sets pool-block granularity (keep
        # at the decode kernel's key block, 256, on TPU);
        # SERVE_PREFIX_CACHE=0 disables radix reuse while keeping
        # paging; SERVE_NUM_BLOCKS oversizes/undersizes the pool from
        # its contiguous-HBM-parity default.  SERVE_PAGED=0 (default)
        # keeps the contiguous ring — the parity oracle.
        # SERVE_KV_QUANT=int8 (docs/serving.md): store paged pool
        # blocks as int8 codes + per-(block, kv-head) f32 scales with
        # the dequant fused into the decode kernels — ~2x resident
        # lanes per HBM byte at a bounded (~17% v5e) per-step cost;
        # enable when the deployment is CAPACITY-bound (kv_blocks_free
        # pinned at 0), keep the default bf16 pool when latency-bound.
        # Requires the paged ring (the pool block is the quantization
        # unit), so it implies SERVE_PAGED=1 — with the OTHER paged
        # knobs (SERVE_BLOCK_SIZE / SERVE_PREFIX_CACHE /
        # SERVE_NUM_BLOCKS) honored exactly as under an explicit
        # SERVE_PAGED=1.
        kvq = os.environ.get("SERVE_KV_QUANT", "none")
        if kvq != "none":
            ring_kw["kv_quant"] = kvq
            if os.environ.get("SERVE_PAGED", "0") != "1":
                print("SERVE_KV_QUANT implies SERVE_PAGED=1 (the pool "
                      "block is the quantization unit)", flush=True)
        if os.environ.get("SERVE_PAGED", "0") == "1" or kvq != "none":
            ring_kw["paged"] = True
            ring_kw["block_size"] = int(
                os.environ.get("SERVE_BLOCK_SIZE", "256"))
            ring_kw["prefix_cache"] = os.environ.get(
                "SERVE_PREFIX_CACHE", "1") == "1"
            if os.environ.get("SERVE_NUM_BLOCKS"):
                ring_kw["num_blocks"] = int(os.environ["SERVE_NUM_BLOCKS"])
            # Hierarchical cache (docs/serving.md): a host-RAM spill
            # tier behind the radix cache — eviction DEMOTES refcount-0
            # cached blocks to pinned host memory instead of discarding
            # them, and a later hit promotes them back byte-exactly
            # (host RAM holds 10-100x more prefix blocks than the pool
            # at a transfer cost far below re-prefill).  Size it with
            # SERVE_HOST_CACHE_BLOCKS (blocks) or SERVE_HOST_CACHE_MB
            # (megabytes, converted at the pool's per-block host cost);
            # 0/unset (default) keeps behavior byte-identical to the
            # tier-less ring.  Pays when the tenant working set exceeds
            # the HBM pool; skip it for latency-bound single-tenant
            # rings whose working set already fits.
            host_blocks = int(os.environ.get("SERVE_HOST_CACHE_BLOCKS",
                                             "0"))
            host_mb = float(os.environ.get("SERVE_HOST_CACHE_MB", "0"))
            if not host_blocks and host_mb > 0:
                from paddle_operator_tpu.infer.paged import (
                    host_block_bytes,
                )

                host_blocks = int(host_mb * 1e6 // host_block_bytes(
                    cfg, ring_kw["block_size"], kvq))
            if host_blocks > 0:
                ring_kw["host_cache_blocks"] = host_blocks
        # SERVE_PREFILL=inline|chunked|disagg (docs/serving.md): how
        # admission prefill reaches the device.  ``chunked`` interleaves
        # SERVE_PREFILL_CHUNK-token slices into ring iterations so a
        # cold long prompt never stalls resident decode lanes for a
        # whole prefill; ``disagg`` moves cold prefills to a separate
        # executor thread + block pool entirely (implies SERVE_PAGED —
        # the handoff is block-granular).  Both are greedy-bit-identical
        # to inline (the dryrun serve-disagg gate pins it).
        prefill_mode = os.environ.get("SERVE_PREFILL", "inline")
        if prefill_mode != "inline":
            ring_kw["prefill_mode"] = prefill_mode
            if prefill_mode == "disagg" and not ring_kw.get("paged"):
                print("SERVE_PREFILL=disagg implies SERVE_PAGED=1 "
                      "(block-granular handoff)", flush=True)
        if prefill_mode == "disagg":
            # cross-host disaggregation (ISSUE 13, docs/serving.md
            # "Cross-host disaggregation"): SERVE_PREFILL_REMOTE=1
            # moves cold prefills to the PREFILL POOL's pods —
            # SERVE_PREFILL_BROKER (the fleet router, operator-
            # injected) forwards each job to the least-loaded ready
            # prefill pod; SERVE_PREFILL_PEERS is the router-less
            # static list.  Unset keeps the in-process executor.
            from paddle_operator_tpu.infer.prefill_serve import (
                remote_prefill_client_from_env,
            )

            rp = remote_prefill_client_from_env()
            if rp is not None:
                ring_kw["prefill_client"] = rp
            # Prefill-pool throughput (ISSUE 14): SERVE_PREFILL_LANES
            # widens the IN-PROCESS engine into an N-lane batched,
            # chunk-interleaved pool (1, the default, keeps the PR 6
            # monolithic engine — the parity oracle);
            # SERVE_PREFILL_STREAM=1 streams completed block groups to
            # the decode side while the rest of the prompt prefills;
            # SERVE_PREFILL_PREFIX_BLOCKS caps the engine's own radix
            # prefix cache (0 disables).  All three are engine-side
            # and greedy-bit-identical to the 1-lane monolithic path
            # (dryrun serve-prefillpool pins it).
            ring_kw["prefill_lanes"] = int(
                os.environ.get("SERVE_PREFILL_LANES", "1") or 1)
            ring_kw["prefill_stream"] = os.environ.get(
                "SERVE_PREFILL_STREAM", "0") == "1"
            ring_kw["prefill_prefix_blocks"] = int(
                os.environ.get("SERVE_PREFILL_PREFIX_BLOCKS", "0")
                or 0)
        if os.environ.get("SERVE_PREFILL_CHUNK"):
            ring_kw["prefill_chunk"] = int(
                os.environ["SERVE_PREFILL_CHUNK"])
        # SERVE_MEGASTEP=N (ISSUE 11, docs/serving.md "Megastep
        # execution"): fuse N ring iterations into ONE compiled
        # dispatch, with eos / token-budget / deadline-tick
        # continuation carried on device — amortizes the Python
        # dispatch tax ~N x on host-bound rings.  Admission,
        # preemption, promotions and handoff attaches move to megastep
        # boundaries, so a queued request can wait up to N iterations
        # for a lane (the TTFT-granularity tradeoff; keep N=1, the
        # byte-identical default, for latency-critical single-tenant
        # rings).
        # 0/unset = the server's single-step default (the CRD contract:
        # spec.serving.megastep 0 means "server default", and an
        # explicit SERVE_MEGASTEP=0 must disable fusion, not crash-loop
        # the pod on the >=1 constructor validation)
        megastep = int(os.environ.get("SERVE_MEGASTEP", "0") or 0)
        if megastep > 1:
            ring_kw["megastep"] = megastep
        # SERVE_PREWARM=0 opts out of the off-thread compile prewarm
        # (the first long prompt then pays the per-bucket insert
        # compile — the lazy-compile cliff the prewarm exists to hide)
        ring_kw["prewarm"] = os.environ.get("SERVE_PREWARM", "1") == "1"
        # SERVE_TRACE=1 (ISSUE 15, docs/observability.md): per-request
        # span capture — requests carry X-Tpujob-Trace contexts, phase
        # spans ride response metadata, and the router stitches
        # cross-pod timelines at /debug/tracez.  Off (default) every
        # capture site is one attribute check; on, token streams are
        # still byte-identical (host timestamps only — the serve-trace
        # dryrun line pins both).  The latency histograms and the
        # flight recorder are always on.
        ring_kw["trace"] = os.environ.get("SERVE_TRACE", "0") == "1"
        # Multi-tenant QoS (ISSUE 10, docs/serving.md):
        # SERVE_PRIORITIES classes (0 most urgent; default 2, requests
        # default to the least urgent — opt-in boosts only), and the
        # preemption knobs: SERVE_PREEMPT=0 disables lane spill,
        # SERVE_PREEMPT_MAX_PER_REQ / SERVE_PREEMPT_BUDGET /
        # SERVE_PREEMPT_WINDOW_S bound thrash.  Defaults are
        # byte-identical to the single-FIFO ring for unannotated
        # traffic.
        from paddle_operator_tpu.infer.qos import (
            AdapterRegistry,
            QoSConfig,
        )

        ring_kw["qos"] = QoSConfig.from_env()
        # SERVE_ADAPTERS: comma list of LoRA adapters served off this
        # ONE base param set (S-LoRA style) — ``name`` (deterministic
        # random smoke adapter), ``name:seed:<int>``, or
        # ``name:/path/to/deltas.npz``.  SERVE_ADAPTER_RANK /
        # SERVE_MAX_ADAPTERS size the fixed-shape pool; per-request
        # ``adapter`` (body key) selects one.  More load/evict at
        # runtime via POST /v1/adapters.
        if spec_k == 0:
            adapters = AdapterRegistry.from_env(cfg)
            if adapters is not None:
                ring_kw["adapters"] = adapters
        elif os.environ.get("SERVE_ADAPTERS", "").strip():
            print("SERVE_ADAPTERS ignored: adapters are not supported "
                  "on speculative rings (the draft proposes base-only)",
                  flush=True)
        if spec_k > 0:
            # SERVE_SPEC_K=K: speculative decoding through the ring.
            # SERVE_DRAFT names the draft config — "auto" derives the
            # shallow/narrow companion (LlamaConfig.draft), any preset
            # name uses that config.  Draft weights restore from
            # TPUJOB_DRAFT_CHECKPOINT_PATH when set (fresh init
            # otherwise — smoke mode, acceptance ~1/vocab).
            draft_name = os.environ.get("SERVE_DRAFT", "auto")
            if draft_name == "auto":
                dcfg = cfg.draft()
            else:
                from paddle_operator_tpu.models.llama import CONFIGS

                dcfg = CONFIGS[draft_name]
            from paddle_operator_tpu.infer.speculative import (
                check_draft_compat,
            )

            check_draft_compat(cfg, dcfg)
            dmodel = Llama(dcfg)

            def dinit():
                dp = dmodel.init(jax.random.PRNGKey(1),
                                 jnp.zeros((1, 8), jnp.int32))["params"]
                return T.TrainState(step=jnp.zeros((), jnp.int32),
                                    params=dp, opt_state=opt.init(dp))

            dpath = os.environ.get("TPUJOB_DRAFT_CHECKPOINT_PATH")
            if dpath:
                dstate, _ = resume_or_init(CheckpointManager(dpath), dinit)
            else:
                dstate = dinit()
            dparams = serving_params(dstate.params, dcfg.dtype)
            if swap_base is not None:
                swap_base["draft_params"] = jax.device_get(dparams)
                swap_base["draft_quant"] = (
                    os.environ.get("SERVE_DRAFT_QUANT", "none")
                    or "none")
            # SERVE_DRAFT_QUANT=int8|int4: quantize the DRAFT only.
            # Spec verify tolerates draft drift by construction — a
            # coarser draft can only lower accept rate, never change
            # emitted tokens — so this is a pure accept-rate/latency
            # trade and the proving ground before SERVE_WEIGHT_QUANT.
            dwq = os.environ.get("SERVE_DRAFT_QUANT", "none") or "none"
            if dwq != "none":
                from paddle_operator_tpu.infer.quant import (
                    SERVING_SKIP,
                    quantize_params,
                )

                dparams = quantize_params(dparams, dcfg, mode=dwq,
                                          skip=SERVING_SKIP)
            ring_kw.update(
                draft_params=dparams, draft_cfg=dcfg, spec_k=spec_k)
    # SERVE_TP=n: tensor-parallel serving over the pod's first n chips
    # (weights a single chip cannot hold — the 7B-on-v5e case).  The
    # mesh carries only the tp axis; DP is separate server replicas.
    mesh = None
    tp = int(os.environ.get("SERVE_TP", "1"))
    if tp > 1:
        from paddle_operator_tpu.parallel.mesh import make_serving_mesh

        mesh = make_serving_mesh(tp)
    print(f"serving {os.environ.get('MODEL_PRESET', '7b')} "
          f"(resumed={resumed}, "
          f"quantize={os.environ.get('QUANTIZE', 'off')}, "
          f"weight_quant={wq}, "
          f"draft_quant={os.environ.get('SERVE_DRAFT_QUANT', 'none') or 'none'}, "
          f"tp={tp}, spec_k={spec_k if continuous else 0}, "
          f"prefill={ring_kw.get('prefill_mode', 'inline') if continuous else '-'}, "
          f"kv_quant={ring_kw.get('kv_quant', 'none') if continuous else '-'}, "
          f"megastep={ring_kw.get('megastep', 1) if continuous else '-'}, "
          f"mode={'continuous' if continuous else 'batch'}) on :{env.port}",
          flush=True)
    srv = make_server("0.0.0.0", env.port, params, cfg,
                      continuous=continuous, mesh=mesh,
                      # fleet identity (operator-injected): labels this
                      # replica's /metrics gauges so the router and the
                      # fleet status block can tell replicas apart
                      job=os.environ.get("TPUJOB_NAME", "local"),
                      replica=os.environ.get("TPUJOB_REPLICA_ID", ""),
                      **ring_kw)
    # the /v1/swap handler reaches the retained base via self.server
    srv.swap_base = swap_base if continuous else None
    # SIGTERM drain (docs/fault-tolerance.md, serving pods): the SAME
    # PreemptionWatcher contract the trainer uses — stop admissions
    # (503 + Retry-After), finish in-flight lanes within the drain
    # budget, flush partials, exit EXIT_PREEMPTED so the reconciler
    # counts the restart as preempted, not failed.  A second SIGTERM
    # exits immediately (partials flushed best-effort).
    from paddle_operator_tpu.ft.preemption import PreemptionWatcher
    from paddle_operator_tpu.infer.chaos import maybe_install_from_env
    from paddle_operator_tpu.infer.resilience import ServingDrain

    batcher = srv.generator.batcher if continuous else None
    if batcher is not None:
        # TPUJOB_CHAOS: deterministic fault injection on the live ring
        # (smoke-testing a deployment's resilience end-to-end)
        maybe_install_from_env(batcher)
        wire_fleet_kv_from_env(batcher, env.port)
        wire_kv_store_from_env(batcher)
    watcher = PreemptionWatcher.install()
    drain = ServingDrain(
        srv, srv.state, batcher=batcher,
        budget_s=float(os.environ.get("SERVE_DRAIN_BUDGET_S", "30")))
    drain.install(watcher)
    srv.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
