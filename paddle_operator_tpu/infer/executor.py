"""Device half of the serving ring: compiled programs + ring state.

ISSUE 6 split the ~1.6k-line ``infer/batcher.py`` into a **scheduler**
(infer/scheduler.py — admission, queues, deadlines, request lifecycle,
resilience hooks; pure host code) and this **executor** (compiled
dispatch, ring/paged caches, prefill and decode step functions; every
``jax`` touch of the serving hot path).  The split is what lets prefill
and decode executors differ: :class:`RingExecutor` owns the decode
ring's resident programs and device state, while
:class:`PrefillExecutor` is a SEPARATE prefill engine (its own thread,
its own block pool) that fills paged KV blocks and hands completed
block tables to the decode ring — the in-process half of DistServe-
style disaggregation (Zhong et al., 2024).

Three prefill paths feed the ring (scheduler knob ``prefill_mode``,
serve.py env ``SERVE_PREFILL``):

- **inline** (the original): admission is ONE compiled prefill-insert
  dispatch on the ring thread — a cold 2k prompt stalls every resident
  decode lane for the full prefill.
- **chunked** (Sarathi-Serve, Agrawal et al., 2024): prefill runs in
  decode-sized token slices (``prefill_chunk``) interleaved into ring
  iterations — intermediate slices only append KV (no lm head), the
  final slice reuses the paged SUFFIX-insert (or the contiguous
  equivalent) to sample the first token, so resident lanes never wait
  more than one slice.
- **disagg**: cold prompts prefill on :class:`PrefillExecutor`'s own
  thread into its own pool; the decode ring's only work is a
  device-to-device block copy + a tiny attach dispatch at handoff.
  Prefix HITS still admit through the radix suffix-insert on the ring
  thread (only uncached suffix tokens are ever prefilled anywhere).

All three are greedy-bit-identical to the inline ring: every prefill
path runs the same compiled op sequences (``decode._forward`` /
``speculative._multi_forward(_paged)``) and samples the first token
through the shared ``_sample_tokens`` rule — pinned by
tests/test_prefill_modes.py and the dryrun ``serve-disagg`` line.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from paddle_operator_tpu.infer import decode as D
from paddle_operator_tpu.models.llama import LlamaConfig, rope_frequencies


class ExecPlan:
    """One resident ring dispatch, fully described host-side
    (ISSUE 11).  The scheduler FILLS a plan (which lanes step, the
    block table snapshot, the adapter tail, how many fused iterations,
    and the per-lane continuation budgets) and the executor REPLAYS it
    (:meth:`RingExecutor.replay`) — one code path serving N=1 (the
    byte-identical legacy dispatch, the oracle) and N>1 (the fused
    megastep).  Admission, preemption, promotions, CoW and handoffs
    all happen BETWEEN plans, so a replay is a pure function of ring
    state + plan — which is what lets the chaos injector and the
    dispatch watchdog wrap it as a unit.

    - ``n_steps``  fused ring iterations (1 = today's dispatch);
    - ``active``   per-lane participation (host bools, [slots]);
    - ``table``    block-table snapshot (np [slots, M]; None on the
      contiguous ring) — prefill-pending rows already trash-masked;
    - ``lora``     trailing adapter operands (lora_step_tail());
    - ``eos``      per-lane eos token id, -1 for none (np int32);
    - ``left``     per-lane remaining token budget — what the device
      may still emit (the admission-sampled first token, if still
      unmaterialized, is already subtracted);
    - ``steps``    per-lane max fused iterations this dispatch (the
      deadline-tick budget; ``n_steps`` when unconstrained).

    ``eos``/``left``/``steps`` are only consulted when ``n_steps > 1``
    — the N=1 replay is operand-for-operand today's dispatch."""

    __slots__ = ("n_steps", "active", "table", "lora", "eos", "left",
                 "steps")

    def __init__(self, n_steps, active, table=None, lora=(),
                 eos=None, left=None, steps=None):
        self.n_steps = int(n_steps)
        self.active = active
        self.table = table
        self.lora = tuple(lora)
        self.eos = eos
        self.left = left
        self.steps = steps


class DispatchResult:
    """Device futures one :meth:`RingExecutor.replay` returns — what
    the scheduler's pipelining queue holds until the consume boundary.
    ``toks`` is [chunk, B] at n_steps=1 and [n, chunk(|K+1), B] fused;
    ``counts`` the host-consumable row counts ([B] spec at N=1,
    [n, B] fused, None plain-1-step); ``raw`` the spec rounds' device
    commit counts (acceptance telemetry); ``ok`` the isfinite
    verdicts (check_finite only)."""

    __slots__ = ("toks", "counts", "ok", "raw", "n_steps")

    def __init__(self, toks, counts, ok, raw, n_steps):
        self.toks = toks
        self.counts = counts
        self.ok = ok
        self.raw = raw
        self.n_steps = n_steps


# ---------------------------------------------------------------------------
# Per-lane-position forward step (moved verbatim from infer/batcher.py)
# ---------------------------------------------------------------------------


def init_ring_cache(cfg: LlamaConfig, slots: int,
                    max_len: int, mesh=None) -> Dict[str, jax.Array]:
    """KV ring: like decode.init_cache (same head-major layout,
    block-aligned allocation, same kv-head tp sharding under a serving
    mesh) but with a per-lane fill position vector instead of one
    scalar."""
    if max_len > cfg.max_seq_len:
        raise ValueError(f"max_len {max_len} exceeds the RoPE table "
                         f"(cfg.max_seq_len={cfg.max_seq_len})")
    alloc = D.cache_alloc_len(max_len)
    shape = (cfg.n_layers, slots, cfg.n_kv_heads, alloc, cfg.head_dim)
    return {
        "k": D.alloc_kv_buffer(cfg, shape, mesh),
        "v": D.alloc_kv_buffer(cfg, shape, mesh),
        "pos": jnp.zeros((slots,), jnp.int32),
    }


def _write_lane(cache_l: jax.Array, kv: jax.Array,
                pos: jax.Array) -> jax.Array:
    """[B, H, S, D] cache layer <- [B, H, 1, D] new row at per-lane pos."""
    return jax.vmap(
        lambda c, x, p: jax.lax.dynamic_update_slice(c, x, (0, p, 0))
    )(cache_l, kv, pos)


def _qkv_ring(cfg: LlamaConfig, lp: Dict[str, Any], x: jax.Array,
              cos: jax.Array, sin: jax.Array, pos: jax.Array,
              lora=None):
    """Pre-attention half for ONE new token per lane at per-lane
    positions ``pos`` [B]: RMSNorm -> projections -> RoPE at each
    lane's own position (the table slice is a plain gather cos[pos]).

    ``lora`` (ISSUE 10): ``(adp_l, aid)`` — one layer's stacked LoRA
    arrays + the per-LANE adapter id vector; the batched gather +
    delta matmul (qos.lora_qkv) runs inside the same compiled step, so
    a mixed-adapter batch is still ONE dispatch."""
    b = x.shape[0]
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = D._rms(x, lp["attn_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    q = D._mm(h, lp["attn"]["wq"]["kernel"], cfg.dtype)
    k = D._mm(h, lp["attn"]["wk"]["kernel"], cfg.dtype)
    v = D._mm(h, lp["attn"]["wv"]["kernel"], cfg.dtype)
    if lora is not None:
        from paddle_operator_tpu.infer.qos import lora_qkv

        q, k, v = lora_qkv(h, lora[0], lora[1], q, k, v, cfg.dtype)
    q = q.reshape(b, 1, hq, d)
    k = k.reshape(b, 1, hkv, d)
    v = v.reshape(b, 1, hkv, d)
    cos_b = cos[pos][:, None, None, :]          # [B, 1, 1, d/2]
    sin_b = sin[pos][:, None, None, :]

    def rot(t):
        t1, t2 = jnp.split(t.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate(
            [t1 * cos_b - t2 * sin_b, t2 * cos_b + t1 * sin_b],
            axis=-1).astype(t.dtype)

    return rot(q), rot(k), v


def _layer_step(cfg: LlamaConfig, lp: Dict[str, Any], x: jax.Array,
                cos: jax.Array, sin: jax.Array, k_cache: jax.Array,
                v_cache: jax.Array, pos: jax.Array, lora=None
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decoder layer for ONE new token per lane ([B, 1, D] at lane
    positions ``pos`` [B]) with the XLA einsum attention.  Same math as
    decode._layer (which this is pinned against) with the scalar
    position generalized to a vector.  The pallas path keeps the caches
    stacked and does not go through here (see _ring_forward)."""
    b = x.shape[0]
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    q, k, v = _qkv_ring(cfg, lp, x, cos, sin, pos, lora=lora)
    k_cache = _write_lane(k_cache, k.transpose(0, 2, 1, 3), pos)
    v_cache = _write_lane(v_cache, v.transpose(0, 2, 1, 3), pos)

    n_rep = hq // hkv
    max_len = k_cache.shape[2]
    qg = q.reshape(b, 1, hkv, n_rep, d)
    scores = jnp.einsum("bthrd,bhsd->bthrs", qg, k_cache,
                        preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(d))
    # lane b may attend cache cols [0, pos_b] (its own new row incl.)
    mask = jnp.arange(max_len)[None, :] <= pos[:, None]      # [B, S]
    scores = jnp.where(mask[:, None, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bthrs,bhsd->bthrd", probs.astype(cfg.dtype),
                     v_cache, preferred_element_type=jnp.float32)
    out = out.reshape(b, 1, hq * d).astype(cfg.dtype)
    x = x + D._mm(out, lp["attn"]["wo"]["kernel"], cfg.dtype)

    n = D._rms(x, lp["mlp_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    if cfg.n_experts > 0:
        ffn = D._moe_ffn(cfg, lp["moe"], n)
    else:
        gate = D._mm(n, lp["mlp"]["w1"]["kernel"], cfg.dtype)
        up = D._mm(n, lp["mlp"]["w3"]["kernel"], cfg.dtype)
        ffn = D._mm(jax.nn.silu(gate) * up, lp["mlp"]["w2"]["kernel"],
                    cfg.dtype)
    return x + ffn, k_cache, v_cache


def _write_lane_stacked(stack: jax.Array, kv: jax.Array, li: jax.Array,
                        pos: jax.Array) -> jax.Array:
    """[L, B, H, S, D] stacked cache <- [B, H, 1, D] new rows at layer
    ``li`` and per-lane positions ``pos``.

    One dynamic_update_slice PER LANE (a static unroll over the slot
    count), not a vmapped/batched update: vmapping over ragged lane
    positions lowers to a scatter, and a scatter into the scan-carried
    stack makes XLA materialize a copy of the whole ring cache per
    layer per tick — measured 30x slower than raw decode.  Chained
    single-row dus ops update the carry in place."""
    b = kv.shape[0]
    for lane in range(b):
        stack = jax.lax.dynamic_update_slice(
            stack, kv[lane][None, None], (li, lane, 0, pos[lane], 0))
    return stack


def _ring_forward(cfg: LlamaConfig, params: Dict[str, Any],
                  tok: jax.Array, cache: Dict[str, jax.Array],
                  mesh=None, lora=None
                  ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """tok [B] at per-lane cache['pos'] -> (logits [B, V], advanced
    cache).  Counterpart of decode._forward for vector positions; like
    it, the pallas path carries the caches STACKED through the layer
    scan so the kernel reads them copy-free (decode.py _forward has the
    why), and under a serving mesh the kernel + output projection run
    TP-sharded in one manual region per layer (the ragged per-lane
    ``pos`` vector is exactly the ``lengths`` operand the kernel's
    index map already takes — replicated across shards)."""
    pos = cache["pos"]
    adp, aid = lora if lora is not None else (None, None)
    x = params["tok_embed"]["embedding"].astype(cfg.dtype)[tok[:, None]]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                cfg.rope_theta)

    attn_impl = cfg.resolved_decode_attn()
    use_sharded = D._use_sharded_kernel(cfg, mesh, attn_impl)
    if D.mesh_tp(mesh) > 1 and not use_sharded:
        attn_impl = "xla"   # whole GQA groups don't split: GSPMD einsum
    stacked_xs = ((params["layers"], adp, jnp.arange(cfg.n_layers))
                  if adp is not None
                  else (params["layers"], jnp.arange(cfg.n_layers)))

    def _unpack(layer_in):
        if adp is not None:
            lp, adp_l, li = layer_in
            return lp, li, (adp_l, aid)
        lp, li = layer_in
        return lp, li, None

    if use_sharded:
        from paddle_operator_tpu.ops.decode_attention import (
            sharded_decode_attention,
        )

        def body(carry, layer_in):
            x, kc, vc = carry
            lp, li, lo = _unpack(layer_in)
            q, k, v = _qkv_ring(cfg, lp, x, cos, sin, pos, lora=lo)
            kc = _write_lane_stacked(kc, k.transpose(0, 2, 1, 3), li, pos)
            vc = _write_lane_stacked(vc, v.transpose(0, 2, 1, 3), li, pos)
            proj = sharded_decode_attention(
                mesh, q[:, 0], kc, vc, pos + 1,
                lp["attn"]["wo"]["kernel"], layer=li,
                interpret=(attn_impl == "pallas-interpret"),
                compute_dtype=cfg.dtype)
            x = x + proj[:, None].astype(cfg.dtype)
            return (D._ffn_residual(cfg, lp, x), kc, vc), ()

        (x, k_new, v_new), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]), stacked_xs)
    elif attn_impl != "xla":
        from paddle_operator_tpu.ops.decode_attention import decode_attention

        b = x.shape[0]
        hq, d = cfg.n_heads, cfg.head_dim

        def body(carry, layer_in):
            x, kc, vc = carry
            lp, li, lo = _unpack(layer_in)
            q, k, v = _qkv_ring(cfg, lp, x, cos, sin, pos, lora=lo)
            kc = _write_lane_stacked(kc, k.transpose(0, 2, 1, 3), li, pos)
            vc = _write_lane_stacked(vc, v.transpose(0, 2, 1, 3), li, pos)
            out = decode_attention(
                q[:, 0], kc, vc, pos + 1, layer=li,
                interpret=(attn_impl == "pallas-interpret"))
            out = out.reshape(b, 1, hq * d).astype(cfg.dtype)
            return (D._finish_layer(cfg, lp, x, out), kc, vc), ()

        (x, k_new, v_new), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]), stacked_xs)
    else:
        def body(x, layer_in):
            if adp is not None:
                lp, adp_l, k_c, v_c = layer_in
                lo = (adp_l, aid)
            else:
                lp, k_c, v_c = layer_in
                lo = None
            y, k_c, v_c = _layer_step(cfg, lp, x, cos, sin, k_c, v_c,
                                      pos, lora=lo)
            return y, (k_c, v_c)

        xs = ((params["layers"], adp, cache["k"], cache["v"])
              if adp is not None
              else (params["layers"], cache["k"], cache["v"]))
        x, (k_new, v_new) = jax.lax.scan(body, x, xs)
    x = D._rms(x, params["final_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    logits = D._mm(x, params["lm_head"]["kernel"],
                   cfg.dtype).astype(jnp.float32)
    return logits[:, 0], {"k": k_new, "v": v_new, "pos": pos + 1}


def _sample_tokens(logits, temp, keys, pos, top_k, top_p):
    """THE per-lane sampling rule — shared by the chunk step and EVERY
    admission insert (inline, chunked final, suffix, disagg) so token 1
    and tokens 2..N can never be drawn under different rules.  logits
    [B, V], temp [B], keys [B, 2], pos [B] -> [B] int32: greedy at temp
    0, else per-lane fold_in(position) (deterministic given (seed,
    pos), independent across lanes and steps) feeding temperature +
    top-k/top-p filtered categorical sampling."""
    greedy = logits.argmax(-1).astype(jnp.int32)
    filt = D._filter_logits(
        logits / jnp.maximum(temp, 1e-6)[:, None], top_k, top_p)
    sub = jax.vmap(jax.random.fold_in)(keys, pos)
    drawn = jax.vmap(
        lambda k, l: jax.random.categorical(k, l))(sub, filt)
    return jnp.where(temp > 0, drawn.astype(jnp.int32), greedy)


def make_chunk_step(cfg: LlamaConfig, chunk_tokens: int,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None, mesh=None,
                    check_finite: bool = False):
    """The ONE resident compiled decode program.

    ``step(params, cache, tok [B], temp [B], keys [B,2], active [B])
    -> (cache', tok', toks [chunk, B])``

    Runs ``chunk_tokens`` ticks for every lane.  Inactive lanes compute
    (their FLOPs are the price of static shapes — standard slot-server
    trade) but neither advance their position nor write meaningful
    state; their emitted tokens are ignored host-side.  The cache is
    donated: the ring buffer must never be copied per chunk.  Under a
    serving mesh the whole chunk remains ONE sharded dispatch — the
    shard_map kernel regions and GSPMD einsums compile into the same
    resident program, no eager per-device ops anywhere.

    ``check_finite=True`` (infer/resilience.py nan_check): the step
    additionally returns ``ok [B]`` — an isfinite fold of every tick's
    logits per lane, so the host can quarantine a NaN-producing lane
    (fail ONE request, never the ring) without shipping the logits
    home.  Token outputs are unchanged; the fold rides the same scan.
    """

    def step(params, cache, tok, temp, keys, active, *lora_args):
        # adapter serving (ISSUE 10): the stacked LoRA arrays + per-lane
        # adapter ids arrive as trailing operands — absent, the traced
        # program is byte-identical to the adapterless ring
        lora = tuple(lora_args) if lora_args else None

        def tick(carry, _):
            # the isfinite fold rides the carry ONLY when requested —
            # the default resident program is unchanged
            if check_finite:
                cache, tok, ok = carry
            else:
                cache, tok = carry
            logits, new_cache = _ring_forward(cfg, params, tok, cache,
                                              mesh=mesh, lora=lora)
            nxt = _sample_tokens(logits, temp, keys, cache["pos"],
                                 top_k, top_p)
            # retired/free lanes: position ZEROED (a stale fill
            # position must never outlive its request — the
            # serving_status staleness fix); their (ignored) writes
            # land at row 0, which the next admission's splice
            # overwrites along with the rest of the lane
            new_cache["pos"] = jnp.where(active, new_cache["pos"], 0)
            nxt = jnp.where(active, nxt, tok)
            if check_finite:
                ok = ok & jnp.all(jnp.isfinite(logits), axis=-1)
                return (new_cache, nxt, ok), nxt
            return (new_cache, nxt), nxt

        if check_finite:
            (cache, tok, ok), toks = jax.lax.scan(
                tick, (cache, tok, jnp.ones(tok.shape, bool)), None,
                length=chunk_tokens)
            return cache, tok, toks, ok
        (cache, tok), toks = jax.lax.scan(
            tick, (cache, tok), None, length=chunk_tokens)
        return cache, tok, toks

    return jax.jit(step, donate_argnums=(1,))


def _mega_advance(toks, raw, live, left, eos):
    """On-device continuation bookkeeping at one fused-iteration
    boundary of a megastep (ISSUE 11) — the EXACT decision the host
    makes between two 1-step dispatches, in compiled form so N ring
    iterations can run without a host round-trip.

    ``toks`` [T, B] is the boundary's emitted tokens (a chunk's ticks,
    or a spec round's committed block), ``raw`` [B] the device-valid
    row count per lane (``chunk`` for plain chunks, ``n_commit`` for
    spec rounds, 0 for lanes that sat the iteration out), ``live`` [B]
    the continuation mask at the iteration's START, ``left`` [B] the
    per-lane remaining token budget and ``eos`` [B] the per-lane eos id
    (-1: none).  Returns ``(count, live', left')``: the tokens the host
    will actually consume for this boundary (up to and INCLUDING an
    eos, capped by the budget — the same walk scheduler._consume runs),
    and the advanced continuation state.  A lane that saw eos or
    exhausted its budget goes dead and free-runs masked until the
    megastep ends."""
    t = toks.shape[0]
    idx = jnp.arange(t)[:, None]
    hitv = (eos[None, :] >= 0) & (toks == eos[None, :])
    hit = hitv.astype(jnp.int32)
    eos_before = (jnp.cumsum(hit, axis=0) - hit) > 0
    valid = ((idx < raw[None, :]) & ~eos_before
             & (idx < left[None, :]) & live[None, :])
    count = valid.sum(axis=0).astype(jnp.int32)
    saw_eos = (hitv & valid).any(axis=0)
    left2 = left - count
    live2 = live & ~saw_eos & (left2 > 0)
    return count, live2, left2


def _mega_continue(toks, raw, live, left, steps, eos):
    """The WHOLE per-boundary continuation update, shared by every
    megastep builder (contiguous, paged, spec) so the token-budget walk
    and the step-budget decrement can never drift between them:
    :func:`_mega_advance` plus the deadline-tick step accounting.
    Returns ``(count, live', left', steps')``."""
    count, live2, left2 = _mega_advance(toks, raw, live, left, eos)
    steps2 = steps - live.astype(jnp.int32)
    live2 = live2 & (steps2 > 0)
    return count, live2, left2, steps2


def make_megastep(cfg: LlamaConfig, chunk_tokens: int, n_steps: int,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None, mesh=None,
                  check_finite: bool = False):
    """N fused ring iterations in ONE compiled dispatch (ISSUE 11): the
    contiguous ring's ``make_chunk_step`` body scanned ``n_steps``
    times with the host's boundary decisions — eos detection, token
    budget, step budget — carried ON DEVICE (:func:`_mega_advance`).
    A lane that finishes mid-megastep free-runs masked: its position
    stops advancing (the pos a live lane would carry is restored from
    the pre-chunk snapshot, so a step-budget-frozen lane could resume)
    and its writes land at its own row 0 exactly like an inactive
    lane's in the 1-step program — which the next admission's splice
    overwrites.  NOTE the contiguous ring must only freeze lanes it
    will EVICT at the boundary (eos / budget exhausted): the masked
    row-0 writes make a frozen-and-resumed lane unsound here (they
    overwrite the first prompt row), so the scheduler never hands a
    contiguous ring a per-lane step budget below ``n_steps`` — the
    paged megastep (trash-block redirect) is the resumable one.

    ``mega(params, cache, tok, temp, keys, active, eos, left, steps,
    *lora) -> (cache', tok', toks [n, chunk, B], counts [n, B]
    [, oks [n, B]])``

    ``counts[r, b]`` is the number of ``toks[r, :, b]`` rows the host
    consumes for iteration ``r`` (0 once the lane is dead); ``oks``
    (check_finite) is the per-iteration isfinite verdict, forced True
    for masked lanes (a free-running dead lane's garbage must not
    quarantine it)."""

    def mega(params, cache, tok, temp, keys, active, eos, left, steps,
             *lora_args):
        lora = tuple(lora_args) if lora_args else None

        def outer(carry, _):
            cache, tok, live, lleft, lsteps = carry
            p0 = cache["pos"]

            def tick(c, _):
                if check_finite:
                    cache, tok, ok = c
                else:
                    cache, tok = c
                logits, new_cache = _ring_forward(cfg, params, tok,
                                                  cache, mesh=mesh,
                                                  lora=lora)
                nxt = _sample_tokens(logits, temp, keys, cache["pos"],
                                     top_k, top_p)
                new_cache["pos"] = jnp.where(live, new_cache["pos"], 0)
                nxt = jnp.where(live, nxt, tok)
                if check_finite:
                    ok = ok & (jnp.all(jnp.isfinite(logits), axis=-1)
                               | ~live)
                    return (new_cache, nxt, ok), nxt
                return (new_cache, nxt), nxt

            if check_finite:
                (cache, tok, ok), toks = jax.lax.scan(
                    tick, (cache, tok, jnp.ones(tok.shape, bool)), None,
                    length=chunk_tokens)
            else:
                (cache, tok), toks = jax.lax.scan(
                    tick, (cache, tok), None, length=chunk_tokens)
            raw = jnp.where(live, chunk_tokens, 0).astype(jnp.int32)
            count, live2, left2, lsteps2 = _mega_continue(
                toks, raw, live, lleft, lsteps, eos)
            # a lane frozen THIS boundary keeps the position it earned
            # (the tick zeroed it); lanes dead from the start stay at
            # their (zeroed) entry position
            cache["pos"] = jnp.where(live, cache["pos"], p0)
            out = (toks, count, ok) if check_finite else (toks, count)
            return (cache, tok, live2, left2, lsteps2), out

        live0 = active & (left > 0) & (steps > 0)
        if check_finite:
            (cache, tok, _, _, _), (toks, counts, oks) = jax.lax.scan(
                outer, (cache, tok, live0, left, steps), None,
                length=n_steps)
            return cache, tok, toks, counts, oks
        (cache, tok, _, _, _), (toks, counts) = jax.lax.scan(
            outer, (cache, tok, live0, left, steps), None,
            length=n_steps)
        return cache, tok, toks, counts

    return jax.jit(mega, donate_argnums=(1,))


def _splice_lane(ring: Dict[str, jax.Array], lane: Dict[str, jax.Array],
                 slot, prompt_len) -> Dict[str, jax.Array]:
    """Zero ring lane ``slot`` and splice a freshly prefilled
    batch-of-one lane cache into it, setting the lane's fill position
    to ``prompt_len`` — the device half of admission, shared by the
    plain, speculative and chunked-final inserts so their splice
    semantics cannot drift.  A lane cache LONGER than the ring lane
    (a chunk-width-padded staging cache) is truncated: rows past the
    ring allocation are pads by construction."""
    ring_alloc = ring["k"].shape[3]
    lane_k, lane_v = lane["k"], lane["v"]
    if lane_k.shape[3] > ring_alloc:
        lane_k = lane_k[:, :, :, :ring_alloc]
        lane_v = lane_v[:, :, :, :ring_alloc]
    k = jnp.zeros_like(ring["k"][:, 0])
    k = jax.lax.dynamic_update_slice(k, lane_k[:, 0], (0, 0, 0, 0))
    v = jnp.zeros_like(ring["v"][:, 0])
    v = jax.lax.dynamic_update_slice(v, lane_v[:, 0], (0, 0, 0, 0))
    new_k = jax.lax.dynamic_update_slice(
        ring["k"], k[:, None], (0, slot, 0, 0, 0))
    new_v = jax.lax.dynamic_update_slice(
        ring["v"], v[:, None], (0, slot, 0, 0, 0))
    return {"k": new_k, "v": new_v,
            "pos": ring["pos"].at[slot].set(prompt_len)}


def make_prefill_insert(cfg: LlamaConfig, bucket: int,
                        top_k: Optional[int] = None,
                        top_p: Optional[float] = None, mesh=None):
    """Per-prompt-bucket compiled admission: prefill a [1, bucket]
    (right-padded) prompt, splice its KV into ring lane ``slot``, sample
    the first token, and update EVERY piece of lane state — tok, temp,
    keys — in the same compiled program.

    One dispatch on purpose: on relayed chips, EAGER ops (``.at[].set``,
    ``argmax``) block until all in-flight device work drains (measured
    ~500 ms behind a decoding chunk), so an admission built from eager
    lane updates stalled the whole ring for ~half a second per request.
    Everything device-side about admission lives inside this jit; the
    host's only jobs are bookkeeping lists.

    Exactness with padding: pad rows fill cache positions PAST the real
    prompt; the causal mask keeps real rows from attending them, the
    first token samples from ``prompt_len - 1`` (the last REAL
    position), the lane position is set to ``prompt_len`` so decode
    overwrites the pad rows before they ever become attendable.

    ``insert(params, cache, tok, temp, keys, prompt [1,bucket],
    prompt_len, slot, temp_val, seed)
    -> (cache', tok', temp', keys', first_token)``
    """

    def insert(params, cache, tok, temp, keys, prompt, prompt_len, slot,
               temp_val, seed, *lora_args):
        lane = D.init_cache(cfg, 1, bucket)
        logits, lane = D._forward(
            cfg, params, prompt, lane, mesh=mesh,
            lora=tuple(lora_args) if lora_args else None)
        logits = logits[0, prompt_len - 1]                  # last real row
        new_cache = _splice_lane(cache, lane, slot, prompt_len)
        # first token through the SHARED sampling rule (_sample_tokens),
        # batch-of-one shaped
        key = jax.random.PRNGKey(seed)
        first = _sample_tokens(
            logits[None], jnp.reshape(temp_val, (1,)).astype(jnp.float32),
            key[None], jnp.reshape(prompt_len - 1, (1,)),
            top_k, top_p)[0]
        return (new_cache,
                tok.at[slot].set(first),
                temp.at[slot].set(temp_val),
                keys.at[slot].set(key),
                first)

    return jax.jit(insert, donate_argnums=(1, 2, 3, 4))


def make_spec_prefill_insert(cfg: LlamaConfig, dcfg: LlamaConfig,
                             bucket: int, top_k: Optional[int] = None,
                             top_p: Optional[float] = None, mesh=None):
    """Admission for the SPECULATIVE ring: one compiled dispatch that
    prefills the prompt into BOTH the target and the draft lane (the
    draft's logits are discarded — it only needs the KV context to
    propose from) and samples the first token from the target, with the
    same exactness-with-padding story as :func:`make_prefill_insert`.

    ``insert(params, dparams, cache, dcache, tok, temp, keys,
    prompt [1,bucket], prompt_len, slot, temp_val, seed)
    -> (cache', dcache', tok', temp', keys', first_token)``
    """

    def insert(params, dparams, cache, dcache, tok, temp, keys, prompt,
               prompt_len, slot, temp_val, seed):
        lane = D.init_cache(cfg, 1, bucket)
        logits, lane = D._forward(cfg, params, prompt, lane, mesh=mesh)
        logits = logits[0, prompt_len - 1]
        new_cache = _splice_lane(cache, lane, slot, prompt_len)
        dlane = D.init_cache(dcfg, 1, bucket)
        _, dlane = D._forward(dcfg, dparams, prompt, dlane,
                              last_only=True, mesh=mesh)
        new_dcache = _splice_lane(dcache, dlane, slot, prompt_len)
        key = jax.random.PRNGKey(seed)
        first = _sample_tokens(
            logits[None], jnp.reshape(temp_val, (1,)).astype(jnp.float32),
            key[None], jnp.reshape(prompt_len - 1, (1,)),
            top_k, top_p)[0]
        return (new_cache, new_dcache,
                tok.at[slot].set(first),
                temp.at[slot].set(temp_val),
                keys.at[slot].set(key),
                first)

    return jax.jit(insert, donate_argnums=(2, 3, 4, 5, 6))


# ---------------------------------------------------------------------------
# Chunked prefill: intermediate slice + final-insert programs
# ---------------------------------------------------------------------------


def make_prefill_chunk(cfg: LlamaConfig, slice_bucket: int,
                       staging_len: int, mesh=None):
    """One INTERMEDIATE chunked-prefill slice against a contiguous
    staging lane cache ([L, 1, H, staging_len, D], donated): append the
    slice's KV rows at absolute positions [start, start + slice_bucket)
    and skip the lm head entirely (only the FINAL slice needs logits).
    Pad rows of the last full-width slice land past the real prompt and
    are either overwritten by the next slice or truncated/masked at
    splice — the contiguous ring's exactness-with-padding story.

    ``chunk(params, lane_k, lane_v, toks [1, slice_bucket], start)
    -> (lane_k', lane_v')``
    """
    from paddle_operator_tpu.infer.speculative import _multi_forward

    def chunk(params, lane_k, lane_v, toks, start, *lora_args):
        cache = {"k": lane_k, "v": lane_v,
                 "pos": jnp.reshape(start, (1,)).astype(jnp.int32)}
        _, new = _multi_forward(
            cfg, params, toks, cache, mesh=mesh, head=False,
            lora=tuple(lora_args) if lora_args else None)
        return new["k"], new["v"]

    return jax.jit(chunk, donate_argnums=(1, 2))


def make_chunked_final_insert(cfg: LlamaConfig, slice_bucket: int,
                              staging_len: int,
                              top_k: Optional[int] = None,
                              top_p: Optional[float] = None, mesh=None):
    """The FINAL chunked-prefill slice for the contiguous ring: run the
    last (right-padded) slice over the staging lane cache, splice the
    completed lane into ring slot ``slot``, and sample the first token
    — the back half of :func:`make_prefill_insert` with the forward
    restricted to the rows the intermediate slices did not cover.

    ``insert(params, cache, lane_k, lane_v, tok, temp, keys,
    toks [1, slice_bucket], n_rows, start, prompt_len, slot, temp_val,
    seed) -> (cache', tok', temp', keys', first_token)``
    """
    from paddle_operator_tpu.infer.speculative import _multi_forward

    def insert(params, cache, lane_k, lane_v, tok, temp, keys, toks,
               n_rows, start, prompt_len, slot, temp_val, seed,
               *lora_args):
        stage = {"k": lane_k, "v": lane_v,
                 "pos": jnp.reshape(start, (1,)).astype(jnp.int32)}
        logits, new_lane = _multi_forward(
            cfg, params, toks, stage, mesh=mesh,
            lora=tuple(lora_args) if lora_args else None)
        logits = logits[0, n_rows - 1]
        new_cache = _splice_lane(cache, new_lane, slot, prompt_len)
        key = jax.random.PRNGKey(seed)
        first = _sample_tokens(
            logits[None], jnp.reshape(temp_val, (1,)).astype(jnp.float32),
            key[None], jnp.reshape(prompt_len - 1, (1,)),
            top_k, top_p)[0]
        return (new_cache,
                tok.at[slot].set(first),
                temp.at[slot].set(temp_val),
                keys.at[slot].set(key),
                first)

    # the staging lane_k/lane_v are consumed but NOT donated: no output
    # shares their shape, so donation only buys an XLA warning
    return jax.jit(insert, donate_argnums=(1, 4, 5, 6))


def make_spec_chunked_final_insert(cfg: LlamaConfig, dcfg: LlamaConfig,
                                   slice_bucket: int, staging_len: int,
                                   bucket: int,
                                   top_k: Optional[int] = None,
                                   top_p: Optional[float] = None,
                                   mesh=None):
    """Chunked final insert for the SPECULATIVE contiguous ring: the
    target's last slice rides the staging cache like
    :func:`make_chunked_final_insert`; the DRAFT prefills its whole
    prompt here in one pass (the draft is depth/4 x heads/2 by
    construction — chunking it would buy a fraction of a fraction) and
    splices alongside.

    ``insert(params, dparams, cache, dcache, lane_k, lane_v, tok, temp,
    keys, toks, n_rows, start, prompt [1, bucket], prompt_len, slot,
    temp_val, seed) -> (cache', dcache', tok', temp', keys', first)``
    """
    from paddle_operator_tpu.infer.speculative import _multi_forward

    def insert(params, dparams, cache, dcache, lane_k, lane_v, tok, temp,
               keys, toks, n_rows, start, prompt, prompt_len, slot,
               temp_val, seed):
        stage = {"k": lane_k, "v": lane_v,
                 "pos": jnp.reshape(start, (1,)).astype(jnp.int32)}
        logits, new_lane = _multi_forward(cfg, params, toks, stage,
                                          mesh=mesh)
        logits = logits[0, n_rows - 1]
        new_cache = _splice_lane(cache, new_lane, slot, prompt_len)
        dlane = D.init_cache(dcfg, 1, bucket)
        _, dlane = D._forward(dcfg, dparams, prompt, dlane,
                              last_only=True, mesh=mesh)
        new_dcache = _splice_lane(dcache, dlane, slot, prompt_len)
        key = jax.random.PRNGKey(seed)
        first = _sample_tokens(
            logits[None], jnp.reshape(temp_val, (1,)).astype(jnp.float32),
            key[None], jnp.reshape(prompt_len - 1, (1,)),
            top_k, top_p)[0]
        return (new_cache, new_dcache,
                tok.at[slot].set(first),
                temp.at[slot].set(temp_val),
                keys.at[slot].set(key),
                first)

    return jax.jit(insert, donate_argnums=(2, 3, 6, 7, 8))


# ---------------------------------------------------------------------------
# Disaggregated prefill: handoff programs + the prefill executor
# ---------------------------------------------------------------------------


def make_attach_lane():
    """The decode ring's half of a disaggregated handoff: ONE tiny
    compiled dispatch that activates lane ``slot`` — fill position,
    carry token, temperature, sampling key — once the prefilled blocks
    have been copied into the decode pool.  No forward runs here;
    that is the point of disaggregation.

    ``attach(pos, tok, temp, keys, slot, first, prompt_len, temp_val,
    seed) -> (pos', tok', temp', keys')``
    """

    def attach(pos, tok, temp, keys, slot, first, prompt_len, temp_val,
               seed):
        return (pos.at[slot].set(prompt_len),
                tok.at[slot].set(first),
                temp.at[slot].set(temp_val),
                keys.at[slot].set(jax.random.PRNGKey(seed)))

    return jax.jit(attach, donate_argnums=(0, 1, 2, 3))


def make_spec_attach(cfg: LlamaConfig, dcfg: LlamaConfig, bucket: int,
                     mesh=None):
    """Disaggregated handoff for the SPECULATIVE ring: the target KV
    arrived by block copy, but the DRAFT lane still needs its prompt
    context to propose from — prefill it here (contiguous splice, the
    draft never pages) together with the lane activation.

    ``attach(dparams, dcache, pos, tok, temp, keys, prompt [1, bucket],
    prompt_len, slot, first, temp_val, seed)
    -> (dcache', pos', tok', temp', keys')``
    """

    def attach(dparams, dcache, pos, tok, temp, keys, prompt, prompt_len,
               slot, first, temp_val, seed):
        dlane = D.init_cache(dcfg, 1, bucket)
        _, dlane = D._forward(dcfg, dparams, prompt, dlane,
                              last_only=True, mesh=mesh)
        new_dcache = _splice_lane(dcache, dlane, slot, prompt_len)
        return (new_dcache,
                pos.at[slot].set(prompt_len),
                tok.at[slot].set(first),
                temp.at[slot].set(temp_val),
                keys.at[slot].set(jax.random.PRNGKey(seed)))

    return jax.jit(attach, donate_argnums=(1, 2, 3, 4, 5))


def make_disagg_prefill(cfg: LlamaConfig, bucket: int, block_size: int,
                        top_k: Optional[int] = None,
                        top_p: Optional[float] = None, mesh=None,
                        quant: bool = False):
    """The prefill executor's whole-prompt program: prefill a
    [1, bucket] prompt into the PREFILL pool's blocks (the same
    ``decode.paged_prefill`` compiled ops as the inline paged insert —
    what keeps the disagg first token bit-identical) and sample the
    first token through the shared rule.  Unlike the ring inserts it
    touches no ring state: the handoff copies blocks and attaches the
    lane later, on the decode thread.

    ``quant=True``: blocks quantize once into the executor's own int8
    pool; the prompt's partial last block lands exact in the pool's
    tail row 0 (the executor pool is one lane wide) — the handoff
    transfer then carries codes, scales AND tail across.

    ``prefill(params, cache, table_row, prompt, prompt_len, temp_val,
    seed) -> (cache', first_token)``
    """

    def prefill(params, cache, table_row, prompt, prompt_len, temp_val,
                seed, *lora_args):
        lora = tuple(lora_args) if lora_args else None
        if quant:
            logits, new_cache, tail_k, tail_v = D.paged_prefill(
                params, cfg, prompt, cache, table_row,
                block_size=block_size, mesh=mesh, quant=True,
                prompt_len=prompt_len, lora=lora)
            new_cache["kt"] = jax.lax.dynamic_update_slice(
                new_cache["kt"], tail_k, (0, 0, 0, 0, 0))
            new_cache["vt"] = jax.lax.dynamic_update_slice(
                new_cache["vt"], tail_v, (0, 0, 0, 0, 0))
        else:
            logits, new_cache = D.paged_prefill(params, cfg, prompt,
                                                cache, table_row,
                                                block_size=block_size,
                                                mesh=mesh, lora=lora)
        logits = logits[0, prompt_len - 1]
        key = jax.random.PRNGKey(seed)
        first = _sample_tokens(
            logits[None], jnp.reshape(temp_val, (1,)).astype(jnp.float32),
            key[None], jnp.reshape(prompt_len - 1, (1,)),
            top_k, top_p)[0]
        return new_cache, first

    # the pool is NOT donated, deliberately: each job's result rides the
    # handoff queue as a snapshot of cache["k"]/["v"], and donating the
    # cache on the NEXT job would delete exactly those buffers while the
    # decode ring's transfer dispatch may still be reading them
    return jax.jit(prefill)


def make_pool_prefill_slice(cfg: LlamaConfig, mesh=None,
                            quant: bool = False):
    """One MULTI-LANE intermediate prefill slice for the N-lane
    prefill engine (ISSUE 14): advance EVERY participating lane's job
    by up to ``slice`` tokens in ONE compiled forward — per-lane block
    tables, per-lane absolute positions, no lm head.  Lanes sitting
    the iteration out ride masked: their rows route to the trash block
    (``limits`` 0) and — quant — their staging-tail writes redirect to
    the trash tail (``mask``), so a paused lane's live tail state is
    never touched.  The batch dimension IS the engine lane index, so
    the pool's per-lane staging tails address directly.

    ``slice(params, cache, tables [N, M], toks [N, sb], starts [N],
    limits [N], mask [N]) -> cache'``

    NOT donated: streamed-handoff frames hold version snapshots of the
    pool arrays (the release protocol in :class:`PrefillExecutor`'s
    docstring), and donating a referenced buffer would delete it under
    the decode side's transfer.

    bf16 writes go WHOLE-BLOCK (``aligned=True`` — the engine rounds
    its chunk to a block multiple and every slice start is
    block-aligned by construction), so the traced write-op count is
    O(lanes x blocks), not O(lanes x rows): at production slice widths
    the per-row unroll is pathological to COMPILE.  The quant tail
    protocol is inherently per-row and keeps the row path."""
    from paddle_operator_tpu.infer.speculative import _multi_forward_paged

    def slice_(params, cache, tables, toks, starts, limits, mask,
               *lora_args):
        lane_cache = {"k": cache["k"], "v": cache["v"], "pos": starts}
        if quant:
            lane_cache["ks"], lane_cache["vs"] = cache["ks"], cache["vs"]
            lane_cache["kt"], lane_cache["vt"] = cache["kt"], cache["vt"]
        _, new = _multi_forward_paged(
            cfg, params, toks, lane_cache, tables, limit=limits,
            mesh=mesh, head=False, quant=quant,
            lane_mask=(mask if quant else None),
            lora=tuple(lora_args) if lora_args else None,
            aligned=not quant)
        out = {"k": new["k"], "v": new["v"], "pos": cache["pos"]}
        if quant:
            out["ks"], out["vs"] = new["ks"], new["vs"]
            out["kt"], out["vt"] = new["kt"], new["vt"]
        return out

    return jax.jit(slice_)


def make_pool_prefill_final(cfg: LlamaConfig,
                            top_k: Optional[int] = None,
                            top_p: Optional[float] = None, mesh=None,
                            quant: bool = False):
    """The FINAL prefill slice for the N-lane engine: run each
    finishing lane's last ``n_rows`` prompt tokens (right-padded to
    the slice width) WITH the lm head, and sample every finishing
    lane's first token through the shared rule — the batched analogue
    of the monolithic path's ``logits[prompt_len - 1]`` +
    ``_sample_tokens`` tail, so first tokens stay bit-identical to the
    1-lane oracle.  Non-finishing lanes ride masked exactly as in
    :func:`make_pool_prefill_slice`; their sampled "firsts" are
    garbage the host ignores.

    ``final(params, cache, tables [N, M], toks [N, sb], n_rows [N],
    starts [N], temps [N], seeds [N], limits [N], mask [N])
    -> (cache', firsts [N])``

    bf16 writes are whole-block like the intermediate slice (the
    straddling block writes its pad rows into the lane's real block —
    :func:`ops.decode_attention.scatter_prefill_blocks`'s
    exactness-with-padding contract: masked in-slice, overwritten by
    decode before any read, and the prefix cache stores only full
    blocks strictly inside the prompt)."""
    from paddle_operator_tpu.infer.speculative import _multi_forward_paged

    def final(params, cache, tables, toks, n_rows, starts, temps,
              seeds, limits, mask, *lora_args):
        lane_cache = {"k": cache["k"], "v": cache["v"], "pos": starts}
        if quant:
            lane_cache["ks"], lane_cache["vs"] = cache["ks"], cache["vs"]
            lane_cache["kt"], lane_cache["vt"] = cache["kt"], cache["vt"]
        logits, new = _multi_forward_paged(
            cfg, params, toks, lane_cache, tables, limit=limits,
            mesh=mesh, quant=quant,
            lane_mask=(mask if quant else None),
            lora=tuple(lora_args) if lora_args else None,
            aligned=not quant)
        out = {"k": new["k"], "v": new["v"], "pos": cache["pos"]}
        if quant:
            out["ks"], out["vs"] = new["ks"], new["vs"]
            out["kt"], out["vt"] = new["kt"], new["vt"]
        # per-lane last REAL row's logits, clamped so masked lanes
        # (n_rows 0) index row 0 harmlessly
        rows = jnp.take_along_axis(
            logits, jnp.maximum(n_rows - 1, 0)[:, None, None],
            axis=1)[:, 0]
        keys = jax.vmap(jax.random.PRNGKey)(seeds)
        firsts = _sample_tokens(rows, temps.astype(jnp.float32), keys,
                                starts + jnp.maximum(n_rows - 1, 0),
                                top_k, top_p)
        return out, firsts

    return jax.jit(final)


class PrefillPrefixCache:
    """The prefill pod's OWN radix prefix cache (ISSUE 14): completed
    full blocks' exact pool bytes, host-resident, keyed by the SAME
    ``utils/radixkey`` rolling-hash chain the decode radix (and the
    router's affinity) use — so a repeated system prompt prefills only
    its suffix ON THE PREFILL SIDE too.  A hit's payloads upload into
    the job's lane blocks through the promote scatter (byte-exact, no
    requantization), which is what keeps a hit bit-identical to cold.
    Bounded LRU by block count; stored chunks are compared on hit (the
    radix collision check).  Payloads may briefly be device arrays
    (async D2H in flight) — :meth:`materialize` settles them before
    the next engine touch, the ``_demote_lazy`` pattern."""

    def __init__(self, capacity_blocks: int) -> None:
        from collections import OrderedDict

        self.cap = int(capacity_blocks)
        self._d: "OrderedDict[Any, tuple]" = OrderedDict()
        self._lazy: List[Dict[str, Any]] = []
        self.hits = 0
        self.lookups = 0

    def __len__(self) -> int:
        return len(self._d)

    def materialize(self) -> None:
        for p in self._lazy:
            for key, val in p.items():
                if not isinstance(val, np.ndarray):
                    p[key] = np.asarray(val)
        self._lazy.clear()

    def put(self, key, chunk: Tuple[int, ...],
            payload: Dict[str, Any], lazy: bool = False) -> None:
        if self.cap <= 0 or key in self._d:
            if key in self._d:
                self._d.move_to_end(key)
            return
        self._d[key] = (chunk, payload)
        if lazy:
            self._lazy.append(payload)
        while len(self._d) > self.cap:
            self._d.popitem(last=False)

    def get(self, key, chunk: Tuple[int, ...]
            ) -> Optional[Dict[str, Any]]:
        ent = self._d.get(key)
        if ent is None or ent[0] != tuple(chunk):
            return None
        self._d.move_to_end(key)
        return ent[1]


class _EngineJob:
    """One in-flight job's host state on the N-lane prefill engine."""

    __slots__ = ("req", "slot", "n", "start", "hit", "frames_done",
                 "prompt")

    def __init__(self, req, slot, start, hit):
        self.req = req
        self.slot = slot
        self.prompt = [int(t) for t in req.prompt]
        self.n = len(self.prompt)
        self.start = start          # next absolute row to prefill
        self.hit = hit              # prefix-cache rows (block-aligned)
        self.frames_done = 0        # blocks already posted as frames


class PrefillExecutor:
    """The disaggregated prefill engine: its OWN thread and its OWN
    block pool, so a cold 2k-token prefill never occupies the decode
    ring's dispatch stream.  The decode scheduler submits ``(request,
    slot)`` jobs; this thread prefills prompts into its private pool
    and posts results the scheduler lands through the handoff path.

    **Two engine shapes** (ISSUE 14):

    - ``lanes == 1`` (default): the ORIGINAL monolithic engine — one
      job at a time, whole prompt in one bucketed compiled forward,
      one ``(request, slot, snapshot, n_blocks, first)`` result.  This
      path is byte-for-byte the PR 6 engine and stays the parity
      ORACLE for everything below.
    - ``lanes >= 2``: a throughput engine.  The pool is N lanes wide
      (lane ``i`` owns the FIXED identity blocks ``[1 + i*M,
      1 + (i+1)*M)``; block 0 stays trash) and the loop is a
      mini-ring: each iteration coalesces every active job into ONE
      batched compiled slice (``make_pool_prefill_slice`` — per-lane
      tables and positions, the ``make_disagg_prefill`` trace
      generalized to the batch dim), amortizing weight streaming and
      dispatch overhead across cold arrivals, and long jobs advance
      one ``prefill_chunk`` slice per iteration ALONGSIDE short jobs
      (chunk-interleaved scheduling — a 40-token prompt is never
      stuck behind a 2k-token one; the Sarathi-Serve argument applied
      to the prefill pool).  Finishing jobs run the lm head + shared
      first-token sample in the batched final program.  Intermediate
      slices append KV only (the ``head=False`` forwards), so the
      interleave is prompt-proportional work.

    **Streamed handoff + the snapshot-lifetime rule.**  With
    ``stream=True`` completed block groups post to ``results`` as
    ``("frame", req, slot, snapshot, lane, j0, j1)`` items the decode
    side uploads WHILE this engine computes the rest — long-prompt
    TTFT collapses to last-chunk + attach.  The terminal item
    ``("final", req, slot, snapshot, lane, j0, n_blocks, first,
    t_done)`` carries the remaining blocks, the (quant) staging tail
    and the sampled first token.  A multi-lane pool with REUSED lanes
    needs a real release protocol where the 1-lane engine needed
    none; the rule is: **a lane is reassigned only after its previous
    job's terminal item has been POSTED, and every posted item pins
    the pool VERSION it snapshotted** — jax arrays are immutable, so
    the next job's writes produce new versions and can never corrupt
    an outstanding snapshot; no engine program donates the pool for
    exactly this reason.  What bounds memory is the decode side
    draining ``results`` every loop pass: at most one pool version per
    undrained item stays alive, and the queue never outlives its
    scheduler.

    **Prefix reuse** (``prefix_blocks > 0``, lanes >= 2): a
    :class:`PrefillPrefixCache` keyed on the shared radix chain; a hit
    uploads cached block bytes into the job's lane and prefill starts
    at the (block-aligned) hit frontier — bit-identical to cold
    because the uploaded bytes ARE a cold run's bytes.  Adapter jobs
    skip the cache (deltas change the KV; the decode radix namespaces
    per adapter, the prefill pool simply abstains).

    Fault isolation: a prefill dispatch failure posts ``(request,
    slot, error)`` tuples — batch-granular on the N-lane engine (one
    fused dispatch serves every active job, so all of them fail and
    retry; the pool is rebuilt lane-clean by the next assignments) —
    and the decode ring (with its watchdog/heal machinery) never sees
    the fault.  Jobs whose request resolved meanwhile (cancel,
    deadline, heal) are dropped at either end."""

    def __init__(self, params: Any, cfg: LlamaConfig, *, max_len: int,
                 block_size: int, buckets: Tuple[int, ...],
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None, mesh=None,
                 kv_quant: str = "none", adapters=None,
                 lanes: int = 1, prefill_chunk: int = 64,
                 stream: bool = False,
                 prefix_blocks: int = 0) -> None:
        from paddle_operator_tpu.infer import paged as PG

        # adapter registry shared with the decode ring (ISSUE 10): a
        # cold adapter prompt must prefill WITH its delta — the KV the
        # handoff copies is the adapter's, not the base model's
        self.adapters = adapters
        self.params = params
        self.cfg = cfg
        self.block_size = int(block_size)
        self.mesh = mesh
        self.kv_quant = kv_quant
        self.quant = kv_quant == "int8"
        self.lanes = max(1, int(lanes))
        self.stream = bool(stream) and self.lanes > 1
        self.prefill_chunk = max(1, int(prefill_chunk))
        if self.lanes > 1 and not self.quant:
            # the bf16 slice/final programs write WHOLE BLOCKS
            # (aligned=True), which needs every slice start
            # block-aligned: round the scheduling quantum up to a
            # block multiple.  The interleave bound coarsens to one
            # block when block_size > chunk — the price of
            # O(blocks) instead of O(rows) traced writes.  The quant
            # engine keeps the configured chunk: its staging-tail
            # protocol is per-row regardless.
            self.prefill_chunk = (-(-self.prefill_chunk
                                    // self.block_size)
                                  * self.block_size)
        alloc = D.cache_alloc_len(max_len)
        self.max_blocks = -(-alloc // self.block_size)
        m = self.max_blocks
        # block 0 stays the trash block, same convention as the decode
        # pool; lane i's job owns the FIXED identity blocks
        # [1 + i*M, 1 + (i+1)*M) — fixed ownership needs no allocator
        self.cache = PG.init_paged_cache(
            cfg, self.lanes, self.lanes * m + 1, self.block_size,
            mesh=mesh, quant=kv_quant)
        self.table_row = jnp.arange(1, m + 1, dtype=jnp.int32)
        self.tables = np.stack(
            [np.arange(1 + i * m, 1 + (i + 1) * m, dtype=np.int32)
             for i in range(self.lanes)])
        # test hook: a callable the loop invokes at each iteration top
        # — the deterministic pause-gate pattern (tests/test_qos.py)
        self.pause_gate = None
        # throughput telemetry (ISSUE 14): batch occupancy EMA (lanes
        # busy / N per engine iteration) and per-job head-of-line
        # queue wait samples — the tpujob_serve_prefill_batch_occupancy
        # / _hol_wait_ms gauges
        self._occ_ema = 0.0
        self._hol: List[float] = []
        self._stats_lock = threading.Lock()
        self.iterations = 0
        self.prefix_hits = 0
        # the prefill pod's own radix prefix cache (multi-lane engine
        # only — the 1-lane path stays the byte-identical oracle)
        self.prefix = (PrefillPrefixCache(prefix_blocks)
                       if prefix_blocks > 0 and self.lanes > 1
                       else None)
        if self.lanes > 1:
            self.buckets = (self.prefill_chunk,)
            self._slice_prog = make_pool_prefill_slice(
                cfg, mesh=mesh, quant=self.quant)
            self._final_prog = make_pool_prefill_final(
                cfg, top_k, top_p, mesh=mesh, quant=self.quant)
            self._progs: Dict[int, Any] = {}
            if self.prefix is not None:
                self._fetch_prog = PG.make_block_fetch(quant=self.quant)
                self._upload_prog = PG.make_promote_blocks(
                    self.block_size, quant=self.quant, donate=False)
        else:
            # the prefill engine's OWN bucket ladder, FINER than the
            # ring's (block-multiple powers of two up to the ring's
            # largest bucket): prefill is stateless-per-job, so it can
            # afford shapes near the prompt length — a 300-token cold
            # prompt runs a 512-row forward instead of the ring's
            # padded 2048-row bucket.  Phases shaping independently is
            # the DistServe argument.
            cap = max(buckets)
            ladder = []
            b = self.block_size
            while b < cap:
                ladder.append(b)
                b *= 2
            self.buckets = tuple(ladder) + (cap,)
            self._progs = {b: make_disagg_prefill(
                cfg, b, self.block_size, top_k, top_p, mesh=mesh,
                quant=self.quant) for b in self.buckets}
        self.jobs: "queue.Queue[tuple]" = queue.Queue()
        self.results: "queue.Queue[tuple]" = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=(self._loop_engine if self.lanes > 1 else self._loop),
            daemon=True, name="prefill-executor")
        self._thread.start()

    def submit(self, req, slot: int) -> None:
        # queue depth is tracked scheduler-side (_disagg_waiting feeds
        # the prefillQueueDepth gauge); the enqueue stamp feeds the
        # head-of-line wait gauge
        self.jobs.put((req, slot, time.monotonic()))

    # -- telemetry (ISSUE 14) ---------------------------------------------

    def batch_occupancy(self) -> float:
        """EMA of lanes-busy / N per engine iteration — 1.0 is a
        saturated batch; the autoscaler divides by it so a half-empty
        pool never reads as a saturated one."""
        with self._stats_lock:
            return round(self._occ_ema, 4)

    def hol_wait_ms_p95(self) -> float:
        """p95 of recent jobs' queue wait (submit -> lane assignment),
        ms — the head-of-line blocking proxy."""
        with self._stats_lock:
            if not self._hol:
                return 0.0
            s = sorted(self._hol)
            return round(s[min(len(s) - 1,
                               int(0.95 * (len(s) - 1)))], 3)

    def _note_wait(self, t_enq: float) -> None:
        with self._stats_lock:
            self._hol.append((time.monotonic() - t_enq) * 1e3)
            if len(self._hol) > 256:
                del self._hol[:len(self._hol) - 256]

    def _note_occ(self, busy: int) -> None:
        occ = busy / self.lanes
        with self._stats_lock:
            self._occ_ema = (occ if not self._occ_ema
                             else 0.8 * self._occ_ema + 0.2 * occ)
            self.iterations += 1

    # -- the 1-lane monolithic loop (the PR 6 engine, the oracle) ----------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                req, slot, t_enq = self.jobs.get(timeout=0.05)
            except queue.Empty:
                continue
            if self.pause_gate is not None:
                self.pause_gate()
            try:
                if req.done.is_set() or req._cancel:
                    continue        # resolved while queued: drop
                self._note_wait(t_enq)
                self._note_occ(1)
                n = len(req.prompt)
                pb = next(b for b in self.buckets if b >= n)
                if pb <= req.dev_prompt.shape[1]:
                    # re-bucket the already-shipped prompt: the ring
                    # bucket is right-padded, so a narrower device
                    # slice keeps every real token
                    prompt = req.dev_prompt[:, :pb]
                else:
                    padded = np.zeros((1, pb), np.int32)
                    padded[0, :n] = req.prompt
                    prompt = jnp.asarray(padded)
                prog = self._progs[pb]
                tail = ()
                if self.adapters is not None:
                    tail = (self.adapters.arrays(),
                            jnp.full((1,), getattr(req, "adapter_idx", 0),
                                     jnp.int32))
                self.cache, first = prog(
                    self.params, self.cache, self.table_row,
                    prompt, n, float(req.temperature), req.seed, *tail)
                n_blocks = -(-len(req.prompt) // self.block_size)
                try:
                    first.copy_to_host_async()
                except AttributeError:
                    pass
                # snapshot refs: immutable arrays — the next job's
                # writes produce a NEW pool version, this one stays
                # readable until the ring's copy dispatch consumes it
                # (quant pools snapshot codes+scales+tails alike)
                snap = {key: self.cache[key]
                        for key in ("k", "v", "ks", "vs", "kt", "vt")
                        if key in self.cache}
                self.results.put((req, slot, snap, n_blocks, first))
            except Exception as e:      # noqa: BLE001 — isolate per job
                self.results.put((req, slot, e))

    # -- the N-lane batched, chunk-interleaved engine (ISSUE 14) -----------

    def _snapshot(self) -> Dict[str, Any]:
        return {key: self.cache[key]
                for key in ("k", "v", "ks", "vs", "kt", "vt")
                if key in self.cache}

    def _prefix_walk(self, prompt: List[int]) -> Tuple[int, list]:
        """Longest cached chain of leading FULL blocks, capped so at
        least one real token remains to prefill (the final slice needs
        a real row to sample from — the same n-1 cap the decode radix
        applies); returns (hit_blocks, payloads)."""
        from paddle_operator_tpu.utils.radixkey import chain_key

        bs = self.block_size
        self.prefix.materialize()
        max_hit = (len(prompt) - 1) // bs
        key = None
        payloads = []
        for j in range(max_hit):
            chunk = tuple(prompt[j * bs:(j + 1) * bs])
            key = chain_key(key, chunk)
            p = self.prefix.get(key, chunk)
            if p is None:
                break
            payloads.append(p)
        return len(payloads), payloads

    def _prefix_upload(self, lane: int, payloads: list) -> None:
        """Land prefix-hit payloads in the lane's identity blocks
        through the (non-donating) promote scatter — byte-exact, the
        PR 8 host-hit discipline."""
        n = len(payloads)
        pad = 1
        while pad < n:
            pad *= 2
        bs = self.block_size
        p0 = payloads[0]
        lcount, _, h, _, d = p0["k"].shape
        slab_k = np.zeros((lcount, 1, h, pad * bs, d), p0["k"].dtype)
        slab_v = np.zeros_like(slab_k)
        from paddle_operator_tpu.infer import paged as PG

        ids = np.full((pad,), PG.TRASH_BLOCK, np.int32)
        for j, payload in enumerate(payloads):
            ids[j] = self.tables[lane][j]
            slab_k[:, 0, :, j * bs:(j + 1) * bs] = payload["k"][:, 0]
            slab_v[:, 0, :, j * bs:(j + 1) * bs] = payload["v"][:, 0]
        c = self.cache
        if self.quant:
            srow_k = np.ones((lcount, pad, h), np.float32)
            srow_v = np.ones_like(srow_k)
            for j, payload in enumerate(payloads):
                srow_k[:, j] = payload["ks"][:, 0]
                srow_v[:, j] = payload["vs"][:, 0]
            c["k"], c["v"], c["ks"], c["vs"] = self._upload_prog(
                c["k"], c["v"], c["ks"], c["vs"], jnp.asarray(slab_k),
                jnp.asarray(slab_v), jnp.asarray(srow_k),
                jnp.asarray(srow_v), jnp.asarray(ids))
        else:
            c["k"], c["v"] = self._upload_prog(
                c["k"], c["v"], jnp.asarray(slab_k),
                jnp.asarray(slab_v), jnp.asarray(ids))

    def _store_prefix(self, lane: int, job: "_EngineJob") -> None:
        """Store the finished job's full blocks (device bytes fetched
        async — the lazy-materialize pattern) under their chain keys.
        Never called for adapter jobs: their KV is delta-dependent."""
        from paddle_operator_tpu.utils.radixkey import chain_key

        bs = self.block_size
        key = None
        c = self.cache
        for j in range(job.n // bs):
            chunk = tuple(job.prompt[j * bs:(j + 1) * bs])
            key = chain_key(key, chunk)
            if self.prefix.get(key, chunk) is not None:
                continue
            blk = int(self.tables[lane][j])
            if self.quant:
                kb, vb, ksb, vsb = self._fetch_prog(
                    c["k"], c["v"], c["ks"], c["vs"], blk)
                payload = {"k": kb, "v": vb, "ks": ksb, "vs": vsb}
            else:
                kb, vb = self._fetch_prog(c["k"], c["v"], blk)
                payload = {"k": kb, "v": vb}
            for val in payload.values():
                try:
                    val.copy_to_host_async()
                except AttributeError:
                    pass
            self.prefix.put(key, chunk, payload, lazy=True)

    def _start_job(self, lane: int, req, slot: int) -> "_EngineJob":
        hit = 0
        if (self.prefix is not None
                and not getattr(req, "adapter_idx", 0)):
            try:
                n_hit, payloads = self._prefix_walk(
                    [int(t) for t in req.prompt])
            except Exception:       # cache is an optimization only
                n_hit, payloads = 0, []
            if n_hit:
                self._prefix_upload(lane, payloads)
                hit = n_hit * self.block_size
                self.prefix_hits += 1
        return _EngineJob(req, slot, hit, hit)

    def _lora_tail(self, active: Dict[int, "_EngineJob"]) -> tuple:
        if self.adapters is None:
            return ()
        aid = np.zeros((self.lanes,), np.int32)
        for lane, job in active.items():
            aid[lane] = getattr(job.req, "adapter_idx", 0)
        return (self.adapters.arrays(), jnp.asarray(aid))

    def _width(self, rows_max: int) -> int:
        """Table width (in blocks) for one batched dispatch:
        smallest power-of-two block count covering the deepest
        participating lane's attended rows, capped at the pool lane
        width.  The gathered lane view — and with it the dense
        attention score width — is the TABLE's width, so slicing the
        table keeps slice work prompt-proportional (the 1-lane
        ladder's property, which a fixed max_len-wide view would
        forfeit: a 256-token job would attend max_len columns of
        masked-out keys).  Power-of-two rounding bounds the compile
        set at log2(max_blocks) shapes per program — jit
        shape-specializes, and each shape is cheap to compile under
        the whole-block write path."""
        need = -(-rows_max // self.block_size)
        w = 1
        while w < need:
            w *= 2
        return min(w, self.max_blocks)

    def _advance(self, active: Dict[int, "_EngineJob"],
                 free: List[int]) -> None:
        """One engine iteration: ONE batched intermediate slice for
        every long job + ONE batched final slice for every finishing
        job, then frame/terminal posts."""
        sb = self.prefill_chunk
        bs = self.block_size
        nl = self.lanes
        inter = [ln for ln, j in sorted(active.items())
                 if j.n - j.start > sb]
        fin = [ln for ln, j in sorted(active.items())
               if j.n - j.start <= sb]
        self._note_occ(len(active))
        tail = self._lora_tail(active)
        from paddle_operator_tpu.infer import paged as PG

        if inter:
            mw = self._width(max(active[ln].start + sb
                                 for ln in inter))
            toks = np.zeros((nl, sb), np.int32)
            starts = np.zeros((nl,), np.int32)
            limits = np.zeros((nl,), np.int32)
            tables = np.full((nl, mw), PG.TRASH_BLOCK, np.int32)
            mask = np.zeros((nl,), bool)
            for ln in inter:
                j = active[ln]
                toks[ln] = j.prompt[j.start:j.start + sb]
                starts[ln] = j.start
                limits[ln] = j.start + sb
                tables[ln] = self.tables[ln][:mw]
                mask[ln] = True
            self.cache = self._slice_prog(
                self.params, self.cache, jnp.asarray(tables),
                jnp.asarray(toks), jnp.asarray(starts),
                jnp.asarray(limits), jnp.asarray(mask), *tail)
            for ln in inter:
                active[ln].start += sb
        firsts = None
        if fin:
            mw = self._width(max(active[ln].start + sb
                                 for ln in fin))
            toks = np.zeros((nl, sb), np.int32)
            starts = np.zeros((nl,), np.int32)
            limits = np.zeros((nl,), np.int32)
            n_rows = np.zeros((nl,), np.int32)
            temps = np.zeros((nl,), np.float32)
            seeds = np.zeros((nl,), np.int32)
            tables = np.full((nl, mw), PG.TRASH_BLOCK, np.int32)
            mask = np.zeros((nl,), bool)
            for ln in fin:
                j = active[ln]
                rem = j.n - j.start
                toks[ln, :rem] = j.prompt[j.start:]
                starts[ln] = j.start
                limits[ln] = j.n
                n_rows[ln] = rem
                temps[ln] = float(j.req.temperature)
                seeds[ln] = int(j.req.seed)
                tables[ln] = self.tables[ln][:mw]
                mask[ln] = True
            self.cache, firsts = self._final_prog(
                self.params, self.cache, jnp.asarray(tables),
                jnp.asarray(toks), jnp.asarray(n_rows),
                jnp.asarray(starts), jnp.asarray(temps),
                jnp.asarray(seeds), jnp.asarray(limits),
                jnp.asarray(mask), *tail)
            try:
                firsts.copy_to_host_async()
            except AttributeError:
                pass
            for ln in fin:
                active[ln].start = active[ln].n
        # streamed frames: post every lane's newly COMPLETED blocks
        # (frames carry full blocks only; the moving write frontier
        # crosses once, on the terminal item).  ONE snapshot after
        # both dispatches serves every post — it pins the pool
        # VERSION, and completed blocks never change after commit.
        snap = (self._snapshot()
                if fin or (self.stream and inter) else None)
        if self.stream:
            for ln in inter:
                j = active[ln]
                done = j.start // bs
                if done > j.frames_done:
                    self.results.put(("frame", j.req, j.slot, snap, ln,
                                      j.frames_done, done))
                    j.frames_done = done
        for ln in fin:
            j = active.pop(ln)
            free.append(ln)
            n_blocks = -(-j.n // bs)
            first = firsts[ln]
            try:
                first.copy_to_host_async()
            except AttributeError:
                pass
            self.results.put(("final", j.req, j.slot, snap, ln,
                              j.frames_done, n_blocks, first,
                              time.monotonic()))
            if (self.prefix is not None
                    and not getattr(j.req, "adapter_idx", 0)):
                try:
                    self._store_prefix(ln, j)
                except Exception:
                    pass            # cache is an optimization only
        free.sort()

    def _loop_engine(self) -> None:
        from collections import deque

        pending: "deque[tuple]" = deque()
        active: Dict[int, _EngineJob] = {}
        free = list(range(self.lanes))
        # depth-2 dispatch pacing (the megastep double-buffer
        # discipline): jax dispatch is async, so an unpaced loop would
        # enqueue a long job's ENTIRE prefill ahead of a short prompt
        # that arrives one host-tick later — the chunk-interleave HOL
        # bound holds in DEVICE order only if host run-ahead is
        # bounded.  Two iterations in flight keep the device busy
        # while a late arrival waits at most ~2 slice quanta to reach
        # the front of the queue.
        fences: "deque[Any]" = deque()
        while not self._stop.is_set():
            if self.pause_gate is not None:
                self.pause_gate()
            # drain the submit queue; block briefly only when idle
            try:
                if not active and not pending:
                    pending.append(self.jobs.get(timeout=0.05))
                while True:
                    pending.append(self.jobs.get_nowait())
            except queue.Empty:
                pass
            # assign free lanes FIFO (lowest lane first — the batch
            # index is the pool lane, determinism matters to tests)
            while free and pending:
                req, slot, t_enq = pending.popleft()
                if req.done.is_set() or req._cancel:
                    continue        # resolved while queued: drop
                lane = free.pop(0)
                try:
                    self._note_wait(t_enq)
                    active[lane] = self._start_job(lane, req, slot)
                except Exception as e:  # noqa: BLE001
                    self.results.put((req, slot, e))
                    free.append(lane)
                    free.sort()
            if not active:
                continue
            try:
                # the fence wait is INSIDE the batch-granular handler:
                # jax dispatch is async, so a device-side failure in a
                # prior slice/final dispatch surfaces HERE, not in
                # _advance — an uncaught one would kill this thread
                # and wedge every queued prefill
                while len(fences) >= 2:
                    fence = fences.popleft()
                    try:
                        fence.block_until_ready()
                    except AttributeError:
                        pass
                self._advance(active, free)
                fences.append(self.cache["k"])
            except Exception as e:      # noqa: BLE001 — batch-granular
                # one fused dispatch served every active job: fail all
                # of them (their clients retry); lanes free clean, and
                # stale fences drop so the failed dispatch cannot
                # re-raise at the next wait
                fences.clear()
                for lane, job in list(active.items()):
                    self.results.put((job.req, job.slot, e))
                    free.append(lane)
                active.clear()
                free.sort()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)


# ---------------------------------------------------------------------------
# RingExecutor: compiled programs + device state for one decode ring
# ---------------------------------------------------------------------------


class RingExecutor:
    """Owns everything device-side about one continuous-batching ring:
    the resident chunk/spec-round program, the per-bucket admission
    inserts (inline, suffix, chunked, spec variants), the KV cache or
    block pool, and the per-lane tok/temp/keys state.  The scheduler
    (infer/scheduler.py ContinuousBatcher) holds NO jax arrays of its
    own — it sequences dispatches on this object, which is what makes
    the prefill/decode executor split (and the watchdog's full device
    rebuild, :meth:`reset_state`) possible.
    """

    # a prefix hit with a LONGER divergent suffix admits through the
    # cold scatter prefill instead: the suffix insert's per-row pool
    # writes unroll O(rows) (paged._write_rows_paged), and past this
    # many rows the block-granular cold path compiles and runs faster
    # than what the cached prefix saves
    SUFFIX_PREFILL_MAX_ROWS = 256

    def __init__(self, params: Any, cfg: LlamaConfig, *, slots: int,
                 max_len: int, chunk_tokens: int,
                 prefill_buckets: Tuple[int, ...] = (),
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None, mesh=None,
                 draft_params: Any = None,
                 draft_cfg: Optional[LlamaConfig] = None, spec_k: int = 0,
                 paged: bool = False, block_size: int = 256,
                 num_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 prefill_mode: str = "inline",
                 prefill_chunk: int = 64,
                 check_finite: bool = False,
                 kv_quant: str = "none",
                 host_cache_blocks: int = 0,
                 adapters=None,
                 megastep: int = 1,
                 prefill_client=None,
                 prefill_lanes: int = 1,
                 prefill_stream: bool = False,
                 prefill_prefix_blocks: int = 0) -> None:
        # many-adapter serving (ISSUE 10, infer/qos.py AdapterRegistry):
        # stacked LoRA deltas served off the one base param set.  The
        # registry's arrays ride every dispatch as trailing operands
        # (lora_step_tail / lora_insert_tail), so load/evict reaches
        # the compiled programs without retraces.  Spec rings refuse:
        # the draft stays base-only by design, and a drafted token
        # stream verified under a different (adapted) target would
        # collapse acceptance — scheduler.submit rejects per-request
        # adapters instead of silently serving base math.
        if adapters is not None and spec_k:
            raise ValueError(
                "adapters are not supported on speculative rings (the "
                "draft proposes base-only); disable one of them")
        self.adapters = adapters
        self.mesh = mesh
        if mesh is not None and D.mesh_tp(mesh) > 1:
            params = D.shard_params_for_serving(params, cfg, mesh)
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.max_len = max_len
        self.chunk = chunk_tokens
        self.check_finite = check_finite
        self.prefill_mode = prefill_mode
        self.buckets = tuple(sorted(prefill_buckets)) or _default_buckets(
            max_len)
        self.top_k, self.top_p = top_k, top_p
        self.paged = bool(paged)
        self.pool: Optional[Any] = None
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1 (got {prefill_chunk})")
        # SERVE_KV_QUANT: int8 codes + per-block scales for the paged
        # pool, dequant fused into the kernels — ~2x resident lanes per
        # HBM byte; "none" (default) keeps the bf16 pool bit-identical
        # to pre-quantization behavior (infer/paged.py module note)
        from paddle_operator_tpu.infer import paged as _PGQ

        if kv_quant not in _PGQ.KV_QUANT_MODES:
            raise ValueError(f"kv_quant {kv_quant!r} not in "
                             f"{_PGQ.KV_QUANT_MODES}")
        self.kv_quant = kv_quant
        self.quant = kv_quant == "int8"
        if self.quant and not self.paged:
            raise ValueError("kv_quant='int8' requires the paged ring "
                             "(the pool block is the quantization "
                             "unit); set paged=True / SERVE_PAGED=1")
        if self.paged:
            from paddle_operator_tpu.infer import paged as PG

            self._pg = PG
            self.block_size = int(block_size)
            self._num_blocks = num_blocks
            self.prefix_cache = prefix_cache and not spec_k
            # ISSUE 8 host spill tier: demoted radix blocks live in
            # host RAM and promote back on hit — only meaningful with
            # the prefix cache on (a spec ring turns both off)
            self.host_cache_blocks = (int(host_cache_blocks)
                                      if self.prefix_cache else 0)
            self.pool = PG.PagedCacheManager(
                slots, max_len, self.block_size, num_blocks,
                prefix_cache=self.prefix_cache,
                host_cache_blocks=self.host_cache_blocks)
            # demote/promote programs exist whenever the ring is paged:
            # the host tier uses them on evict/hit, and spill_lane /
            # restore_lane (the preemption primitive) reuse the same
            # byte-copy path with the tier off (both lru_cached)
            self._fetch_prog = PG.make_block_fetch(
                quant=(kv_quant == "int8"))
            self._promote_prog = PG.make_promote_blocks(
                self.block_size, quant=(kv_quant == "int8"))
            # prefill buckets scatter whole blocks: round each up to a
            # block multiple, capped at the lane view
            self.buckets = tuple(sorted(
                {min(-(-b // self.block_size) * self.block_size,
                     self.pool.view_len) for b in self.buckets}))
            self._copy_block = PG.make_block_copier(quant=self.quant)
            self._tail_init = PG.make_tail_init() if self.quant else None
        else:
            self.block_size = int(block_size)
            self.prefix_cache = False
            self.host_cache_blocks = 0
        # device-resident megastep (ISSUE 11): SERVE_MEGASTEP fused
        # ring iterations per dispatch.  Programs are compiled per N
        # (megastep_prog) so the scheduler can drop to N=1 (the
        # byte-identical oracle) at any time; ``megastep`` here is the
        # configured default the prewarm compiles ahead.
        self.megastep = max(1, int(megastep))
        self._mega: Dict[int, Any] = {}
        self._suffix_inserts: Dict[int, Any] = {}
        # chunked-prefill compile caches: intermediate slice + final
        # insert programs, keyed by staging length (contiguous) or just
        # the fixed slice bucket (paged — writes are table-driven)
        self._chunk_progs: Dict[Any, Any] = {}
        self._final_inserts: Dict[Any, Any] = {}
        self._attach = None
        self._spec_attach: Dict[int, Any] = {}
        self._transfer = None
        # demoted payloads whose device->host copy is still settling
        # (_demote_fetch): materialized to numpy on the next tier touch
        self._demote_lazy: List[Dict[str, Any]] = []

        self.spec_k = int(spec_k)
        self.draft_cfg = draft_cfg
        if self.spec_k > 0:
            from paddle_operator_tpu.infer.speculative import (
                check_draft_compat,
                make_spec_round_fn,
            )

            if draft_params is None or draft_cfg is None:
                raise ValueError("spec_k > 0 requires draft_params and "
                                 "draft_cfg (see LlamaConfig.draft())")
            check_draft_compat(cfg, draft_cfg)
            if max_len > draft_cfg.max_seq_len:
                raise ValueError(
                    f"draft max_seq_len ({draft_cfg.max_seq_len}) < ring "
                    f"max_len ({max_len}); derive the draft with "
                    "cfg.draft() to inherit the target's RoPE table")
            if mesh is not None and D.mesh_tp(mesh) > 1:
                draft_params = D.shard_params_for_serving(
                    draft_params, draft_cfg, mesh)
            self.draft_params = draft_params
            self.spec_step = make_spec_round_fn(
                cfg, draft_cfg, self.spec_k, top_k, top_p, mesh=mesh,
                paged=self.paged, quant=self.quant)
            self.step = None
            if self.paged:
                # target prefill scatters into the pool; the DRAFT lane
                # stays a contiguous splice (speculative.py docstring)
                self.inserts = {b: self._pg.make_paged_spec_prefill_insert(
                    cfg, draft_cfg, b, self.block_size, top_k, top_p,
                    mesh=mesh, quant=self.quant) for b in self.buckets}
            else:
                self.inserts = {b: make_spec_prefill_insert(
                    cfg, draft_cfg, b, top_k, top_p, mesh=mesh)
                    for b in self.buckets}
        else:
            self.draft_params = None
            self.spec_step = None
            if self.paged:
                self.step = self._pg.make_paged_chunk_step(
                    cfg, chunk_tokens, top_k, top_p, mesh=mesh,
                    check_finite=check_finite, quant=self.quant)
                self.inserts = {b: self._pg.make_paged_prefill_insert(
                    cfg, b, self.block_size, top_k, top_p, mesh=mesh,
                    quant=self.quant)
                    for b in self.buckets}
            else:
                self.step = make_chunk_step(cfg, chunk_tokens, top_k,
                                            top_p, mesh=mesh,
                                            check_finite=check_finite)
                self.inserts = {b: make_prefill_insert(cfg, b, top_k,
                                                       top_p, mesh=mesh)
                                for b in self.buckets}

        # the disaggregated prefill engine (prefill_mode="disagg"):
        # built here so its compile set and pool live with the rest of
        # the device state; the scheduler drives its queues.  With a
        # ``prefill_client`` (ISSUE 13 cross-host disaggregation —
        # infer/prefill_serve.RemotePrefillClient) the engine lives in
        # its OWN pods: the client satisfies the same submit/results
        # contract, its results are HOST payloads the scheduler lands
        # through the promote scatter, and only the tiny attach
        # dispatch runs here — no local prefill pool, no local
        # whole-prompt compiles.
        self.prefill_exec: Optional[Any] = None
        self.prefill_remote = False
        self.prefill_lanes = max(1, int(prefill_lanes))
        self.prefill_stream = bool(prefill_stream)
        self._frame_transfer = None
        self._tail_copy = None
        if prefill_mode == "disagg":
            if not self.paged:
                raise ValueError("prefill_mode='disagg' requires the "
                                 "paged ring (block-granular handoff)")
            self._attach = make_attach_lane()
            if prefill_client is not None:
                self.prefill_exec = prefill_client
                self.prefill_remote = True
            else:
                self.prefill_exec = PrefillExecutor(
                    self.params, cfg, max_len=max_len,
                    block_size=self.block_size, buckets=self.buckets,
                    top_k=top_k, top_p=top_p, mesh=mesh,
                    kv_quant=self.kv_quant, adapters=adapters,
                    lanes=self.prefill_lanes,
                    prefill_chunk=self.prefill_chunk,
                    stream=self.prefill_stream,
                    prefix_blocks=int(prefill_prefix_blocks))
                if self.prefill_lanes > 1:
                    # N-lane engine handoffs land frame-wise: block
                    # groups via the frame transfer, the (quant)
                    # staging tail once via the lane-addressed copy —
                    # the 1-lane monolithic path keeps the fused
                    # make_pool_transfer (the oracle trace, untouched)
                    self._frame_transfer = self._pg.make_pool_frame_transfer(
                        self.pool.max_blocks, quant=self.quant)
                    if self.quant:
                        self._tail_copy = self._pg.make_pool_tail_copy()
                else:
                    self._transfer = self._pg.make_pool_transfer(
                        self.pool.max_blocks, quant=self.quant)

        self.reset_state()

    # -- state lifecycle ---------------------------------------------------

    def reset_state(self) -> None:
        """(Re)build every piece of mutable device state from scratch —
        construction AND the watchdog's self-heal both land here, so a
        rebuilt ring can never carry poisoned state forward.  Compiled
        programs are kept (they are pure)."""
        if self.paged:
            # ALWAYS a fresh allocator: the radix cache keys blocks of
            # the about-to-be-replaced device arrays — carrying it over
            # would map zeroed blocks as a "cached" prefix.  The host
            # tier resets WITH it: in-flight promotions are dropped and
            # a rebuilt ring re-walks the radix from cold (host payloads
            # keyed against the dead allocator's chain state must never
            # promote into the fresh pool)
            self.pool = self._pg.PagedCacheManager(
                self.slots, self.max_len, self.block_size,
                self._num_blocks, prefix_cache=self.prefix_cache,
                host_cache_blocks=self.host_cache_blocks)
            if self.host_cache_blocks:
                self.pool.demote_fetch = self._demote_fetch
            self._demote_lazy.clear()   # payloads of the dead tier
            self.cache = self._pg.init_paged_cache(
                self.cfg, self.slots, self.pool.total, self.block_size,
                mesh=self.mesh, quant=self.kv_quant)
        else:
            self.cache = init_ring_cache(self.cfg, self.slots,
                                         self.max_len, mesh=self.mesh)
        if self.spec_k:
            self.dcache = init_ring_cache(self.draft_cfg, self.slots,
                                          self.max_len, mesh=self.mesh)
        else:
            self.dcache = None
        self.tok = jnp.zeros((self.slots,), jnp.int32)
        self.temp = jnp.zeros((self.slots,), jnp.float32)
        self.keys = jnp.zeros((self.slots, 2), jnp.uint32)
        # per-lane adapter id HOST mirror (ISSUE 10): set at admission,
        # zeroed at evict, shipped with every adapter-aware dispatch.
        # Host-side (not donated device state) because it changes only
        # at admission and the step reads it as a tiny operand.
        self.aid = np.zeros((self.slots,), np.int32)

    def swap_weights(self, params: Any, draft_params: Any = None) -> tuple:
        """Replace the served param trees in place — the device half of
        the live weight swap (ISSUE 19), for a flip that keeps this
        executor (same cfg / mesh / ring geometry).  The compiled
        programs take params as a traced OPERAND, so a new checkpoint —
        even one whose weight-quant mode differs: the leaf types are
        the dispatch (infer/quant.py) — re-traces lazily on its first
        dispatch instead of needing any rebuild here.  Returns the old
        ``(params, draft_params)`` so the caller can roll back an
        aborted swap; dropping the returned references frees the HBM.

        The caller (ContinuousBatcher swap path) has QUIESCED the
        ring — nothing in flight, every lane parked — and runs
        reset_state() right after the flip, so cached KV computed
        under the old generation can never serve the new one."""
        if self.spec_k and draft_params is None:
            raise ValueError(
                "speculative ring: a weight swap must ship the draft "
                "with the target (drafts are verified against the NEW "
                "params only; a stale draft would silently collapse "
                "acceptance)")
        if self.mesh is not None and D.mesh_tp(self.mesh) > 1:
            params = D.shard_params_for_serving(params, self.cfg,
                                                self.mesh)
            if draft_params is not None:
                draft_params = D.shard_params_for_serving(
                    draft_params, self.draft_cfg, self.mesh)
        old, old_draft = self.params, self.draft_params
        self.params = params
        if self.spec_k:
            self.draft_params = draft_params
        if self.prefill_exec is not None and not self.prefill_remote:
            # the in-process prefill engine dispatches the same tree
            # (already sharded above); the scheduler quiesced its
            # queues before the flip, so no job reads a torn reference
            self.prefill_exec.params = self.params
        return old, old_draft

    # -- adapter (LoRA) dispatch tails (ISSUE 10) --------------------------

    def lora_step_tail(self) -> tuple:
        """Trailing operands for the resident chunk step: the stacked
        adapter arrays + the per-lane id vector — or () when adapters
        are off, keeping every dispatch byte-identical to today's."""
        if self.adapters is None:
            return ()
        return (self.adapters.arrays(), jnp.asarray(self.aid))

    def lora_insert_tail(self, aid_val: int) -> tuple:
        """Trailing operands for a batch-of-one admission insert."""
        if self.adapters is None:
            return ()
        return (self.adapters.arrays(),
                jnp.full((1,), int(aid_val), jnp.int32))

    # -- plan replay: the ONE resident dispatch path (ISSUE 11) ------------

    def megastep_prog(self, n: int):
        """The compiled N-fused-iteration program for this ring's mode
        (contiguous / paged / quant / spec), compiled once per N."""
        prog = self._mega.get(n)
        if prog is None:
            if self.spec_k:
                from paddle_operator_tpu.infer.speculative import (
                    make_spec_megastep,
                )

                prog = make_spec_megastep(
                    self.cfg, self.draft_cfg, self.spec_k, n,
                    self.top_k, self.top_p, mesh=self.mesh,
                    paged=self.paged, quant=self.quant)
            elif self.paged:
                prog = self._pg.make_paged_megastep(
                    self.cfg, self.chunk, n, self.top_k, self.top_p,
                    mesh=self.mesh, check_finite=self.check_finite,
                    quant=self.quant)
            else:
                prog = make_megastep(
                    self.cfg, self.chunk, n, self.top_k, self.top_p,
                    mesh=self.mesh, check_finite=self.check_finite)
            self._mega[n] = prog
        return prog

    def replay(self, plan: ExecPlan) -> DispatchResult:
        """THE plan replayer: execute one scheduler-filled
        :class:`ExecPlan` against the ring's device state.  Every
        resident decode dispatch — 1-step or fused — enters the device
        through here, which is the seam the chaos injector wraps and
        the watchdog brackets.  At ``n_steps == 1`` the dispatch is
        operand-for-operand the pre-plan code path (the traced
        programs are the SAME objects — ``self.step``/``self.spec_step``
        — so pacing/chaos wrappers installed on them keep working and
        the N=1 stream is byte-identical to the pre-refactor ring)."""
        active = jnp.asarray(plan.active, bool)
        tbl = jnp.asarray(plan.table) if plan.table is not None else None
        if plan.n_steps == 1:
            if self.spec_k:
                spec_args = (self.params, self.draft_params, self.cache,
                             self.dcache)
                if self.paged:
                    spec_args += (tbl,)
                (self.cache, self.dcache, self.tok, toks,
                 counts) = self.spec_step(
                    *spec_args, self.tok, self.temp, self.keys, active)
                return DispatchResult(toks, counts, None, counts, 1)
            if self.paged:
                out = self.step(self.params, self.cache, tbl, self.tok,
                                self.temp, self.keys, active, *plan.lora)
            else:
                out = self.step(self.params, self.cache, self.tok,
                                self.temp, self.keys, active, *plan.lora)
            if self.check_finite:
                self.cache, self.tok, toks, ok = out
            else:
                (self.cache, self.tok, toks), ok = out, None
            return DispatchResult(toks, None, ok, None, 1)
        prog = self.megastep_prog(plan.n_steps)
        eos = jnp.asarray(plan.eos, jnp.int32)
        left = jnp.asarray(plan.left, jnp.int32)
        steps = jnp.asarray(plan.steps, jnp.int32)
        if self.spec_k:
            spec_args = (self.params, self.draft_params, self.cache,
                         self.dcache)
            if self.paged:
                spec_args += (tbl,)
            (self.cache, self.dcache, self.tok, toks, raw,
             counts) = prog(*spec_args, self.tok, self.temp, self.keys,
                            active, eos, left, steps)
            return DispatchResult(toks, counts, None, raw, plan.n_steps)
        if self.paged:
            out = prog(self.params, self.cache, tbl, self.tok, self.temp,
                       self.keys, active, eos, left, steps, *plan.lora)
        else:
            out = prog(self.params, self.cache, self.tok, self.temp,
                       self.keys, active, eos, left, steps, *plan.lora)
        if self.check_finite:
            self.cache, self.tok, toks, counts, oks = out
        else:
            (self.cache, self.tok, toks, counts), oks = out, None
        return DispatchResult(toks, counts, oks, None, plan.n_steps)

    # -- lazily-compiled admission programs --------------------------------

    def suffix_bucket(self, n: int) -> int:
        """Compile bucket for a prefix-hit SUFFIX forward — sized
        independently of the prompt buckets (whose smallest entry can
        be prompt-sized: a 1-token suffix must not pay a 2048-row
        forward).  Power-of-two ladder up to one block, then block
        multiples; the compile set stays bounded by
        log2(block_size) + SUFFIX_PREFILL_MAX_ROWS / block_size."""
        cap = self.pool.view_len
        b = 8
        while b < min(n, self.block_size):
            b *= 2
        if b < n:
            b = -(-n // self.block_size) * self.block_size
        return min(b, cap)

    def suffix_insert(self, sb: int):
        ins = self._suffix_inserts.get(sb)
        if ins is None:
            ins = self._pg.make_paged_suffix_insert(
                self.cfg, sb, self.block_size, self.top_k, self.top_p,
                mesh=self.mesh, quant=self.quant)
            self._suffix_inserts[sb] = ins
        return ins

    def pool_bytes(self) -> int:
        """Device bytes held by the KV cache (block pool incl. scale
        planes and staging tails, or the contiguous ring) — the
        ``tpujob_serve_kv_pool_bytes`` gauge.  Pure shape arithmetic,
        no device sync."""
        import numpy as np

        total = 0
        for key in ("k", "v", "ks", "vs", "kt", "vt"):
            buf = self.cache.get(key)
            if buf is not None:
                total += int(np.prod(buf.shape)) * buf.dtype.itemsize
        return total

    def param_bytes(self) -> int:
        """HBM bytes of the params tree(s) this ring dispatches (target
        + draft when speculative) — the ``tpujob_serve_param_bytes``
        gauge, pool_bytes()'s weight-side sibling.  Pure shape
        arithmetic, no device sync; int8 code leaves count 1 byte/param
        + their f32 scale planes, so the gauge shows the quantization
        saving directly."""
        from paddle_operator_tpu.infer import quant as Q

        total = Q.param_bytes(self.params)
        if getattr(self, "draft_params", None) is not None:
            total += Q.param_bytes(self.draft_params)
        return total

    # -- host spill tier: demote fetch + batched promote (ISSUE 8) --------

    def _demote_fetch(self, blk: int) -> Dict[str, Any]:
        """PagedCacheManager.demote_fetch hook: one block's exact device
        bytes, captured WITHOUT blocking the ring thread.  The slice is
        an async dispatch (stream-ordered after every write to the
        block, so it reads final content) and the device->host copy is
        kicked with ``copy_to_host_async`` — no sync here, residents
        never stall on a demotion.  The payload dict initially holds
        the small sliced device arrays; the NEXT tier touch (another
        demotion, or nothing — a promote reads them as-is) materializes
        the PREVIOUS payloads to numpy in place, releasing their device
        buffers, so at most one admission's worth of demoted slices is
        ever device-resident."""
        # materialize earlier payloads first: their D2H copies have
        # long completed, so the asarray is a cheap buffer read
        for d in self._demote_lazy:
            for key, val in d.items():
                if not isinstance(val, np.ndarray):
                    d[key] = np.asarray(val)
        self._demote_lazy.clear()
        c = self.cache
        if self.quant:
            kb, vb, ksb, vsb = self._fetch_prog(c["k"], c["v"], c["ks"],
                                                c["vs"], blk)
            payload = {"k": kb, "v": vb, "ks": ksb, "vs": vsb}
        else:
            kb, vb = self._fetch_prog(c["k"], c["v"], blk)
            payload = {"k": kb, "v": vb}
        for val in payload.values():
            try:
                val.copy_to_host_async()
            except AttributeError:      # interpret-mode ndarray
                pass
        self._demote_lazy.append(payload)
        return payload

    @staticmethod
    def _promote_pad(n: int) -> int:
        """Pad a promote batch to a power of two so a handful of
        compiles serves every batch size (the ids pad with the trash
        block — garbage written there is its job)."""
        p = 1
        while p < n:
            p *= 2
        return p

    def dispatch_promotions(self, promotes) -> None:
        """Upload a batch of host-tier payloads into their RESERVED
        pool blocks in one donated jit (``promotes``:
        pool.take_promotions() output).  The host->device transfer and
        the scatter are both ASYNC dispatches: they overlap the decode
        chunk already in flight on the device, and the runtime orders
        them before the admission insert / CoW dispatched next — the
        prefetch never stalls resident lanes and activation naturally
        waits on transfer completion."""
        n = len(promotes)
        pad = self._promote_pad(n)
        bs = self.block_size
        p0 = promotes[0][1]
        lcount, _, h, _, d = p0["k"].shape
        slab_k = np.zeros((lcount, 1, h, pad * bs, d), p0["k"].dtype)
        slab_v = np.zeros_like(slab_k)
        ids = np.full((pad,), self._pg.TRASH_BLOCK, np.int32)
        for j, (dst, payload, _key) in enumerate(promotes):
            ids[j] = dst
            slab_k[:, 0, :, j * bs:(j + 1) * bs] = payload["k"][:, 0]
            slab_v[:, 0, :, j * bs:(j + 1) * bs] = payload["v"][:, 0]
        c = self.cache
        if self.quant:
            # pad scale rows hold the all-zero-block sentinel 1.0 so a
            # (never-read) trash write still dequantizes finite
            srow_k = np.ones((lcount, pad, h), np.float32)
            srow_v = np.ones_like(srow_k)
            for j, (dst, payload, _key) in enumerate(promotes):
                srow_k[:, j] = payload["ks"][:, 0]
                srow_v[:, j] = payload["vs"][:, 0]
            c["k"], c["v"], c["ks"], c["vs"] = self._promote_prog(
                c["k"], c["v"], c["ks"], c["vs"], jnp.asarray(slab_k),
                jnp.asarray(slab_v), jnp.asarray(srow_k),
                jnp.asarray(srow_v), jnp.asarray(ids))
        else:
            c["k"], c["v"] = self._promote_prog(
                c["k"], c["v"], jnp.asarray(slab_k), jnp.asarray(slab_v),
                jnp.asarray(ids))

    # -- lane spill/restore: the preemption primitive (ISSUE 8) -----------

    def spill_lane(self, slot: int) -> Dict[str, Any]:
        """Capture a LIVE lane to host: its mapped blocks' exact pool
        bytes (codes + scales under int8, plus the bf16 staging tail),
        its fill position and its carry token / temperature / sampling
        key — everything :meth:`restore_lane` needs to resume the lane
        bit-identically.  The caller retires the lane afterwards
        (freeing its blocks for the preempting request); this method
        only reads.  This is the generic preemption/handoff primitive
        ROADMAP items 4 (priority preemption) and 5 (hot swap via lane
        handoff) consume — tested for exactness in
        tests/test_hostcache.py.

        The capture is plain host bytes on purpose: ISSUE 12 wraps it
        in a self-describing wire envelope (utils/fleetkv.encode_lane)
        and a PEER replica restores it through this same
        spill-dict contract (``ContinuousBatcher.adopt``) —
        cross-replica lane migration is this method plus HTTP.  The
        gather is full (unsharded) host bytes, so a tp=1 spill may
        restore onto a tp=2 ring: the promote scatter re-shards."""
        pm = self.pool
        m = pm.mapped_count[slot]
        ids = jnp.asarray([int(pm.table[slot][j]) for j in range(m)],
                          jnp.int32)
        c = self.cache
        spill: Dict[str, Any] = {
            "n_blocks": m,
            "pos": int(np.asarray(c["pos"])[slot]),
            "tok": int(np.asarray(self.tok)[slot]),
            "temp": float(np.asarray(self.temp)[slot]),
            "key": np.asarray(self.keys)[slot].copy(),
            "k": np.asarray(jnp.take(c["k"], ids, axis=1)),
            "v": np.asarray(jnp.take(c["v"], ids, axis=1)),
        }
        if self.quant:
            spill["ks"] = np.asarray(jnp.take(c["ks"], ids, axis=1))
            spill["vs"] = np.asarray(jnp.take(c["vs"], ids, axis=1))
            spill["kt"] = np.asarray(c["kt"][:, slot])
            spill["vt"] = np.asarray(c["vt"][:, slot])
        if self.spec_k:
            # the DRAFT lane is resident context too (contiguous ring):
            # a spec round resumed without it would re-propose from a
            # zeroed draft cache and diverge from the uninterrupted
            # stream the moment any draft is accepted.  The whole lane
            # alloc is captured — rows past dpos are junk the fill mask
            # already hides, and exactness beats a slice here.
            spill["dk"] = np.asarray(self.dcache["k"][:, slot])
            spill["dv"] = np.asarray(self.dcache["v"][:, slot])
            spill["dpos"] = int(np.asarray(self.dcache["pos"])[slot])
        if self.adapters is not None:
            spill["aid"] = int(self.aid[slot])
        return spill

    def restore_lane(self, slot: int, spill: Dict[str, Any]) -> None:
        """Re-admit a spilled lane into (empty) ``slot``: map fresh
        pool blocks, upload the spilled bytes through the same promote
        scatter a host hit uses, restore the staging tail, and attach
        the lane state (pos/tok/temp/keys) — the resumed decode stream
        is bit-identical to the uninterrupted one because every byte
        the forward reads is a copy of what was captured.  The re-admit
        rides the same suffix-insert-shaped contract as admission: the
        restored rows play the role of a full prefix hit, so no forward
        runs here at all."""
        pm = self.pool
        if pm.mapped_count[slot]:
            raise AssertionError(f"slot {slot} still holds blocks")
        m = spill["n_blocks"]
        pm.ensure(slot, m * self.block_size)
        promotes = []
        for j in range(m):
            payload = {"k": spill["k"][:, j:j + 1],
                       "v": spill["v"][:, j:j + 1]}
            if self.quant:
                payload["ks"] = spill["ks"][:, j:j + 1]
                payload["vs"] = spill["vs"][:, j:j + 1]
            promotes.append((int(pm.table[slot][j]), payload, None))
        if promotes:
            self.dispatch_promotions(promotes)
        if self.quant:
            self.cache["kt"] = self.cache["kt"].at[:, slot].set(
                jnp.asarray(spill["kt"]))
            self.cache["vt"] = self.cache["vt"].at[:, slot].set(
                jnp.asarray(spill["vt"]))
        if self.spec_k:
            self.dcache["k"] = self.dcache["k"].at[:, slot].set(
                jnp.asarray(spill["dk"]))
            self.dcache["v"] = self.dcache["v"].at[:, slot].set(
                jnp.asarray(spill["dv"]))
            self.dcache["pos"] = self.dcache["pos"].at[slot].set(
                spill["dpos"])
        if self.adapters is not None and "aid" in spill:
            self.aid[slot] = spill["aid"]
        self.cache["pos"] = self.cache["pos"].at[slot].set(spill["pos"])
        self.tok = self.tok.at[slot].set(spill["tok"])
        self.temp = self.temp.at[slot].set(spill["temp"])
        self.keys = self.keys.at[slot].set(jnp.asarray(spill["key"]))

    def chunk_prog(self, staging_len: Optional[int]):
        """Intermediate chunked-prefill slice program: paged (keyed by
        the fixed slice width) or contiguous (keyed by staging
        length)."""
        sb = self.prefill_chunk
        key = ("paged", sb) if self.paged else ("ring", sb, staging_len)
        prog = self._chunk_progs.get(key)
        if prog is None:
            if self.paged:
                prog = self._pg.make_paged_prefill_chunk(
                    self.cfg, sb, self.block_size, mesh=self.mesh,
                    quant=self.quant)
            else:
                prog = make_prefill_chunk(self.cfg, sb, staging_len,
                                          mesh=self.mesh)
            self._chunk_progs[key] = prog
        return prog

    def final_insert(self, staging_len: Optional[int],
                     bucket: Optional[int] = None):
        """Final chunked-prefill slice program.  Paged rings reuse the
        SUFFIX insert (a chunked prefill's last slice IS a suffix
        insert whose 'hit' is the rows the earlier slices wrote) —
        shared compile with the radix-hit path; spec rings get the
        draft-prefilling variants."""
        sb = self.prefill_chunk
        if self.paged and not self.spec_k:
            return self.suffix_insert(sb)
        if self.paged:
            key = ("paged-spec", sb, bucket)
            prog = self._final_inserts.get(key)
            if prog is None:
                prog = self._pg.make_paged_spec_suffix_insert(
                    self.cfg, self.draft_cfg, sb, bucket,
                    self.block_size, self.top_k, self.top_p,
                    mesh=self.mesh, quant=self.quant)
                self._final_inserts[key] = prog
            return prog
        if self.spec_k:
            key = ("ring-spec", sb, staging_len, bucket)
            prog = self._final_inserts.get(key)
            if prog is None:
                prog = make_spec_chunked_final_insert(
                    self.cfg, self.draft_cfg, sb, staging_len, bucket,
                    self.top_k, self.top_p, mesh=self.mesh)
                self._final_inserts[key] = prog
            return prog
        key = ("ring", sb, staging_len)
        prog = self._final_inserts.get(key)
        if prog is None:
            prog = make_chunked_final_insert(
                self.cfg, sb, staging_len, self.top_k, self.top_p,
                mesh=self.mesh)
            self._final_inserts[key] = prog
        return prog

    def spec_attach(self, bucket: int):
        prog = self._spec_attach.get(bucket)
        if prog is None:
            prog = make_spec_attach(self.cfg, self.draft_cfg, bucket,
                                    mesh=self.mesh)
            self._spec_attach[bucket] = prog
        return prog

    def staging_len(self, bucket: int) -> int:
        """Contiguous chunked prefill stages in a private lane cache
        whose length is the bucket rounded up to whole slices, so every
        full-width slice write stays in bounds (a clamped
        dynamic_update_slice would silently shift pad rows over real
        ones).  The splice truncates back to the ring allocation."""
        sb = self.prefill_chunk
        return -(-bucket // sb) * sb

    def make_staging(self, bucket: int) -> Tuple[jax.Array, jax.Array]:
        """Fresh zeroed staging K/V for one contiguous chunked prefill
        ([L, 1, H_kv, staging_len(bucket), D], kv-head-sharded like the
        ring cache so the slice programs compile against one layout)."""
        sl = self.staging_len(bucket)
        shape = (self.cfg.n_layers, 1, self.cfg.n_kv_heads, sl,
                 self.cfg.head_dim)
        return (D.alloc_kv_buffer(self.cfg, shape, self.mesh),
                D.alloc_kv_buffer(self.cfg, shape, self.mesh))

    # -- prewarm -----------------------------------------------------------

    def prewarm(self) -> None:
        """Compile the admission/step programs NOW, against throwaway
        state of the real shapes/shardings, so the first long prompt of
        a fresh server never pays a multi-second XLA compile on the
        serving path (the jit dispatch cache keys on
        shape/dtype/sharding — identical dummies make the real call a
        cache hit).  Runs off-thread from the scheduler (opt-out:
        prewarm=False / SERVE_PREWARM=0); jax dispatch is thread-safe,
        and donated dummy buffers are garbage by design."""
        slots = self.slots
        if self.paged:
            cache = self._pg.init_paged_cache(
                self.cfg, slots, self.pool.total, self.block_size,
                mesh=self.mesh, quant=self.kv_quant)
            tbl = jnp.zeros((slots, self.pool.max_blocks), jnp.int32)
        else:
            cache = init_ring_cache(self.cfg, slots, self.max_len,
                                    mesh=self.mesh)
            tbl = None
        tok = jnp.zeros((slots,), jnp.int32)
        temp = jnp.zeros((slots,), jnp.float32)
        keys = jnp.zeros((slots, 2), jnp.uint32)
        active = jnp.zeros((slots,), bool)
        dcache = (init_ring_cache(self.draft_cfg, slots, self.max_len,
                                  mesh=self.mesh) if self.spec_k else None)
        # adapter-aware rings dispatch with trailing lora operands —
        # warm THOSE traces (the tail-less ones would never run)
        st = self.lora_step_tail()
        it = self.lora_insert_tail(0)
        # the resident step first: it is the program every lane shares
        if self.spec_k:
            args = (self.params, self.draft_params, cache, dcache)
            if self.paged:
                args += (tbl,)
            out = self.spec_step(*args, tok, temp, keys, active)
            cache, dcache, tok = out[0], out[1], out[2]
        elif self.paged:
            out = self.step(self.params, cache, tbl, tok, temp, keys,
                            active, *st)
            cache, tok = out[0], out[1]
        else:
            out = self.step(self.params, cache, tok, temp, keys, active,
                            *st)
            cache, tok = out[0], out[1]
        if self.megastep > 1:
            # the configured megastep program (ISSUE 11): without this
            # the FIRST loaded moment after boot pays the N-step compile
            prog = self.megastep_prog(self.megastep)
            eos = jnp.full((slots,), -1, jnp.int32)
            left = jnp.ones((slots,), jnp.int32)
            stp = jnp.full((slots,), self.megastep, jnp.int32)
            if self.spec_k:
                args = (self.params, self.draft_params, cache, dcache)
                if self.paged:
                    args += (tbl,)
                out = prog(*args, tok, temp, keys, active, eos, left,
                           stp)
                cache, dcache, tok = out[0], out[1], out[2]
            elif self.paged:
                out = prog(self.params, cache, tbl, tok, temp, keys,
                           active, eos, left, stp, *st)
                cache, tok = out[0], out[1]
            else:
                out = prog(self.params, cache, tok, temp, keys, active,
                           eos, left, stp, *st)
                cache, tok = out[0], out[1]
        for b in self.buckets:
            prompt = jnp.zeros((1, b), jnp.int32)
            if self.spec_k and self.paged:
                row = jnp.zeros((self.pool.max_blocks,), jnp.int32)
                cache, dcache, tok, temp, keys, _ = self.inserts[b](
                    self.params, self.draft_params, cache, dcache, row,
                    tok, temp, keys, prompt, 1, 0, 0.0, 0)
            elif self.spec_k:
                cache, dcache, tok, temp, keys, _ = self.inserts[b](
                    self.params, self.draft_params, cache, dcache, tok,
                    temp, keys, prompt, 1, 0, 0.0, 0)
            elif self.paged:
                row = jnp.zeros((self.pool.max_blocks,), jnp.int32)
                cache, tok, temp, keys, _ = self.inserts[b](
                    self.params, cache, row, tok, temp, keys, prompt,
                    1, 0, 0.0, 0, *it)
            else:
                cache, tok, temp, keys, _ = self.inserts[b](
                    self.params, cache, tok, temp, keys, prompt, 1, 0,
                    0.0, 0, *it)
        if self.paged and not self.spec_k:
            # the SUFFIX-insert ladder: a radix prefix hit (even a
            # partial-tail one on an otherwise cold prompt) admits
            # through make_paged_suffix_insert, and its first use used
            # to charge one request the compile — warm every bucket
            # the ladder can produce, plus the CoW block copier the
            # same admission path dispatches
            row = jnp.zeros((self.pool.max_blocks,), jnp.int32)
            cap = min(self.SUFFIX_PREFILL_MAX_ROWS, self.pool.view_len)
            sbs, n = set(), 1
            while n <= min(self.block_size, cap):   # power-of-2 rungs
                sbs.add(self.suffix_bucket(n))
                n *= 2
            n = self.block_size                     # block-multiple rungs
            while n <= cap:
                sbs.add(self.suffix_bucket(n))
                n += self.block_size
            for sb in sorted(sbs):
                toks = jnp.zeros((1, sb), jnp.int32)
                cache, tok, temp, keys, _ = self.suffix_insert(sb)(
                    self.params, cache, row, tok, temp, keys, toks,
                    1, 0, 0, 0.0, 0, *it)
            if self.quant:
                self._copy_block(jnp.zeros_like(cache["k"]),
                                 jnp.zeros_like(cache["v"]),
                                 jnp.zeros_like(cache["ks"]),
                                 jnp.zeros_like(cache["vs"]), 0, 0)
                # the mid-block radix-hit admission also dispatches the
                # staging-tail seed (scheduler._dispatch_cow)
                self._tail_init(jnp.zeros_like(cache["kt"]),
                                jnp.zeros_like(cache["vt"]),
                                cache["k"], cache["ks"], cache["v"],
                                cache["vs"], 0, 0)
            else:
                k = jnp.zeros_like(cache["k"])
                self._copy_block(k, jnp.zeros_like(cache["v"]), 0, 0)
            if self.host_cache_blocks or self.prefill_remote:
                # host-tier programs: the demote fetch and the promote
                # upload at the small pad ladder rungs a typical
                # admission batches into — otherwise the FIRST host hit
                # pays the promote compile inside its TTFT.  A REMOTE
                # disagg ring lands every cold handoff through the
                # same promote scatter, so it warms the ladder too.
                lc, _, h, bsz, dd = cache["k"].shape
                if self.host_cache_blocks and self.quant:
                    self._fetch_prog(cache["k"], cache["v"],
                                     cache["ks"], cache["vs"], 0)
                elif self.host_cache_blocks:
                    self._fetch_prog(cache["k"], cache["v"], 0)
                pad = 1
                # inclusive of _promote_pad(max_blocks): a 9-block
                # table pads its largest batch to 16, which must be in
                # the warmed set too
                while pad <= self._promote_pad(self.pool.max_blocks):
                    ids = jnp.zeros((pad,), jnp.int32)
                    slab = jnp.zeros((lc, 1, h, pad * bsz, dd),
                                     cache["k"].dtype)
                    if self.quant:
                        srow = jnp.ones((lc, pad, h), jnp.float32)
                        out = self._promote_prog(
                            jnp.zeros_like(cache["k"]),
                            jnp.zeros_like(cache["v"]),
                            jnp.zeros_like(cache["ks"]),
                            jnp.zeros_like(cache["vs"]),
                            slab, slab, srow, srow, ids)
                    else:
                        out = self._promote_prog(
                            jnp.zeros_like(cache["k"]),
                            jnp.zeros_like(cache["v"]), slab, slab, ids)
                    del out
                    pad *= 2
        if self.prefill_exec is not None and not self.prefill_remote:
            # the disagg engine's programs compile on the PREFILL
            # thread (they never stall decode), but the first cold
            # prompt would still pay them in its TTFT — run each
            # against the executor's own pool (no donation, and pool
            # content only matters mid-job, so racing a live job is
            # safe); the handoff transfer + attach ride along.
            # (Remote rings skip this: their whole-prompt programs
            # live — and prewarm — in the prefill pods.)
            pe = self.prefill_exec
            for b, prog in pe._progs.items():
                prog(self.params, pe.cache, pe.table_row,
                     jnp.zeros((1, b), jnp.int32), 1, 0.0, 0, *it)
            m = self.pool.max_blocks
            ids = jnp.zeros((m,), jnp.int32)
            if pe.lanes > 1:
                # the N-lane engine's batched slice/final programs —
                # one compile PER table-width ladder rung (_width's
                # power-of-two set: dispatches pass only as many
                # blocks as the deepest active job needs, and jit
                # shape-specializes) — plus the frame-wise handoff
                # ops (ISSUE 14)
                nl, sb = pe.lanes, pe.prefill_chunk
                z = lambda *s: jnp.zeros(s, jnp.int32)   # noqa: E731
                ptail = (pe.adapters.arrays(),
                         z(nl)) if pe.adapters is not None else ()
                mask = jnp.zeros((nl,), bool)
                w = 1
                while True:
                    mw = min(w, pe.max_blocks)
                    pe._slice_prog(self.params, pe.cache,
                                   z(nl, mw), z(nl, sb), z(nl),
                                   z(nl), mask, *ptail)
                    pe._final_prog(self.params, pe.cache,
                                   z(nl, mw), z(nl, sb),
                                   jnp.ones((nl,), jnp.int32), z(nl),
                                   jnp.zeros((nl,), jnp.float32),
                                   z(nl), z(nl), mask, *ptail)
                    if w >= pe.max_blocks:
                        break
                    w *= 2
                if self.quant:
                    self._frame_transfer(
                        jnp.zeros_like(cache["k"]),
                        jnp.zeros_like(cache["v"]),
                        jnp.zeros_like(cache["ks"]),
                        jnp.zeros_like(cache["vs"]),
                        pe.cache["k"], pe.cache["v"],
                        pe.cache["ks"], pe.cache["vs"], ids, ids)
                    self._tail_copy(jnp.zeros_like(cache["kt"]),
                                    jnp.zeros_like(cache["vt"]),
                                    pe.cache["kt"], pe.cache["vt"],
                                    0, 0)
                else:
                    self._frame_transfer(jnp.zeros_like(cache["k"]),
                                         jnp.zeros_like(cache["v"]),
                                         pe.cache["k"], pe.cache["v"],
                                         ids, ids)
            elif self.quant:
                self._transfer(jnp.zeros_like(cache["k"]),
                               jnp.zeros_like(cache["v"]),
                               jnp.zeros_like(cache["ks"]),
                               jnp.zeros_like(cache["vs"]),
                               jnp.zeros_like(cache["kt"]),
                               jnp.zeros_like(cache["vt"]),
                               pe.cache["k"], pe.cache["v"],
                               pe.cache["ks"], pe.cache["vs"],
                               pe.cache["kt"], pe.cache["vt"],
                               ids, ids, 0)
            else:
                self._transfer(jnp.zeros_like(cache["k"]),
                               jnp.zeros_like(cache["v"]),
                               pe.cache["k"], pe.cache["v"], ids, ids)
        if self.prefill_mode == "chunked":
            # the chunked path's first long prompt dispatches slice +
            # final programs instead of the bucket inserts — warm those
            # too, or the compile cliff just moves
            sb = self.prefill_chunk
            toks = jnp.zeros((1, sb), jnp.int32)
            if self.paged:
                row = jnp.zeros((self.pool.max_blocks,), jnp.int32)
                chunk_args = (self.params, cache, row, toks, 0, 0)
                if self.quant:      # quant slices take a trailing slot
                    chunk_args += (0,)
                cache = self.chunk_prog(None)(*chunk_args, *it)
                if self.spec_k:
                    for b in self.buckets:
                        prompt = jnp.zeros((1, b), jnp.int32)
                        out = self.final_insert(None, b)(
                            self.params, self.draft_params, cache,
                            dcache, row, tok, temp, keys, toks, 1, 0, 0,
                            prompt, 1, 0.0, 0)
                        cache, dcache, tok, temp, keys = out[:5]
                else:
                    out = self.final_insert(None)(
                        self.params, cache, row, tok, temp, keys, toks,
                        1, 0, 0, 0.0, 0, *it)
                    cache, tok, temp, keys = out[:4]
            else:
                for b in self.buckets:
                    sl = self.staging_len(b)
                    lk, lv = self.make_staging(b)
                    if sl > sb:
                        lk, lv = self.chunk_prog(sl)(self.params, lk, lv,
                                                     toks, 0, *it)
                    if self.spec_k:
                        prompt = jnp.zeros((1, b), jnp.int32)
                        out = self.final_insert(sl, b)(
                            self.params, self.draft_params, cache,
                            dcache, lk, lv, tok, temp, keys, toks, 1, 0,
                            prompt, 1, 0, 0.0, 0)
                        cache, dcache, tok, temp, keys = out[:5]
                    else:
                        out = self.final_insert(sl)(
                            self.params, cache, lk, lv, tok, temp, keys,
                            toks, 1, 0, 1, 0, 0.0, 0, *it)
                        cache, tok, temp, keys = out[:4]


def _default_buckets(max_len: int) -> Tuple[int, ...]:
    """2-3 prefill compile buckets, always ending at max_len so every
    admissible prompt has a bucket."""
    out: List[int] = []
    b = 64
    while b < max_len and len(out) < 2:
        out.append(b)
        b *= 8
    out.append(max_len)
    return tuple(out)
