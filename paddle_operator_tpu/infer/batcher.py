"""Continuous-batching decode ring (VERDICT r3 item 5).

The reference generation server (infer/serve.py Generator) jits whole
batches and serves them synchronously, so staggered requests serialize
behind each other.  This module is the serving scheduler that fixes
that, TPU-style:

- **One resident compiled step.** A fixed ring of ``slots`` decode
  lanes shares a single KV cache ``[L, slots, H_kv, max_len, D]`` and
  ONE jitted multi-token decode step (a ``lax.scan`` over
  ``chunk_tokens`` ticks).  No per-request compiles in the decode loop,
  ever — shapes are static regardless of arrival pattern.
- **Per-slot positions.** Unlike ``infer/decode.py`` (one scalar fill
  position for the whole batch), every lane carries its own ``pos`` so
  sequences of different lengths decode side by side.  The per-lane
  cache write is a vmapped ``dynamic_update_slice``; the causal mask
  compares cache columns against each lane's own position.  Math is
  pinned to ``decode.generate`` by tests/test_batcher.py.
- **Admission at chunk boundaries.** A request joins by prefilling its
  prompt into a free lane (prompt-length-bucketed compiles: pads fill
  cache rows PAST the real tokens, which the causal mask hides and
  later decode writes overwrite — exact semantics, bounded compile
  set), then rides the shared chunk step until eos / budget, then the
  lane frees for the next request.  Chunking amortizes the host↔device
  round-trip over ``chunk_tokens`` tokens (the same RTT honesty issue
  bench.py measures around).
- Sampling: greedy or per-lane temperature (a [slots] array feeding one
  compiled program); optional top-k/top-p are server-global statics.

Reference scope note: the reference operator ships no serving path at
all (model execution lives in user containers); this is framework
surface beyond parity, built because SURVEY §5 makes long-context
serving a first-class obligation.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from paddle_operator_tpu.infer import decode as D
from paddle_operator_tpu.infer.resilience import (
    DispatchWatchdog,
    LaneQuarantined,
    RestartBudget,
    RetriableError,
    RingResilience,
    ShuttingDown,
)
from paddle_operator_tpu.models.llama import LlamaConfig, rope_frequencies


# ---------------------------------------------------------------------------
# Device side: per-lane-position forward step
# ---------------------------------------------------------------------------


def init_ring_cache(cfg: LlamaConfig, slots: int,
                    max_len: int, mesh=None) -> Dict[str, jax.Array]:
    """KV ring: like decode.init_cache (same head-major layout,
    block-aligned allocation, same kv-head tp sharding under a serving
    mesh) but with a per-lane fill position vector instead of one
    scalar."""
    if max_len > cfg.max_seq_len:
        raise ValueError(f"max_len {max_len} exceeds the RoPE table "
                         f"(cfg.max_seq_len={cfg.max_seq_len})")
    alloc = D.cache_alloc_len(max_len)
    shape = (cfg.n_layers, slots, cfg.n_kv_heads, alloc, cfg.head_dim)
    return {
        "k": D.alloc_kv_buffer(cfg, shape, mesh),
        "v": D.alloc_kv_buffer(cfg, shape, mesh),
        "pos": jnp.zeros((slots,), jnp.int32),
    }


def _write_lane(cache_l: jax.Array, kv: jax.Array,
                pos: jax.Array) -> jax.Array:
    """[B, H, S, D] cache layer <- [B, H, 1, D] new row at per-lane pos."""
    return jax.vmap(
        lambda c, x, p: jax.lax.dynamic_update_slice(c, x, (0, p, 0))
    )(cache_l, kv, pos)


def _qkv_ring(cfg: LlamaConfig, lp: Dict[str, Any], x: jax.Array,
              cos: jax.Array, sin: jax.Array, pos: jax.Array):
    """Pre-attention half for ONE new token per lane at per-lane
    positions ``pos`` [B]: RMSNorm -> projections -> RoPE at each
    lane's own position (the table slice is a plain gather cos[pos])."""
    b = x.shape[0]
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = D._rms(x, lp["attn_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    q = D._mm(h, lp["attn"]["wq"]["kernel"], cfg.dtype).reshape(b, 1, hq, d)
    k = D._mm(h, lp["attn"]["wk"]["kernel"], cfg.dtype).reshape(b, 1, hkv, d)
    v = D._mm(h, lp["attn"]["wv"]["kernel"], cfg.dtype).reshape(b, 1, hkv, d)
    cos_b = cos[pos][:, None, None, :]          # [B, 1, 1, d/2]
    sin_b = sin[pos][:, None, None, :]

    def rot(t):
        t1, t2 = jnp.split(t.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate(
            [t1 * cos_b - t2 * sin_b, t2 * cos_b + t1 * sin_b],
            axis=-1).astype(t.dtype)

    return rot(q), rot(k), v


def _layer_step(cfg: LlamaConfig, lp: Dict[str, Any], x: jax.Array,
                cos: jax.Array, sin: jax.Array, k_cache: jax.Array,
                v_cache: jax.Array, pos: jax.Array
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decoder layer for ONE new token per lane ([B, 1, D] at lane
    positions ``pos`` [B]) with the XLA einsum attention.  Same math as
    decode._layer (which this is pinned against) with the scalar
    position generalized to a vector.  The pallas path keeps the caches
    stacked and does not go through here (see _ring_forward)."""
    b = x.shape[0]
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    q, k, v = _qkv_ring(cfg, lp, x, cos, sin, pos)
    k_cache = _write_lane(k_cache, k.transpose(0, 2, 1, 3), pos)
    v_cache = _write_lane(v_cache, v.transpose(0, 2, 1, 3), pos)

    n_rep = hq // hkv
    max_len = k_cache.shape[2]
    qg = q.reshape(b, 1, hkv, n_rep, d)
    scores = jnp.einsum("bthrd,bhsd->bthrs", qg, k_cache,
                        preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(d))
    # lane b may attend cache cols [0, pos_b] (its own new row incl.)
    mask = jnp.arange(max_len)[None, :] <= pos[:, None]      # [B, S]
    scores = jnp.where(mask[:, None, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bthrs,bhsd->bthrd", probs.astype(cfg.dtype),
                     v_cache, preferred_element_type=jnp.float32)
    out = out.reshape(b, 1, hq * d).astype(cfg.dtype)
    x = x + D._mm(out, lp["attn"]["wo"]["kernel"], cfg.dtype)

    n = D._rms(x, lp["mlp_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    if cfg.n_experts > 0:
        ffn = D._moe_ffn(cfg, lp["moe"], n)
    else:
        gate = D._mm(n, lp["mlp"]["w1"]["kernel"], cfg.dtype)
        up = D._mm(n, lp["mlp"]["w3"]["kernel"], cfg.dtype)
        ffn = D._mm(jax.nn.silu(gate) * up, lp["mlp"]["w2"]["kernel"],
                    cfg.dtype)
    return x + ffn, k_cache, v_cache


def _write_lane_stacked(stack: jax.Array, kv: jax.Array, li: jax.Array,
                        pos: jax.Array) -> jax.Array:
    """[L, B, H, S, D] stacked cache <- [B, H, 1, D] new rows at layer
    ``li`` and per-lane positions ``pos``.

    One dynamic_update_slice PER LANE (a static unroll over the slot
    count), not a vmapped/batched update: vmapping over ragged lane
    positions lowers to a scatter, and a scatter into the scan-carried
    stack makes XLA materialize a copy of the whole ring cache per
    layer per tick — measured 30x slower than raw decode.  Chained
    single-row dus ops update the carry in place."""
    b = kv.shape[0]
    for lane in range(b):
        stack = jax.lax.dynamic_update_slice(
            stack, kv[lane][None, None], (li, lane, 0, pos[lane], 0))
    return stack


def _ring_forward(cfg: LlamaConfig, params: Dict[str, Any],
                  tok: jax.Array, cache: Dict[str, jax.Array],
                  mesh=None) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """tok [B] at per-lane cache['pos'] -> (logits [B, V], advanced
    cache).  Counterpart of decode._forward for vector positions; like
    it, the pallas path carries the caches STACKED through the layer
    scan so the kernel reads them copy-free (decode.py _forward has the
    why), and under a serving mesh the kernel + output projection run
    TP-sharded in one manual region per layer (the ragged per-lane
    ``pos`` vector is exactly the ``lengths`` operand the kernel's
    index map already takes — replicated across shards)."""
    pos = cache["pos"]
    x = params["tok_embed"]["embedding"].astype(cfg.dtype)[tok[:, None]]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                cfg.rope_theta)

    attn_impl = cfg.resolved_decode_attn()
    use_sharded = D._use_sharded_kernel(cfg, mesh, attn_impl)
    if D.mesh_tp(mesh) > 1 and not use_sharded:
        attn_impl = "xla"   # whole GQA groups don't split: GSPMD einsum
    if use_sharded:
        from paddle_operator_tpu.ops.decode_attention import (
            sharded_decode_attention,
        )

        def body(carry, layer_in):
            x, kc, vc = carry
            lp, li = layer_in
            q, k, v = _qkv_ring(cfg, lp, x, cos, sin, pos)
            kc = _write_lane_stacked(kc, k.transpose(0, 2, 1, 3), li, pos)
            vc = _write_lane_stacked(vc, v.transpose(0, 2, 1, 3), li, pos)
            proj = sharded_decode_attention(
                mesh, q[:, 0], kc, vc, pos + 1,
                lp["attn"]["wo"]["kernel"], layer=li,
                interpret=(attn_impl == "pallas-interpret"),
                compute_dtype=cfg.dtype)
            x = x + proj[:, None].astype(cfg.dtype)
            return (D._ffn_residual(cfg, lp, x), kc, vc), ()

        (x, k_new, v_new), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["layers"], jnp.arange(cfg.n_layers)))
    elif attn_impl != "xla":
        from paddle_operator_tpu.ops.decode_attention import decode_attention

        b = x.shape[0]
        hq, d = cfg.n_heads, cfg.head_dim

        def body(carry, layer_in):
            x, kc, vc = carry
            lp, li = layer_in
            q, k, v = _qkv_ring(cfg, lp, x, cos, sin, pos)
            kc = _write_lane_stacked(kc, k.transpose(0, 2, 1, 3), li, pos)
            vc = _write_lane_stacked(vc, v.transpose(0, 2, 1, 3), li, pos)
            out = decode_attention(
                q[:, 0], kc, vc, pos + 1, layer=li,
                interpret=(attn_impl == "pallas-interpret"))
            out = out.reshape(b, 1, hq * d).astype(cfg.dtype)
            return (D._finish_layer(cfg, lp, x, out), kc, vc), ()

        (x, k_new, v_new), _ = jax.lax.scan(
            body, (x, cache["k"], cache["v"]),
            (params["layers"], jnp.arange(cfg.n_layers)))
    else:
        def body(x, layer_in):
            lp, k_c, v_c = layer_in
            y, k_c, v_c = _layer_step(cfg, lp, x, cos, sin, k_c, v_c, pos)
            return y, (k_c, v_c)

        x, (k_new, v_new) = jax.lax.scan(
            body, x, (params["layers"], cache["k"], cache["v"]))
    x = D._rms(x, params["final_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    logits = D._mm(x, params["lm_head"]["kernel"],
                   cfg.dtype).astype(jnp.float32)
    return logits[:, 0], {"k": k_new, "v": v_new, "pos": pos + 1}


def _sample_tokens(logits, temp, keys, pos, top_k, top_p):
    """THE per-lane sampling rule — shared by the chunk step and the
    admission insert so token 1 and tokens 2..N can never be drawn
    under different rules.  logits [B, V], temp [B], keys [B, 2],
    pos [B] -> [B] int32: greedy at temp 0, else per-lane
    fold_in(position) (deterministic given (seed, pos), independent
    across lanes and steps) feeding temperature + top-k/top-p
    filtered categorical sampling."""
    greedy = logits.argmax(-1).astype(jnp.int32)
    filt = D._filter_logits(
        logits / jnp.maximum(temp, 1e-6)[:, None], top_k, top_p)
    sub = jax.vmap(jax.random.fold_in)(keys, pos)
    drawn = jax.vmap(
        lambda k, l: jax.random.categorical(k, l))(sub, filt)
    return jnp.where(temp > 0, drawn.astype(jnp.int32), greedy)


def make_chunk_step(cfg: LlamaConfig, chunk_tokens: int,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None, mesh=None,
                    check_finite: bool = False):
    """The ONE resident compiled decode program.

    ``step(params, cache, tok [B], temp [B], keys [B,2], active [B])
    -> (cache', tok', toks [chunk, B])``

    Runs ``chunk_tokens`` ticks for every lane.  Inactive lanes compute
    (their FLOPs are the price of static shapes — standard slot-server
    trade) but neither advance their position nor write meaningful
    state; their emitted tokens are ignored host-side.  The cache is
    donated: the ring buffer must never be copied per chunk.  Under a
    serving mesh the whole chunk remains ONE sharded dispatch — the
    shard_map kernel regions and GSPMD einsums compile into the same
    resident program, no eager per-device ops anywhere.

    ``check_finite=True`` (infer/resilience.py nan_check): the step
    additionally returns ``ok [B]`` — an isfinite fold of every tick's
    logits per lane, so the host can quarantine a NaN-producing lane
    (fail ONE request, never the ring) without shipping the logits
    home.  Token outputs are unchanged; the fold rides the same scan.
    """

    def step(params, cache, tok, temp, keys, active):
        def tick(carry, _):
            # the isfinite fold rides the carry ONLY when requested —
            # the default resident program is unchanged
            if check_finite:
                cache, tok, ok = carry
            else:
                cache, tok = carry
            logits, new_cache = _ring_forward(cfg, params, tok, cache,
                                              mesh=mesh)
            nxt = _sample_tokens(logits, temp, keys, cache["pos"],
                                 top_k, top_p)
            # retired/free lanes: position ZEROED (a stale fill
            # position must never outlive its request — the
            # serving_status staleness fix); their (ignored) writes
            # land at row 0, which the next admission's splice
            # overwrites along with the rest of the lane
            new_cache["pos"] = jnp.where(active, new_cache["pos"], 0)
            nxt = jnp.where(active, nxt, tok)
            if check_finite:
                ok = ok & jnp.all(jnp.isfinite(logits), axis=-1)
                return (new_cache, nxt, ok), nxt
            return (new_cache, nxt), nxt

        if check_finite:
            (cache, tok, ok), toks = jax.lax.scan(
                tick, (cache, tok, jnp.ones(tok.shape, bool)), None,
                length=chunk_tokens)
            return cache, tok, toks, ok
        (cache, tok), toks = jax.lax.scan(
            tick, (cache, tok), None, length=chunk_tokens)
        return cache, tok, toks

    return jax.jit(step, donate_argnums=(1,))


def _splice_lane(ring: Dict[str, jax.Array], lane: Dict[str, jax.Array],
                 slot, prompt_len) -> Dict[str, jax.Array]:
    """Zero ring lane ``slot`` and splice a freshly prefilled
    batch-of-one lane cache into it, setting the lane's fill position
    to ``prompt_len`` — the device half of admission, shared by the
    plain and speculative inserts so their splice semantics cannot
    drift."""
    k = jnp.zeros_like(ring["k"][:, 0])
    k = jax.lax.dynamic_update_slice(k, lane["k"][:, 0], (0, 0, 0, 0))
    v = jnp.zeros_like(ring["v"][:, 0])
    v = jax.lax.dynamic_update_slice(v, lane["v"][:, 0], (0, 0, 0, 0))
    new_k = jax.lax.dynamic_update_slice(
        ring["k"], k[:, None], (0, slot, 0, 0, 0))
    new_v = jax.lax.dynamic_update_slice(
        ring["v"], v[:, None], (0, slot, 0, 0, 0))
    return {"k": new_k, "v": new_v,
            "pos": ring["pos"].at[slot].set(prompt_len)}


def make_prefill_insert(cfg: LlamaConfig, bucket: int,
                        top_k: Optional[int] = None,
                        top_p: Optional[float] = None, mesh=None):
    """Per-prompt-bucket compiled admission: prefill a [1, bucket]
    (right-padded) prompt, splice its KV into ring lane ``slot``, sample
    the first token, and update EVERY piece of lane state — tok, temp,
    keys — in the same compiled program.

    One dispatch on purpose: on relayed chips, EAGER ops (``.at[].set``,
    ``argmax``) block until all in-flight device work drains (measured
    ~500 ms behind a decoding chunk), so an admission built from eager
    lane updates stalled the whole ring for ~half a second per request.
    Everything device-side about admission lives inside this jit; the
    host's only jobs are bookkeeping lists.

    Exactness with padding: pad rows fill cache positions PAST the real
    prompt; the causal mask keeps real rows from attending them, the
    first token samples from ``prompt_len - 1`` (the last REAL
    position), the lane position is set to ``prompt_len`` so decode
    overwrites the pad rows before they ever become attendable.

    ``insert(params, cache, tok, temp, keys, prompt [1,bucket],
    prompt_len, slot, temp_val, seed)
    -> (cache', tok', temp', keys', first_token)``
    """

    def insert(params, cache, tok, temp, keys, prompt, prompt_len, slot,
               temp_val, seed):
        lane = D.init_cache(cfg, 1, bucket)
        logits, lane = D._forward(cfg, params, prompt, lane, mesh=mesh)
        logits = logits[0, prompt_len - 1]                  # last real row
        new_cache = _splice_lane(cache, lane, slot, prompt_len)
        # first token through the SHARED sampling rule (_sample_tokens),
        # batch-of-one shaped
        key = jax.random.PRNGKey(seed)
        first = _sample_tokens(
            logits[None], jnp.reshape(temp_val, (1,)).astype(jnp.float32),
            key[None], jnp.reshape(prompt_len - 1, (1,)),
            top_k, top_p)[0]
        return (new_cache,
                tok.at[slot].set(first),
                temp.at[slot].set(temp_val),
                keys.at[slot].set(key),
                first)

    return jax.jit(insert, donate_argnums=(1, 2, 3, 4))


def make_spec_prefill_insert(cfg: LlamaConfig, dcfg: LlamaConfig,
                             bucket: int, top_k: Optional[int] = None,
                             top_p: Optional[float] = None, mesh=None):
    """Admission for the SPECULATIVE ring: one compiled dispatch that
    prefills the prompt into BOTH the target and the draft lane (the
    draft's logits are discarded — it only needs the KV context to
    propose from) and samples the first token from the target, with the
    same exactness-with-padding story as :func:`make_prefill_insert`.

    ``insert(params, dparams, cache, dcache, tok, temp, keys,
    prompt [1,bucket], prompt_len, slot, temp_val, seed)
    -> (cache', dcache', tok', temp', keys', first_token)``
    """

    def insert(params, dparams, cache, dcache, tok, temp, keys, prompt,
               prompt_len, slot, temp_val, seed):
        lane = D.init_cache(cfg, 1, bucket)
        logits, lane = D._forward(cfg, params, prompt, lane, mesh=mesh)
        logits = logits[0, prompt_len - 1]
        new_cache = _splice_lane(cache, lane, slot, prompt_len)
        dlane = D.init_cache(dcfg, 1, bucket)
        _, dlane = D._forward(dcfg, dparams, prompt, dlane,
                              last_only=True, mesh=mesh)
        new_dcache = _splice_lane(dcache, dlane, slot, prompt_len)
        key = jax.random.PRNGKey(seed)
        first = _sample_tokens(
            logits[None], jnp.reshape(temp_val, (1,)).astype(jnp.float32),
            key[None], jnp.reshape(prompt_len - 1, (1,)),
            top_k, top_p)[0]
        return (new_cache, new_dcache,
                tok.at[slot].set(first),
                temp.at[slot].set(temp_val),
                keys.at[slot].set(key),
                first)

    return jax.jit(insert, donate_argnums=(2, 3, 4, 5, 6))


# ---------------------------------------------------------------------------
# Host side: the scheduler
# ---------------------------------------------------------------------------


def _fold_seed(seed: int) -> int:
    """Fold an out-of-int32-range seed to [0, 2**31) via the splitmix64
    finalizer (a bijection on 64-bit ints before the final fold) —
    distinct wide seeds stay distinct with overwhelming probability,
    unlike the ``& 0x7FFFFFFF`` mask that mapped s and s + 2**31 to the
    same sampling stream."""
    x = seed & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    x ^= x >> 31
    return x & 0x7FFFFFFF


class QueueFull(RuntimeError):
    """submit() backpressure signal: the bounded request queue stayed
    full past the put timeout.  A RuntimeError subclass so serve.py's
    generic 503 mapping already handles it (retry/fail-over, not a
    client error) while callers that care can catch it specifically."""


class _Request:
    __slots__ = ("prompt", "max_new", "temperature", "seed", "eos",
                 "done", "out", "error", "_stream", "_cancel",
                 "dev_prompt", "bucket", "accepted", "drafted",
                 "deadline", "deadline_exceeded")

    def __init__(self, prompt, max_new, temperature, seed, eos,
                 wants_stream=False, deadline=None):
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.seed = seed
        self.eos = eos
        self.done = threading.Event()
        self.out: Optional[List[int]] = None
        self.error: Optional[Exception] = None
        self._cancel = False
        # absolute time.monotonic() deadline (or None): the ring retires
        # the lane when it passes — the request RESOLVES with the tokens
        # produced so far and this flag set (the 504-style partial), so
        # a slow client can never pin a lane / its paged blocks
        self.deadline: Optional[float] = deadline
        self.deadline_exceeded = False
        # speculative-decoding telemetry (spec_k > 0 rings): drafts
        # offered / accepted for THIS request — serve.py surfaces the
        # rate per response
        self.accepted = 0
        self.drafted = 0
        # padded prompt, transferred to device on the SUBMIT thread
        # (batcher.submit): on relayed chips a host->device copy costs a
        # full round-trip, and paying it on the decode-ring thread
        # stalls every lane; caller threads pay it concurrently instead
        self.dev_prompt: Optional[jax.Array] = None
        self.bucket: int = 0
        # token streaming is opt-in (submit(stream=True)): the dominant
        # result()-only path must not pay per-token queue puts inside
        # the decode-ring thread that gates every lane's throughput
        self._stream: Optional["queue.Queue"] = (
            queue.Queue() if wants_stream else None)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error is not None:
            raise self.error
        return self.out

    @property
    def accept_rate(self) -> Optional[float]:
        """Speculative acceptance rate for this request (accepted
        drafts / offered drafts), or None when the ring is not
        speculative (or no round has consumed yet)."""
        if not self.drafted:
            return None
        return round(self.accepted / self.drafted, 4)

    def cancel(self) -> None:
        """Stop decoding this request: the ring evicts its lane at the
        next chunk boundary (or drops it from the queue if not yet
        admitted) and ``result()`` returns the tokens produced so far.
        A disconnect-abandoned long stream must not keep occupying a
        decode lane to its full token budget."""
        self._cancel = True

    def stream(self, timeout: Optional[float] = None):
        """Yield generated tokens as the ring emits them (one int at a
        time, arriving in chunk-sized bursts).  Raises the request's
        error at the point of failure; `timeout` bounds the wait for
        EACH burst, not the whole generation."""
        if self._stream is None:
            raise RuntimeError("request was not submitted with "
                               "stream=True")
        while True:
            try:
                item = self._stream.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError("no tokens within timeout") from None
            if item is None:
                if self.error is not None:
                    raise self.error
                return
            yield item


class ContinuousBatcher:
    """Slot scheduler over the resident chunk step.

    ``submit()`` is thread-safe and returns a handle whose ``result()``
    blocks until the sequence finishes; the decode loop runs on a
    background thread, admitting queued requests into free lanes at
    chunk boundaries (bucketed prefill) and evicting lanes on eos /
    budget.  ``stats`` counts admissions, evictions, decoded chunks and
    the high-water mark of concurrently active lanes — the numbers the
    slot-reuse tests pin.

    ``paged=True`` (infer/paged.py) swaps the per-lane contiguous KV
    region for a global block pool + per-lane block tables with a radix
    prefix cache: blocks allocate on demand as a lane's ``pos`` crosses
    block boundaries, free when the lane retires, and admissions that
    hit a cached prefix map those blocks read-only (CoW before the
    first divergent write) and prefill only the suffix.  Greedy token
    streams are BIT-IDENTICAL to the contiguous ring — ``paged=False``
    is both the fallback and the parity oracle.  ``block_size`` sets
    pool-block granularity (keep it at ops/decode_attention.py
    DEFAULT_BLOCK_K on TPU so the paged kernel's key block IS the pool
    block), ``num_blocks`` the pool size (default: contiguous-HBM
    parity, slots * blocks-per-lane), ``prefix_cache=False`` disables
    radix reuse (it is also off in speculative mode, where admission
    must prefill the draft lane anyway).
    """

    # a prefix hit with a LONGER divergent suffix admits through the
    # cold scatter prefill instead: the suffix insert's per-row pool
    # writes unroll O(rows) (paged._write_rows_paged), and past this
    # many rows the block-granular cold path compiles and runs faster
    # than what the cached prefix saves
    SUFFIX_PREFILL_MAX_ROWS = 256

    def __init__(self, params: Any, cfg: LlamaConfig, *, slots: int = 8,
                 max_len: Optional[int] = None, chunk_tokens: int = 8,
                 prefill_buckets: Tuple[int, ...] = (),
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 pipeline_depth: int = 2, mesh=None,
                 draft_params: Any = None,
                 draft_cfg: Optional[LlamaConfig] = None,
                 spec_k: int = 0,
                 max_queue: int = 0,
                 queue_timeout: float = 5.0,
                 paged: bool = False,
                 block_size: int = 256,
                 num_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 resilience: Optional[RingResilience] = None) -> None:
        # ``mesh`` (parallel/mesh.py make_serving_mesh): serve
        # tensor-parallel — params are laid out over tp once here, the
        # ring cache shards over the kv-head axis, and the resident
        # chunk/insert programs compile sharded (shard_map pallas
        # kernel + GSPMD einsums).  Token streams are identical to the
        # single-device ring (tests/test_batcher.py pins it).
        self.mesh = mesh
        if mesh is not None and D.mesh_tp(mesh) > 1:
            params = D.shard_params_for_serving(params, cfg, mesh)
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.max_len = max_len or cfg.max_seq_len
        self.chunk = chunk_tokens
        # fault tolerance (infer/resilience.py): with a RingResilience a
        # ring-level dispatch fault fails the RESIDENT requests with a
        # retriable 503 and rebuilds the ring from scratch (fresh
        # cache/pool; queued work re-admitted) behind exponential
        # backoff, until the restart budget flips ``healthy`` — without
        # one the batcher keeps its legacy die-on-first-error behavior.
        self.resilience = resilience
        self._budget = (RestartBudget(resilience)
                        if resilience is not None else None)
        self._check_finite = bool(resilience and resilience.nan_check)
        if self._check_finite and spec_k:
            raise ValueError("nan_check is not supported on speculative "
                             "rings (the spec round has no per-lane "
                             "finite fold); disable one of them")
        self.healthy = True
        self._draining = False
        self._rebuilding = False
        # ring-level fault observed (by the loop thread or the watchdog
        # monitor) and not yet healed; the loop rebuilds at the next top
        self._fault: Optional[Exception] = None
        self._watchdog: Optional[DispatchWatchdog] = None
        if resilience is not None and resilience.watchdog:
            self._watchdog = DispatchWatchdog(
                resilience, self._on_stall, self._on_hard_stall)
        # max dispatched-but-unconsumed chunks; the oldest is consumed
        # once `depth` are in flight, so depth 2 = one chunk always
        # decoding while the host consumes the previous one (depth 1
        # disables the overlap entirely).  Deeper than 2 delays the
        # eviction bookkeeping by depth-1 chunks, so freed lanes sit
        # idle before re-admission — lane turnover costs more than the
        # extra hidden round-trip saves (measured).
        self.pipeline_depth = max(1, pipeline_depth)
        self.buckets = tuple(sorted(prefill_buckets)) or _default_buckets(
            self.max_len)
        self._top_k, self._top_p = top_k, top_p
        # paged mode (infer/paged.py): the per-lane contiguous KV region
        # becomes a global block pool + per-lane block tables — blocks
        # allocate on demand as each lane's pos crosses a block boundary
        # and free when the lane retires, and completed-prefill blocks
        # feed a radix prefix cache so shared prompts prefill ONCE.  The
        # contiguous ring stays the paged path's parity oracle
        # (SERVE_PAGED=0); greedy token streams are bit-identical.
        self.paged = bool(paged)
        self.pool: Optional[Any] = None
        if self.paged:
            from paddle_operator_tpu.infer import paged as PG

            self._pg = PG
            self.block_size = int(block_size)
            # prefix reuse needs one canonical prefill per prefix;
            # speculative admission prefills target AND draft, so the
            # cache is disabled there (paging itself still applies)
            # kept for watchdog rebuilds: a self-heal reconstructs the
            # pool (and its radix cache) from scratch with these
            self._num_blocks = num_blocks
            self._prefix_cache = prefix_cache and not spec_k
            self.pool = PG.PagedCacheManager(
                slots, self.max_len, self.block_size, num_blocks,
                prefix_cache=self._prefix_cache)
            # prefill buckets scatter whole blocks: round each up to a
            # block multiple, capped at the lane view
            self.buckets = tuple(sorted(
                {min(-(-b // self.block_size) * self.block_size,
                     self.pool.view_len) for b in self.buckets}))
            self._copy_block = PG.make_block_copier()
            self._suffix_inserts: Dict[int, Any] = {}
        # speculative mode (spec_k > 0): the resident step becomes ONE
        # draft-propose + chunked-verify round (infer/speculative.py) —
        # per round every active lane advances by its OWN accept length
        # (1..spec_k+1 tokens), landing in the per-lane pos vector, so
        # divergent accepts cost no extra compiles.  A second ring cache
        # holds the draft's KV, admitted/rewound in lockstep.
        self.spec_k = int(spec_k)
        self.draft_cfg = draft_cfg
        if self.spec_k > 0:
            from paddle_operator_tpu.infer.speculative import (
                check_draft_compat,
                make_spec_round_fn,
            )

            if draft_params is None or draft_cfg is None:
                raise ValueError("spec_k > 0 requires draft_params and "
                                 "draft_cfg (see LlamaConfig.draft())")
            check_draft_compat(cfg, draft_cfg)
            if self.max_len > draft_cfg.max_seq_len:
                raise ValueError(
                    f"draft max_seq_len ({draft_cfg.max_seq_len}) < ring "
                    f"max_len ({self.max_len}); derive the draft with "
                    "cfg.draft() to inherit the target's RoPE table")
            if mesh is not None and D.mesh_tp(mesh) > 1:
                draft_params = D.shard_params_for_serving(
                    draft_params, draft_cfg, mesh)
            self.draft_params = draft_params
            self._spec_step = make_spec_round_fn(
                cfg, draft_cfg, self.spec_k, top_k, top_p, mesh=mesh,
                paged=self.paged)
            if self.paged:
                # target prefill scatters into the pool; the DRAFT lane
                # stays a contiguous splice (speculative.py docstring)
                self._inserts = {b: self._pg.make_paged_spec_prefill_insert(
                    cfg, draft_cfg, b, self.block_size, top_k, top_p,
                    mesh=mesh) for b in self.buckets}
            else:
                self._inserts = {b: make_spec_prefill_insert(
                    cfg, draft_cfg, b, top_k, top_p, mesh=mesh)
                    for b in self.buckets}
            self.dcache = init_ring_cache(draft_cfg, slots, self.max_len,
                                          mesh=mesh)
        else:
            self.draft_params = None
            self.dcache = None
            if self.paged:
                self._step = self._pg.make_paged_chunk_step(
                    cfg, chunk_tokens, top_k, top_p, mesh=mesh,
                    check_finite=self._check_finite)
                self._inserts = {b: self._pg.make_paged_prefill_insert(
                    cfg, b, self.block_size, top_k, top_p, mesh=mesh)
                    for b in self.buckets}
            else:
                self._step = make_chunk_step(cfg, chunk_tokens, top_k,
                                             top_p, mesh=mesh,
                                             check_finite=self._check_finite)
                self._inserts = {b: make_prefill_insert(cfg, b, top_k,
                                                        top_p, mesh=mesh)
                                 for b in self.buckets}

        if self.paged:
            self.cache = self._pg.init_paged_cache(
                cfg, slots, self.pool.total, self.block_size, mesh=mesh)
        else:
            self.cache = init_ring_cache(cfg, slots, self.max_len,
                                         mesh=mesh)
        self.tok = jnp.zeros((slots,), jnp.int32)
        self.temp = jnp.zeros((slots,), jnp.float32)
        self.keys = jnp.zeros((slots, 2), jnp.uint32)
        self.lane: List[Optional[_Request]] = [None] * slots
        self._lane_out: List[List[int]] = [[] for _ in range(slots)]
        self._lane_left = [0] * slots
        # host mirror of each lane's device fill position — set by
        # admission, advanced at consume, ZEROED on eviction so
        # serving_status never reports a retired lane's stale pos (and,
        # paged, so on-demand block mapping tracks the true frontier)
        self._lane_pos = [0] * slots
        # per-lane device future of the admission-sampled first token,
        # materialized at the next chunk consume (async admission)
        self._lane_first: List[Optional[jax.Array]] = [None] * slots

        # bounded admission queue (max_queue > 0): submit() blocks up to
        # queue_timeout for a slot, then REJECTS (QueueFull) — saturation
        # degrades into backpressure instead of unbounded request RAM
        self.max_queue = int(max_queue)
        self._queue_timeout = queue_timeout
        self._pending: "queue.Queue[_Request]" = queue.Queue(
            maxsize=self.max_queue)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self.stats = {"admitted": 0, "evicted": 0, "chunks": 0,
                      "max_active": 0, "rejected_queue_full": 0,
                      "spec_accepted": 0, "spec_drafted": 0,
                      # prefill accounting: the prefix-cache acceptance
                      # gate — a full prefix hit admits with ZERO
                      # prefill forward passes over cached blocks
                      "prefill_calls": 0, "prefill_tokens": 0,
                      "cow_copies": 0,
                      # fault-tolerance accounting (infer/resilience.py):
                      # deadline partials delivered, self-healing ring
                      # rebuilds, and NaN-quarantined lanes — surfaced
                      # through serving_status -> tpujob_serve_* gauges
                      "deadline_exceeded": 0, "watchdog_restarts": 0,
                      "quarantined_lanes": 0}
        # served-token telemetry for serving_status(): cumulative emitted
        # tokens since construction (the /metrics tokens-per-sec gauge)
        self._tokens_emitted = 0
        self._t_start = time.monotonic()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-ring")
        self._thread.start()

    # -- public ------------------------------------------------------------

    def submit(self, prompt, *, max_new_tokens: int,
               temperature: float = 0.0, seed: int = 0,
               eos_token: Optional[int] = None,
               stream: bool = False,
               request_id: Optional[str] = None,
               deadline_s: Optional[float] = None) -> _Request:
        """Queue one generation request; returns a handle whose
        ``result()``/``stream()`` deliver the tokens.

        ``deadline_s`` (serve.py: the ``X-Request-Deadline`` header):
        relative budget in seconds for the WHOLE generation.  When it
        expires the ring retires the lane at the next chunk boundary —
        its paged blocks freed, the request resolving with the tokens
        produced so far and ``handle.deadline_exceeded`` set (the
        504-style partial) — so one slow/greedy client can never pin a
        lane indefinitely.  Requests still queued at expiry resolve
        prompt-only with the same flag.

        ``request_id`` (optional, e.g. serve.py's per-row id) is woven
        into every validation error so an operator reading a rejection
        in a multi-request log knows WHICH request overflowed —
        validation runs (and raises) BEFORE the host-side tokenize copy
        and device transfer below, so a rejected request costs no
        bandwidth.

        ``seed``: sampling seed with an effective range of [0, 2**31) —
        it rides into the compiled insert as an int32 traced argument.
        In-range seeds are used as-is (streams are stable across
        versions for the common case); anything outside (negative or
        >= 2**31 — clients send arbitrary 64-bit ints, serve.py even
        derives seed+i per row) is folded through a splitmix64 hash
        rather than truncated, so distinct wide seeds keep distinct
        streams (masking would collide s with s + 2**31)."""
        rid = f" [request {request_id}]" if request_id is not None else ""
        n = len(prompt)
        if not n:
            raise ValueError(f"empty prompt{rid}")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1{rid}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0{rid}")
        if self._draining:
            raise ShuttingDown("server draining; retry another replica")
        if self._stop.is_set() or not self._thread.is_alive():
            raise ShuttingDown("batcher closed")
        if n > self.buckets[-1]:
            raise ValueError(
                f"prompt length {n} exceeds the largest prefill "
                f"bucket ({self.buckets[-1]}){rid}")
        if self.spec_k:
            # a verify round starting at the last in-budget position
            # (prompt + max_new - 2) writes rows through pos + spec_k,
            # so spec_k - 1 positions of headroom must exist past
            # prompt + max_new (infer/speculative.py has the derivation)
            if n + max_new_tokens + self.spec_k - 1 > self.max_len:
                raise ValueError(
                    f"prompt ({n}) + max_new_tokens "
                    f"({max_new_tokens}) + speculative headroom "
                    f"({self.spec_k - 1}) exceeds max_len "
                    f"({self.max_len}){rid}")
        else:
            # the FIRST token is sampled from the prefill logits, so only
            # max_new-1 tokens ride chunk steps; the worst-case cache
            # position is prompt + ceil((max_new-1)/chunk)*chunk
            # (validating with ceil(max_new/chunk) rejected requests up
            # to chunk-1 tokens INSIDE capacity)
            budget = -(-(max_new_tokens - 1) // self.chunk) * self.chunk
            if n + budget > self.max_len:
                raise ValueError(
                    f"prompt ({n}) + chunk-rounded budget "
                    f"({budget}) exceeds max_len ({self.max_len}){rid}")
        # validation passed: NOW pay the tokenize copy
        prompt = list(map(int, prompt))
        # int32-range seeds pass through untouched; wide/negative seeds
        # hash-fold (see docstring)
        seed = int(seed)
        if not 0 <= seed < 0x80000000:
            seed = _fold_seed(seed)
        if self.max_queue and self._pending.full():
            # shed BEFORE the host->device prompt transfer below: the
            # rejection path is the overload path, and a full round-trip
            # device copy per shed request (relayed chips) would spend
            # exactly the bandwidth backpressure exists to protect.
            # Non-authoritative (racy) — the timed put below enforces
            # the bound; this only waits for space to appear first.
            deadline = time.monotonic() + self._queue_timeout
            while self._pending.full():
                if self._stop.is_set() or self._draining:
                    raise ShuttingDown("batcher shutting down")
                if time.monotonic() >= deadline:
                    self.stats["rejected_queue_full"] += 1
                    raise QueueFull(
                        f"request queue full (max_queue={self.max_queue},"
                        f" waited {self._queue_timeout}s)")
                time.sleep(0.005)
        req = _Request(prompt, max_new_tokens, temperature, seed,
                       eos_token, wants_stream=stream,
                       deadline=(time.monotonic() + deadline_s
                                 if deadline_s is not None else None))
        # pad + ship the prompt to the device HERE, on the caller's
        # thread — see _Request.dev_prompt
        req.bucket = self._bucket_for(len(prompt))
        padded = np.zeros((1, req.bucket), np.int32)
        padded[0, :len(prompt)] = prompt
        req.dev_prompt = jnp.asarray(padded)
        # bounded queue: poll briefly for a slot (smooths bursts) then
        # reject — the caller's thread, not the decode ring, pays the
        # wait.  Short put ticks so close()/drain() interrupt a BLOCKED
        # submitter with ShuttingDown immediately instead of leaving it
        # hanging out the full queue timeout against a dead ring.
        deadline = time.monotonic() + self._queue_timeout
        while True:
            if self._stop.is_set() or self._draining:
                raise ShuttingDown("batcher shutting down")
            try:
                self._pending.put(req, timeout=0.05)
                break
            except queue.Full:
                if time.monotonic() >= deadline:
                    self.stats["rejected_queue_full"] += 1
                    raise QueueFull(
                        f"request queue full (max_queue={self.max_queue},"
                        f" waited {self._queue_timeout}s)") from None
        if self._stop.is_set() and not req.done.is_set():
            # loop died between the liveness check above and the put:
            # fail the request instead of letting result() hang
            self._finish(req, ShuttingDown("batcher closed"))
            return req
        self._wake.set()
        return req

    def serving_status(self) -> Dict[str, Any]:
        """The ``TPUJob.status.serving`` block (camelCase, like
        GoodputTracker.to_status): cumulative served-token throughput,
        speculative acceptance rate, and current queue depth — what the
        manager exports as ``tpujob_serve_*`` gauges on /metrics
        (utils/observability.py serving_gauges)."""
        elapsed = max(1e-9, time.monotonic() - self._t_start)
        drafted = self.stats["spec_drafted"]
        # per-lane visibility EXCLUDES retired lanes: _evict zeroes the
        # host pos mirror (and the compiled step zeroes the device pos),
        # so a freed lane can never leak its last request's fill
        # position or tokens into the telemetry (test_serve_metrics)
        return {
            "tokensPerSec": round(self._tokens_emitted / elapsed, 2),
            "acceptRate": (round(self.stats["spec_accepted"] / drafted, 4)
                           if drafted else 0.0),
            "queueDepth": self._pending.qsize(),
            "tokensTotal": self._tokens_emitted,
            "activeLanes": sum(r is not None for r in self.lane),
            "lanePos": [int(p) for p in self._lane_pos],
            "prefixHitRate": (self.pool.hit_rate() if self.pool is not None
                              else 0.0),
            "kvBlocksFree": (self.pool.blocks_free()
                             if self.pool is not None else 0),
            "kvBlocksHwm": (self.pool.stats["blocks_hwm"]
                            if self.pool is not None else 0),
            # fault tolerance (infer/resilience.py): drain/rebuild
            # visibility for /readyz and the CRD's status.serving block
            "draining": self._draining,
            "healthy": self.healthy,
            "deadlineExceeded": self.stats["deadline_exceeded"],
            "watchdogRestarts": self.stats["watchdog_restarts"],
            "quarantinedLanes": self.stats["quarantined_lanes"],
        }

    @property
    def accepting(self) -> bool:
        """Readiness (/readyz): the ring takes new admissions — not
        draining, not mid-rebuild, loop alive, budget unspent."""
        return (self.healthy and not self._draining
                and not self._rebuilding and not self._stop.is_set()
                and self._thread.is_alive())

    def drain(self, budget_s: float = 30.0) -> None:
        """SIGTERM drain (the serving half of docs/fault-tolerance.md):
        stop admissions — queued and newly submitted requests fail with
        :class:`ShuttingDown` (503 + Retry-After upstream) — let the
        RESIDENT lanes finish within ``budget_s``, cancel stragglers at
        the budget (their callers receive the tokens produced so far;
        paged blocks verifiably return to the pool), then close."""
        self._draining = True
        self._wake.set()
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline and self._thread.is_alive():
            if all(r is None for r in self.lane) and self._pending.empty():
                break
            time.sleep(0.02)
        for req in list(self.lane):
            if req is not None:
                req.cancel()            # partial flush at chunk boundary
        grace = time.monotonic() + max(5.0, budget_s)
        while (any(r is not None for r in self.lane)
               and self._thread.is_alive()
               and time.monotonic() < grace):
            time.sleep(0.02)
        self.close()

    def abort(self, error: Optional[Exception] = None) -> None:
        """Second-SIGTERM semantics: immediate teardown.  Resident
        requests RESOLVE with their partial tokens (best-effort flush —
        an undrained kill would have lost them entirely); queued ones
        fail with ShuttingDown."""
        self._draining = True
        self._stop.set()
        self._wake.set()
        for i, req in enumerate(self.lane):
            if req is not None and not req.done.is_set():
                req.out = req.prompt + self._lane_out[i]
                self._finish(req)
        self._shed_queue(error or ShuttingDown("server killed"))

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=30)
        if self._watchdog is not None:
            self._watchdog.close()
        # late blocked submitters can land requests after the loop's own
        # drain pass — sweep again so none hangs at result()
        self._shed_queue(ShuttingDown("batcher closed"))

    # -- fault handling ----------------------------------------------------

    def _shed_queue(self, error: Exception) -> None:
        while True:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                return
            self._finish(req, error)

    def _on_stall(self, elapsed: float) -> None:
        """Watchdog monitor callback: a dispatch/consume wait crossed
        N x rolling-p95.  Fail the resident requests NOW — their
        clients get retriable 503s while the ring thread is still stuck
        inside the wedged dispatch — and flag the rebuild the loop runs
        once it unwedges."""
        err = RetriableError(
            f"compiled dispatch stalled {elapsed:.1f}s (watchdog "
            f"threshold {self._watchdog.threshold():.1f}s); ring "
            "rebuilding — retry")
        for req in list(self.lane):
            if req is not None and not req.done.is_set():
                self._finish(req, err)
        self._fault = err

    def _on_hard_stall(self, elapsed: float) -> None:
        """The stall outlived hard_stall_factor x threshold: the host
        thread is unrecoverably stuck inside the runtime.  Flip
        /healthz so the orchestrator replaces the pod (crash-only)."""
        self.healthy = False

    def _heal(self, err: Exception) -> bool:
        """Self-heal after a ring-level fault: fail whatever is still
        resident with a retriable error, rebuild every piece of device
        state from scratch (cache, paged pool + radix cache, lane
        state), back off exponentially.  Returns False — and flips
        ``healthy`` — when the restart budget is exhausted (the loop
        then dies the legacy way and /healthz goes unhealthy)."""
        wrapped = (err if isinstance(err, RetriableError)
                   else RetriableError(
                       f"ring dispatch failed ({err}); rebuilt — retry"))
        # decide + account for the restart BEFORE unblocking any client:
        # a caller released by the _finish below may immediately read
        # stats/healthy, and must see the restart it was shed for
        healing = self._budget is not None and not self._budget.exhausted
        if healing:
            self._rebuilding = True
            self.stats["watchdog_restarts"] += 1
        else:
            self.healthy = False
        for req in list(self.lane):
            if req is not None and not req.done.is_set():
                self._finish(req, wrapped)
        self.lane = [None] * self.slots
        self._lane_out = [[] for _ in range(self.slots)]
        self._lane_left = [0] * self.slots
        self._lane_pos = [0] * self.slots
        self._lane_first = [None] * self.slots
        if not healing:
            return False
        backoff = self._budget.spend()
        if self.paged:
            self.pool = self._pg.PagedCacheManager(
                self.slots, self.max_len, self.block_size,
                self._num_blocks, prefix_cache=self._prefix_cache)
            self.cache = self._pg.init_paged_cache(
                self.cfg, self.slots, self.pool.total, self.block_size,
                mesh=self.mesh)
        else:
            self.cache = init_ring_cache(self.cfg, self.slots,
                                         self.max_len, mesh=self.mesh)
        if self.spec_k:
            self.dcache = init_ring_cache(self.draft_cfg, self.slots,
                                          self.max_len, mesh=self.mesh)
        self.tok = jnp.zeros((self.slots,), jnp.int32)
        self.temp = jnp.zeros((self.slots,), jnp.float32)
        self.keys = jnp.zeros((self.slots, 2), jnp.uint32)
        self._stop.wait(backoff)
        self._rebuilding = False
        return True

    def _expire_deadlines(self) -> None:
        now = time.monotonic()
        for i, req in enumerate(self.lane):
            if (req is not None and req.deadline is not None
                    and now >= req.deadline and not req.done.is_set()):
                req.deadline_exceeded = True
                self.stats["deadline_exceeded"] += 1
                self._evict(i)        # resolves with the partial tokens

    # -- loop --------------------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"no bucket fits prompt length {n}")

    def _suffix_bucket(self, n: int) -> int:
        """Compile bucket for a prefix-hit SUFFIX forward — sized
        independently of the prompt buckets (whose smallest entry can
        be prompt-sized: a 1-token suffix must not pay a 2048-row
        forward).  Power-of-two ladder up to one block, then block
        multiples; the compile set stays bounded by
        log2(block_size) + SUFFIX_PREFILL_MAX_ROWS / block_size."""
        cap = self.pool.view_len
        b = 8
        while b < min(n, self.block_size):
            b *= 2
        if b < n:
            b = -(-n // self.block_size) * self.block_size
        return min(b, cap)

    def _admit(self, slot: int, req: _Request) -> None:
        """Admission is ONE compiled dispatch and nothing else on the
        device path (make_prefill_insert does the splice, first-token
        sample and all lane-state updates in a single jit): eager ops
        here would block behind whatever chunk is decoding — measured
        ~500 ms EACH on relayed chips — and admissions were dominating
        served throughput.  The first token stays a device future,
        materialized at the next chunk consume
        (:meth:`_materialize_first`)."""
        n = len(req.prompt)
        if self.paged:
            first = self._admit_paged(slot, req)
        elif self.spec_k:
            (self.cache, self.dcache, self.tok, self.temp, self.keys,
             first) = self._inserts[req.bucket](
                self.params, self.draft_params, self.cache, self.dcache,
                self.tok, self.temp, self.keys, req.dev_prompt,
                n, slot, float(req.temperature), req.seed)
            self.stats["prefill_calls"] += 1
            self.stats["prefill_tokens"] += n
        else:
            self.cache, self.tok, self.temp, self.keys, first = \
                self._inserts[req.bucket](
                    self.params, self.cache, self.tok, self.temp,
                    self.keys, req.dev_prompt, n, slot,
                    float(req.temperature), req.seed)
            self.stats["prefill_calls"] += 1
            self.stats["prefill_tokens"] += n
        try:                            # ship the first token host-ward
            first.copy_to_host_async()  # early: TTFT then needs no
        except AttributeError:          # extra round-trip at consume
            pass
        self.lane[slot] = req
        self._lane_out[slot] = []
        self._lane_first[slot] = first
        self._lane_left[slot] = req.max_new
        self._lane_pos[slot] = n
        self.stats["admitted"] += 1
        if req.max_new == 1:
            # degenerate budget: sync now and free the lane immediately
            # rather than riding a whole wasted chunk
            self._materialize_first(slot, req)
            self._evict(slot)

    def _admit_paged(self, slot: int, req: _Request):
        """Paged admission: map blocks (radix hits read-only, CoW'd
        where the suffix will write, fresh for the rest), then ONE
        compiled insert — the full-prompt scatter insert cold, the
        suffix-only insert on a prefix hit.  A full prefix hit runs a
        ONE-token forward (the first sampled token needs the last
        prompt position's logits — logits are not cached, KV is) and
        zero forwards over cached blocks; the prefill-call counters are
        the tests' acceptance gate for that claim."""
        n = len(req.prompt)
        # max_suffix: beyond it a prefix hit is not worth taking — the
        # suffix insert's per-row pool writes (paged._write_rows_paged)
        # unroll O(rows), so a long divergent suffix admits faster
        # through the cold block-granular scatter prefill; the
        # allocator then maps fresh blocks instead of the cached ones
        # (never written over) when spec mode is off
        hit_len, cow = self.pool.admit(          # NoFreeBlocks -> req fails
            slot, req.prompt, max_suffix=self.SUFFIX_PREFILL_MAX_ROWS)
        for src, dst in cow:
            self.cache["k"], self.cache["v"] = self._copy_block(
                self.cache["k"], self.cache["v"], src, dst)
        self.stats["cow_copies"] = self.pool.stats["cow_copies"]
        tbl_row = jnp.asarray(self.pool.table[slot])
        if self.spec_k:
            (self.cache, self.dcache, self.tok, self.temp, self.keys,
             first) = self._inserts[req.bucket](
                self.params, self.draft_params, self.cache, self.dcache,
                tbl_row, self.tok, self.temp, self.keys, req.dev_prompt,
                n, slot, float(req.temperature), req.seed)
            self.stats["prefill_calls"] += 1
            self.stats["prefill_tokens"] += n
        elif hit_len:
            suffix = req.prompt[hit_len:]
            sb = self._suffix_bucket(len(suffix))
            ins = self._suffix_inserts.get(sb)
            if ins is None:
                ins = self._pg.make_paged_suffix_insert(
                    self.cfg, sb, self.block_size, self._top_k,
                    self._top_p, mesh=self.mesh)
                self._suffix_inserts[sb] = ins
            padded = np.zeros((1, sb), np.int32)
            padded[0, :len(suffix)] = suffix
            self.cache, self.tok, self.temp, self.keys, first = ins(
                self.params, self.cache, tbl_row, self.tok, self.temp,
                self.keys, jnp.asarray(padded), len(suffix), hit_len,
                slot, float(req.temperature), req.seed)
            self.stats["prefill_calls"] += 1
            self.stats["prefill_tokens"] += len(suffix)
        else:
            self.cache, self.tok, self.temp, self.keys, first = \
                self._inserts[req.bucket](
                    self.params, self.cache, tbl_row, self.tok,
                    self.temp, self.keys, req.dev_prompt, n, slot,
                    float(req.temperature), req.seed)
            self.stats["prefill_calls"] += 1
            self.stats["prefill_tokens"] += n
        # register this lane's full prompt blocks for future admissions
        # (content is valid for any later dispatch — same device stream)
        self.pool.publish(slot, req.prompt)
        return first

    def _materialize_first(self, i: int, req: _Request) -> None:
        """Bring the admission-sampled first token to the host (the only
        per-request sync, folded into a chunk consume) and run it through
        the same budget/eos/stream bookkeeping as chunk tokens."""
        fd = self._lane_first[i]
        if fd is None:
            return
        self._lane_first[i] = None
        t = int(fd)
        self._lane_out[i].append(t)
        self._tokens_emitted += 1
        if req._stream is not None:
            req._stream.put(t)
        self._lane_left[i] -= 1
        if req.eos is not None and t == req.eos:
            self._lane_left[i] = 0

    @staticmethod
    def _finish(req: _Request, error: Optional[Exception] = None) -> None:
        # a request that already RESOLVED keeps its outcome: attaching a
        # late error (e.g. the loop's shutdown sweep racing abort()'s
        # partial flush) would turn a delivered partial into a raise
        if error is not None and req.error is None \
                and not req.done.is_set():
            req.error = error
        # done BEFORE the stream sentinel: a stream() consumer that sees
        # the close must find result() already resolvable
        req.done.set()
        if req._stream is not None:
            req._stream.put(None)

    def _evict(self, slot: int) -> None:
        # host bookkeeping ONLY — no device ops (an eager .at[].set here
        # blocks behind the in-flight chunk on relayed chips).  The
        # lane's stale temp/keys are harmless: inactive lanes' tokens
        # are ignored, and the next admission overwrites all lane state
        # inside its compiled insert.
        req = self.lane[slot]
        self.lane[slot] = None
        self._lane_pos[slot] = 0        # retired lanes report no pos
        if self.pool is not None:
            # return the lane's blocks: published prompt blocks become
            # reclaimable cache, private ones rejoin the free list; the
            # zeroed table row routes any in-flight pipelined write for
            # this lane into the trash block
            self.pool.retire(slot)
        self.stats["evicted"] += 1
        if req is not None and not req.done.is_set():
            # error-path evictions can race ahead of the first consume
            self._materialize_first(slot, req)
            req.out = req.prompt + self._lane_out[slot]
            self._finish(req)
        else:
            # already resolved (watchdog stall / quarantine failed it
            # from another thread): just release the lane state
            self._lane_first[slot] = None

    def _loop(self) -> None:
        try:
            self._loop_body()
        except Exception as e:       # unrecoverable failure: fail loudly
            # flip dead-state BEFORE unblocking any client: a caller
            # released by the _finish below may immediately submit
            # again, and must be refused rather than queued into a void
            self.healthy = False
            self._stop.set()
            for req in self.lane:
                if req is not None:
                    self._finish(req, e)
            self.lane = [None] * self.slots
        # drain: fail whatever is still queued or resident
        for i, req in enumerate(self.lane):
            if req is not None:
                self._finish(req, ShuttingDown("batcher closed"))
                self.lane[i] = None
        self._shed_queue(ShuttingDown("batcher closed"))

    def _scrub_lane_blocks(self, slot: int) -> None:
        """Zero lane ``slot``'s PRIVATE pool blocks before they return
        to the free list: a NaN row in a re-mapped block would poison
        the next lane through the masked-tail contraction (softmax
        underflows masked columns to exactly 0, but 0 * NaN = NaN) —
        the same invariant the contiguous ring keeps by zeroing the
        whole lane at splice, block-granular.

        PUBLISHED (radix-cached) blocks are skipped: they hold shared
        prefix KV other admissions still read, and this lane cannot
        have poisoned them — every block the lane writes is private by
        construction (admit CoWs any hit block at/after the first
        written position).  One fused scatter over all victim blocks
        per pool (not one eager update per block): each ``.at[].set``
        materializes a full pool copy, and this runs on the ring
        thread behind the in-flight chunk."""
        row = self.pool.table[slot]
        blks = [int(row[j]) for j in range(self.pool.mapped_count[slot])
                if self.pool.ref[int(row[j])] == 1
                and int(row[j]) not in self.pool.by_block]
        if blks:
            idx = jnp.asarray(blks)
            self.cache["k"] = self.cache["k"].at[:, idx].set(0)
            self.cache["v"] = self.cache["v"].at[:, idx].set(0)

    def _consume(self, chunk_reqs, toks, counts=None, ok=None) -> None:
        """Apply one finished chunk's tokens ([chunk, slots] on host).
        ``chunk_reqs`` pins each lane to the REQUEST the chunk was
        dispatched for: under pipelining a lane may have been evicted
        (and even re-admitted) since dispatch — such in-flight tokens
        belong to the old request and are dropped.

        ``counts`` (speculative mode): per-lane count of VALID rows in
        ``toks`` — the variable accept-length advance.  Lane i takes
        ``toks[:counts[i], i]`` (its accepted drafts + the correction
        token); None means every row is valid (plain chunk mode).  The
        budget/eos walk below is shared, so an eos landing mid-
        speculated-block truncates exactly like one landing mid-chunk —
        no tokens after eos ever reach the result or the stream.

        ``ok`` (nan_check mode): per-lane isfinite verdict for this
        chunk — a False lane is QUARANTINED: its request fails
        (:class:`LaneQuarantined`), its blocks are scrubbed + freed,
        and no token of the poisoned chunk reaches any consumer.  The
        other lanes are attention-independent, so their streams stay
        bit-identical to a fault-free run."""
        for i, req in chunk_reqs:
            if req is None or self.lane[i] is not req \
                    or req.done.is_set():
                continue
            if ok is not None and not bool(ok[i]):
                self.stats["quarantined_lanes"] += 1
                if self.pool is not None:
                    self._scrub_lane_blocks(i)
                self._finish(req, LaneQuarantined(
                    f"lane {i} produced non-finite logits; request "
                    "failed, lane quarantined (ring unaffected)"))
                self._evict(i)
                continue
            self._materialize_first(i, req)
            n = toks.shape[0] if counts is None else int(counts[i])
            # the host fill-position mirror advances exactly like the
            # device pos (chunk ticks, or the spec round's commit count)
            self._lane_pos[i] += n
            if counts is not None:
                self.stats["spec_drafted"] += self.spec_k
                self.stats["spec_accepted"] += max(0, n - 1)
                req.drafted += self.spec_k
                req.accepted += max(0, n - 1)
            for t in toks[:n, i]:
                if self._lane_left[i] <= 0:
                    break
                self._lane_out[i].append(int(t))
                self._tokens_emitted += 1
                if req._stream is not None:
                    req._stream.put(int(t))
                self._lane_left[i] -= 1
                if req.eos is not None and int(t) == req.eos:
                    self._lane_left[i] = 0
            if self._lane_left[i] <= 0:
                self._evict(i)

    def _consume_oldest(self, pending: List[tuple]) -> None:
        """Pop + apply the oldest in-flight chunk.  The blocking
        device->host completion wait sits under the watchdog: a wedged
        dispatch surfaces HERE on real chips (dispatches are async), and
        the monitor fails the waiting clients while this thread is still
        stuck."""
        chunk_reqs, toks_dev, counts_dev, ok_dev = pending.pop(0)
        wd = self._watchdog
        if wd is not None:
            wd.begin()
        try:
            toks = np.asarray(toks_dev)
            counts = None if counts_dev is None else np.asarray(counts_dev)
            ok = None if ok_dev is None else np.asarray(ok_dev)
        finally:
            if wd is not None:
                wd.end()
        if self._fault is None:     # stall-failed chunks must not apply
            self._consume(chunk_reqs, toks, counts, ok)

    def _loop_body(self) -> None:
        # Up to ``pipeline_depth`` chunks in flight at all times (when
        # lanes are active): the host consumes chunk N's tokens — per-
        # token queue pushes, evict bookkeeping, and crucially the
        # device->host transfer latency — WHILE the device decodes
        # chunks N+1..N+depth.  Without this the ring serializes RTT
        # with compute; depth 1 was still RTT-bound on relayed chips
        # whose round-trip exceeds a chunk's device time (measured by
        # bench.py measure_ring_throughput), hence depth 2 by default.
        pending: List[tuple] = []   # [(chunk_reqs, toks, counts, ok)]
        while not self._stop.is_set():
            # ring-level fault (dispatch raised, or the watchdog
            # declared a stall): drop the in-flight chunks and self-heal
            # — rebuild everything device-side, re-admit queued work —
            # or die (legacy / budget exhausted) via the raise, which
            # the _loop wrapper turns into fail-everything + unhealthy
            if self._fault is not None:
                err, self._fault = self._fault, None
                pending.clear()
                if not self._heal(err):
                    raise err
                continue
            if self._draining:
                # drain: no new admissions; whatever is queued sheds
                # with ShuttingDown (clients retry another replica)
                self._shed_queue(ShuttingDown(
                    "server draining; retry another replica"))
            self._expire_deadlines()
            # cancelled lanes leave at the chunk boundary: the request
            # resolves with whatever tokens it has, the lane frees for
            # the next admission (serve.py calls cancel() when a stream
            # consumer disconnects mid-generation)
            for i, r in enumerate(self.lane):
                if r is not None and r._cancel:
                    self._evict(i)
            # admit into free lanes
            while not self._draining and any(r is None for r in self.lane):
                try:
                    req = self._pending.get_nowait()
                except queue.Empty:
                    break
                if req._cancel:                 # cancelled while queued
                    req.out = list(req.prompt)
                    self._finish(req)
                    continue
                if (req.deadline is not None
                        and time.monotonic() >= req.deadline):
                    # expired while queued: prompt-only 504 partial —
                    # resolved, never silently dropped
                    req.deadline_exceeded = True
                    self.stats["deadline_exceeded"] += 1
                    req.out = list(req.prompt)
                    self._finish(req)
                    continue
                slot = self.lane.index(None)
                try:
                    self._admit(slot, req)
                except Exception as e:          # bad request: fail it only
                    self._finish(req, e)
                    self.lane[slot] = None
                    self._lane_pos[slot] = 0
                    if self.pool is not None:
                        # admission may have mapped blocks before the
                        # dispatch failed — unmap them (no-op when the
                        # allocator itself rejected)
                        self.pool.retire(slot)

            active_idx = [i for i, r in enumerate(self.lane)
                          if r is not None]
            if not active_idx:
                if pending:
                    try:
                        self._consume_oldest(pending)
                    except Exception as e:
                        self._fault = e
                    continue            # eviction may have freed lanes
                self._wake.wait(timeout=0.1)
                self._wake.clear()
                continue
            self.stats["max_active"] = max(self.stats["max_active"],
                                           len(active_idx))

            tbl = None
            if self.paged:
                # on-demand block mapping: grow each active lane's table
                # to cover this dispatch PLUS every chunk already in
                # flight for it (the host pos mirror lags dispatched-
                # but-unconsumed work; spec rounds advance a
                # data-dependent 1..K+1, so the bound is the worst case).
                # An UNDERSIZED pool (num_blocks oversubscription) can
                # run dry mid-generation: only the lane that cannot
                # grow fails — evicting it (its request resolves with
                # the error) frees its blocks for the rest of the ring,
                # which must keep serving.
                advance = (self.spec_k + 1) if self.spec_k else self.chunk
                for i in list(active_idx):
                    inflight = sum(
                        1 for chunk_reqs, _, _, _ in pending
                        for j, r in chunk_reqs
                        if j == i and r is self.lane[i])
                    try:
                        self.pool.ensure(
                            i, self._lane_pos[i] + (inflight + 1) * advance)
                    except self._pg.NoFreeBlocks as e:
                        r = self.lane[i]
                        if r is not None and r.error is None:
                            r.error = e
                        self._evict(i)
                        active_idx.remove(i)
                if not active_idx:
                    continue        # every lane starved: retry the loop
                tbl = self.pool.device_table()
            active = jnp.asarray(
                [r is not None for r in self.lane], bool)
            # async dispatch: returns device futures immediately.  The
            # watchdog brackets it anyway — a chaos-injected host-side
            # hang (and a synchronous-dispatch backend) wedges HERE —
            # and any raise becomes a ring fault handled at the loop top
            # (fail resident requests retriably, rebuild, back off).
            wd = self._watchdog
            if wd is not None:
                wd.begin()
            try:
                ok_dev = None
                if self.spec_k:
                    spec_args = (self.params, self.draft_params,
                                 self.cache, self.dcache)
                    if self.paged:
                        spec_args += (tbl,)
                    (self.cache, self.dcache, self.tok, toks_dev,
                     counts_dev) = self._spec_step(
                        *spec_args, self.tok, self.temp, self.keys,
                        active)
                elif self.paged:
                    out = self._step(
                        self.params, self.cache, tbl, self.tok,
                        self.temp, self.keys, active)
                    counts_dev = None
                    if self._check_finite:
                        self.cache, self.tok, toks_dev, ok_dev = out
                    else:
                        self.cache, self.tok, toks_dev = out
                else:
                    out = self._step(
                        self.params, self.cache, self.tok, self.temp,
                        self.keys, active)
                    counts_dev = None
                    if self._check_finite:
                        self.cache, self.tok, toks_dev, ok_dev = out
                    else:
                        self.cache, self.tok, toks_dev = out
            except Exception as e:
                self._fault = e
                continue
            finally:
                if wd is not None:
                    wd.end()
            self.stats["chunks"] += 1
            # kick the device->host copy NOW, before the consume wait:
            # by consume time the tokens are already on the wire and
            # np.asarray is a cheap completion wait instead of a full
            # round-trip on the ring's critical path
            for dev in (toks_dev, counts_dev, ok_dev):
                try:
                    dev.copy_to_host_async()
                except AttributeError:  # None / interpret-mode ndarray
                    pass
            pending.append(([(i, self.lane[i]) for i in active_idx],
                            toks_dev, counts_dev, ok_dev))
            if len(pending) >= self.pipeline_depth:
                try:
                    self._consume_oldest(pending)
                except Exception as e:
                    self._fault = e


def _default_buckets(max_len: int) -> Tuple[int, ...]:
    """2-3 prefill compile buckets, always ending at max_len so every
    admissible prompt has a bucket."""
    out: List[int] = []
    b = 64
    while b < max_len and len(out) < 2:
        out.append(b)
        b *= 8
    out.append(max_len)
    return tuple(out)
