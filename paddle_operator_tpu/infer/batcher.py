"""Continuous-batching decode ring — compatibility facade.

ISSUE 6 split this module's ~1.6k lines into:

- ``infer/scheduler.py`` — the host scheduler (:class:`ContinuousBatcher`:
  admission, queues, deadlines, request lifecycle, resilience hooks,
  and the ``prefill_mode=inline|chunked|disagg`` admission paths);
- ``infer/executor.py`` — the device executor (compiled chunk/insert
  programs, ring/paged cache state, the chunked-prefill slice programs,
  and the disaggregated :class:`~paddle_operator_tpu.infer.executor.
  PrefillExecutor`).

Every public (and test-pinned private) name keeps importing from here,
so existing callers — serve.py, bench.py, the dryrun gates, the test
suite, the chaos injector — see one unchanged surface.  New code should
import from the split modules directly.
"""

from paddle_operator_tpu.infer.executor import (  # noqa: F401
    PrefillExecutor,
    RingExecutor,
    _default_buckets,
    _layer_step,
    _qkv_ring,
    _ring_forward,
    _sample_tokens,
    _splice_lane,
    _write_lane,
    _write_lane_stacked,
    init_ring_cache,
    make_attach_lane,
    make_chunk_step,
    make_chunked_final_insert,
    make_disagg_prefill,
    make_prefill_chunk,
    make_prefill_insert,
    make_spec_attach,
    make_spec_chunked_final_insert,
    make_spec_prefill_insert,
)
from paddle_operator_tpu.infer.qos import (  # noqa: F401
    AdapterRegistry,
    MultiClassQueue,
    QoSConfig,
)
from paddle_operator_tpu.infer.scheduler import (  # noqa: F401
    PREFILL_MODES,
    ContinuousBatcher,
    QueueFull,
    _fold_seed,
    _Request,
)

__all__ = [
    "ContinuousBatcher", "QueueFull", "PrefillExecutor", "RingExecutor",
    "PREFILL_MODES", "init_ring_cache", "make_chunk_step",
    "make_prefill_insert", "make_spec_prefill_insert",
    "make_prefill_chunk", "make_chunked_final_insert",
    "make_spec_chunked_final_insert", "make_attach_lane",
    "make_spec_attach", "make_disagg_prefill",
    "QoSConfig", "AdapterRegistry", "MultiClassQueue",
]
