"""Host half of the serving ring: the continuous-batching scheduler.

ISSUE 6 split ``infer/batcher.py`` into this scheduler (admission,
queues, deadlines, request lifecycle, resilience hooks — pure host
code; the only jax it touches is sequencing dispatches on its
executor) and ``infer/executor.py`` (compiled programs + device state).
:class:`ContinuousBatcher` keeps its name, constructor surface and
behavior — ``infer/batcher.py`` re-exports it — and gains the prefill
modes the split exists for:

- ``prefill_mode="inline"``: admission prefills the whole prompt in one
  compiled dispatch on the ring thread (the original behavior — one
  cold 2k prompt stalls every resident decode lane for a full prefill).
- ``prefill_mode="chunked"``: prefill runs in ``prefill_chunk``-token
  slices, at most ONE slice per ring iteration interleaved with the
  decode chunk — resident lanes never wait more than one slice
  (Sarathi-Serve).  Works on the contiguous and the paged ring.
- ``prefill_mode="disagg"``: cold prompts prefill on a separate
  :class:`~paddle_operator_tpu.infer.executor.PrefillExecutor` thread
  into its own block pool; the ring's only admission work is a
  device-to-device block copy + a tiny attach dispatch (DistServe,
  in-process).  Requires the paged ring; radix prefix HITS still admit
  through the suffix insert on the ring thread, so only uncached
  suffix tokens are ever prefilled anywhere.

All three modes are greedy-bit-identical to the inline ring and compose
with spec decode, paged KV, deadlines, drain, and the watchdog rebuild
(tests/test_prefill_modes.py; dryrun ``serve-disagg``).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from paddle_operator_tpu.infer import executor as X
from paddle_operator_tpu.infer import qos as QOS
from paddle_operator_tpu.utils import tracing as TR
from paddle_operator_tpu.infer.resilience import (
    DispatchWatchdog,
    LaneMigrated,
    LaneQuarantined,
    RestartBudget,
    RetriableError,
    RingResilience,
    ShuttingDown,
)
from paddle_operator_tpu.models.llama import LlamaConfig

PREFILL_MODES = ("inline", "chunked", "disagg")


def _fold_seed(seed: int) -> int:
    """Fold an out-of-int32-range seed to [0, 2**31) via the splitmix64
    finalizer (a bijection on 64-bit ints before the final fold) —
    distinct wide seeds stay distinct with overwhelming probability,
    unlike the ``& 0x7FFFFFFF`` mask that mapped s and s + 2**31 to the
    same sampling stream."""
    x = seed & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    x ^= x >> 31
    return x & 0x7FFFFFFF


class QueueFull(RuntimeError):
    """submit() backpressure signal: the bounded request queue stayed
    full past the put timeout.  A RuntimeError subclass so serve.py's
    generic 503 mapping already handles it (retry/fail-over, not a
    client error) while callers that care can catch it specifically."""


class _Request:
    __slots__ = ("prompt", "max_new", "temperature", "seed", "eos",
                 "done", "out", "error", "_stream", "_cancel",
                 "dev_prompt", "bucket", "accepted", "drafted",
                 "deadline", "deadline_exceeded",
                 "priority", "adapter", "adapter_idx", "ns", "preempts",
                 "request_id", "migrate_state",
                 "trace", "t_submit", "t_first", "t_last_tok",
                 "t_prefill0")

    def __init__(self, prompt, max_new, temperature, seed, eos,
                 wants_stream=False, deadline=None):
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.seed = seed
        self.eos = eos
        self.done = threading.Event()
        self.out: Optional[List[int]] = None
        self.error: Optional[Exception] = None
        self._cancel = False
        # absolute time.monotonic() deadline (or None): the ring retires
        # the lane when it passes — the request RESOLVES with the tokens
        # produced so far and this flag set (the 504-style partial), so
        # a slow client can never pin a lane / its paged blocks
        self.deadline: Optional[float] = deadline
        self.deadline_exceeded = False
        # speculative-decoding telemetry (spec_k > 0 rings): drafts
        # offered / accepted for THIS request — serve.py surfaces the
        # rate per response
        self.accepted = 0
        self.drafted = 0
        # multi-tenant QoS (ISSUE 10, infer/qos.py): admission class
        # (0 most urgent), the request's adapter (name, registry slot,
        # and radix-cache namespace) and how many times it has been
        # preemption-spilled (the per-request anti-thrash cap)
        self.priority = 0
        self.adapter: Optional[str] = None
        self.adapter_idx = 0
        self.ns = 0
        self.preempts = 0
        # fleet-level KV (ISSUE 12): the client's idempotent id (the
        # migration retrieval key) and this request's migration state —
        # None (never offered), "inflight" (envelope on the wire) or
        # "failed" (peer refused; never re-offered, resumes locally)
        self.request_id: Optional[str] = None
        self.migrate_state: Optional[str] = None
        # observability (ISSUE 15): per-request span accumulator
        # (None = tracing off for this request — every capture site is
        # one attribute check) + the host timestamps the latency
        # histograms observe at the scheduler's EXISTING blocking
        # points (submit, first-token materialization, chunk consume)
        self.trace: Optional[TR.RequestTrace] = None
        self.t_submit = time.monotonic()
        self.t_first: Optional[float] = None
        self.t_last_tok: Optional[float] = None
        self.t_prefill0: Optional[float] = None
        # padded prompt, transferred to device on the SUBMIT thread
        # (batcher.submit): on relayed chips a host->device copy costs a
        # full round-trip, and paying it on the decode-ring thread
        # stalls every lane; caller threads pay it concurrently instead
        self.dev_prompt: Optional[Any] = None
        self.bucket: int = 0
        # token streaming is opt-in (submit(stream=True)): the dominant
        # result()-only path must not pay per-token queue puts inside
        # the decode-ring thread that gates every lane's throughput
        self._stream: Optional["queue.Queue"] = (
            queue.Queue() if wants_stream else None)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error is not None:
            raise self.error
        return self.out

    @property
    def accept_rate(self) -> Optional[float]:
        """Speculative acceptance rate for this request (accepted
        drafts / offered drafts), or None when the ring is not
        speculative (or no round has consumed yet)."""
        if not self.drafted:
            return None
        return round(self.accepted / self.drafted, 4)

    def cancel(self) -> None:
        """Stop decoding this request: the ring evicts its lane at the
        next chunk boundary (or drops it from the queue if not yet
        admitted) and ``result()`` returns the tokens produced so far.
        A disconnect-abandoned long stream must not keep occupying a
        decode lane to its full token budget."""
        self._cancel = True

    def stream(self, timeout: Optional[float] = None):
        """Yield generated tokens as the ring emits them (one int at a
        time, arriving in chunk-sized bursts).  Raises the request's
        error at the point of failure; `timeout` bounds the wait for
        EACH burst, not the whole generation."""
        if self._stream is None:
            raise RuntimeError("request was not submitted with "
                               "stream=True")
        while True:
            try:
                item = self._stream.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError("no tokens within timeout") from None
            if item is None:
                if self.error is not None:
                    raise self.error
                return
            yield item


class _PrefillState:
    """Host bookkeeping for one mid-flight CHUNKED prefill: the slice
    frontier plus (contiguous only) the staging K/V the slices append
    into."""

    __slots__ = ("req", "start", "hit_len", "seq", "lane_k", "lane_v")

    def __init__(self, req, start, hit_len, seq, lane_k=None, lane_v=None):
        self.req = req
        self.start = start          # next absolute row to prefill
        self.hit_len = hit_len      # radix-hit rows (paged; 0 otherwise)
        self.seq = seq              # admission order — oldest advances
        self.lane_k = lane_k
        self.lane_v = lane_v


class _ParkedLane:
    """Host bookkeeping for one PREEMPTED lane (ISSUE 10): the
    byte-exact device spill (RingExecutor.spill_lane) plus the host
    mirrors a restore re-attaches — the request itself stays
    unresolved, invisible to the client except as latency."""

    __slots__ = ("req", "spill", "out", "left", "pos", "seq",
                 "migrating", "t_parked")

    def __init__(self, req, spill, out, left, pos, seq):
        self.req = req
        self.spill = spill
        self.out = out          # tokens emitted before the spill
        self.left = left        # remaining token budget
        self.pos = pos          # fill position at the spill boundary
        self.seq = seq          # park order — FIFO within a class
        # fleet-level KV (ISSUE 12): envelope on the wire to a peer —
        # the restore path must not resume a lane mid-migration
        self.migrating = False
        self.t_parked = time.monotonic()


# sentinel: swap_weights(mesh=...) distinguishes "keep the current
# mesh" (the common checkpoint bump) from "resize to mesh=None" (an
# explicit tp=1 downsize) — None is a legal target, so a default of
# None cannot carry "unchanged"
_KEEP_MESH = object()


class _SwapRequest:
    """One posted live weight swap (ISSUE 19), handed from the caller's
    thread to the ring loop: the NEW param trees (already loaded,
    quantized, host- or device-resident — the expensive I/O happened
    off the ring thread), the target mesh for a TP resize, and the
    completion event the caller blocks on.  ``error`` is set instead
    of ``result`` when the swap aborted — the ring then still serves
    the OLD generation (all-or-nothing)."""

    __slots__ = ("params", "draft_params", "mesh", "generation",
                 "done", "error", "result")

    def __init__(self, params, draft_params, mesh, generation):
        self.params = params
        self.draft_params = draft_params
        self.mesh = mesh                # _KEEP_MESH = no resize
        self.generation = generation    # None = bump by one
        self.done = threading.Event()
        self.error: Optional[Exception] = None
        self.result: Optional[Dict[str, Any]] = None


class ContinuousBatcher:
    """Slot scheduler over the resident chunk step.

    ``submit()`` is thread-safe and returns a handle whose ``result()``
    blocks until the sequence finishes; the decode loop runs on a
    background thread, admitting queued requests into free lanes at
    chunk boundaries (bucketed prefill) and evicting lanes on eos /
    budget.  ``stats`` counts admissions, evictions, decoded chunks and
    the high-water mark of concurrently active lanes — the numbers the
    slot-reuse tests pin.

    Device state and compiled programs live on the
    :class:`~paddle_operator_tpu.infer.executor.RingExecutor`
    (``self.executor``); this object only sequences dispatches on it.
    The legacy attribute surface (``cache``/``pool``/``_step``/...)
    forwards there so tests and the chaos injector keep working.

    ``paged=True`` (infer/paged.py) swaps the per-lane contiguous KV
    region for a global block pool + per-lane block tables with a radix
    prefix cache; greedy token streams stay BIT-IDENTICAL to the
    contiguous ring (``paged=False`` is both the fallback and the
    parity oracle).  ``prefill_mode``/``prefill_chunk`` select how
    admission prefill reaches the device (module docstring);
    ``prewarm`` compiles the admission/step programs off-thread at
    construction so the first long prompt pays no compile cliff
    (SERVE_PREWARM=0 opts out).
    """

    SUFFIX_PREFILL_MAX_ROWS = X.RingExecutor.SUFFIX_PREFILL_MAX_ROWS

    def __init__(self, params: Any, cfg: LlamaConfig, *, slots: int = 8,
                 max_len: Optional[int] = None, chunk_tokens: int = 8,
                 prefill_buckets=(), top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 pipeline_depth: int = 2, mesh=None,
                 draft_params: Any = None,
                 draft_cfg: Optional[LlamaConfig] = None,
                 spec_k: int = 0,
                 max_queue: int = 0,
                 queue_timeout: float = 5.0,
                 paged: bool = False,
                 block_size: int = 256,
                 num_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 prefill_mode: str = "inline",
                 prefill_chunk: int = 64,
                 prewarm: bool = False,
                 kv_quant: str = "none",
                 host_cache_blocks: int = 0,
                 resilience: Optional[RingResilience] = None,
                 qos: Optional[QOS.QoSConfig] = None,
                 adapters: Optional[QOS.AdapterRegistry] = None,
                 megastep: int = 1,
                 prefill_client=None,
                 prefill_lanes: int = 1,
                 prefill_stream: bool = False,
                 prefill_prefix_blocks: int = 0,
                 trace: Optional[bool] = None,
                 generation: int = 0) -> None:
        if prefill_mode not in PREFILL_MODES:
            raise ValueError(f"prefill_mode {prefill_mode!r} not in "
                             f"{PREFILL_MODES}")
        if prefill_mode == "disagg":
            # the disaggregated handoff is block-granular by design —
            # the paged pool IS the transfer unit
            paged = True
        self.cfg = cfg
        self.slots = slots
        self.max_len = max_len or cfg.max_seq_len
        self.chunk = chunk_tokens
        self.prefill_mode = prefill_mode
        # device-resident megastep (ISSUE 11, SERVE_MEGASTEP): fuse N
        # ring iterations into ONE compiled dispatch, with eos /
        # token-budget / deadline-tick continuation carried on device.
        # Admission, preemption, promotions, CoW and handoff attaches
        # happen only at megastep boundaries; N=1 (default) dispatches
        # the byte-identical legacy program (the oracle).
        self.megastep = int(megastep)
        if self.megastep < 1:
            raise ValueError(f"megastep must be >= 1 (got {megastep})")
        # rolling per-iteration wall estimate (EMA over consumed
        # dispatches): the deadline-tick budget converts a request's
        # remaining seconds into fused iterations with it.  0 = no
        # estimate yet (deadlines then bind at megastep boundaries
        # only, exactly like N=1 binds at chunk boundaries).
        self._step_s_est = 0.0
        # fault tolerance (infer/resilience.py): with a RingResilience a
        # ring-level dispatch fault fails the RESIDENT requests with a
        # retriable 503 and rebuilds the ring from scratch (fresh
        # cache/pool; queued work re-admitted) behind exponential
        # backoff, until the restart budget flips ``healthy`` — without
        # one the batcher keeps its legacy die-on-first-error behavior.
        self.resilience = resilience
        self._budget = (RestartBudget(resilience)
                        if resilience is not None else None)
        self._check_finite = bool(resilience and resilience.nan_check)
        if self._check_finite and spec_k:
            raise ValueError("nan_check is not supported on speculative "
                             "rings (the spec round has no per-lane "
                             "finite fold); disable one of them")
        self.healthy = True
        self._draining = False
        self._rebuilding = False
        # ring-level fault observed (by the loop thread or the watchdog
        # monitor) and not yet healed; the loop rebuilds at the next top
        self._fault: Optional[Exception] = None
        self._watchdog: Optional[DispatchWatchdog] = None
        if resilience is not None and resilience.watchdog:
            self._watchdog = DispatchWatchdog(
                resilience, self._on_stall, self._on_hard_stall)
        # max dispatched-but-unconsumed chunks; the oldest is consumed
        # once `depth` are in flight, so depth 2 = one chunk always
        # decoding while the host consumes the previous one (depth 1
        # disables the overlap entirely).  Deeper than 2 delays the
        # eviction bookkeeping by depth-1 chunks, so freed lanes sit
        # idle before re-admission — lane turnover costs more than the
        # extra hidden round-trip saves (measured).
        self.pipeline_depth = max(1, pipeline_depth)

        # multi-tenant QoS (ISSUE 10, infer/qos.py): priority classes,
        # preemption knobs, and the optional adapter registry — the
        # defaults (2 classes, everything defaulting to the least
        # urgent one, no adapters) keep single-tenant behavior
        # byte-identical to the pre-QoS ring
        self.qos = qos if qos is not None else QOS.QoSConfig()
        self.adapters = adapters

        # observability (ISSUE 15, utils/tracing.py).  Span capture is
        # OPT-IN (``trace=`` / SERVE_TRACE=1) and zero-cost when off:
        # requests then carry ``trace=None`` and every capture site is
        # one attribute check.  Spans only wrap host timestamps around
        # blocking points the loop already has — capture never adds a
        # device sync, and greedy token streams are byte-identical
        # either way (dryrun ``serve-trace``).  The latency histograms
        # (TTFT / inter-token / e2e / queue-wait) and the flight
        # recorder are always-on metrics, like the gauges.
        pod = os.environ.get("TPUJOB_REPLICA_ID", "")
        if trace is None:
            trace = TR.trace_enabled()
        self.tracer: Optional[TR.Tracer] = (
            TR.Tracer(pod=pod) if trace else None)
        self.hist = TR.ServeHistograms()
        self.flightrec = TR.FlightRecorder(pod=pod)

        # the device half: compiled programs + cache/pool/lane state.
        # The kwargs are kept (ISSUE 19): a live TP resize rebuilds the
        # executor around a NEW mesh with the geometry otherwise
        # byte-identical — one construction site, one swap site, no
        # drift between them.
        self._exec_kw = dict(
            slots=slots, max_len=self.max_len,
            chunk_tokens=chunk_tokens, prefill_buckets=prefill_buckets,
            top_k=top_k, top_p=top_p, draft_cfg=draft_cfg,
            spec_k=spec_k, paged=paged, block_size=block_size,
            num_blocks=num_blocks, prefix_cache=prefix_cache,
            prefill_mode=prefill_mode, prefill_chunk=prefill_chunk,
            check_finite=self._check_finite, kv_quant=kv_quant,
            host_cache_blocks=host_cache_blocks, adapters=adapters,
            megastep=self.megastep, prefill_client=prefill_client,
            prefill_lanes=prefill_lanes, prefill_stream=prefill_stream,
            prefill_prefix_blocks=prefill_prefix_blocks)
        self.executor = X.RingExecutor(
            params, cfg, mesh=mesh, draft_params=draft_params,
            **self._exec_kw)
        self.mesh = mesh
        # live weight swap (ISSUE 19): the generation of the params
        # currently dispatched (SERVE_GENERATION seeds it; each swap
        # bumps or sets it), and the single-slot pending-swap request
        # the ring loop consumes at a quiesced boundary
        self.generation = int(generation)
        self._swap_req: Optional[_SwapRequest] = None
        self._swap_lock = threading.Lock()
        self.paged = self.executor.paged
        self.kv_quant = self.executor.kv_quant
        self.spec_k = self.executor.spec_k
        self.draft_cfg = self.executor.draft_cfg
        self._top_k, self._top_p = top_k, top_p
        # cross-host disaggregation (ISSUE 13): stamp the remote
        # prefill client with THIS ring's handoff fingerprint — every
        # POST carries it, the prefill pod refuses a mismatch with
        # 409, and the client re-validates the returned envelope
        # before the scheduler ever touches its bytes
        if self.executor.prefill_remote:
            self.executor.prefill_exec.fingerprint = \
                self.handoff_fingerprint()

        self.lane: List[Optional[_Request]] = [None] * slots
        self._lane_out: List[List[int]] = [[] for _ in range(slots)]
        self._lane_left = [0] * slots
        # host mirror of each lane's device fill position — set by
        # admission, advanced at consume, ZEROED on eviction so
        # serving_status never reports a retired lane's stale pos (and,
        # paged, so on-demand block mapping tracks the true frontier)
        self._lane_pos = [0] * slots
        # per-lane device future of the admission-sampled first token,
        # materialized at the next chunk consume (async admission)
        self._lane_first: List[Optional[Any]] = [None] * slots
        # prefill-in-flight bookkeeping: lanes reserved but not yet
        # decode-active — chunked slices mid-flight, or a disagg prompt
        # away on the prefill executor (slot -> _PrefillState / request)
        self._prefilling: Dict[int, _PrefillState] = {}
        self._disagg_waiting: Dict[int, _Request] = {}
        # streamed handoff (ISSUE 14): per-slot upload timestamps of
        # frames landed BEFORE the terminal item — the overlap proof
        # (an uploaded frame whose stamp precedes the engine's
        # prefill-done stamp provably overlapped prefill compute)
        self._handoff_frame_t: Dict[int, List[float]] = {}
        self._admit_seq = 0

        # bounded admission queue (max_queue > 0): submit() blocks up to
        # queue_timeout for a slot, then REJECTS (QueueFull) — saturation
        # degrades into backpressure instead of unbounded request RAM.
        # The bound is PER CLASS (infer/qos.py MultiClassQueue): a
        # lower-priority flood sheds its own overflow without eating the
        # express class's admission budget.
        self.max_queue = int(max_queue)
        self._queue_timeout = queue_timeout
        self._pending = QOS.MultiClassQueue(
            self.qos.priorities, maxsize=self.max_queue)
        # preemption-spilled lanes awaiting re-admission (ISSUE 10) +
        # the rolling anti-thrash budget bounding how often residents
        # may be spilled at all
        self._parked: List[_ParkedLane] = []
        self._preempt_budget = QOS.PreemptionBudget(
            self.qos.preempt_budget, self.qos.preempt_window_s)
        # fleet-level KV (ISSUE 12).  ``migrate_out(meta, spill)`` —
        # wired by serve.py to a utils/fleetkv.FleetKVClient — offers a
        # parked lane's envelope to the fleet (router-brokered);
        # ``peer_fetch(tokens, ns)`` asks the fleet for demoted prefix
        # blocks.  Both default None = the pod-local pre-fleet ring.
        self.migrate_out = None
        self.peer_fetch = None
        # durable prefix store (ISSUE 17): the persistent tier below
        # host/peer — wired by serve.py via attach_kv_store().  The
        # submit-thread probe order becomes peer -> store: on a peer
        # miss (or with no fleet wired at all) the store is consulted
        # directly and hits land through the same import -> host-hit
        # -> batched-promote path.  None = pre-store behavior.
        self.kv_store = None
        # drain-by-migration: SIGTERM/scale-down drain parks residents
        # and migrates them out instead of waiting out completions
        # (completion-wait remains the fallback for lanes no peer takes)
        self._migrate_on_drain = False
        # parked lanes older than this migrate to an idle peer even
        # outside a drain (None/<=0 disables)
        self.migrate_parked_s: Optional[float] = None
        # cross-thread handoffs, all drained by the ring loop: lanes
        # adopted FROM peers (HTTP thread -> loop), migration-attempt
        # completions (worker thread -> loop), and fetched peer prefix
        # payloads awaiting radix import (submit thread -> loop)
        self._adopt_q: "queue.Queue[_ParkedLane]" = queue.Queue()
        self._migr_done: "queue.Queue[tuple]" = queue.Queue()
        self._host_imports: "queue.Queue[tuple]" = queue.Queue()
        # chains already asked of the fleet (hit or miss) — a cold
        # prefix must not trigger one fetch per request in a burst
        self._peer_fetch_seen: "OrderedDict[Any, bool]" = OrderedDict()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self.stats = {"admitted": 0, "evicted": 0, "chunks": 0,
                      "max_active": 0, "rejected_queue_full": 0,
                      # QoS accounting (ISSUE 10): lanes spilled for
                      # more urgent work and spilled lanes resumed —
                      # the tpujob_serve_lane_preemptions_total gauge
                      "preempted_lanes": 0, "restored_lanes": 0,
                      # fleet-level KV (ISSUE 12): lanes migrated OUT
                      # to a peer (completed handoffs), lanes adopted
                      # IN from peers, and prefix chains fetched from
                      # a peer's host tier
                      "lane_migrations": 0, "adopted_lanes": 0,
                      "peer_prefix_fetches": 0,
                      # durable prefix store (ISSUE 17): submit-thread
                      # store consults and the subset that returned
                      # blocks — kvStoreHitRate's numerator/denominator
                      # fold the store's own counters at status time
                      "kv_store_probes": 0, "kv_store_hits": 0,
                      "spec_accepted": 0, "spec_drafted": 0,
                      # prefill accounting: the prefix-cache acceptance
                      # gate — a full prefix hit admits with ZERO
                      # prefill forward passes over cached blocks.
                      # chunked_prefill_tokens counts the share that
                      # arrived in interleaved slices; disagg_prefills
                      # the prompts prefilled off the ring thread.
                      "prefill_calls": 0, "prefill_tokens": 0,
                      "chunked_prefill_tokens": 0, "disagg_prefills": 0,
                      # cross-host disaggregation (ISSUE 13): cold
                      # prompts whose prefill ran in a PREFILL POOL
                      # pod and handed off over the wire
                      "remote_prefills": 0,
                      # streamed handoff (ISSUE 14): block-group
                      # frames landed ahead of their terminal item,
                      # and the subset whose upload stamp PRECEDES the
                      # engine's prefill-done stamp — the
                      # transfer-overlaps-compute proof the gate pins
                      "handoff_frames": 0, "overlapped_frames": 0,
                      "cow_copies": 0,
                      # hierarchical-cache accounting (ISSUE 8): blocks
                      # uploaded back from the host tier — cumulative
                      # across watchdog rebuilds (the pool's own stats
                      # reset with the allocator)
                      "promoted_blocks": 0,
                      # fault-tolerance accounting (infer/resilience.py):
                      # deadline partials delivered, self-healing ring
                      # rebuilds, and NaN-quarantined lanes — surfaced
                      # through serving_status -> tpujob_serve_* gauges
                      "deadline_exceeded": 0, "watchdog_restarts": 0,
                      "quarantined_lanes": 0,
                      # live weight swap (ISSUE 19): completed in-place
                      # flips (checkpoint bumps and TP resizes; aborted
                      # swaps do not count — the ring kept serving the
                      # old generation)
                      "weight_swaps": 0}
        # served-token telemetry for serving_status(): cumulative emitted
        # tokens since construction (the /metrics tokens-per-sec gauge)
        self._tokens_emitted = 0
        self._t_start = time.monotonic()
        # off-thread compile prewarm (opt-in param; serve.py flips it on
        # unless SERVE_PREWARM=0): without it the per-bucket insert (and
        # the chunked slice programs) compile lazily on the FIRST prompt
        # that needs them, charging one unlucky request a full XLA
        # compile — tens of seconds for a big model.
        self.prewarmed = threading.Event()
        if prewarm:
            threading.Thread(target=self._prewarm, daemon=True,
                             name="prefill-prewarm").start()
        else:
            self.prewarmed.set()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-ring")
        self._thread.start()

    # -- executor state forwarding (legacy surface: tests + chaos) ---------

    @property
    def params(self):
        return self.executor.params

    @property
    def draft_params(self):
        return self.executor.draft_params

    @property
    def buckets(self):
        return self.executor.buckets

    @property
    def block_size(self):
        return self.executor.block_size

    @property
    def cache(self):
        return self.executor.cache

    @cache.setter
    def cache(self, v):
        self.executor.cache = v

    @property
    def dcache(self):
        return self.executor.dcache

    @dcache.setter
    def dcache(self, v):
        self.executor.dcache = v

    @property
    def tok(self):
        return self.executor.tok

    @tok.setter
    def tok(self, v):
        self.executor.tok = v

    @property
    def temp(self):
        return self.executor.temp

    @temp.setter
    def temp(self, v):
        self.executor.temp = v

    @property
    def keys(self):
        return self.executor.keys

    @keys.setter
    def keys(self, v):
        self.executor.keys = v

    @property
    def pool(self):
        return self.executor.pool

    @property
    def _step(self):
        return self.executor.step

    @_step.setter
    def _step(self, fn):
        self.executor.step = fn

    @property
    def _spec_step(self):
        return self.executor.spec_step

    @_spec_step.setter
    def _spec_step(self, fn):
        self.executor.spec_step = fn

    @property
    def _inserts(self):
        return self.executor.inserts

    @property
    def _suffix_inserts(self):
        return self.executor._suffix_inserts

    def _prewarm(self) -> None:
        try:
            self.executor.prewarm()
        except Exception:
            # a prewarm failure must never take the server down — the
            # lazily-compiling fallback path still works
            pass
        finally:
            self.prewarmed.set()

    # -- public ------------------------------------------------------------

    def submit(self, prompt, *, max_new_tokens: int,
               temperature: float = 0.0, seed: int = 0,
               eos_token: Optional[int] = None,
               stream: bool = False,
               request_id: Optional[str] = None,
               deadline_s: Optional[float] = None,
               priority: Optional[int] = None,
               adapter: Optional[str] = None,
               trace_ctx: Optional[tuple] = None) -> _Request:
        """Queue one generation request; returns a handle whose
        ``result()``/``stream()`` deliver the tokens.

        ``trace_ctx`` (ISSUE 15): ``(trace_id, parent_span_id|None)``
        from the ``X-Tpujob-Trace`` header — on a tracing-enabled ring
        (SERVE_TRACE=1) the request accumulates phase spans under that
        context and ``handle.trace`` rides response metadata so the
        router stitches one cross-pod timeline.  Ignored (zero-cost)
        when tracing is off; a tracing ring with no context still
        traces under a locally-minted trace id.

        ``deadline_s`` (serve.py: the ``X-Request-Deadline`` header):
        relative budget in seconds for the WHOLE generation.  When it
        expires the ring retires the lane at the next chunk boundary —
        its paged blocks freed, the request resolving with the tokens
        produced so far and ``handle.deadline_exceeded`` set (the
        504-style partial) — so one slow/greedy client can never pin a
        lane indefinitely.  Requests still queued at expiry resolve
        prompt-only with the same flag.

        ``request_id`` (optional, e.g. serve.py's per-row id) is woven
        into every validation error so an operator reading a rejection
        in a multi-request log knows WHICH request overflowed —
        validation runs (and raises) BEFORE the host-side tokenize copy
        and device transfer below, so a rejected request costs no
        bandwidth.

        ``seed``: sampling seed with an effective range of [0, 2**31) —
        it rides into the compiled insert as an int32 traced argument.
        In-range seeds are used as-is (streams are stable across
        versions for the common case); anything outside (negative or
        >= 2**31 — clients send arbitrary 64-bit ints, serve.py even
        derives seed+i per row) is folded through a splitmix64 hash
        rather than truncated, so distinct wide seeds keep distinct
        streams (masking would collide s with s + 2**31)."""
        rid = f" [request {request_id}]" if request_id is not None else ""
        n = len(prompt)
        if not n:
            raise ValueError(f"empty prompt{rid}")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1{rid}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0{rid}")
        # QoS class (ISSUE 10): 0 most urgent; unannotated requests get
        # the least urgent class (priorities are opt-in boosts)
        prio = (self.qos.default_priority if priority is None
                else int(priority))
        if not 0 <= prio < self.qos.priorities:
            raise ValueError(
                f"priority {prio} outside [0, {self.qos.priorities}) — "
                f"this ring serves {self.qos.priorities} class(es){rid}")
        adapter_idx = adapter_ns = 0
        if adapter is not None:
            if self.spec_k:
                raise ValueError(
                    f"adapters are not supported on speculative rings "
                    f"(the draft proposes base-only){rid}")
            if self.adapters is None:
                raise ValueError(
                    f"no adapter registry on this ring (SERVE_ADAPTERS "
                    f"unset) for adapter {adapter!r}{rid}")
            adapter_idx, adapter_ns = \
                self.adapters.resolve_ns(adapter)      # ValueError
        if self._draining:
            raise ShuttingDown("server draining; retry another replica")
        if self._stop.is_set() or not self._thread.is_alive():
            raise ShuttingDown("batcher closed")
        if n > self.buckets[-1]:
            raise ValueError(
                f"prompt length {n} exceeds the largest prefill "
                f"bucket ({self.buckets[-1]}){rid}")
        if self.spec_k:
            # a verify round starting at the last in-budget position
            # (prompt + max_new - 2) writes rows through pos + spec_k,
            # so spec_k - 1 positions of headroom must exist past
            # prompt + max_new (infer/speculative.py has the derivation)
            if n + max_new_tokens + self.spec_k - 1 > self.max_len:
                raise ValueError(
                    f"prompt ({n}) + max_new_tokens "
                    f"({max_new_tokens}) + speculative headroom "
                    f"({self.spec_k - 1}) exceeds max_len "
                    f"({self.max_len}){rid}")
        else:
            # the FIRST token is sampled from the prefill logits, so only
            # max_new-1 tokens ride chunk steps; the worst-case cache
            # position is prompt + ceil((max_new-1)/chunk)*chunk
            # (validating with ceil(max_new/chunk) rejected requests up
            # to chunk-1 tokens INSIDE capacity)
            budget = -(-(max_new_tokens - 1) // self.chunk) * self.chunk
            if n + budget > self.max_len:
                raise ValueError(
                    f"prompt ({n}) + chunk-rounded budget "
                    f"({budget}) exceeds max_len ({self.max_len}){rid}")
        # validation passed: NOW pay the tokenize copy
        prompt = list(map(int, prompt))
        # int32-range seeds pass through untouched; wide/negative seeds
        # hash-fold (see docstring)
        seed = int(seed)
        if not 0 <= seed < 0x80000000:
            seed = _fold_seed(seed)
        if self.max_queue and self._pending.full(prio):
            # shed BEFORE the host->device prompt transfer below: the
            # rejection path is the overload path, and a full round-trip
            # device copy per shed request (relayed chips) would spend
            # exactly the bandwidth backpressure exists to protect.
            # Non-authoritative (racy) — the timed put below enforces
            # the bound; this only waits for space to appear first.
            # Per-CLASS bound: a flooded batch class sheds its own
            # overflow here while the other classes stay admittable.
            deadline = time.monotonic() + self._queue_timeout
            while self._pending.full(prio):
                if self._stop.is_set() or self._draining:
                    raise ShuttingDown("batcher shutting down")
                if time.monotonic() >= deadline:
                    self.stats["rejected_queue_full"] += 1
                    raise QueueFull(
                        f"request queue full (max_queue={self.max_queue},"
                        f" priority {prio},"
                        f" waited {self._queue_timeout}s)")
                time.sleep(0.005)
        req = _Request(prompt, max_new_tokens, temperature, seed,
                       eos_token, wants_stream=stream,
                       deadline=(time.monotonic() + deadline_s
                                 if deadline_s is not None else None))
        if self.tracer is not None:
            req.trace = self.tracer.begin(ctx=trace_ctx,
                                          request_id=request_id)
            # workload-shape stamps (ISSUE 18): with these on the root
            # span, an exported span tree alone reconstructs the
            # request the fleet served — router/replay.py rebuilds
            # open-loop replay schedules from exactly these attrs
            req.trace.annotate(promptLen=len(prompt),
                               maxNew=int(max_new_tokens),
                               prio=prio,
                               adapter=adapter)
        req.priority = prio
        req.adapter = adapter
        req.adapter_idx = adapter_idx
        req.ns = adapter_ns if adapter_idx else 0
        req.request_id = request_id
        # fleet-level KV (ISSUE 12): a cold prefix may be warm in a
        # PEER's host tier — fetch its demoted blocks now, on the
        # caller's thread, so the admission below host-hits them.
        # Base-namespace chains only: adapter namespaces are salted
        # per-LOAD per-replica, so their chain keys never agree across
        # pods by design.
        # Probe order peer -> store (ISSUE 17): the durable store is
        # consulted on a peer miss, or directly when no fleet peer
        # fetch is wired (single-replica rings still warm-start).
        if ((self.peer_fetch is not None or self.kv_store is not None)
                and req.ns == 0 and self.pool is not None
                and self.pool.host is not None):
            try:
                self._maybe_peer_fetch(prompt)
            except Exception:
                pass    # fetch is an optimization, never a failure
        # pad + ship the prompt to the device HERE, on the caller's
        # thread — see _Request.dev_prompt
        req.bucket = self._bucket_for(len(prompt))
        padded = np.zeros((1, req.bucket), np.int32)
        padded[0, :len(prompt)] = prompt
        req.dev_prompt = jnp.asarray(padded)
        # bounded queue: poll briefly for a slot (smooths bursts) then
        # reject — the caller's thread, not the decode ring, pays the
        # wait.  Short put ticks so close()/drain() interrupt a BLOCKED
        # submitter with ShuttingDown immediately instead of leaving it
        # hanging out the full queue timeout against a dead ring.
        deadline = time.monotonic() + self._queue_timeout
        while True:
            if self._stop.is_set() or self._draining:
                raise ShuttingDown("batcher shutting down")
            try:
                self._pending.put(req, prio, timeout=0.05)
                break
            except queue.Full:
                if time.monotonic() >= deadline:
                    self.stats["rejected_queue_full"] += 1
                    raise QueueFull(
                        f"request queue full (max_queue={self.max_queue},"
                        f" priority {prio},"
                        f" waited {self._queue_timeout}s)") from None
        if self._stop.is_set() and not req.done.is_set():
            # loop died between the liveness check above and the put:
            # fail the request instead of letting result() hang
            self._finish(req, ShuttingDown("batcher closed"))
            return req
        self._wake.set()
        return req

    def prefill_queue_depth(self) -> int:
        """Requests admitted to a lane but still PREFILLING: chunked
        slices mid-flight plus disagg jobs queued/running on the
        prefill executor or awaiting handoff — the
        ``tpujob_serve_prefill_queue_depth`` gauge."""
        depth = len(self._prefilling) + len(self._disagg_waiting)
        return depth

    def _prefill_engine_stat(self, name: str, default):
        """A LOCAL prefill engine's telemetry (lanes, batch occupancy,
        HOL wait) — 0s on rings without one (inline/chunked/remote):
        remote pools export their own via prefill_serve."""
        pe = self.executor.prefill_exec
        if pe is None or self.executor.prefill_remote:
            return default
        if name == "lanes":
            return pe.lanes
        return getattr(pe, name)()

    def weight_quant_mode(self) -> str:
        """Weight-quant storage mode of the TARGET params actually
        dispatched ("none"/"int8"/"int4") — detected from leaf dtypes
        (infer/quant.py), not a threaded flag, so the status block
        stays truthful about the tree on device."""
        from paddle_operator_tpu.infer import quant as Q

        return Q.weight_quant_mode(getattr(self.executor, "params", {}))

    def draft_quant_mode(self) -> str:
        """Weight-quant mode of the DRAFT params ("none" on
        non-speculative rings) — SERVE_DRAFT_QUANT's visibility."""
        from paddle_operator_tpu.infer import quant as Q

        dp = getattr(self.executor, "draft_params", None)
        return Q.weight_quant_mode(dp) if dp is not None else "none"

    def _kv_store_usage(self) -> Tuple[int, int]:
        """``(blocks, bytes)`` resident in the durable store — (0, 0)
        with the store off, and degrades to (0, 0) on a backend listing
        error (telemetry must never fail a scrape)."""
        if self.kv_store is None:
            return 0, 0
        try:
            return self.kv_store.usage()
        except OSError:
            return 0, 0

    def serving_status(self) -> Dict[str, Any]:
        """The ``TPUJob.status.serving`` block (camelCase, like
        GoodputTracker.to_status): cumulative served-token throughput,
        speculative acceptance rate, and current queue depth — what the
        manager exports as ``tpujob_serve_*`` gauges on /metrics
        (utils/observability.py serving_gauges)."""
        elapsed = max(1e-9, time.monotonic() - self._t_start)
        drafted = self.stats["spec_drafted"]
        pf_tok = self.stats["prefill_tokens"]
        kv_store_blocks, kv_store_bytes = self._kv_store_usage()
        # per-lane visibility EXCLUDES retired lanes: _evict zeroes the
        # host pos mirror (and the compiled step zeroes the device pos),
        # so a freed lane can never leak its last request's fill
        # position or tokens into the telemetry (test_serve_metrics)
        return {
            "tokensPerSec": round(self._tokens_emitted / elapsed, 2),
            "acceptRate": (round(self.stats["spec_accepted"] / drafted, 4)
                           if drafted else 0.0),
            "queueDepth": self._pending.qsize(),
            "tokensTotal": self._tokens_emitted,
            "activeLanes": sum(r is not None for r in self.lane),
            "lanePos": [int(p) for p in self._lane_pos],
            "prefixHitRate": (self.pool.hit_rate() if self.pool is not None
                              else 0.0),
            "kvBlocksFree": (self.pool.blocks_free()
                             if self.pool is not None else 0),
            "kvBlocksHwm": (self.pool.stats["blocks_hwm"]
                            if self.pool is not None else 0),
            # hierarchical cache (ISSUE 8): blocks resident in the host
            # spill tier, the share of looked-up prefix tokens served
            # from host payloads, and cumulative promotions — the
            # tpujob_serve_host_* gauges (all 0 with the tier off)
            "hostCacheBlocks": (self.pool.host_blocks()
                                if self.pool is not None else 0),
            "hostHitRate": (self.pool.host_hit_rate()
                            if self.pool is not None else 0.0),
            "promotedBlocks": self.stats["promoted_blocks"],
            # prefill-path visibility (ISSUE 6): which admission path
            # this ring runs, how many admitted requests are still
            # prefilling, and the share of prefill tokens that arrived
            # in interleaved chunked slices
            "prefillMode": self.prefill_mode,
            "prefillQueueDepth": self.prefill_queue_depth(),
            # prefill-pool throughput (ISSUE 14): engine lanes, batch
            # occupancy EMA, head-of-line wait p95 and streamed-frame
            # counters — the tpujob_serve_prefill_batch_occupancy /
            # _hol_wait_ms / _lanes gauges (a REMOTE ring reports 0s
            # here; the prefill pods export their own)
            "prefillLanes": self._prefill_engine_stat("lanes", 0),
            "prefillBatchOccupancy": self._prefill_engine_stat(
                "batch_occupancy", 0.0),
            "prefillHolWaitMs": self._prefill_engine_stat(
                "hol_wait_ms_p95", 0.0),
            "handoffFrames": self.stats["handoff_frames"],
            "overlappedFrames": self.stats["overlapped_frames"],
            # quantized-pool visibility (SERVE_KV_QUANT): which storage
            # mode the pool runs and its device bytes (codes + scales +
            # staging tails, or the bf16 pool/ring) — the capacity an
            # operator sizes num_blocks against
            "kvQuantMode": self.kv_quant,
            "kvPoolBytes": self.executor.pool_bytes(),
            # weight quantization (SERVE_WEIGHT_QUANT /
            # SERVE_DRAFT_QUANT): storage mode of the target and draft
            # param trees actually dispatched (detected from leaf
            # dtypes) and their summed HBM bytes — the
            # tpujob_serve_weight_quant_mode / _param_bytes gauges; the
            # bytes gauge shows the quantization saving directly
            "weightQuantMode": self.weight_quant_mode(),
            "draftQuantMode": self.draft_quant_mode(),
            "paramBytes": self.executor.param_bytes(),
            "chunkedPrefillTokenShare": (
                round(self.stats["chunked_prefill_tokens"] / pf_tok, 4)
                if pf_tok else 0.0),
            # multi-tenant QoS (ISSUE 10): per-class queue depth (index
            # = class, 0 most urgent), cumulative preemption spills,
            # lanes currently parked awaiting re-admission, and the
            # adapter registry's live set (names feed the router's
            # adapter-affinity scrape; the count is the
            # tpujob_serve_active_adapters gauge)
            "priorityQueueDepth": self._pending.qsize_by_class(),
            "preemptedLanes": self.stats["preempted_lanes"],
            "parkedLanes": len(self._parked),
            # fleet-level KV (ISSUE 12): lanes migrated out / adopted
            # in, peer prefix-chain fetches, and the previously
            # invisible host-tier dropped-oldest overflows — the
            # tpujob_serve_lane_migrations_total /
            # _peer_prefix_fetches_total / _host_cache_evictions_total
            # gauges
            "laneMigrations": self.stats["lane_migrations"],
            "adoptedLanes": self.stats["adopted_lanes"],
            "peerPrefixFetches": self.stats["peer_prefix_fetches"],
            # cross-host disaggregation (ISSUE 13): handoffs landed
            # from the prefill pool — the
            # tpujob_serve_remote_prefills_total gauge
            "remotePrefills": self.stats["remote_prefills"],
            "hostCacheEvictions": (self.pool.host_evictions()
                                   if self.pool is not None else 0),
            # durable prefix store (ISSUE 17): blocks/bytes resident in
            # the persistent tier, the share of submit-thread store
            # probes that returned blocks, and janitor removals
            # (TTL + size budget) — the tpujob_serve_kv_store_* gauges
            # (all 0 with the store off)
            "kvStoreBlocks": kv_store_blocks,
            "kvStoreBytes": kv_store_bytes,
            "kvStoreHitRate": (self.kv_store.hit_rate()
                               if self.kv_store is not None else 0.0),
            "kvStoreEvictions": (self.kv_store.evictions()
                                 if self.kv_store is not None else 0),
            "activeAdapters": (len(self.adapters)
                               if self.adapters is not None else 0),
            "adapterNames": (self.adapters.names()
                             if self.adapters is not None else []),
            # device-resident megastep (ISSUE 11): fused iterations per
            # dispatch and the measured host-dispatch amortization —
            # the tpujob_serve_megastep_n / _dispatches_per_token gauges
            "megastepN": self.megastep,
            "dispatchesPerToken": (
                round(self.stats["chunks"] / self._tokens_emitted, 4)
                if self._tokens_emitted else 0.0),
            # observability (ISSUE 15): the four latency histogram
            # snapshots (cumulative counts for /metrics exposition,
            # rolling-window counts for folding) and the window's TTFT
            # p95 — what aggregate_fleet_serving folds fleet-wide and
            # the SLO autoscaler reads instead of a point gauge
            "latencyHist": self.hist.snapshot(),
            "ttftP95Ms": round(self.hist.ttft.p95() or 0.0, 3),
            # fault tolerance (infer/resilience.py): drain/rebuild
            # visibility for /readyz and the CRD's status.serving block
            "draining": self._draining,
            "healthy": self.healthy,
            "deadlineExceeded": self.stats["deadline_exceeded"],
            "watchdogRestarts": self.stats["watchdog_restarts"],
            "quarantinedLanes": self.stats["quarantined_lanes"],
            # live weight swap / elastic TP resize (ISSUE 19): the
            # generation this replica serves and its current TP degree
            # — the tpujob_serve_generation gauge, the reconciler's
            # roll trigger, and the router's /statusz mid-roll view
            "weightGeneration": int(self.generation),
            "servingTp": self.serving_tp(),
            "weightSwaps": self.stats["weight_swaps"],
        }

    @property
    def accepting(self) -> bool:
        """Readiness (/readyz): the ring takes new admissions — not
        draining, not mid-rebuild, not mid-swap, loop alive, budget
        unspent.  Mid-swap is a READINESS event, not an availability
        one: the router marks the replica down and routes new traffic
        elsewhere while requests already here queue through the flip
        (bounded TTFT inflation, zero 5xx)."""
        return (self.healthy and not self._draining
                and not self._rebuilding and self._swap_req is None
                and not self._stop.is_set()
                and self._thread.is_alive())

    # -- live weight swap / elastic TP resize (ISSUE 19) -------------------

    @property
    def swapping(self) -> bool:
        """True while a posted swap awaits (or is executing) its
        quiesced boundary — the /readyz mark-down window."""
        return self._swap_req is not None

    def serving_tp(self) -> int:
        """Tensor-parallel degree of the CURRENT executor's mesh — the
        ``servingTp`` status key; tracks a live TP resize."""
        mesh = self.executor.mesh
        return int(X.D.mesh_tp(mesh)) if mesh is not None else 1

    def swap_weights(self, params: Any, *, draft_params: Any = None,
                     mesh: Any = _KEEP_MESH,
                     generation: Optional[int] = None,
                     timeout: Optional[float] = 120.0
                     ) -> Dict[str, Any]:
        """Live weight swap / elastic TP resize (ISSUE 19): flip the
        served param trees — and, with ``mesh=``, the TP mesh — without
        restarting the process or dropping a single request.

        Call from any thread (serve.py's ``/v1/swap`` handler).  The
        expensive work (checkpoint load, quantize) happened on the
        CALLER's thread before this call; here the request posts to
        the ring loop, which at the next megastep/chunk boundary:
        quiesces the dispatch pipeline, parks every resident lane via
        the PR 10 spill (full unsharded host bytes), flips params —
        rebuilding the executor when the mesh changes — drops the old
        generation's radix/host cache (its KV must never serve the new
        weights), and restores the parked lanes through the promote
        scatter, which re-shards, so a tp=1 lane legally resumes on a
        tp=2 ring.  LoRA adapters re-gather automatically: the
        registry's delta stacks ride every dispatch as operands
        against whatever base is current.  All-or-nothing: any flip
        failure (and a watchdog rebuild racing the swap) restores the
        old params and generation, and this raises.

        ``generation=None`` bumps the generation by one; an explicit
        value sets it (the fleet roll passes spec.serving.generation).
        Returns the post-swap status summary."""
        if self.pool is None:
            raise ValueError(
                "live weight swap requires the paged ring "
                "(SERVE_PAGED=1): resident lanes park through the "
                "block-granular spill")
        if not self.accepting and self._swap_req is None:
            raise ShuttingDown(
                "ring is draining/rebuilding/stopped; not swapping")
        sw = _SwapRequest(params, draft_params, mesh, generation)
        with self._swap_lock:
            if self._swap_req is not None:
                raise ValueError("a weight swap is already in flight")
            self._swap_req = sw
        self._wake.set()
        if not sw.done.wait(timeout):
            # the ring never reached a boundary (wedged dispatch): the
            # watchdog/heal path will fail the request; un-post so the
            # replica does not stay unready forever
            with self._swap_lock:
                if self._swap_req is sw:
                    self._swap_req = None
            raise RetriableError(
                f"weight swap timed out after {timeout}s awaiting a "
                "quiesced boundary; the ring still serves generation "
                f"{self.generation} — retry")
        if sw.error is not None:
            raise sw.error
        return sw.result or {}

    def _park_residents_for_swap(self) -> int:
        """Park every resident decode lane at THE boundary (the caller
        consumed all in-flight dispatches, so device state and host
        mirrors agree).  Same spill the QoS preemption and the
        drain-by-migration path use — the restore after the flip is
        the existing promote-scatter re-admission."""
        parked = 0
        for i, r in enumerate(self.lane):
            if r is None or r.done.is_set() or r._cancel:
                continue
            self._preempt(i)
            parked += 1
        return parked

    def _do_swap(self) -> None:
        """Execute the posted swap at the quiesced boundary (ring loop
        only; ``pending`` already drained by the caller).  The flip is
        all-or-nothing: the OLD executor/params stay authoritative
        until the new state is fully built, and any failure rolls back
        to them — parked lanes then restore onto the old ring and the
        generation never moves."""
        with self._swap_lock:
            sw, self._swap_req = self._swap_req, None
        if sw is None:
            return
        t0 = time.monotonic()
        ex = self.executor
        resize = sw.mesh is not _KEEP_MESH and sw.mesh is not ex.mesh
        self.flightrec.record(
            "swap_begin", generation=sw.generation,
            resize=bool(resize),
            residents=sum(r is not None for r in self.lane))
        try:
            parked = self._park_residents_for_swap()
            if resize:
                # TP resize: build the NEW executor first (fresh
                # programs compiled against the new mesh, fresh
                # pool/cache) while the old one stays intact — a
                # construction failure leaves the ring exactly as it
                # was.  Peak HBM transiently holds both param sets and
                # both pools (docs/serving.md sizes the headroom).
                new_ex = X.RingExecutor(
                    sw.params, self.cfg, mesh=sw.mesh,
                    draft_params=sw.draft_params, **self._exec_kw)
                old_ex, self.executor = self.executor, new_ex
                self.mesh = sw.mesh
                if (old_ex.prefill_exec is not None
                        and not old_ex.prefill_remote):
                    old_ex.prefill_exec.close()
            else:
                old_params, old_draft = ex.swap_weights(
                    sw.params, sw.draft_params)
                try:
                    # fresh pool + radix: KV computed under the old
                    # generation must never serve the new one
                    ex.reset_state()
                except Exception:
                    ex.swap_weights(old_params, old_draft)
                    ex.reset_state()
                    raise
                del old_params, old_draft    # last refs free the HBM
        except Exception as e:
            self.flightrec.record("swap_failed", error=str(e)[:200])
            sw.error = e
            sw.done.set()
            return
        self.generation = (int(sw.generation)
                           if sw.generation is not None
                           else self.generation + 1)
        # the rebuilt pool is fresh: re-attach the durable store and
        # re-stamp its fingerprint (generation rides the fingerprint,
        # so old-generation store entries refuse wholesale instead of
        # warming the new weights with stale KV)
        if (self.kv_store is not None and self.pool is not None
                and self.pool.host is not None):
            self.pool.attach_store(self.kv_store)
            if getattr(self.kv_store, "fingerprint", None) is not None:
                self.kv_store.fingerprint = self._fingerprint()
        # cross-host disaggregation: the prefill pods must serve the
        # same generation/quant mode — re-stamp the client fingerprint
        # so a mismatched pool 409s instead of handing off stale KV
        if self.executor.prefill_remote:
            self.executor.prefill_exec.fingerprint = \
                self.handoff_fingerprint()
        self._peer_fetch_seen.clear()   # re-ask the fleet post-swap
        self.stats["weight_swaps"] += 1
        self.flightrec.record(
            "swap_done", generation=self.generation,
            tp=self.serving_tp(), parked=parked,
            ms=round((time.monotonic() - t0) * 1e3, 1))
        sw.result = {"generation": self.generation,
                     "servingTp": self.serving_tp(),
                     "parkedLanes": parked,
                     "weightQuantMode": self.weight_quant_mode(),
                     "swapMs": round((time.monotonic() - t0) * 1e3, 1)}
        sw.done.set()
        self._wake.set()    # restores run on the next pass

    def drain(self, budget_s: float = 30.0) -> None:
        """SIGTERM drain (the serving half of docs/fault-tolerance.md):
        stop admissions — queued and newly submitted requests fail with
        :class:`ShuttingDown` (503 + Retry-After upstream) — let the
        RESIDENT lanes finish within ``budget_s`` (lanes still
        PREFILLING — chunked slices or a disagg handoff — finish their
        prefill and their decode like any resident), cancel stragglers
        at the budget (their callers receive the tokens produced so
        far; paged blocks verifiably return to the pool), then close."""
        self.flightrec.record(
            "drain_start", residents=sum(r is not None
                                         for r in self.lane),
            parked=len(self._parked), queued=self._pending.qsize())
        self._draining = True
        self._wake.set()
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline and self._thread.is_alive():
            if all(r is None for r in self.lane) \
                    and self._pending.empty() and not self._parked:
                break
            time.sleep(0.02)
        for req in list(self.lane):
            if req is not None:
                req.cancel()            # partial flush at chunk boundary
        for pk in list(self._parked):
            pk.req.cancel()             # parked partials flush too
        grace = time.monotonic() + max(5.0, budget_s)
        while ((any(r is not None for r in self.lane) or self._parked)
               and self._thread.is_alive()
               and time.monotonic() < grace):
            time.sleep(0.02)
        self.flightrec.record(
            "drain_done", stragglers=sum(r is not None
                                         for r in self.lane))
        self.close()

    def abort(self, error: Optional[Exception] = None) -> None:
        """Second-SIGTERM semantics: immediate teardown.  Resident
        requests RESOLVE with their partial tokens (best-effort flush —
        an undrained kill would have lost them entirely); queued ones
        fail with ShuttingDown."""
        self.flightrec.record("abort",
                              error=(str(error)[:200] if error
                                     else None))
        self._draining = True
        self._stop.set()
        self._wake.set()
        for i, req in enumerate(self.lane):
            if req is not None and not req.done.is_set():
                req.out = req.prompt + self._lane_out[i]
                self._finish(req)
        for pk in self._parked:         # parked partials resolve too
            if not pk.req.done.is_set():
                pk.req.out = pk.req.prompt + pk.out
                self._finish(pk.req)
        self._parked.clear()
        self._shed_queue(error or ShuttingDown("server killed"))

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=30)
        if self._watchdog is not None:
            self._watchdog.close()
        if self.executor.prefill_exec is not None:
            self.executor.prefill_exec.close()
        # late blocked submitters can land requests after the loop's own
        # drain pass — sweep again so none hangs at result()
        self._shed_queue(ShuttingDown("batcher closed"))

    # -- fault handling ----------------------------------------------------

    def _shed_queue(self, error: Exception) -> None:
        while True:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                return
            self._finish(req, error)

    def _on_stall(self, elapsed: float) -> None:
        """Watchdog monitor callback: a dispatch/consume wait crossed
        N x rolling-p95.  Fail the resident requests NOW — their
        clients get retriable 503s while the ring thread is still stuck
        inside the wedged dispatch — and flag the rebuild the loop runs
        once it unwedges."""
        err = RetriableError(
            f"compiled dispatch stalled {elapsed:.1f}s (watchdog "
            f"threshold {self._watchdog.threshold():.1f}s); ring "
            "rebuilding — retry")
        for req in list(self.lane):
            if req is not None and not req.done.is_set():
                self._finish(req, err)
        self._fault = err

    def _on_hard_stall(self, elapsed: float) -> None:
        """The stall outlived hard_stall_factor x threshold: the host
        thread is unrecoverably stuck inside the runtime.  Flip
        /healthz so the orchestrator replaces the pod (crash-only)."""
        self.healthy = False

    def _heal(self, err: Exception) -> bool:
        """Self-heal after a ring-level fault: fail whatever is still
        resident with a retriable error, rebuild every piece of device
        state from scratch (cache, paged pool + radix cache, lane
        state — RingExecutor.reset_state), back off exponentially.
        Requests mid-prefill (chunked or away on the prefill executor)
        fail with the residents; a disagg result for a healed-away
        request is dropped at handoff.  Returns False — and flips
        ``healthy`` — when the restart budget is exhausted (the loop
        then dies the legacy way and /healthz goes unhealthy)."""
        wrapped = (err if isinstance(err, RetriableError)
                   else RetriableError(
                       f"ring dispatch failed ({err}); rebuilt — retry"))
        # decide + account for the restart BEFORE unblocking any client:
        # a caller released by the _finish below may immediately read
        # stats/healthy, and must see the restart it was shed for
        healing = self._budget is not None and not self._budget.exhausted
        if healing:
            self._rebuilding = True
            self.stats["watchdog_restarts"] += 1
        else:
            self.healthy = False
        # flight recorder (ISSUE 15): the rebuild is exactly the event
        # a crash-time dump exists for — record it and persist the
        # whole ring NOW, before the backoff sleep a hard kill could
        # land inside
        self.flightrec.record("watchdog_rebuild",
                              error=str(err)[:200], healing=healing,
                              residents=sum(r is not None
                                            for r in self.lane))
        self.flightrec.dump_file("watchdog_rebuild")
        for req in list(self.lane):
            if req is not None and not req.done.is_set():
                self._finish(req, wrapped)
        # parked lanes fail with the residents: their spills reference
        # nothing device-side (host bytes), but their CLIENTS deserve
        # the same retriable signal the rebuild sends everyone else
        for pk in self._parked:
            if not pk.req.done.is_set():
                self._finish(pk.req, wrapped)
        self._parked.clear()
        self.lane = [None] * self.slots
        self._lane_out = [[] for _ in range(self.slots)]
        self._lane_left = [0] * self.slots
        self._lane_pos = [0] * self.slots
        self._lane_first = [None] * self.slots
        self._prefilling.clear()
        self._disagg_waiting.clear()
        self._handoff_frame_t.clear()
        # a watchdog rebuild ABORTS any pending live swap (ISSUE 19):
        # the rebuild restores the OLD generation's params (reset_state
        # keeps self.executor.params), so the swap caller must retry —
        # all-or-nothing, never a half-flipped ring
        with self._swap_lock:
            sw, self._swap_req = self._swap_req, None
        if sw is not None:
            sw.error = RetriableError(
                "ring rebuilt mid-swap; the old generation was "
                "restored — retry the swap")
            sw.done.set()
        if not healing:
            return False
        backoff = self._budget.spend()
        self.executor.reset_state()
        # the rebuilt pool is fresh (store=None): re-attach the durable
        # store (ISSUE 17) — surviving restarts is its whole point, and
        # the rebuilt radix re-fills from it via the normal store probe
        if (self.kv_store is not None and self.pool is not None
                and self.pool.host is not None):
            self.pool.attach_store(self.kv_store)
        self._stop.wait(backoff)
        self._rebuilding = False
        return True

    def _expire_deadlines(self) -> None:
        now = time.monotonic()
        for i, req in enumerate(self.lane):
            if (req is not None and req.deadline is not None
                    and now >= req.deadline and not req.done.is_set()):
                req.deadline_exceeded = True
                self.stats["deadline_exceeded"] += 1
                self.flightrec.record("deadline_expired", lane=i,
                                      rid=req.request_id)
                self._evict(i)        # resolves with the partial tokens
        # parked lanes keep their deadline semantics: an expired one
        # resolves with the tokens it had at the spill boundary (the
        # same 504-style partial a resident gets).  A lane whose
        # envelope is ON THE WIRE is left alone until the outcome
        # lands: expiring it here while a peer adopts would deliver a
        # 504 partial the dedupe LRU records as final AND decode the
        # full stream on the adopter (the deadline travels in the
        # envelope, so the adopter enforces it after a success).
        for pk in list(self._parked):
            req = pk.req
            if pk.migrating:
                continue
            if (req.deadline is not None and now >= req.deadline
                    and not req.done.is_set()):
                req.deadline_exceeded = True
                self.stats["deadline_exceeded"] += 1
                req.out = req.prompt + pk.out
                self._finish(req)
                self._parked.remove(pk)

    # -- admission ---------------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"no bucket fits prompt length {n}")

    def _dispatch_cow(self, slot: int, cow, hit_len: int) -> None:
        """Dispatch the admission's copy-on-write block copies (codes +
        scales under SERVE_KV_QUANT=int8), then — quant only — seed the
        lane's bf16 staging tail when the radix hit lands MID-BLOCK:
        the lane's write-frontier block already holds quantized prefix
        rows (its CoW'd private copy), and both the suffix forward's
        tail-substituted read of [block_start, hit_len) and the
        eventual on-completion requantize of the WHOLE block need those
        rows present in the tail (paged.make_tail_init).

        Runs the admission's host-tier PROMOTIONS first (ISSUE 8): any
        radix hit the walk classified as host-resident reserved its
        device block inside pool.admit — the batched donated upload
        must reach the stream BEFORE a CoW that may copy a promoted
        block and before the insert that reads it.  All dispatches are
        async, so the transfer overlaps whatever chunk is already
        decoding; activation (the insert) is stream-ordered behind the
        transfer's completion."""
        ex = self.executor
        promotes = self.pool.take_promotions()
        if promotes:
            ex.dispatch_promotions(promotes)
            self.stats["promoted_blocks"] += len(promotes)
        if ex.quant:
            for src, dst in cow:
                (ex.cache["k"], ex.cache["v"], ex.cache["ks"],
                 ex.cache["vs"]) = ex._copy_block(
                    ex.cache["k"], ex.cache["v"], ex.cache["ks"],
                    ex.cache["vs"], src, dst)
        else:
            for src, dst in cow:
                ex.cache["k"], ex.cache["v"] = ex._copy_block(
                    ex.cache["k"], ex.cache["v"], src, dst)
        self.stats["cow_copies"] = self.pool.stats["cow_copies"]
        if ex.quant and hit_len % self.block_size:
            blk = int(self.pool.table[slot][hit_len // self.block_size])
            ex.cache["kt"], ex.cache["vt"] = ex._tail_init(
                ex.cache["kt"], ex.cache["vt"], ex.cache["k"],
                ex.cache["ks"], ex.cache["v"], ex.cache["vs"], slot, blk)

    def _activate(self, slot: int, req: _Request, first) -> None:
        """A lane's prefill completed (whatever path delivered it):
        wire up the decode-side bookkeeping so the next chunk dispatch
        includes it."""
        try:                            # ship the first token host-ward
            first.copy_to_host_async()  # early: TTFT then needs no
        except AttributeError:          # extra round-trip at consume
            pass
        n = len(req.prompt)
        self._lane_out[slot] = []
        self._lane_first[slot] = first
        self._lane_left[slot] = req.max_new
        self._lane_pos[slot] = n
        if req.max_new == 1:
            # degenerate budget: sync now and free the lane immediately
            # rather than riding a whole wasted chunk
            self._materialize_first(slot, req)
            self._evict(slot)

    def _admit(self, slot: int, req: _Request) -> None:
        """Admission entry: reserve the lane, then route by prefill
        mode.  ``inline`` is ONE compiled dispatch and nothing else on
        the device path (make_prefill_insert does the splice,
        first-token sample and all lane-state updates in a single jit):
        eager ops here would block behind whatever chunk is decoding —
        measured ~500 ms EACH on relayed chips.  ``chunked`` maps
        blocks / allocates staging and lets the loop interleave slices;
        ``disagg`` ships cold prompts to the prefill executor (prefix
        hits stay inline — the suffix insert is already cheap)."""
        ex = self.executor
        n = len(req.prompt)
        # queue-wait telemetry (ISSUE 15): submit -> this admission,
        # observed into the queue-wait histogram (and, traced, a span
        # carrying the QoS class) — the p95 the autoscaler's depth
        # model can finally be checked against
        now = time.monotonic()
        self.hist.queue_wait.observe((now - req.t_submit) * 1e3)
        if req.trace is not None:
            req.trace.add("queue_wait", req.t_submit, now,
                          prio=req.priority)
        self.flightrec.record("admit", rid=req.request_id, slot=slot,
                              prio=req.priority,
                              mode=self.prefill_mode)
        # reserve the lane FIRST: the admin surface's in-use snapshot
        # (serve.py lanes_in_use) reads lane/parked/queue from another
        # thread, and a request popped from the queue but not yet
        # lane-visible would otherwise slip through an evict guard
        self.lane[slot] = req
        if req.adapter_idx and self.adapters is not None:
            # re-validate at admission: the adapter could have been
            # evicted (and its slot even reloaded with ANOTHER tenant's
            # deltas) while this request sat queued — the load
            # generation captured at submit is the identity check (the
            # admission exception path releases the lane)
            try:
                live_ns = self.adapters.ns_of(req.adapter_idx)
            except KeyError:
                live_ns = -1
            if live_ns != req.ns:
                raise ValueError(
                    f"adapter {req.adapter!r} was evicted/replaced "
                    "while this request was queued; resubmit")
        # the lane's adapter id (host mirror): every adapter-aware
        # dispatch from here on gathers this lane's LoRA pair
        ex.aid[slot] = req.adapter_idx
        # reset the lane's host mirrors NOW, not at activation: a
        # chunked/disagg lane evicted MID-PREFILL (cancel, deadline,
        # drain) resolves through ``req.prompt + _lane_out[slot]``, and
        # the previous occupant's tokens must never leak into it
        self._lane_out[slot] = []
        self._lane_first[slot] = None
        if self.prefill_mode == "chunked":
            self._admit_chunked(slot, req)
            self.stats["admitted"] += 1
            return
        if self.prefill_mode == "disagg":
            self._admit_disagg(slot, req)
            self.stats["admitted"] += 1
            return
        if self.paged:
            first = self._admit_paged(slot, req)
        elif self.spec_k:
            (ex.cache, ex.dcache, ex.tok, ex.temp, ex.keys,
             first) = ex.inserts[req.bucket](
                ex.params, ex.draft_params, ex.cache, ex.dcache,
                ex.tok, ex.temp, ex.keys, req.dev_prompt,
                n, slot, float(req.temperature), req.seed)
            self.stats["prefill_calls"] += 1
            self.stats["prefill_tokens"] += n
        else:
            ex.cache, ex.tok, ex.temp, ex.keys, first = \
                ex.inserts[req.bucket](
                    ex.params, ex.cache, ex.tok, ex.temp,
                    ex.keys, req.dev_prompt, n, slot,
                    float(req.temperature), req.seed,
                    *ex.lora_insert_tail(req.adapter_idx))
            self.stats["prefill_calls"] += 1
            self.stats["prefill_tokens"] += n
        # counted only once the insert dispatched: a NoFreeBlocks /
        # insert failure above fails the request and must not drift
        # ``admitted`` past real admissions (the slot-reuse tests and
        # the bench saturation wait both read it)
        self.stats["admitted"] += 1
        self._activate(slot, req, first)

    def _admit_paged(self, slot: int, req: _Request):
        """Inline paged admission: map blocks (radix hits read-only,
        CoW'd where the suffix will write, fresh for the rest), then
        ONE compiled insert — the full-prompt scatter insert cold, the
        suffix-only insert on a prefix hit.  A full prefix hit runs a
        ONE-token forward (the first sampled token needs the last
        prompt position's logits — logits are not cached, KV is) and
        zero forwards over cached blocks; the prefill-call counters are
        the tests' acceptance gate for that claim."""
        ex = self.executor
        n = len(req.prompt)
        # max_suffix: beyond it a prefix hit is not worth taking — the
        # suffix insert's per-row pool writes (paged._write_rows_paged)
        # unroll O(rows), so a long divergent suffix admits faster
        # through the cold block-granular scatter prefill; the
        # allocator then maps fresh blocks instead of the cached ones
        # (never written over) when spec mode is off
        hit_len, cow = self.pool.admit(          # NoFreeBlocks -> req fails
            slot, req.prompt, max_suffix=self.SUFFIX_PREFILL_MAX_ROWS,
            ns=req.ns)
        self._dispatch_cow(slot, cow, hit_len)
        tbl_row = jnp.asarray(self.pool.table[slot])
        if self.spec_k:
            (ex.cache, ex.dcache, ex.tok, ex.temp, ex.keys,
             first) = ex.inserts[req.bucket](
                ex.params, ex.draft_params, ex.cache, ex.dcache,
                tbl_row, ex.tok, ex.temp, ex.keys, req.dev_prompt,
                n, slot, float(req.temperature), req.seed)
            self.stats["prefill_calls"] += 1
            self.stats["prefill_tokens"] += n
        elif hit_len:
            first = self._suffix_admit(slot, req, tbl_row, hit_len)
        else:
            ex.cache, ex.tok, ex.temp, ex.keys, first = \
                ex.inserts[req.bucket](
                    ex.params, ex.cache, tbl_row, ex.tok,
                    ex.temp, ex.keys, req.dev_prompt, n, slot,
                    float(req.temperature), req.seed,
                    *ex.lora_insert_tail(req.adapter_idx))
            self.stats["prefill_calls"] += 1
            self.stats["prefill_tokens"] += n
        # register this lane's full prompt blocks for future admissions
        # (content is valid for any later dispatch — same device stream;
        # adapter lanes publish under their namespace, so reuse happens
        # within a tenant's fine-tune and never across)
        self.pool.publish(slot, req.prompt, ns=req.ns)
        return first

    def _suffix_admit(self, slot: int, req: _Request, tbl_row, hit_len):
        """Prefix-hit admission: one suffix-only insert over the
        uncached tail — shared by the inline paged path and disagg's
        hit short-circuit."""
        ex = self.executor
        suffix = req.prompt[hit_len:]
        sb = ex.suffix_bucket(len(suffix))
        ins = ex.suffix_insert(sb)
        padded = np.zeros((1, sb), np.int32)
        padded[0, :len(suffix)] = suffix
        ex.cache, ex.tok, ex.temp, ex.keys, first = ins(
            ex.params, ex.cache, tbl_row, ex.tok, ex.temp,
            ex.keys, jnp.asarray(padded), len(suffix), hit_len,
            slot, float(req.temperature), req.seed,
            *ex.lora_insert_tail(req.adapter_idx))
        self.stats["prefill_calls"] += 1
        self.stats["prefill_tokens"] += len(suffix)
        return first

    def _admit_chunked(self, slot: int, req: _Request) -> None:
        """Chunked admission: reserve the lane and (paged) map its
        blocks now — the loop then advances ONE prefill slice per ring
        iteration (:meth:`_advance_prefill`), so resident decode lanes
        never wait more than one slice."""
        ex = self.executor
        hit_len = 0
        if self.paged:
            hit_len, cow = self.pool.admit(
                slot, req.prompt, max_suffix=self.SUFFIX_PREFILL_MAX_ROWS,
                ns=req.ns)
            self._dispatch_cow(slot, cow, hit_len)
            lane_k = lane_v = None
        else:
            lane_k, lane_v = ex.make_staging(req.bucket)
        self._admit_seq += 1
        self._prefilling[slot] = _PrefillState(
            req, hit_len, hit_len, self._admit_seq, lane_k, lane_v)

    def _advance_prefill(self, slot: int) -> None:
        """Dispatch the NEXT chunked-prefill slice for lane ``slot``:
        an intermediate slice appends KV only; the final slice runs the
        suffix/final insert (first-token sample + lane activation) and
        publishes the prompt's blocks to the radix cache."""
        ex = self.executor
        st = self._prefilling[slot]
        req = st.req
        n = len(req.prompt)
        sb = ex.prefill_chunk
        remaining = n - st.start
        t_slice0 = time.monotonic()
        if remaining > sb:
            # intermediate slice: KV only, no logits, no lane state
            toks = np.zeros((1, sb), np.int32)
            toks[0, :] = req.prompt[st.start:st.start + sb]
            if self.paged:
                tbl_row = jnp.asarray(self.pool.table[slot])
                args = (ex.params, ex.cache, tbl_row, jnp.asarray(toks),
                        st.start, st.start + sb)
                if ex.quant:    # quant slices address the lane's tail
                    args += (slot,)
                ex.cache = ex.chunk_prog(None)(
                    *args, *ex.lora_insert_tail(req.adapter_idx))
            else:
                sl = ex.staging_len(req.bucket)
                st.lane_k, st.lane_v = ex.chunk_prog(sl)(
                    ex.params, st.lane_k, st.lane_v, jnp.asarray(toks),
                    st.start, *ex.lora_insert_tail(req.adapter_idx))
            st.start += sb
            self.stats["prefill_calls"] += 1
            self.stats["prefill_tokens"] += sb
            self.stats["chunked_prefill_tokens"] += sb
            if req.trace is not None:
                req.trace.add("prefill_slice", t_slice0,
                              start=st.start - sb, tokens=sb)
            return
        # final slice
        toks = np.zeros((1, sb), np.int32)
        toks[0, :remaining] = req.prompt[st.start:]
        toks = jnp.asarray(toks)
        if self.paged and not self.spec_k:
            ins = ex.final_insert(None)
            ex.cache, ex.tok, ex.temp, ex.keys, first = ins(
                ex.params, ex.cache, jnp.asarray(self.pool.table[slot]),
                ex.tok, ex.temp, ex.keys, toks, remaining, st.start,
                slot, float(req.temperature), req.seed,
                *ex.lora_insert_tail(req.adapter_idx))
        elif self.paged:
            ins = ex.final_insert(None, req.bucket)
            (ex.cache, ex.dcache, ex.tok, ex.temp, ex.keys, first) = ins(
                ex.params, ex.draft_params, ex.cache, ex.dcache,
                jnp.asarray(self.pool.table[slot]), ex.tok, ex.temp,
                ex.keys, toks, remaining, st.start, slot,
                req.dev_prompt, n, float(req.temperature), req.seed)
        elif self.spec_k:
            sl = ex.staging_len(req.bucket)
            ins = ex.final_insert(sl, req.bucket)
            (ex.cache, ex.dcache, ex.tok, ex.temp, ex.keys, first) = ins(
                ex.params, ex.draft_params, ex.cache, ex.dcache,
                st.lane_k, st.lane_v, ex.tok, ex.temp, ex.keys, toks,
                remaining, st.start, req.dev_prompt, n, slot,
                float(req.temperature), req.seed)
        else:
            sl = ex.staging_len(req.bucket)
            ins = ex.final_insert(sl)
            ex.cache, ex.tok, ex.temp, ex.keys, first = ins(
                ex.params, ex.cache, st.lane_k, st.lane_v, ex.tok,
                ex.temp, ex.keys, toks, remaining, st.start, n, slot,
                float(req.temperature), req.seed,
                *ex.lora_insert_tail(req.adapter_idx))
        self.stats["prefill_calls"] += 1
        self.stats["prefill_tokens"] += remaining
        self.stats["chunked_prefill_tokens"] += remaining
        if req.trace is not None:
            req.trace.add("prefill_slice", t_slice0, start=st.start,
                          tokens=remaining, final=True)
        del self._prefilling[slot]
        if self.paged:
            self.pool.publish(slot, req.prompt, ns=req.ns)
        self._activate(slot, req, first)

    def _admit_disagg(self, slot: int, req: _Request) -> None:
        """Disaggregated admission: a radix prefix HIT admits inline
        through the suffix insert (the cached blocks live in the decode
        pool; the suffix forward is already cheap).  A COLD prompt maps
        fresh decode-pool blocks now (reserved — the handoff can never
        fail on NoFreeBlocks) and ships the prefill to the executor
        thread; the loop attaches the lane when the result lands."""
        hit_len, cow = self.pool.admit(
            slot, req.prompt, max_suffix=self.SUFFIX_PREFILL_MAX_ROWS,
            ns=req.ns)
        if hit_len and not self.spec_k:
            self._dispatch_cow(slot, cow, hit_len)
            first = self._suffix_admit(
                slot, req, jnp.asarray(self.pool.table[slot]), hit_len)
            self.pool.publish(slot, req.prompt, ns=req.ns)
            self._activate(slot, req, first)
            return
        # cold: fresh blocks are already mapped by admit (hit_len == 0
        # here unless spec, whose prefix cache is off -> also 0).  The
        # post-admit hook still runs: a hit_len-0 PARTIAL-tail hit can
        # map (and host-promote) one block whose upload/CoW must not
        # stay pending — the handoff overwrites the lane's view, but
        # the promoted entry re-anchored in the radix cache and a later
        # hit on it must read real bytes
        self._dispatch_cow(slot, cow, hit_len)
        ex = self.executor
        if ex.prefill_remote and req.adapter_idx:
            # remote prefill pods serve the BASE param set: an adapter
            # prompt prefilled there would hand off base-model KV under
            # a tenant's namespace.  Admit it inline on the ring thread
            # instead (exactly the SERVE_PREFILL=inline cold path) —
            # correctness first; adapter traffic simply skips the
            # remote TTFT win.
            n = len(req.prompt)
            ex.cache, ex.tok, ex.temp, ex.keys, first = \
                ex.inserts[req.bucket](
                    ex.params, ex.cache,
                    jnp.asarray(self.pool.table[slot]), ex.tok,
                    ex.temp, ex.keys, req.dev_prompt, n, slot,
                    float(req.temperature), req.seed,
                    *ex.lora_insert_tail(req.adapter_idx))
            self.stats["prefill_calls"] += 1
            self.stats["prefill_tokens"] += n
            self.pool.publish(slot, req.prompt, ns=req.ns)
            self._activate(slot, req, first)
            return
        self._disagg_waiting[slot] = req
        req.t_prefill0 = time.monotonic()
        ex.prefill_exec.submit(req, slot)

    def _land_handoff_blocks(self, slot: int, payload, lane, j0: int,
                             j1: int) -> None:
        """Upload one handoff block group ``[j0, j1)`` into the lane's
        already-reserved decode-pool blocks: the batched promote
        scatter for remote (host) payloads, the frame transfer for
        in-process (device snapshot) payloads.  Shared by streamed
        frames and the terminal item's remainder — both async
        dispatches that overlap whatever chunk is decoding."""
        if j1 <= j0:
            return
        ex = self.executor
        if ex.prefill_remote:
            promotes = []
            for i, j in enumerate(range(j0, j1)):
                p = {"k": payload["k"][:, i:i + 1],
                     "v": payload["v"][:, i:i + 1]}
                if ex.quant:
                    p["ks"] = payload["ks"][:, i:i + 1]
                    p["vs"] = payload["vs"][:, i:i + 1]
                promotes.append(
                    (int(self.pool.table[slot][j]), p, None))
            ex.dispatch_promotions(promotes)
            return
        m = self.pool.max_blocks
        n = j1 - j0
        src_ids = np.zeros((m,), np.int32)
        dst_ids = np.zeros((m,), np.int32)
        src_ids[:n] = ex.prefill_exec.tables[lane][j0:j1]
        dst_ids[:n] = self.pool.table[slot][j0:j1]
        if ex.quant:
            (ex.cache["k"], ex.cache["v"], ex.cache["ks"],
             ex.cache["vs"]) = ex._frame_transfer(
                ex.cache["k"], ex.cache["v"], ex.cache["ks"],
                ex.cache["vs"], payload["k"], payload["v"],
                payload["ks"], payload["vs"], jnp.asarray(src_ids),
                jnp.asarray(dst_ids))
        else:
            ex.cache["k"], ex.cache["v"] = ex._frame_transfer(
                ex.cache["k"], ex.cache["v"], payload["k"],
                payload["v"], jnp.asarray(src_ids),
                jnp.asarray(dst_ids))

    def _land_remote_tail(self, slot: int, payload) -> None:
        """A REMOTE handoff's (int8) staging tail: the wire payload's
        exact bf16 tail row lands in decode tail row ``slot``."""
        ex = self.executor
        ex.cache["kt"] = ex.cache["kt"].at[:, slot].set(
            jnp.asarray(payload["kt"][:, 0]))
        ex.cache["vt"] = ex.cache["vt"].at[:, slot].set(
            jnp.asarray(payload["vt"][:, 0]))

    def _drain_handoffs(self) -> None:
        """Attach completed disaggregated prefills: device-to-device
        block copy from the prefill executor's pool into the lane's
        already-mapped decode-pool blocks, then one tiny attach
        dispatch (pos/tok/temp/keys).  Results for requests that
        resolved meanwhile (cancel, deadline, heal) are dropped — their
        decode blocks were already retired with the lane.

        STREAMED handoff (ISSUE 14): the N-lane engine (and the
        streaming remote client) post ``("frame", req, slot, payload,
        lane, j0, j1)`` block-group items WHILE the prompt is still
        prefilling, then a terminal ``("final", req, slot, payload,
        lane, j0, n_blocks, first, t_done)`` with the remainder +
        (int8) staging tail + first token — so the decode-side upload
        (and the DCN wire, remote) overlaps the remaining prefill
        compute.  Frames for a resolved request drop exactly like
        stale results; a retried stream simply re-uploads from block
        0 (uploads are idempotent by destination — the blocks were
        reserved at admission)."""
        ex = self.executor
        pexec = ex.prefill_exec
        while True:
            try:
                item = pexec.results.get_nowait()
            except queue.Empty:
                return
            if isinstance(item[0], str):
                kind, req, slot = item[0], item[1], item[2]
                if (self._disagg_waiting.get(slot) is not req
                        or self.lane[slot] is not req
                        or req.done.is_set()):
                    continue                # stale frame/final: drop
                if kind == "frame":
                    _, _, _, payload, lane, j0, j1 = item
                    t_fr0 = time.monotonic()
                    self._land_handoff_blocks(slot, payload, lane,
                                              j0, j1)
                    self.stats["handoff_frames"] += 1
                    self._handoff_frame_t.setdefault(slot, []).append(
                        time.monotonic())
                    if req.trace is not None:
                        # host time of the streamed-frame upload
                        # dispatch (async — it overlaps the decoding
                        # chunk; the overlap proof is the stats
                        # counter, the span is the timeline marker)
                        req.trace.add("handoff_frame", t_fr0, j0=j0,
                                      j1=j1)
                    continue
                _, _, _, payload, lane, j0, n_blocks, first, t_done = \
                    item
                del self._disagg_waiting[slot]
                self._land_handoff_blocks(slot, payload, lane, j0,
                                          n_blocks)
                if ex.quant:
                    if ex.prefill_remote:
                        self._land_remote_tail(slot, payload)
                    else:
                        ex.cache["kt"], ex.cache["vt"] = ex._tail_copy(
                            ex.cache["kt"], ex.cache["vt"],
                            payload["kt"], payload["vt"], lane, slot)
                stamps = self._handoff_frame_t.pop(slot, [])
                self.stats["overlapped_frames"] += sum(
                    1 for t in stamps if t < t_done)
                if ex.prefill_remote:
                    self.stats["remote_prefills"] += 1
                self._attach_handoff(slot, req, len(req.prompt), first)
                continue
            req, slot = item[0], item[1]
            if (self._disagg_waiting.get(slot) is not req
                    or self.lane[slot] is not req or req.done.is_set()):
                continue                    # stale result: drop
            del self._disagg_waiting[slot]
            if len(item) == 3:              # (req, slot, error)
                self._finish(req, item[2])
                self._evict(slot)
                continue
            _, _, snap, n_blocks, first = item
            n = len(req.prompt)
            if ex.prefill_remote:
                # cross-host handoff (ISSUE 13): ``snap`` is the wire
                # envelope's HOST payload — per-block pool bytes the
                # prefill pod captured.  Land the whole range through
                # the streamed path's shared helper (the batched
                # promote scatter a host-tier hit uses, PR 8 — byte-
                # exact upload, codes+scales verbatim under int8) +
                # the exact wire tail, then the identical attach path
                # as in-process.
                self._land_handoff_blocks(slot, snap, None, 0,
                                          n_blocks)
                if ex.quant:
                    self._land_remote_tail(slot, snap)
                self.stats["remote_prefills"] += 1
                self._attach_handoff(slot, req, n, first)
                continue
            # src blocks are the executor's fixed identity row 1..M;
            # dst blocks were mapped at admission.  Both id vectors pad
            # to the table width with the TRASH block — garbage written
            # there is the trash block's job — so ONE transfer compile
            # serves every prompt length.
            m = self.pool.max_blocks
            src_ids = np.zeros((m,), np.int32)
            dst_ids = np.zeros((m,), np.int32)
            src_ids[:n_blocks] = np.arange(1, n_blocks + 1)
            dst_ids[:n_blocks] = self.pool.table[slot][:n_blocks]
            if ex.quant:
                # codes, scales AND the prompt's partial-block staging
                # tail cross the handoff (src tail row 0 — the executor
                # pool is one lane wide — lands in decode tail ``slot``)
                (ex.cache["k"], ex.cache["v"], ex.cache["ks"],
                 ex.cache["vs"], ex.cache["kt"],
                 ex.cache["vt"]) = ex._transfer(
                    ex.cache["k"], ex.cache["v"], ex.cache["ks"],
                    ex.cache["vs"], ex.cache["kt"], ex.cache["vt"],
                    snap["k"], snap["v"], snap["ks"], snap["vs"],
                    snap["kt"], snap["vt"], jnp.asarray(src_ids),
                    jnp.asarray(dst_ids), slot)
            else:
                ex.cache["k"], ex.cache["v"] = ex._transfer(
                    ex.cache["k"], ex.cache["v"], snap["k"], snap["v"],
                    jnp.asarray(src_ids), jnp.asarray(dst_ids))
            self._attach_handoff(slot, req, n, first)

    def _attach_handoff(self, slot: int, req: _Request, n: int,
                        first) -> None:
        """The handoff's decode-side tail, shared by the in-process
        (device block copy) and remote (promote-scatter upload) paths:
        one tiny attach dispatch — spec rings additionally prefill the
        DRAFT lane here, which is why the handoff snapshot never
        carries draft state — then publish + activate."""
        ex = self.executor
        t_att0 = time.monotonic()
        if req.trace is not None and req.t_prefill0 is not None:
            # the whole off-ring prefill phase: executor-queue wait +
            # prefill compute (+ the DCN wire, remote — whose own span
            # the RemotePrefillClient stamps) up to this attach
            req.trace.add("disagg_prefill", req.t_prefill0, t_att0,
                          remote=bool(ex.prefill_remote))
        if self.spec_k:
            (ex.dcache, ex.cache["pos"], ex.tok, ex.temp,
             ex.keys) = ex.spec_attach(req.bucket)(
                ex.draft_params, ex.dcache, ex.cache["pos"], ex.tok,
                ex.temp, ex.keys, req.dev_prompt, n, slot, first,
                float(req.temperature), req.seed)
        else:
            (ex.cache["pos"], ex.tok, ex.temp,
             ex.keys) = ex._attach(
                ex.cache["pos"], ex.tok, ex.temp, ex.keys, slot,
                first, n, float(req.temperature), req.seed)
        self.stats["prefill_calls"] += 1
        self.stats["prefill_tokens"] += n
        self.stats["disagg_prefills"] += 1
        if req.trace is not None:
            req.trace.add("handoff_attach", t_att0, slot=slot)
        self.pool.publish(slot, req.prompt, ns=req.ns)
        self._activate(slot, req, first)

    # -- consume / evict ---------------------------------------------------

    def _materialize_first(self, i: int, req: _Request) -> None:
        """Bring the admission-sampled first token to the host (the only
        per-request sync, folded into a chunk consume) and run it through
        the same budget/eos/stream bookkeeping as chunk tokens."""
        fd = self._lane_first[i]
        if fd is None:
            return
        self._lane_first[i] = None
        t = int(fd)
        # TTFT (ISSUE 15): submit -> the first token's host
        # materialization, observed ONCE per request (adopted lanes
        # produced their first token at the origin — ``t_first`` is
        # pre-stamped there, so a migrated stream never double-counts)
        now = time.monotonic()
        if req.t_first is None:
            req.t_first = now
            self.hist.ttft.observe((now - req.t_submit) * 1e3)
            if req.trace is not None:
                req.trace.add("ttft", req.t_submit, now)
        req.t_last_tok = now
        self._lane_out[i].append(t)
        self._tokens_emitted += 1
        if req._stream is not None:
            req._stream.put(t)
        self._lane_left[i] -= 1
        if req.eos is not None and t == req.eos:
            self._lane_left[i] = 0

    def _finish(self, req: _Request,
                error: Optional[Exception] = None) -> None:
        # a request that already RESOLVED keeps its outcome: attaching a
        # late error (e.g. the loop's shutdown sweep racing abort()'s
        # partial flush) would turn a delivered partial into a raise
        if error is not None and req.error is None \
                and not req.done.is_set():
            req.error = error
        if not req.done.is_set():
            # e2e latency (ISSUE 15): successful resolutions only —
            # deadline partials included (they ARE the request's e2e),
            # errors excluded (a 503 shed in 2ms is not a latency)
            if req.error is None:
                self.hist.e2e.observe(
                    (time.monotonic() - req.t_submit) * 1e3)
            if req.trace is not None:
                req.trace.finish(
                    error=(type(req.error).__name__
                           if req.error is not None else None))
        # done BEFORE the stream sentinel: a stream() consumer that sees
        # the close must find result() already resolvable
        req.done.set()
        if req._stream is not None:
            req._stream.put(None)

    def _evict(self, slot: int) -> None:
        # host bookkeeping ONLY — no device ops (an eager .at[].set here
        # blocks behind the in-flight chunk on relayed chips).  The
        # lane's stale temp/keys are harmless: inactive lanes' tokens
        # are ignored, and the next admission overwrites all lane state
        # inside its compiled insert.
        req = self.lane[slot]
        self.lane[slot] = None
        self._lane_pos[slot] = 0        # retired lanes report no pos
        self.executor.aid[slot] = 0     # adapter hygiene (host mirror)
        # a lane evicted MID-PREFILL (cancel, deadline, drain) drops its
        # slice/handoff state; a late disagg result is dropped by the
        # identity check in _drain_handoffs
        self._prefilling.pop(slot, None)
        self._disagg_waiting.pop(slot, None)
        self._handoff_frame_t.pop(slot, None)
        if self.pool is not None:
            # return the lane's blocks: published prompt blocks become
            # reclaimable cache, private ones rejoin the free list; the
            # zeroed table row routes any in-flight pipelined write for
            # this lane into the trash block
            self.pool.retire(slot)
        self.stats["evicted"] += 1
        if req is not None and not req.done.is_set():
            # error-path evictions can race ahead of the first consume
            self._materialize_first(slot, req)
            req.out = req.prompt + self._lane_out[slot]
            self._finish(req)
        else:
            # already resolved (watchdog stall / quarantine failed it
            # from another thread): just release the lane state
            self._lane_first[slot] = None

    # -- preemptive lane spill (ISSUE 10) ----------------------------------

    def _best_parked(self) -> Optional[_ParkedLane]:
        """The parked lane that should resume next: most urgent class
        first, then park order (FIFO within a class).  Lanes whose
        envelope is on the wire to a peer (ISSUE 12) are not
        restorable — resuming one locally while a peer adopts it would
        decode the same stream twice."""
        candidates = [p for p in self._parked if not p.migrating]
        if not candidates:
            return None
        return min(candidates, key=lambda p: (p.req.priority, p.seq))

    def _waiting_class(self) -> Optional[int]:
        """Most urgent class with WAITING work (queued head or parked
        head) — the demand side of the preemption decision."""
        cq = self._pending.peek_class()
        pk = self._best_parked()
        cp = pk.req.priority if pk is not None else None
        if cq is None:
            return cp
        return cq if cp is None else min(cq, cp)

    def _preempt_victim(self) -> Optional[int]:
        """Pick the lane to spill for waiting more-urgent work, or None
        when preemption should not fire: needs the paged pool (the
        spill rides it), a fully busy ring, a STRICTLY less urgent
        resident than the waiting head, anti-thrash budget headroom,
        and a victim not already bounced past its per-request cap.
        Lanes still mid-prefill are never victims (their spill state
        is not yet well-defined — they finish their prefill first)."""
        if (self.pool is None or not self.qos.preempt or self._draining
                or any(r is None for r in self.lane)):
            return None
        demand = self._waiting_class()
        if demand is None or not self._preempt_budget.ok():
            return None
        prefill_pending = self._pending_prefill_slots()
        best, best_key = None, None
        for i, r in enumerate(self.lane):
            if (r is None or i in prefill_pending or r.done.is_set()
                    or r.priority <= demand
                    or r.preempts >= self.qos.max_preempts_per_request):
                continue
            # least urgent first; among equals the SHORTEST lane spills
            # (smallest byte capture, least to re-upload)
            key = (r.priority, -self._lane_pos[i])
            if best_key is None or key > best_key:
                best, best_key = i, key
        return best

    def _preempt(self, slot: int) -> None:
        """Spill resident lane ``slot`` to host and free its lane and
        blocks for more urgent work.  The caller has QUIESCED the
        dispatch pipeline, so device state and host mirrors agree at a
        chunk boundary — the spill captures exactly the consumed
        stream, and the later restore resumes bit-identically
        (tests/test_qos.py pins it against unpreempted oracles).  The
        request stays UNRESOLVED: its client sees added latency, never
        an error or a truncated stream."""
        req = self.lane[slot]
        self._materialize_first(slot, req)
        if self._lane_left[slot] <= 0 or req.done.is_set():
            self._evict(slot)       # finished at the boundary anyway
            return
        t_sp0 = time.monotonic()
        spill = self.executor.spill_lane(slot)
        if req.trace is not None:
            req.trace.add("spill", t_sp0,
                          pos=int(self._lane_pos[slot]))
        self.flightrec.record("preempt", rid=req.request_id,
                              slot=slot, prio=req.priority)
        self._admit_seq += 1
        self._parked.append(_ParkedLane(
            req, spill, self._lane_out[slot], self._lane_left[slot],
            self._lane_pos[slot], self._admit_seq))
        self.lane[slot] = None
        self._lane_out[slot] = []
        self._lane_pos[slot] = 0
        self._lane_first[slot] = None
        self.executor.aid[slot] = 0
        self.pool.retire(slot)      # blocks free for the preemptor
        req.preempts += 1
        self._preempt_budget.spend()
        self.stats["preempted_lanes"] += 1

    def _try_restore(self, pk: _ParkedLane) -> bool:
        """Re-admit parked lane ``pk`` into a free slot: re-map fresh
        blocks, upload the spilled bytes, re-attach the host mirrors.
        Returns False (lane stays parked) when the pool cannot hold its
        blocks right now — the next loop pass retries as blocks free."""
        req = pk.req
        if req._cancel or req.done.is_set():
            self._parked.remove(pk)
            if not req.done.is_set():
                req.out = req.prompt + pk.out
                self._finish(req)
            return True
        slot = self.lane.index(None)
        t_rs0 = time.monotonic()
        try:
            self.executor.restore_lane(slot, pk.spill)
        except self.executor._pg.NoFreeBlocks:
            self.pool.retire(slot)  # roll back ensure's partial mapping
            return False
        if req.trace is not None:
            req.trace.add("restore", t_rs0, slot=slot)
        self._parked.remove(pk)
        self.lane[slot] = req
        self._lane_out[slot] = pk.out
        self._lane_left[slot] = pk.left
        self._lane_pos[slot] = pk.pos
        self._lane_first[slot] = None
        self.stats["restored_lanes"] += 1
        return True

    # -- fleet-level KV: migration + peer prefix fetch (ISSUE 12) ----------

    def _fingerprint(self) -> Dict[str, Any]:
        """The ring geometry an envelope must match byte-layout-wise.
        tp is deliberately ABSENT: spills are full host bytes (the
        capture gathers across shards) and restores re-shard through
        the promote scatter, so a tp=1 lane may adopt onto a tp=2 ring
        and vice versa."""
        ex = self.executor
        return {"layers": int(self.cfg.n_layers),
                "kvHeads": int(self.cfg.n_kv_heads),
                "headDim": int(self.cfg.head_dim),
                "blockSize": int(ex.block_size),
                "quant": ex.kv_quant,
                "specK": int(ex.spec_k),
                # live swap (ISSUE 19): generation IS part of the
                # envelope — KV computed under generation r must never
                # serve generation r+1's weights (migration, peer
                # fetch, and the durable store all refuse across a
                # bump).  A TP resize without a generation bump keeps
                # fleet KV flowing, exactly as the tp-absent rule
                # intends.
                "generation": int(self.generation)}

    def attach_kv_store(self, store) -> None:
        """Wire the durable prefix store (ISSUE 17,
        infer/kvstore.KVBlockStore) into both halves: the POOL's spill
        path (host-tier overflow drops persist instead of discarding,
        their radix nodes surviving store-resident) and the SUBMIT
        probe (peer -> store order).  Requires a paged pool with the
        host tier — there is nothing to spill or promote without
        them."""
        if self.pool is None or self.pool.host is None:
            raise ValueError(
                "KV store requires paged attention with the host cache "
                "tier (host_cache_blocks > 0)")
        self.pool.attach_store(store)
        self.kv_store = store

    def handoff_fingerprint(self) -> Dict[str, Any]:
        """The geometry + sampling rule a remote-prefill HANDOFF
        envelope must match (ISSUE 13) — narrower than the migration
        fingerprint: spec depth is absent (the draft lane prefills
        decode-side at attach) and top-k/top-p are PRESENT (the
        prefill pod samples the first token through the shared
        rule)."""
        from paddle_operator_tpu.infer.prefill_serve import (
            handoff_fingerprint,
        )

        return handoff_fingerprint(
            self.cfg, block_size=self.executor.block_size,
            kv_quant=self.kv_quant, top_k=self._top_k,
            top_p=self._top_p, wquant=self.weight_quant_mode(),
            generation=self.generation)

    def _migration_meta(self, pk: _ParkedLane) -> Dict[str, Any]:
        """The JSON half of a lane envelope: request identity + stream
        state + the ring fingerprint the adopter validates against."""
        req = pk.req
        return {"requestId": req.request_id,
                "prompt": [int(t) for t in req.prompt],
                "out": [int(t) for t in pk.out],
                "left": int(pk.left),
                "maxNew": int(req.max_new),
                "temperature": float(req.temperature),
                "seed": int(req.seed),
                "eos": req.eos,
                "priority": int(req.priority),
                "adapter": req.adapter,
                # the REMAINING deadline budget travels (absolute
                # monotonic stamps are process-local): the adopter
                # re-anchors it, so a migrated lane keeps the PR 10
                # 504-partial-at-deadline contract
                "deadlineS": (round(req.deadline - time.monotonic(), 3)
                              if req.deadline is not None else None),
                # ISSUE 15: the origin's completed spans travel with
                # the lane so the adopter's trace seeds from them and
                # the stitched cross-pod timeline stays ONE tree (the
                # adopter's request root parents onto the origin's)
                "trace": (req.trace.to_wire()
                          if req.trace is not None else None),
                "fingerprint": self._fingerprint()}

    def adopt(self, meta: Dict[str, Any],
              spill: Dict[str, Any]) -> _Request:
        """Adopt a migrated lane from a peer (the ``/v1/kv/restore``
        entry point, called on an HTTP handler thread): validate the
        envelope against THIS ring, re-resolve the adapter by name,
        and park it — the ring loop re-admits it through the exact
        promote-scatter + attach path a local preemption uses, so the
        resumed stream is bit-identical to the unmigrated one.
        Raises :class:`~paddle_operator_tpu.utils.fleetkv.
        EnvelopeError` (409 upstream) on any mismatch — a refused
        migration falls back to completion-wait at the origin, never
        to a corrupted lane here."""
        from paddle_operator_tpu.utils import fleetkv as FK

        if self.pool is None:
            raise FK.EnvelopeError(
                "lane adoption requires the paged ring (the spill is "
                "block-granular); this replica is contiguous")
        FK.check_fingerprint(meta, self._fingerprint())
        if self._draining or self._stop.is_set() or not self.healthy:
            raise ShuttingDown("replica not accepting migrations")
        left = int(meta["left"])
        if left <= 0:
            raise FK.EnvelopeError(
                "migrated lane has no remaining token budget")
        ex = self.executor
        m = int(spill["n_blocks"])
        exp = (self.cfg.n_layers, m, self.cfg.n_kv_heads,
               ex.block_size, self.cfg.head_dim)
        for name in ("k", "v"):
            if tuple(spill[name].shape) != exp:
                raise FK.EnvelopeError(
                    f"lane payload {name} shape "
                    f"{tuple(spill[name].shape)} != expected {exp}")
        if ex.quant and not all(k in spill for k in
                                ("ks", "vs", "kt", "vt")):
            raise FK.EnvelopeError(
                "int8 ring: lane envelope missing scale/tail planes")
        if ex.spec_k and not all(k in spill for k in
                                 ("dk", "dv", "dpos")):
            raise FK.EnvelopeError(
                "speculative ring: lane envelope missing draft lane")
        adapter = meta.get("adapter")
        aidx = ns = 0
        if adapter:
            if self.adapters is None:
                raise FK.EnvelopeError(
                    f"adapter {adapter!r} is not served here "
                    "(no registry)")
            try:
                aidx, ns = self.adapters.resolve_ns(adapter)
            except ValueError as e:
                raise FK.EnvelopeError(str(e)) from None
        prompt = [int(t) for t in meta["prompt"]]
        out = [int(t) for t in meta.get("out", ())]
        dl = meta.get("deadlineS")
        req = _Request(prompt,
                       int(meta.get("maxNew", left + len(out))),
                       float(meta.get("temperature", spill["temp"])),
                       int(meta.get("seed", 0)), meta.get("eos"),
                       deadline=(time.monotonic() + max(0.0, float(dl))
                                 if dl is not None else None))
        req.priority = min(max(0, int(meta.get(
            "priority", self.qos.default_priority))),
            self.qos.priorities - 1)
        req.adapter = adapter
        req.adapter_idx = aidx
        req.ns = ns if aidx else 0
        req.request_id = meta.get("requestId")
        # TTFT was produced (and observed) at the ORIGIN — pre-stamp
        # t_first so this ring can never double-count a migrated
        # stream's first token into its own TTFT histogram
        req.t_first = time.monotonic()
        if self.tracer is not None:
            wire = meta.get("trace")
            if isinstance(wire, dict) and wire.get("spans"):
                # same trace id, parented on the ORIGIN's request root:
                # the stitched timeline stays one parentless-root tree
                req.trace = self.tracer.begin(
                    ctx=(wire.get("traceId"), wire.get("rootId")),
                    request_id=req.request_id)
                req.trace.seed(wire["spans"])
            else:
                req.trace = self.tracer.begin(
                    request_id=req.request_id)
            req.trace.add("adopt", time.monotonic(),
                          blocks=int(spill["n_blocks"]))
        self.flightrec.record("adopt", rid=req.request_id,
                              blocks=int(spill["n_blocks"]))
        spill = dict(spill)
        # adapter SLOT ids are replica-local: re-stamp with OUR slot
        if self.adapters is not None:
            spill["aid"] = aidx
        else:
            spill.pop("aid", None)
        pk = _ParkedLane(req, spill, out, left, int(spill["pos"]), 0)
        self.stats["adopted_lanes"] += 1
        self._adopt_q.put(pk)
        self._wake.set()
        return req

    def _maybe_peer_fetch(self, prompt) -> None:
        """Submit-thread half of the fleet prefix probe, order
        peer -> store (ISSUE 17): when the prompt's full-block chain
        is not fully covered locally, ask the fleet (one bounded HTTP
        round-trip on the CALLER's thread — never the ring's) for
        demoted payloads; on a peer miss consult the durable store
        directly (a bounded disk read, same thread discipline).
        Either hit queues payloads for radix import at the next loop
        pass, so this request's admission host-hits them."""
        from paddle_operator_tpu.utils import fleetkv as FK
        from paddle_operator_tpu.utils.radixkey import chain_key

        pool = self.pool
        bs = pool.bs
        tokens = [int(t) for t in prompt]
        n_full = len(tokens) // bs
        if n_full == 0:
            return
        keys: List[Any] = []
        key = None
        for j in range(n_full):
            key = chain_key(key, tuple(tokens[j * bs:(j + 1) * bs]))
            keys.append(key)
        # local coverage probe — a racy read against the ring thread's
        # radix mutations; any surprise is caught by submit's except
        # and the fetch simply skipped.  An entry counts as covered
        # only if it is SERVABLE (device- or host-resident): a
        # store-resident node is exactly what the probe below re-fills.
        covered = 0
        for k in keys:
            e = pool.entries.get(k)
            if e is None or not pool._servable(e):
                break
            covered += 1
        if covered >= n_full:
            return
        # the seen-cache dedupes the PEER round-trip only (one HTTP
        # ask per distinct chain — a repeat miss must not hammer the
        # fleet); the store probe below stays outside it: a clean
        # store miss costs one local file stat, and a store-resident
        # node's whole purpose is to be RE-probed on a later walk
        tail = keys[-1]
        seen = tail in self._peer_fetch_seen
        if seen:
            self._peer_fetch_seen.move_to_end(tail)
        else:
            self._peer_fetch_seen[tail] = True
            while len(self._peer_fetch_seen) > 1024:
                self._peer_fetch_seen.popitem(last=False)
        if self.peer_fetch is not None and not seen:
            buf = self.peer_fetch(tokens, 0)
            if buf:
                meta, chunks, idx, payloads = FK.decode_prefix(buf)
                FK.check_fingerprint(meta, self._fingerprint())
                if idx:
                    self._host_imports.put((chunks, idx, payloads, 0))
                    self.stats["peer_prefix_fetches"] += 1
                    self._wake.set()
                    return
        if self.kv_store is None:
            return
        self.stats["kv_store_probes"] += 1
        chunks, idx, payloads, _fp = self.kv_store.fetch(
            tokens, bs, ns=0, skip=covered)
        if not idx:
            return
        self.stats["kv_store_hits"] += 1
        self._host_imports.put((chunks, idx, payloads, 0))
        self._wake.set()

    def _kick_migration(self, pk: _ParkedLane) -> None:
        """Offer one parked lane to the fleet on a side thread (the
        POST must never stall the ring)."""
        pk.migrating = True
        pk.req.migrate_state = "inflight"
        threading.Thread(target=self._migrate_worker, args=(pk,),
                         daemon=True, name="kv-migrate").start()

    def _migrate_worker(self, pk: _ParkedLane) -> None:
        ok = False
        try:
            ok = bool(self.migrate_out(self._migration_meta(pk),
                                       pk.spill))
        except Exception:
            ok = False
        self._migr_done.put((pk, ok))
        self._wake.set()

    def _pump_fleetkv(self, pending: List[tuple]) -> None:
        """One loop pass of fleet-KV work: land adopted lanes in the
        parked list, apply migration-attempt outcomes, import fetched
        peer prefix payloads, and — draining with migration on, or a
        parked lane past its patience — offer lanes to the fleet."""
        # the ring loop is the ONLY consumer of these queues, so the
        # empty() pre-checks (cheap, no exception) are race-free
        while not self._adopt_q.empty():
            pk = self._adopt_q.get_nowait()
            if self._stop.is_set() or self._draining:
                # raced shutdown: the adopter promised nothing yet —
                # fail retriably so the client's next retry re-routes
                self._finish(pk.req, ShuttingDown(
                    "replica shut down before the adopted lane ran"))
                continue
            self._admit_seq += 1
            pk.seq = self._admit_seq
            self._parked.append(pk)
        while not self._migr_done.empty():
            pk, ok = self._migr_done.get_nowait()
            if pk not in self._parked:
                continue    # healed/cancelled away mid-flight
            self.flightrec.record("migrate_out", ok=bool(ok),
                                  rid=pk.req.request_id)
            if ok:
                self._parked.remove(pk)
                self.stats["lane_migrations"] += 1
                pk.req.migrate_state = "done"
                self._finish(pk.req, LaneMigrated(
                    "lane migrated to a peer replica; retry with the "
                    "same request_id to collect the result"))
            else:
                # peer refused / unreachable: resume locally, never
                # re-offer (completion-wait is the drain fallback)
                pk.req.migrate_state = "failed"
                pk.migrating = False
        while not self._host_imports.empty():
            chunks, idx, payloads, ns = self._host_imports.get_nowait()
            if self.pool is not None:
                try:
                    self.pool.import_host_blocks(chunks, idx, payloads,
                                                 ns=ns)
                except Exception:
                    pass    # an import is an optimization, never a fault
        if self.migrate_out is None:
            return
        drain_migrate = (self._draining and self._migrate_on_drain
                         and self.pool is not None)
        if drain_migrate:
            # park every resident decode lane at THE boundary (all
            # in-flight chunks consumed, device state and host mirrors
            # agree) so its spill captures exactly the consumed stream
            prefill_pending = self._pending_prefill_slots()
            todo = [i for i, r in enumerate(self.lane)
                    if r is not None and i not in prefill_pending
                    and not r.done.is_set() and not r._cancel
                    and r.migrate_state is None and r._stream is None
                    and r.request_id is not None]
            if todo:
                try:
                    while pending:
                        self._consume_oldest(pending)
                except Exception as e:
                    self._fault = e
                    return
                for i in todo:
                    r = self.lane[i]
                    if (r is not None and not r.done.is_set()
                            and r.migrate_state is None):
                        self._preempt(i)
        now = time.monotonic()
        for pk in list(self._parked):
            r = pk.req
            if (pk.migrating or r.migrate_state is not None
                    or r.request_id is None or r._stream is not None
                    or r._cancel or r.done.is_set()):
                continue
            if drain_migrate or (
                    self.migrate_parked_s is not None
                    and self.migrate_parked_s > 0
                    and now - pk.t_parked >= self.migrate_parked_s):
                self._kick_migration(pk)

    def _loop(self) -> None:
        try:
            self._loop_body()
        except Exception as e:       # unrecoverable failure: fail loudly
            # flip dead-state BEFORE unblocking any client: a caller
            # released by the _finish below may immediately submit
            # again, and must be refused rather than queued into a void
            self.healthy = False
            self._stop.set()
            for req in self.lane:
                if req is not None:
                    self._finish(req, e)
            self.lane = [None] * self.slots
        # drain: fail whatever is still queued, resident or parked
        for i, req in enumerate(self.lane):
            if req is not None:
                self._finish(req, ShuttingDown("batcher closed"))
                self.lane[i] = None
        for pk in self._parked:
            self._finish(pk.req, ShuttingDown("batcher closed"))
        self._parked.clear()
        self._shed_queue(ShuttingDown("batcher closed"))

    def _scrub_lane_blocks(self, slot: int, req=None) -> None:
        """Zero lane ``slot``'s PRIVATE pool blocks before they return
        to the free list: a NaN row in a re-mapped block would poison
        the next lane through the masked-tail contraction (softmax
        underflows masked columns to exactly 0, but 0 * NaN = NaN) —
        the same invariant the contiguous ring keeps by zeroing the
        whole lane at splice, block-granular.

        PUBLISHED (radix-cached) blocks are skipped: they hold shared
        prefix KV other admissions still read, and this lane cannot
        have poisoned them — every block the lane writes is private by
        construction (admit CoWs any hit block at/after the first
        written position).  One fused scatter over all victim blocks
        per pool (not one eager update per block): each ``.at[].set``
        materializes a full pool copy, and this runs on the ring
        thread behind the in-flight chunk."""
        ex = self.executor
        row = self.pool.table[slot]
        blks = [int(row[j]) for j in range(self.pool.mapped_count[slot])
                if self.pool.ref[int(row[j])] == 1
                and int(row[j]) not in self.pool.by_block]
        if blks:
            idx = jnp.asarray(blks)
            ex.cache["k"] = ex.cache["k"].at[:, idx].set(0)
            ex.cache["v"] = ex.cache["v"].at[:, idx].set(0)
            if ex.quant:
                # reset the victims' scale planes to the all-zero-block
                # sentinel (paged.quantize_kv): zero codes x a stale
                # (possibly garbage) scale must still dequantize finite
                ex.cache["ks"] = ex.cache["ks"].at[:, idx].set(1.0)
                ex.cache["vs"] = ex.cache["vs"].at[:, idx].set(1.0)
        if ex.quant:
            # the lane's bf16 staging tail is private write-frontier
            # state — the poisoned rows may live ONLY there (an
            # incomplete block never reached the pool)
            ex.cache["kt"] = ex.cache["kt"].at[:, slot].set(0)
            ex.cache["vt"] = ex.cache["vt"].at[:, slot].set(0)
        if req is not None:
            # host tier (ISSUE 8): demoted payloads on the quarantined
            # lane's prompt chain are opaque host bytes that cannot be
            # re-verified — drop them so the prefix re-prefills clean
            self.pool.scrub_host_chain(req.prompt, ns=req.ns)

    def _consume(self, chunk_reqs, toks, counts=None, ok=None,
                 spec_raw=None) -> None:
        """Apply one finished chunk's tokens ([chunk, slots] on host).
        ``chunk_reqs`` pins each lane to the REQUEST the chunk was
        dispatched for: under pipelining a lane may have been evicted
        (and even re-admitted) since dispatch — such in-flight tokens
        belong to the old request and are dropped.

        ``counts`` (speculative mode, and every fused megastep
        boundary): per-lane count of VALID rows in ``toks``.  Lane i
        takes ``toks[:counts[i], i]``; None means every row is valid
        (plain 1-step chunk mode).  The budget/eos walk below is
        shared, so an eos landing mid-speculated-block truncates
        exactly like one landing mid-chunk — no tokens after eos ever
        reach the result or the stream.

        ``spec_raw`` (speculative mode only): per-lane DEVICE commit
        counts — the acceptance-telemetry numbers and the device
        position advance (a megastep boundary's ``counts`` may be
        eos/budget-truncated below it; a raw count of 0 marks a fused
        round the lane sat out, which must not feed the stats).

        ``ok`` (nan_check mode): per-lane isfinite verdict for this
        chunk — a False lane is QUARANTINED: its request fails
        (:class:`LaneQuarantined`), its blocks are scrubbed + freed,
        and no token of the poisoned chunk reaches any consumer.  The
        other lanes are attention-independent, so their streams stay
        bit-identical to a fault-free run."""
        now = time.monotonic()
        for i, req in chunk_reqs:
            if req is None or self.lane[i] is not req \
                    or req.done.is_set():
                continue
            if ok is not None and not bool(ok[i]):
                self.stats["quarantined_lanes"] += 1
                self.flightrec.record("nan_quarantine", lane=i,
                                      rid=req.request_id)
                if self.pool is not None:
                    self._scrub_lane_blocks(i, req)
                self._finish(req, LaneQuarantined(
                    f"lane {i} produced non-finite logits; request "
                    "failed, lane quarantined (ring unaffected)"))
                self._evict(i)
                continue
            self._materialize_first(i, req)
            n = toks.shape[0] if counts is None else int(counts[i])
            if spec_raw is not None:
                n_raw = int(spec_raw[i])
                if n_raw == 0:
                    continue    # fused round the (dead) lane sat out
                # the host fill-position mirror advances like the
                # device pos: the round's full commit count, even when
                # the eos/budget walk below stops earlier (the lane is
                # then evicted and its pos zeroed regardless)
                self._lane_pos[i] += n_raw
                self.stats["spec_drafted"] += self.spec_k
                self.stats["spec_accepted"] += max(0, n_raw - 1)
                req.drafted += self.spec_k
                req.accepted += max(0, n_raw - 1)
            else:
                # plain chunks advance chunk ticks while the lane runs
                # (a fused boundary's count is the device advance: full
                # chunks while live, 0 once dead)
                self._lane_pos[i] += n
            emitted = 0
            for t in toks[:n, i]:
                if self._lane_left[i] <= 0:
                    break
                self._lane_out[i].append(int(t))
                self._tokens_emitted += 1
                emitted += 1
                if req._stream is not None:
                    req._stream.put(int(t))
                self._lane_left[i] -= 1
                if req.eos is not None and int(t) == req.eos:
                    self._lane_left[i] = 0
            if emitted:
                # chunk-granular inter-token latency (ISSUE 15): the
                # consume boundary is the host's only per-token clock;
                # the mean gap over the chunk's tokens is observed once
                # per lane-consume (docs/observability.md notes the
                # granularity)
                if req.t_last_tok is not None and now > req.t_last_tok:
                    self.hist.itl.observe(
                        (now - req.t_last_tok) * 1e3 / emitted)
                req.t_last_tok = now
            if self._lane_left[i] <= 0:
                self._evict(i)

    def _consume_oldest(self, pending: List[tuple]) -> None:
        """Pop + apply the oldest in-flight dispatch (one chunk, or one
        megastep's N fused boundaries).  The blocking device->host
        completion wait sits under the watchdog: a wedged dispatch
        surfaces HERE on real chips (dispatches are async), and the
        monitor fails the waiting clients while this thread is still
        stuck.  The watchdog region scales with the dispatch's fused
        iteration count — a legal N-step wait is ~N x a 1-step one."""
        chunk_reqs, res, t0 = pending.pop(0)
        wd = self._watchdog
        if wd is not None:
            wd.begin(scale=res.n_steps)
        try:
            toks = np.asarray(res.toks)
            counts = None if res.counts is None else np.asarray(res.counts)
            ok = None if res.ok is None else np.asarray(res.ok)
            raw = None if res.raw is None else np.asarray(res.raw)
        finally:
            if wd is not None:
                wd.end()
        # per-iteration wall estimate for the deadline-tick budget:
        # dispatch->consume covers the pipeline wait too, so the EMA
        # overestimates — conservative (a lane freezes a little early
        # and resumes next dispatch, never late)
        per = (time.monotonic() - t0) / res.n_steps
        self._step_s_est = (per if not self._step_s_est
                            else 0.8 * self._step_s_est + 0.2 * per)
        # decode-phase spans (ISSUE 15): one span per consumed
        # dispatch per traced lane, covering dispatch -> completion
        # wait — megastep-granular by construction, and bounded by the
        # RequestTrace span cap on long generations
        if any(r is not None and r.trace is not None
               for _, r in chunk_reqs):
            t1 = time.monotonic()
            for _, r in chunk_reqs:
                if r is not None and r.trace is not None:
                    r.trace.add("decode_dispatch", t0, t1,
                                steps=res.n_steps)
        if self._fault is not None:
            return              # stall-failed chunks must not apply
        if res.n_steps == 1:
            if self.spec_k:
                self._consume(chunk_reqs, toks, counts=counts, ok=ok,
                              spec_raw=counts)
            else:
                self._consume(chunk_reqs, toks, ok=ok)
            return
        # fused megastep: apply the N boundaries in order — each is
        # exactly one 1-step consume, with the eos/budget walk the
        # device precomputed (counts) and the spec telemetry counts
        # (raw).  A lane evicted at boundary r drops out of rounds
        # r+1.. through the chunk_reqs identity guard.
        for r in range(res.n_steps):
            self._consume(chunk_reqs, toks[r], counts=counts[r],
                          ok=None if ok is None else ok[r],
                          spec_raw=None if raw is None else raw[r])

    def _pending_prefill_slots(self) -> set:
        """Lanes reserved but not yet decode-active."""
        return set(self._prefilling) | set(self._disagg_waiting)

    def _loop_body(self) -> None:
        # Up to ``pipeline_depth`` chunks in flight at all times (when
        # lanes are active): the host consumes chunk N's tokens — per-
        # token queue pushes, evict bookkeeping, and crucially the
        # device->host transfer latency — WHILE the device decodes
        # chunks N+1..N+depth.  Without this the ring serializes RTT
        # with compute; depth 1 was still RTT-bound on relayed chips
        # whose round-trip exceeds a chunk's device time (measured by
        # bench.py measure_ring_throughput), hence depth 2 by default.
        pending: List[tuple] = []   # [(chunk_reqs, toks, counts, ok)]
        while not self._stop.is_set():
            # re-bound every pass: a live swap (ISSUE 19) may have
            # replaced the executor object at the previous boundary
            ex = self.executor
            # ring-level fault (dispatch raised, or the watchdog
            # declared a stall): drop the in-flight chunks and self-heal
            # — rebuild everything device-side, re-admit queued work —
            # or die (legacy / budget exhausted) via the raise, which
            # the _loop wrapper turns into fail-everything + unhealthy
            if self._fault is not None:
                err, self._fault = self._fault, None
                pending.clear()
                if not self._heal(err):
                    raise err
                continue
            if self._draining:
                # drain: no new admissions; whatever is queued sheds
                # with ShuttingDown (clients retry another replica)
                self._shed_queue(ShuttingDown(
                    "server draining; retry another replica"))
            # fleet-level KV (ISSUE 12): adopted lanes land, migration
            # outcomes apply, peer prefix payloads import, and — when
            # draining with migration on — residents park + offer out
            self._pump_fleetkv(pending)
            if self._fault is not None:
                continue
            self._expire_deadlines()
            # cancelled lanes leave at the chunk boundary: the request
            # resolves with whatever tokens it has, the lane frees for
            # the next admission (serve.py calls cancel() when a stream
            # consumer disconnects mid-generation)
            for i, r in enumerate(self.lane):
                if r is not None and r._cancel:
                    self._evict(i)
            # parked lanes honor cancel too — a disconnect-abandoned
            # preempted request must not wait for a free lane to die.
            # Mid-migration lanes wait for the wire outcome first
            # (the _expire_deadlines rationale)
            for pk in list(self._parked):
                if pk.migrating:
                    continue
                if pk.req._cancel or pk.req.done.is_set():
                    self._parked.remove(pk)
                    if not pk.req.done.is_set():
                        pk.req.out = pk.req.prompt + pk.out
                        self._finish(pk.req)
            # disaggregated prefills that completed since last pass:
            # block-copy handoff + lane attach (cheap dispatches).
            # Gated on the ENGINE, not on _disagg_waiting: a result
            # posted for an evicted request must still be popped (and
            # dropped), or its full prefill-pool K/V snapshot stays
            # pinned in the results queue until the next cold admission
            if ex.prefill_exec is not None:
                try:
                    self._drain_handoffs()
                except Exception as e:
                    self._fault = e
                    continue
            # live weight swap (ISSUE 19): a posted swap fires at THE
            # quiesced boundary — every in-flight dispatch consumed,
            # no lane mid-prefill (admissions pause below while the
            # swap is pending, so prefills drain within a few passes).
            # The flip parks residents, swaps params/mesh, and the
            # parked lanes restore through the normal path right after.
            if self._swap_req is not None:
                if not self._prefilling and not self._disagg_waiting:
                    try:
                        while pending:
                            self._consume_oldest(pending)
                    except Exception as e:
                        self._fault = e
                        continue
                    if self._fault is None:
                        self._do_swap()
                    continue
                # lanes still prefilling: fall through (slices advance,
                # handoffs land); the swap fires once they finish
            # admit into free lanes: parked (preempted) lanes resume
            # ahead of queued work of the same class — they were
            # admitted first and already hold tokens — and queued work
            # pops in class-then-FIFO order (infer/qos.py).  Restores
            # run even while DRAINING: a parked lane is admitted work
            # the drain budget promises to finish.
            while any(r is None for r in self.lane):
                if self._swap_req is not None:
                    # swap pending: admissions/restores pause so the
                    # quiesce converges (restores would re-fill lanes
                    # the flip is about to park); both resume on the
                    # pass after _do_swap
                    break
                pk = self._best_parked()
                cq = (None if self._draining
                      else self._pending.peek_class())
                if pk is not None and (cq is None
                                       or pk.req.priority <= cq):
                    if not self._try_restore(pk):
                        break       # free blocks tight: retry next pass
                    continue
                if cq is None:
                    break
                try:
                    req = self._pending.get_nowait()
                except queue.Empty:
                    break
                if req._cancel:                 # cancelled while queued
                    req.out = list(req.prompt)
                    self._finish(req)
                    continue
                if (req.deadline is not None
                        and time.monotonic() >= req.deadline):
                    # expired while queued: prompt-only 504 partial —
                    # resolved, never silently dropped
                    req.deadline_exceeded = True
                    self.stats["deadline_exceeded"] += 1
                    req.out = list(req.prompt)
                    self._finish(req)
                    continue
                slot = self.lane.index(None)
                t_admit0 = time.monotonic()
                try:
                    self._admit(slot, req)
                    if req.trace is not None:
                        # host time of the admission dispatch (inline:
                        # the one compiled insert; chunked/disagg: the
                        # block map/reserve — the slices/handoff get
                        # their own spans)
                        req.trace.add("admit", t_admit0, slot=slot,
                                      mode=self.prefill_mode)
                except Exception as e:          # bad request: fail it only
                    self._finish(req, e)
                    self.lane[slot] = None
                    self._lane_pos[slot] = 0
                    self._prefilling.pop(slot, None)
                    self._disagg_waiting.pop(slot, None)
                    if self.pool is not None:
                        # admission may have mapped blocks before the
                        # dispatch failed — unmap them (no-op when the
                        # allocator itself rejected)
                        self.pool.retire(slot)
            # preemptive lane spill (ISSUE 10): more urgent work is
            # waiting and every lane is busy — quiesce the dispatch
            # pipeline (THE chunk boundary: device state and host
            # mirrors agree), re-pick the victim (a consumed chunk may
            # have evicted it, or freed a lane outright), spill it, and
            # re-run admission with the freed lane/blocks
            if self._preempt_victim() is not None:
                while pending:
                    try:
                        self._consume_oldest(pending)
                    except Exception as e:
                        self._fault = e
                        break
                if self._fault is None:
                    victim = self._preempt_victim()
                    if victim is not None:
                        self._preempt(victim)
                continue

            # chunked prefill: advance exactly ONE slice per iteration
            # (oldest admission first) — the interleave that bounds how
            # long resident decode lanes ever wait
            if self._prefilling:
                slot = min(self._prefilling,
                           key=lambda s: self._prefilling[s].seq)
                req = self._prefilling[slot].req
                wd = self._watchdog
                if wd is not None:
                    wd.begin()
                try:
                    self._advance_prefill(slot)
                except Exception as e:          # fail THIS request only
                    self._finish(req, e)
                    self._evict(slot)
                finally:
                    if wd is not None:
                        wd.end()

            prefill_pending = self._pending_prefill_slots()
            active_idx = [i for i, r in enumerate(self.lane)
                          if r is not None and i not in prefill_pending]
            if not active_idx:
                if pending:
                    try:
                        self._consume_oldest(pending)
                    except Exception as e:
                        self._fault = e
                    continue            # eviction may have freed lanes
                if prefill_pending:
                    # no decode work, but prefill in flight: spin the
                    # loop (chunked slices run back-to-back; disagg
                    # handoffs land as soon as they arrive)
                    self._wake.wait(timeout=0.002)
                    self._wake.clear()
                    continue
                self._wake.wait(timeout=0.1)
                self._wake.clear()
                continue
            self.stats["max_active"] = max(self.stats["max_active"],
                                           len(active_idx))

            n_mega = self.megastep
            advance = (self.spec_k + 1) if self.spec_k else self.chunk
            tbl_np = None
            if self.paged:
                # on-demand block mapping: grow each active lane's table
                # to cover this dispatch PLUS every chunk already in
                # flight for it (the host pos mirror lags dispatched-
                # but-unconsumed work; spec rounds advance a
                # data-dependent 1..K+1, so the bound is the worst case;
                # a fused megastep advances up to n_steps iterations,
                # capped by the lane's own remaining token budget — the
                # pipelining-aware projection extended to N steps).
                # An UNDERSIZED pool (num_blocks oversubscription) can
                # run dry mid-generation: only the lane that cannot
                # grow fails — evicting it (its request resolves with
                # the error) frees its blocks for the rest of the ring,
                # which must keep serving.
                for i in list(active_idx):
                    inflight = sum(
                        entry_res.n_steps
                        for chunk_reqs, entry_res, _ in pending
                        for j, r in chunk_reqs
                        if j == i and r is self.lane[i])
                    left_i = max(1, self._lane_left[i])
                    my_steps = (min(n_mega, left_i) if self.spec_k
                                else min(n_mega, -(-left_i // self.chunk)))
                    try:
                        self.pool.ensure(
                            i, self._lane_pos[i]
                            + (inflight + my_steps) * advance)
                    except self.executor._pg.NoFreeBlocks as e:
                        r = self.lane[i]
                        if r is not None and r.error is None:
                            r.error = e
                        self._evict(i)
                        active_idx.remove(i)
                if not active_idx:
                    continue        # every lane starved: retry the loop
                tbl_np = self.pool.table
                if prefill_pending:
                    # lanes mid-prefill hold REAL mapped blocks, but the
                    # chunk step writes every lane's (ignored) token at
                    # its zeroed pos — mask their rows to the trash
                    # block so an inactive write can never touch a
                    # block a prefill slice / handoff is filling
                    tbl_np = tbl_np.copy()
                    tbl_np[sorted(prefill_pending)] = \
                        self.executor._pg.TRASH_BLOCK
            # fill the plan (ISSUE 11): which lanes step, the table
            # snapshot, the adapter tail, the fused iteration count and
            # — N>1 — the per-lane continuation budgets the device
            # carries across boundaries (eos id, remaining tokens, and
            # the deadline-tick step budget)
            eos_v = left_v = steps_v = None
            if n_mega > 1:
                eos_v = np.full((self.slots,), -1, np.int32)
                left_v = np.zeros((self.slots,), np.int32)
                steps_v = np.full((self.slots,), n_mega, np.int32)
                now = time.monotonic()
                for i in active_idx:
                    r = self.lane[i]
                    if r.eos is not None:
                        eos_v[i] = int(r.eos)
                    # the device budget EXCLUDES the admission-sampled
                    # first token when it is still unmaterialized — the
                    # host consumes it out of the same max_new
                    left_v[i] = max(
                        0, self._lane_left[i]
                        - (1 if self._lane_first[i] is not None else 0))
                    if (self.paged and r.deadline is not None
                            and self._step_s_est > 0):
                        # deadline-tick budget: stop the lane at the
                        # boundary nearest its deadline instead of
                        # free-running the whole megastep past it.
                        # Paged only — a step-frozen lane resumes
                        # through the trash-redirect invariants the
                        # contiguous ring does not have.
                        remaining = r.deadline - now
                        steps_v[i] = max(1, min(
                            n_mega, int(remaining / self._step_s_est)))
            plan = X.ExecPlan(
                n_mega,
                [r is not None and i not in prefill_pending
                 for i, r in enumerate(self.lane)],
                table=tbl_np, lora=ex.lora_step_tail(),
                eos=eos_v, left=left_v, steps=steps_v)
            # async dispatch through THE plan replayer: returns device
            # futures immediately.  The watchdog brackets it (scaled by
            # the fused iteration count — a legal N-step dispatch is
            # ~N x a 1-step one) — a chaos-injected host-side hang (and
            # a synchronous-dispatch backend) wedges HERE — and any
            # raise becomes a ring fault handled at the loop top (fail
            # resident requests retriably, rebuild, back off).
            wd = self._watchdog
            if wd is not None:
                wd.begin(scale=n_mega)
            try:
                res = ex.replay(plan)
            except Exception as e:
                self._fault = e
                continue
            finally:
                if wd is not None:
                    wd.end()
            self.stats["chunks"] += 1
            # kick the device->host copy NOW, before the consume wait:
            # by consume time the tokens are already on the wire and
            # np.asarray is a cheap completion wait instead of a full
            # round-trip on the ring's critical path
            for dev in (res.toks, res.counts, res.ok, res.raw):
                try:
                    dev.copy_to_host_async()
                except AttributeError:  # None / interpret-mode ndarray
                    pass
            pending.append(([(i, self.lane[i]) for i in active_idx],
                            res, time.monotonic()))
            if len(pending) >= self.pipeline_depth:
                try:
                    self._consume_oldest(pending)
                except Exception as e:
                    self._fault = e
