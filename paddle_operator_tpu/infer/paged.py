"""Paged KV cache + radix prefix reuse for the serving ring.

The continuous-batching ring (infer/batcher.py) allocates one
contiguous ``[L, slots, H_kv, max_len, D]`` KV region per lane and
re-prefills every prompt from scratch: every resident lane pays
worst-case ``max_len`` HBM whether it holds 40 tokens or 2000, and a
fleet of requests sharing a 2k system prompt pays the same prefill over
and over (BENCH_r05: TTFT ~279 ms at prompt 128; decode throughput
2801 -> 1606 tok/s as cache_len grows 128 -> 2240).  This module is the
vLLM/SGLang answer (PagedAttention, Kwon et al. SOSP'23; RadixAttention,
Zheng et al. 2024) in this codebase's TPU-native terms:

- **Block pool** ``[L, num_blocks, H_kv, block_size, D]`` plus per-lane
  block tables ``[slots, max_blocks_per_lane]`` int32: lane KV is a
  list of pool blocks, allocated on demand as the lane's ``pos``
  crosses a block boundary and returned to a free list when the lane
  retires.  Pool block 0 is a reserved TRASH block — freed lanes and
  pad rows write there, so an in-flight pipelined chunk can never
  corrupt a block that was re-allocated under it.
- **Radix prefix cache** (host side): completed-prefill FULL blocks are
  keyed by a rolling hash chain of their token prefix.  A new request
  that hits a cached prefix maps those blocks READ-ONLY into its table
  (refcounted) and prefills only the suffix — a shared system prompt
  costs one prefill ever.  A partially-filled tail that matches the
  prefix of a cached block maps that block too (zero prefill beyond the
  mandatory last-token forward) and is **copied-on-write** before the
  lane's first write lands in it.
- **Kernel/fallback split**: on TPU the pallas decode kernel walks the
  block table through its *index map*
  (ops/decode_attention.py ``paged_decode_attention`` — blocks stream
  straight from their pool rows, dead tails skipped); the XLA einsum
  path gathers the lane view with one ``take`` per layer
  (:func:`_gather_lane_view`) — the copy the kernel exists to avoid,
  kept as the CPU/odd-shape fallback.
- **Exactness**: greedy token streams are bit-identical to the
  contiguous ring (the ``SERVE_PAGED=0`` fallback and parity oracle) —
  the gathered/paged view presents the same values at every attendable
  position and masked tail columns contribute exact zeros, the same
  invariant the contiguous ring's pad rows already rely on.  Pinned by
  tests/test_paged.py and the dryrun ``serve-paged`` line.

Mesh/TP: the pool shards over its kv-head axis exactly like the ring
cache (parallel/sharding.py kv_cache_sharding — the pool's axis 2);
tables and lengths replicate.

**Hierarchical cache (ISSUE 8)**: with ``host_cache_blocks > 0`` the
radix cache gains a HOST-RAM spill tier (:class:`HostCacheTier`,
SGLang-HiCache / CachedAttention style).  Eviction DEMOTES a
refcount-0 cached block — its exact device bytes (bf16 rows, or int8
codes + scales under SERVE_KV_QUANT=int8) fetched to pinned numpy —
instead of discarding it, keeping the radix node alive with a host
location (``_CacheEntry.block is None``).  Admission's radix walk then
classifies hits three ways: **HBM** (map read-only, as today),
**host** (reserve a device block at admission and upload the payload
via one batched donated promote jit — :func:`make_promote_blocks`,
whose bf16 path reuses the same ``scatter_prefill_blocks`` whole-block
writes the prefill path uses), or **cold** (prefill the suffix).
Demote/promote is a byte COPY, never a re-quantize, so a host hit is
bit-identical to an HBM hit; host RAM holds 10-100x more prefix blocks
than the pool at a transfer cost far below re-prefill.  The same
fetch/upload primitive backs :meth:`RingExecutor.spill_lane` /
``restore_lane`` — the lane-preemption building block ROADMAP items
4/5 consume.  ``host_cache_blocks=0`` (the default) leaves every code
path byte-identical to the pre-tier behavior.
"""

from __future__ import annotations

import functools
import heapq
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from paddle_operator_tpu.infer import decode as D
from paddle_operator_tpu.models.llama import LlamaConfig, rope_frequencies
from paddle_operator_tpu.utils.radixkey import chain_key as _radix_chain_key

TRASH_BLOCK = 0

# SERVE_KV_QUANT: "none" keeps the bf16 pool (the default AND the
# parity oracle — byte-identical to pre-quantization behavior); "int8"
# stores pool blocks as int8 codes + one f32 scale per (layer, block,
# kv-head), with dequant fused into the paged kernels
# (ops/decode_attention.py _paged_kernel_quant) / the gather view.
# The win is CAPACITY, not kernel latency: ~2x resident lanes per HBM
# byte, with a bounded per-step regression (the decode_attention.py
# header has the v5e physics; bench.py measure_quantized_pool the
# measured trade).
KV_QUANT_MODES = ("none", "int8")


def quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One pool block (…, bs, D) -> (int8 codes, f32 absmax/127 scale
    over the trailing two axes — per-(…, kv-head) when called on
    [L, 1, H, bs, D] tiles).  An all-zero block gets scale 1.0 so the
    dequant never divides by zero; round-half-even + clip to ±127 keeps
    the quantize→dequant→quantize roundtrip BIT-EXACT (the max element
    maps to ±127, so the recomputed scale is identical — pinned by
    tests/test_kvquant.py)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=(-2, -1))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    codes = jnp.clip(jnp.round(xf / scale[..., None, None]), -127, 127)
    return codes.astype(jnp.int8), scale


def dequantize_kv(codes: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """codes (…, bs, D) x scale (…) -> values in ``dtype``."""
    return (codes.astype(jnp.float32)
            * scale[..., None, None].astype(jnp.float32)).astype(dtype)


class NoFreeBlocks(RuntimeError):
    """The pool has no free block and no reclaimable (refcount-0)
    cached block — admission/growth must fail loudly rather than
    corrupt a mapped block."""


# ---------------------------------------------------------------------------
# Host side: block allocator + radix prefix cache
# ---------------------------------------------------------------------------


class _CacheEntry:
    __slots__ = ("key", "block", "chunk", "parent", "freed_at", "ns",
                 "stored")

    def __init__(self, key, block, chunk, parent, ns=0):
        self.key = key
        # device pool block id, or None while the entry's content lives
        # in the host tier (demoted — the radix node stays alive and a
        # later hit promotes it back into a fresh device block)
        self.block: Optional[int] = block
        self.chunk = chunk        # the bs tokens this block's KV encodes
        self.parent = parent      # chain key of the preceding block
        self.freed_at: Optional[int] = None   # LRU clock at refcount 0
        # radix namespace (0 = base model): the durable store abstains
        # for adapter namespaces (their chain salts are per-load
        # per-replica), so the spill hook needs to know
        self.ns = ns
        # durable-store residency (ISSUE 17): True while the entry's
        # bytes live ONLY in the KV store — no device block, no host
        # payload.  The radix walk treats it as a miss (it cannot be
        # served locally) but the node survives so a store fetch can
        # re-fill it through import_host_blocks.
        self.stored = False


def host_block_bytes(cfg: LlamaConfig, block_size: int,
                     quant: str = "none") -> int:
    """Host bytes one demoted block costs in the spill tier: K + V rows
    ([L, H_kv, bs, D] each — bf16 2 bytes/elem, or int8 codes plus the
    per-(layer, kv-head) f32 scale planes).  serve.py divides
    ``SERVE_HOST_CACHE_MB`` by this to size ``host_cache_blocks``."""
    rows = cfg.n_layers * cfg.n_kv_heads * block_size * cfg.head_dim
    if quant == "int8":
        return 2 * rows + 2 * cfg.n_layers * cfg.n_kv_heads * 4
    return 2 * rows * 2


class HostCacheTier:
    """The bounded host-RAM ring behind the radix cache: demoted block
    payloads (numpy dicts — ``k``/``v`` rows, plus ``ks``/``vs`` scale
    rows under int8), keyed by the entry's chain key, LRU within the
    tier.  ``put`` on a full tier drops the oldest payloads and returns
    their keys so the manager can retire the orphaned radix nodes; a
    promote ``pop`` moves the payload back out (demote/promote is a
    move, never a copy-with-two-owners — one canonical location per
    block keeps the accounting exact)."""

    def __init__(self, capacity: int) -> None:
        if capacity < 1:
            raise ValueError(f"host tier capacity must be >= 1 "
                             f"(got {capacity}); use host_cache_blocks=0 "
                             "to disable the tier")
        self.capacity = int(capacity)
        self._data: "Dict[Any, Dict[str, Any]]" = {}   # insertion = LRU age
        self.stats = {"demoted": 0, "promoted": 0, "overflow_drops": 0}
        # durable-store spill hook (ISSUE 17): called with
        # ``(key, payload)`` BEFORE an overflow drop deletes the
        # payload — the manager's last chance to persist bytes that
        # would otherwise be silently discarded.  None = pre-store
        # behavior, byte-identical.
        self.on_spill: Optional[Callable[[Any, Dict[str, Any]], None]] = None

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key) -> bool:
        return key in self._data

    def keys(self):
        return self._data.keys()

    def put(self, key, payload: Dict[str, Any],
            pinned: frozenset = frozenset()) -> List[Any]:
        """Store one demoted payload; returns the keys LRU-dropped to
        make room (the caller must drop their radix entries).

        ``pinned``: keys that must NOT be overflow-dropped — the
        current admission's host-hit chain (an eviction-triggered
        demotion mid-admit could otherwise drop the very payload the
        promotion is about to pop).  With every resident key pinned the
        tier temporarily exceeds its bound by at most the chain length;
        the manager trims back once the admission releases its pins."""
        dropped: List[Any] = []
        self._data.pop(key, None)
        while len(self._data) >= self.capacity:
            old = next((k for k in self._data if k not in pinned), None)
            if old is None:
                break                   # all pinned: exceed, trim later
            if self.on_spill is not None:
                self.on_spill(old, self._data[old])
            del self._data[old]
            dropped.append(old)
            self.stats["overflow_drops"] += 1
        self._data[key] = payload
        self.stats["demoted"] += 1
        return dropped

    def trim(self) -> List[Any]:
        """Drop oldest payloads until back within the bound (after an
        admission that pinned its chain released the pins)."""
        dropped: List[Any] = []
        while len(self._data) > self.capacity:
            old = next(iter(self._data))
            if self.on_spill is not None:
                self.on_spill(old, self._data[old])
            del self._data[old]
            dropped.append(old)
            self.stats["overflow_drops"] += 1
        return dropped

    def pop(self, key) -> Dict[str, Any]:
        """Remove + return a payload for promotion back to the pool."""
        payload = self._data.pop(key)
        self.stats["promoted"] += 1
        return payload

    def peek(self, key) -> Optional[Dict[str, Any]]:
        """Read a payload WITHOUT removing it — the peer prefix-fetch
        export (ISSUE 12): cross-replica fetch is a COPY (the wire
        serializer np.asarray's the values), so the one-canonical-
        location rule above still holds within this replica."""
        return self._data.get(key)

    def drop(self, key) -> None:
        self._data.pop(key, None)


class PagedCacheManager:
    """Host-side truth for the pool: free list, per-block lane
    refcounts, the per-slot block tables (numpy mirror shipped to the
    device with every dispatch), and the radix prefix cache.

    Block states partition the allocatable ids (1..num_blocks; 0 is the
    trash block):

    - **free**: on the free list;
    - **mapped**: referenced by >= 1 lane table (``ref[b] > 0``) —
      possibly ALSO cached (a published prompt block still in use);
    - **cached**: in the radix cache at refcount 0 — reclaimable, LRU
      by refcount-0 age when the free list runs dry.

    ``check_invariant()`` asserts the partition exactly
    (free + mapped + cached-only == num_blocks, refcounts == table
    occurrences) — the leak/double-free gate the tests run across
    admit/retire/cancel/CoW paths.
    """

    def __init__(self, slots: int, max_len: int, block_size: int,
                 num_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 host_cache_blocks: int = 0) -> None:
        alloc = D.cache_alloc_len(max_len)
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1 (got {block_size})")
        self.bs = int(block_size)
        self.max_blocks = -(-alloc // self.bs)          # per-lane table width
        self.view_len = self.max_blocks * self.bs       # gathered lane view
        # default pool = contiguous-ring HBM parity: every lane can still
        # reach max_len; the paging win is that lanes that DON'T leave
        # the rest free (for more lanes, or for the prefix cache)
        self.num_blocks = int(num_blocks or slots * self.max_blocks)
        if self.num_blocks < self.max_blocks:
            raise ValueError(
                f"num_blocks ({self.num_blocks}) smaller than one lane's "
                f"worst case ({self.max_blocks} blocks)")
        self.total = self.num_blocks + 1                # + trash block 0
        self.free: List[int] = list(range(self.total - 1, 0, -1))
        self.ref = np.zeros((self.total,), np.int64)
        self.table = np.zeros((slots, self.max_blocks), np.int32)
        self.mapped_count = [0] * slots
        self.prefix_cache = bool(prefix_cache)
        self.entries: Dict[Any, _CacheEntry] = {}       # chain key -> entry
        self.by_block: Dict[int, Any] = {}              # block -> chain key
        self.children: Dict[Any, set] = {}              # parent key -> keys
        self._tick = 0
        # age-ordered refcount-0 index (the satellite O(log n) eviction
        # fix): a lazy-deletion min-heap of (freed_at, seq, key) pushed
        # at every ref -> 0 transition; pop-time validation discards
        # items whose entry was since re-mapped, dropped, or demoted.
        # Selection semantics are IDENTICAL to the old full scan
        # (:meth:`_select_victim_scan`, kept as the regression oracle).
        self._ref0_heap: List[Tuple[int, int, Any]] = []
        self._heap_seq = 0
        # host spill tier (ISSUE 8): demoted refcount-0 cached blocks
        # keep their radix node alive with their bytes in host RAM; the
        # executor wires ``demote_fetch`` (block id -> numpy payload)
        # after construction.  0 blocks = tier off = pre-tier behavior.
        self.host = (HostCacheTier(host_cache_blocks)
                     if host_cache_blocks else None)
        self.demote_fetch: Optional[Callable[[int], Dict[str, Any]]] = None
        # durable prefix store (ISSUE 17): the persistent tier below
        # the host tier — wired via attach_store().  None (the
        # default) keeps every path byte-identical to pre-store
        # behavior, including the silent overflow discard.
        self.store = None
        # the in-flight admission's host-hit chain keys: shielded from
        # tier overflow drops while the admit that will pop them runs
        # (HostCacheTier.put pinned=)
        self._pinned_host_keys: frozenset = frozenset()
        # promotions ALLOCATED by the current admit() and not yet
        # uploaded: [(dst_block, payload, key)] — the scheduler drains
        # them (take_promotions) into ONE batched donated device upload
        # BEFORE the CoW copies / admission insert it dispatches next
        self._pending_promotes: List[Tuple[int, Dict[str, Any], Any]] = []
        # chaos hook (infer/chaos.py pool_oom): the next N allocations
        # raise NoFreeBlocks regardless of free-list state, so the
        # starvation/eviction paths are exercisable deterministically
        # without actually draining the pool
        self.chaos_fail_allocs = 0
        self.stats = {
            "prefix_lookup_tokens": 0, "prefix_hit_tokens": 0,
            "prefix_lookups": 0, "prefix_full_hits": 0,
            "cow_copies": 0, "cache_evictions": 0, "blocks_hwm": 0,
            # host-tier accounting: blocks demoted to / promoted from
            # host RAM, and the prefix-hit tokens served out of host
            # payloads (the hostHitRate numerator)
            "host_demotions": 0, "host_promotions": 0,
            "host_hit_tokens": 0,
            # durable store (ISSUE 17): payloads offered to the store
            # writer on host-tier overflow (the previously-silent
            # discards), and store-fetched blocks re-filled into
            # store-resident radix nodes
            "store_spills": 0, "store_refills": 0,
            # fleet-level KV (ISSUE 12): demoted blocks imported from a
            # PEER replica's host tier (they promote through the normal
            # host-hit path on the next admission)
            "peer_blocks_imported": 0,
        }

    # -- allocation --------------------------------------------------------

    def blocks_free(self) -> int:
        return len(self.free)

    def blocks_cached(self) -> int:
        """DEVICE-resident cached blocks currently reclaimable
        (refcount 0); host-demoted entries hold no pool block."""
        return sum(1 for e in self.entries.values()
                   if e.block is not None and self.ref[e.block] == 0)

    def host_blocks(self) -> int:
        """Blocks currently resident in the host spill tier."""
        return len(self.host) if self.host is not None else 0

    def host_hit_rate(self) -> float:
        """Share of looked-up prefix tokens served from HOST payloads
        (the promote path) — the ``hostHitRate`` status key."""
        lk = self.stats["prefix_lookup_tokens"]
        return (round(self.stats["host_hit_tokens"] / lk, 4)
                if lk else 0.0)

    def _alloc_one(self) -> int:
        if self.chaos_fail_allocs > 0:
            self.chaos_fail_allocs -= 1
            raise NoFreeBlocks("chaos: injected pool OOM")
        if not self.free:
            self._evict_lru()
        blk = self.free.pop()
        used = self.num_blocks - len(self.free)
        self.stats["blocks_hwm"] = max(self.stats["blocks_hwm"], used)
        return blk

    def _promoting_blocks(self) -> set:
        """Blocks reserved by the CURRENT admission's promotions whose
        uploads have not dispatched yet.  They must never be eviction
        victims: a CoW in the same admit can drop such a block to
        refcount 0, and demoting it would fetch device bytes the
        pending upload has not written (garbage host payload) while the
        upload later scatters into whoever re-allocated the block."""
        return {dst for dst, _, _ in self._pending_promotes}

    def _select_victim_scan(self) -> Optional[_CacheEntry]:
        """The ORIGINAL O(n·children) victim scan, kept verbatim as the
        regression oracle for :meth:`_select_victim`: prefer leaves (no
        children — evicting an inner node only strands its subtree for
        later aging), oldest refcount-0 age among them."""
        promoting = self._promoting_blocks()
        victims = [e for e in self.entries.values()
                   if e.block is not None and self.ref[e.block] == 0
                   and e.block not in promoting]
        if not victims:
            return None
        leaves = [e for e in victims
                  if not self.children.get(e.key)]
        pool = leaves or victims
        return min(pool, key=lambda e: (e.freed_at
                                        if e.freed_at is not None else 0))

    def _heap_push(self, e: _CacheEntry) -> None:
        self._heap_seq += 1
        heapq.heappush(self._ref0_heap,
                       (e.freed_at if e.freed_at is not None else 0,
                        self._heap_seq, e.key))

    def _select_victim(self) -> Optional[_CacheEntry]:
        """Heap-backed victim selection, O(log n) amortized: pop the
        refcount-0 index in age order, discarding stale items (entry
        re-mapped, dropped, or demoted since push — ``freed_at`` is the
        version stamp) and setting valid NON-leaves aside; the first
        valid leaf wins (it is the min-age leaf, since the heap orders
        ALL ref-0 entries by age).  A treeful of inner nodes with no
        leaf at all falls back to the oldest set-aside entry — exactly
        the scan's semantics, pinned by the victim-parity regression
        test."""
        promoting = self._promoting_blocks()
        stash: List[Tuple[int, int, Any]] = []
        defer: List[Tuple[int, int, Any]] = []
        victim: Optional[_CacheEntry] = None
        while self._ref0_heap:
            fa, seq, key = heapq.heappop(self._ref0_heap)
            e = self.entries.get(key)
            if (e is None or e.block is None
                    or self.ref[e.block] != 0
                    or (e.freed_at if e.freed_at is not None else 0) != fa):
                continue                     # stale: lazily deleted
            if e.block in promoting:
                defer.append((fa, seq, key))  # NOT selectable this round
                continue
            if self.children.get(key):
                stash.append((fa, seq, key))  # valid, but not a leaf
                continue
            victim = e
            break
        if victim is None and stash:
            fa, seq, key = stash.pop(0)       # oldest valid non-leaf
            victim = self.entries[key]
        for item in stash:                    # survivors stay indexed
            heapq.heappush(self._ref0_heap, item)
        for item in defer:                    # evictable once uploaded
            heapq.heappush(self._ref0_heap, item)
        return victim

    def _evict_lru(self) -> None:
        """Reclaim ONE cached refcount-0 block.  With the host tier
        enabled the victim DEMOTES — its exact device bytes move to
        host RAM and the radix node stays alive at a host location
        (``block = None``), so a later admission promotes it back
        instead of re-prefilling; without the tier (the default) the
        entry is discarded exactly as before."""
        victim = self._select_victim()
        if victim is None:
            raise NoFreeBlocks(
                f"all {self.num_blocks} pool blocks are lane-mapped; "
                "grow num_blocks or retire lanes first")
        blk = victim.block
        if self.host is not None and self.demote_fetch is not None:
            payload = self.demote_fetch(blk)
            self.by_block.pop(blk, None)
            victim.block = None
            for key in self.host.put(victim.key, payload,
                                     pinned=self._pinned_host_keys):
                self._drop_host_entry(key)
            self.stats["host_demotions"] += 1
        else:
            self._drop_entry(victim)
        self.free.append(blk)
        self.stats["cache_evictions"] += 1

    def _drop_host_entry(self, key) -> None:
        """A host-tier payload aged out (LRU overflow).  Without a
        durable store: retire its radix node — the prefix is now truly
        cold again (same unlink as a device drop; ``by_block.pop(None)``
        is a no-op for host entries, whose keys there are block ints).
        With the store attached (ISSUE 17) and a base-namespace entry,
        the payload was just offered to the store writer (the tier's
        ``on_spill`` hook fires before the delete) — the node SURVIVES
        at ``block=None, stored=True`` so a later walk can re-probe the
        store instead of re-prefilling."""
        e = self.entries.get(key)
        if e is None:
            return
        if self.store is not None and not e.ns:
            e.stored = True
            return
        self._drop_entry(e)

    def attach_store(self, store) -> None:
        """Wire the durable prefix store (infer/kvstore.KVBlockStore)
        below the host tier: overflow drops persist instead of
        discarding, and their radix nodes survive store-resident.
        Requires the host tier (there is nothing to spill without
        it)."""
        if self.host is None:
            raise ValueError("KV store requires the host cache tier "
                             "(host_cache_blocks > 0)")
        self.store = store
        self.host.on_spill = self._spill_to_store

    def _spill_to_store(self, key, payload: Dict[str, Any]) -> None:
        """HostCacheTier overflow hook: offer the about-to-be-dropped
        payload to the store's background writer (bounded drop-oldest
        queue — never blocks the ring thread).  Adapter namespaces
        abstain: their chain salts are per-load per-replica, so a
        persisted entry could never be re-keyed."""
        e = self.entries.get(key)
        if e is None or e.ns or self.store is None:
            return
        self.store.offer(key, e.chunk, payload, ns=0)
        self.stats["store_spills"] += 1

    def _servable(self, e: _CacheEntry) -> bool:
        """Can this radix node serve a hit RIGHT NOW — device-resident,
        or host-resident with its payload actually in the tier?  A
        store-resident node (``stored=True``, payload on disk only)
        cannot: admit would have nothing to promote.  With the store
        off every ``block=None`` entry is in the tier by the
        demoted==host-keys invariant, so this is byte-identical to the
        pre-store walk."""
        if e.block is not None:
            return True
        return self.host is not None and e.key in self.host

    def _drop_entry(self, e: _CacheEntry) -> None:
        del self.entries[e.key]
        self.by_block.pop(e.block, None)
        kids = self.children.get(e.parent)
        if kids is not None:
            kids.discard(e.key)
            if not kids:
                del self.children[e.parent]

    def _release_block(self, blk: int) -> None:
        """One lane unmaps ``blk``: decref; at 0 it either becomes a
        reclaimable cached block (stamped with its LRU age) or goes
        straight back to the free list."""
        if blk == TRASH_BLOCK:
            return
        if self.ref[blk] <= 0:
            raise AssertionError(f"double free of pool block {blk}")
        self.ref[blk] -= 1
        if self.ref[blk] == 0:
            key = self.by_block.get(blk)
            if key is not None:
                self._tick += 1
                e = self.entries[key]
                e.freed_at = self._tick
                self._heap_push(e)      # enters the ref-0 age index
            else:
                self.free.append(blk)

    # -- radix cache -------------------------------------------------------

    @staticmethod
    def _chain_key(parent, chunk: Tuple[int, ...]):
        """Rolling key for one full block: hash-chained on the parent
        key so equal chunks under different prefixes never collide; the
        stored entry keeps the raw chunk, so a (vanishingly unlikely)
        hash collision is caught by the equality check in lookup.

        The definition lives in utils/radixkey.py (jax-free) because
        the fleet router keys its consistent-hash affinity on the SAME
        chain — one function, so router placement and replica radix
        hits cannot drift apart."""
        return _radix_chain_key(parent, chunk)

    @staticmethod
    def _root_key(ns: int):
        """Chain root for namespace ``ns`` (many-adapter serving,
        ISSUE 10): 0 is the unsalted legacy chain — byte-identical
        keying for base-model traffic — while a non-zero namespace
        (AdapterRegistry.ns_of, a fresh token per adapter LOAD) starts
        the chain at a salted key.  An adapter changes wk/wv, so its
        prefix KV is a different tensor than the base model's for the
        SAME tokens; namespacing makes cross-adapter hits impossible by
        construction, including after an evict+reload reuses a slot.
        Ints only (no str) so the value stays deterministic across
        processes, like the rest of utils/radixkey.py."""
        if not ns:
            return None
        return _radix_chain_key(0x5A17ED, (int(ns),))

    def _lookup(self, tokens: Tuple[int, ...], ns: int = 0):
        """Walk the cached chain: full-block hits, then at most one
        partial-tail hit (a cached child block whose chunk STARTS with
        the remaining < bs tokens — mappable read-only, CoW'd before
        the lane's first write into it).  Returns
        (entries, full_hit_tokens, used_partial) — each entry either
        DEVICE-resident (``block`` set: map read-only, as always) or
        HOST-resident (``block is None``: admit promotes it into a
        fresh device block before mapping)."""
        bs = self.bs
        hits: List[_CacheEntry] = []
        key = self._root_key(ns)
        j = 0
        n = len(tokens)
        while (j + 1) * bs <= n:
            chunk = tokens[j * bs:(j + 1) * bs]
            k2 = self._chain_key(key, chunk)
            e = self.entries.get(k2)
            if e is None or e.chunk != chunk or not self._servable(e):
                break
            hits.append(e)
            key = k2
            j += 1
        hit = j * bs
        partial = False
        rem = tokens[j * bs:]
        if rem and len(rem) < bs:
            for ck in self.children.get(key, ()):
                e = self.entries[ck]
                if e.chunk[:len(rem)] == rem and self._servable(e):
                    hits.append(e)
                    hit += len(rem)
                    partial = True
                    break
        return hits, hit, partial

    def take_promotions(self) -> List[Tuple[int, Dict[str, Any], Any]]:
        """Drain the promotions the last ``admit`` allocated:
        [(dst_block, host_payload, chain_key)].  The scheduler turns
        the batch into ONE donated device upload
        (RingExecutor.dispatch_promotions) dispatched BEFORE the CoW
        copies and the admission insert, so every later read on the
        stream observes the promoted bytes."""
        out, self._pending_promotes = self._pending_promotes, []
        return out

    # -- lane lifecycle ----------------------------------------------------

    def admit(self, slot: int, prompt,
              max_suffix: Optional[int] = None, ns: int = 0
              ) -> Tuple[int, List[Tuple[int, int]]]:
        """Map blocks for a new lane: radix hits read-only (refcounted),
        copy-on-write for any shared block the suffix/decode writes will
        land in, fresh blocks for the rest of the prompt.  Returns
        ``(hit_len, cow)`` — the usable prefix length (the suffix
        ``prompt[hit_len:]`` still needs a forward; always >= 1 token,
        since the first sampled token needs the last prompt position's
        logits) and the [(src, dst)] block copies the caller must run
        BEFORE the admission dispatch.

        ``max_suffix``: a hit whose remaining suffix exceeds it is NOT
        taken (fresh blocks throughout, hit_len 0) — the caller's
        suffix forward may be worse than a cold prefill past some
        width, and declining the hit up front means cached blocks are
        never mapped into a lane that will scatter over them."""
        tokens = tuple(int(t) for t in prompt)
        n = len(tokens)
        bs = self.bs
        if self.mapped_count[slot]:
            raise AssertionError(f"slot {slot} still holds blocks")
        if self.prefix_cache:
            hit_entries, hit_full, _partial = self._lookup(tokens, ns)
            self.stats["prefix_lookups"] += 1
            self.stats["prefix_lookup_tokens"] += n
            if (max_suffix is not None
                    and n - min(hit_full, n - 1) > max_suffix):
                hit_entries, hit_full = [], 0
        else:
            hit_entries, hit_full = [], 0
        hit_len = min(hit_full, n - 1)
        self.stats["prefix_hit_tokens"] += hit_len
        if hit_len and hit_len == n - 1 and hit_full >= n:
            self.stats["prefix_full_hits"] += 1

        row = self.table[slot]
        host_tokens_this_admit = 0
        # pin this admission's WHOLE hit chain: a demotion fired by one
        # of our own allocations must never overflow-drop a payload we
        # are about to pop (the tier may exceed its bound by the chain
        # length until the finally trims it back).  Device-resident hit
        # entries pin too — an entry not yet mapped by this loop is
        # refcount-0 and can itself be demoted mid-admit, at which
        # point its turn takes the promote branch and pops its payload.
        self._pinned_host_keys = frozenset(e.key for e in hit_entries)
        try:
            for j, e in enumerate(hit_entries):
                if e.block is None:
                    # HOST hit: reserve a device block NOW (so the
                    # whole admission either fits or fails up front)
                    # and queue the byte-exact upload — the scheduler
                    # dispatches the batch before the insert.  The
                    # entry re-anchors device-side (promote-on-hit):
                    # later admissions hit it in HBM again.
                    dst = self._alloc_one()
                    payload = self.host.pop(e.key)
                    e.block = dst
                    self.by_block[dst] = e.key
                    self._pending_promotes.append((dst, payload, e.key))
                    self.stats["host_promotions"] += 1
                    tok_inc = min(bs, max(0, hit_len - j * bs))
                    self.stats["host_hit_tokens"] += tok_inc
                    host_tokens_this_admit += tok_inc
                blk = e.block
                row[j] = blk
                self.ref[blk] += 1
                self.mapped_count[slot] = j + 1
            # CoW: every shared block at/after the first written block
            # (index hit_len // bs) gets a private copy — by
            # construction that is at most the last hit block
            cow: List[Tuple[int, int]] = []
            first_write_blk = hit_len // bs
            for j in range(first_write_blk, len(hit_entries)):
                src = int(row[j])
                dst = self._alloc_one()
                self.ref[dst] += 1
                self._release_block(src)
                row[j] = dst
                cow.append((src, dst))
                self.stats["cow_copies"] += 1
            # fresh blocks for the rest of the prompt
            need = -(-n // bs)
            while self.mapped_count[slot] < need:
                blk = self._alloc_one()
                self.ref[blk] += 1
                row[self.mapped_count[slot]] = blk
                self.mapped_count[slot] += 1
        except NoFreeBlocks:
            # roll back promotions this admit allocated: their uploads
            # never dispatched, so the re-anchored entries would map
            # GARBAGE device blocks as cached prefix — move each back
            # to the host tier (there is room: we just popped them) and
            # let retire() below free the reserved dst blocks
            for dst, payload, key in self._pending_promotes:
                e = self.entries.get(key)
                if e is not None:
                    for k2 in self.host.put(key, payload,
                                            pinned=self._pinned_host_keys):
                        self._drop_host_entry(k2)
                    e.block = None
                self.by_block.pop(dst, None)
                # a promoted block the CoW already released sits at
                # refcount 0 with no radix anchor left — retire() below
                # can't reach it (the lane maps its CoW copy instead),
                # so return it to the free list here or it leaks out of
                # the free/mapped/cached partition entirely
                if self.ref[dst] == 0 and dst not in self.free:
                    self.free.append(dst)
                self.stats["host_promotions"] -= 1
            self._pending_promotes = []
            # the host-served token accounting rolls back with them: a
            # failed admission served nothing, and hostHitRate must not
            # drift upward on NoFreeBlocks churn
            self.stats["host_hit_tokens"] -= host_tokens_this_admit
            self.retire(slot)
            raise
        finally:
            if self.host is not None:
                self._pinned_host_keys = frozenset()
                for key in self.host.trim():    # back within the bound
                    self._drop_host_entry(key)
        return hit_len, cow

    def publish(self, slot: int, prompt, ns: int = 0) -> None:
        """Register the lane's FULL prompt blocks in the radix cache
        (called once the admission prefill is dispatched — later
        readers are later dispatches on the same stream, so they
        observe the written blocks).  Blocks already cached under the
        same key are left alone (a racing lane prefilled the same
        prefix — its copy stays canonical)."""
        if not self.prefix_cache:
            return
        tokens = tuple(int(t) for t in prompt)
        bs = self.bs
        key = self._root_key(ns)
        for j in range(len(tokens) // bs):
            chunk = tokens[j * bs:(j + 1) * bs]
            k2 = self._chain_key(key, chunk)
            e = self.entries.get(k2)
            if e is None:
                blk = int(self.table[slot, j])
                if blk != TRASH_BLOCK and blk not in self.by_block:
                    self.entries[k2] = _CacheEntry(k2, blk, chunk, key,
                                                   ns=ns)
                    self.by_block[blk] = k2
                    self.children.setdefault(key, set()).add(k2)
            elif e.block is None and e.stored and e.chunk == chunk:
                # store-resident node whose prefix this lane just
                # re-prefilled: re-anchor it device-side (the lane's
                # block holds exactly this chunk's KV) — otherwise the
                # walk keeps breaking at the store-only node even
                # though the bytes were just computed
                blk = int(self.table[slot, j])
                if blk != TRASH_BLOCK and blk not in self.by_block:
                    e.block = blk
                    e.stored = False
                    self.by_block[blk] = k2
            key = k2

    def ensure(self, slot: int, pos_needed: int) -> None:
        """Grow the lane's table so blocks cover positions
        [0, pos_needed) — the on-demand allocation the decode loop runs
        before each dispatch as ``pos`` approaches a block boundary.
        Capped at the lane view; overshoot rows (pipelined chunks past
        the budget) self-route to the trash block / the lane's own last
        block and are discarded with the lane."""
        need = min(-(-int(pos_needed) // self.bs), self.max_blocks)
        row = self.table[slot]
        while self.mapped_count[slot] < need:
            blk = self._alloc_one()
            self.ref[blk] += 1
            row[self.mapped_count[slot]] = blk
            self.mapped_count[slot] += 1

    def retire(self, slot: int) -> None:
        """Lane done (eos/budget/cancel/error): unmap every block —
        published ones become reclaimable cache, private ones go back
        to the free list — and zero the table row so any in-flight
        pipelined chunk writes land in the trash block."""
        row = self.table[slot]
        for j in range(self.mapped_count[slot]):
            self._release_block(int(row[j]))
        row[:] = TRASH_BLOCK
        self.mapped_count[slot] = 0

    def scrub_host_chain(self, prompt, ns: int = 0) -> int:
        """Quarantine hygiene (ISSUE 8): drop every HOST-tier payload
        on ``prompt``'s radix chain.  Device-side the quarantine scrub
        can prove published blocks clean (the lane only ever writes
        private CoW'd copies), but a demoted payload is an opaque host
        byte blob that can no longer be re-verified against the pool —
        after a NaN quarantine the conservative move is to forget the
        lane's chain from the tier and let the prefix re-prefill.
        With the durable store attached (ISSUE 17) the same argument
        applies one tier down: every store copy along the chain is
        deleted and store-resident nodes are retired, never marked
        ``stored`` — a quarantined chain must not resurrect from disk.
        Returns the number of payloads dropped."""
        if self.host is None and self.store is None:
            return 0
        tokens = tuple(int(t) for t in prompt)
        key = self._root_key(ns)
        dropped = 0
        for j in range(len(tokens) // self.bs):
            chunk = tokens[j * self.bs:(j + 1) * self.bs]
            key = self._chain_key(key, chunk)
            if self.store is not None and not ns:
                # the store may hold a copy of ANY chain block (it
                # persists overflow drops, device residency since is
                # irrelevant) — delete unconditionally along the chain
                self.store.delete(key, ns=0)
            e = self.entries.get(key)
            if e is None:
                continue    # gap in the chain: deeper entries may remain
            if e.block is None:
                if self.host is not None:
                    self.host.drop(key)
                self._drop_entry(e)
                dropped += 1
        return dropped

    # -- fleet-level KV: peer prefix export/import (ISSUE 12) --------------

    def host_evictions(self) -> int:
        """Cumulative dropped-oldest tier overflows — previously
        invisible (the ``tpujob_serve_host_cache_evictions_total``
        gauge)."""
        return (self.host.stats["overflow_drops"]
                if self.host is not None else 0)

    def export_host_chain(self, prompt, ns: int = 0):
        """The peer-fetch EXPORT: walk ``prompt``'s radix chain and
        collect every HOST-resident (demoted) full block along it —
        ``(chunks, block_idx, payloads)`` where ``chunks`` lists EVERY
        full block's tokens from the chain start (the importer needs
        them to recompute parent keys) and ``block_idx``/``payloads``
        the demoted subset that actually travels.  Device-resident
        blocks are skipped but the walk continues: the importer may
        already hold the head locally, in which case a host-resident
        tail alone completes its chain.  Only demoted payloads ship —
        device blocks would need a ring-thread fetch against buffers
        the resident step donates, and host bytes are already exactly
        what the importer's promote path uploads.

        Called from an HTTP handler thread while the ring thread
        mutates the radix — callers must treat any exception as
        "nothing to export" (the serve handler returns 204)."""
        if self.host is None:
            return [], [], []
        tokens = tuple(int(t) for t in prompt)
        bs = self.bs
        chunks = []
        block_idx = []
        payloads = []
        key = self._root_key(ns)
        for j in range(len(tokens) // bs):
            chunk = tokens[j * bs:(j + 1) * bs]
            key = self._chain_key(key, chunk)
            e = self.entries.get(key)
            if e is None or e.chunk != chunk:
                break               # chain cold from here on
            chunks.append(list(chunk))
            if e.block is None:
                payload = self.host.peek(key)
                if payload is not None:
                    block_idx.append(j)
                    payloads.append(payload)
        return chunks, block_idx, payloads

    def import_host_blocks(self, chunks, block_idx, payloads,
                           ns: int = 0) -> int:
        """The peer-fetch IMPORT (ring thread only): insert fetched
        demoted payloads into OUR host tier + radix, exactly as if this
        replica had demoted them — the next admission's radix walk
        host-hits them and promotes through the normal batched upload
        (byte-exact, the ISSUE 8 path).  Keys already present (device-
        or host-resident) are left alone; tier overflow drops the
        oldest as usual.  Returns the number of blocks imported."""
        if self.host is None or not self.prefix_cache:
            return 0
        bs = self.bs
        keys = []
        key = self._root_key(ns)
        for chunk in chunks:
            if len(chunk) != bs:
                return 0            # malformed: full blocks only
            key = self._chain_key(key, tuple(int(t) for t in chunk))
            keys.append(key)
        imported = 0
        for j, payload in zip(block_idx, payloads):
            if not 0 <= j < len(keys):
                continue
            existing = self.entries.get(keys[j])
            if existing is not None:
                # store-resident node (ISSUE 17): its bytes live only
                # on disk — REFILL the host tier so the next admission
                # host-hits it; any other resident entry is left alone
                if (existing.block is None and existing.stored
                        and existing.chunk == tuple(
                            int(t) for t in chunks[j])):
                    for dropped in self.host.put(
                            keys[j], payload,
                            pinned=self._pinned_host_keys):
                        self._drop_host_entry(dropped)
                    existing.stored = False
                    self.stats["store_refills"] += 1
                    imported += 1
                continue
            if j and keys[j - 1] not in self.entries:
                # _lookup walks the chain from the root and stops at
                # the first missing key: a block whose parent is
                # present neither locally nor in this import would be
                # UNREACHABLE — stored host bytes no admission could
                # ever hit.  (Earlier imported blocks are already in
                # self.entries, so contiguous imports chain through.)
                continue
            parent = keys[j - 1] if j else self._root_key(ns)
            chunk = tuple(int(t) for t in chunks[j])
            e = _CacheEntry(keys[j], None, chunk, parent, ns=ns)
            self.entries[keys[j]] = e
            self.children.setdefault(parent, set()).add(keys[j])
            for dropped in self.host.put(keys[j], payload,
                                         pinned=self._pinned_host_keys):
                self._drop_host_entry(dropped)
            imported += 1
        self.stats["peer_blocks_imported"] += imported
        return imported

    def device_table(self) -> jax.Array:
        return jnp.asarray(self.table)

    # -- accounting --------------------------------------------------------

    def hit_rate(self) -> float:
        lk = self.stats["prefix_lookup_tokens"]
        return round(self.stats["prefix_hit_tokens"] / lk, 4) if lk else 0.0

    def check_invariant(self) -> None:
        """free + mapped + cached-only == num_blocks, with refcounts
        exactly equal to table occurrences and no id in two states."""
        free = set(self.free)
        assert len(free) == len(self.free), "free list holds duplicates"
        assert TRASH_BLOCK not in free, "trash block leaked to free list"
        occurrences: Dict[int, int] = {}
        for row in self.table:
            for blk in row:
                if blk != TRASH_BLOCK:
                    occurrences[int(blk)] = occurrences.get(int(blk), 0) + 1
        for blk, cnt in occurrences.items():
            assert self.ref[blk] == cnt, \
                f"block {blk}: ref {self.ref[blk]} != {cnt} table uses"
            assert blk not in free, f"block {blk} mapped AND free"
        mapped = set(occurrences)
        for blk in range(1, self.total):
            if self.ref[blk] and blk not in mapped:
                raise AssertionError(f"block {blk} refcounted but unmapped")
        cached_only = {e.block for e in self.entries.values()
                       if e.block is not None and self.ref[e.block] == 0}
        assert not (cached_only & free), "cached block on the free list"
        assert len(free) + len(mapped) + len(cached_only) \
            == self.num_blocks, (
            f"pool partition broken: {len(free)} free + {len(mapped)} "
            f"mapped + {len(cached_only)} cached != {self.num_blocks}")
        # host-tier accounting (ISSUE 8): every demoted entry's payload
        # is in the tier, every tier payload has a live radix node, the
        # tier respects its bound, and nothing is promoting outside an
        # admission (take_promotions drains before the dispatch) — so
        # free + mapped + cached + promoting == num_blocks holds with
        # promoting == len(_pending_promotes) counted inside `mapped`
        # (promoted blocks are lane-refcounted the moment they are
        # reserved)
        # store-resident nodes (ISSUE 17) hold NO local payload: their
        # bytes are on disk only, so they are excluded from the
        # demoted==host-keys identity and must be disjoint from the
        # tier.  With the store off no entry can be stored, so the
        # original identity is checked unchanged.
        stored_keys = {e.key for e in self.entries.values()
                       if e.block is None and e.stored}
        if self.store is None:
            assert not stored_keys, \
                "store-resident entry without a KV store"
        demoted = {e.key for e in self.entries.values()
                   if e.block is None and not e.stored}
        if self.host is not None:
            host_keys = set(self.host.keys())
            assert demoted == host_keys, (
                f"host tier desync: {len(demoted)} demoted entries vs "
                f"{len(host_keys)} host payloads")
            assert not (stored_keys & host_keys), \
                "store-resident entry also holds a host payload"
            assert len(self.host) <= self.host.capacity, \
                "host tier exceeded its bound"
            promoting = {dst for dst, _, _ in self._pending_promotes}
            assert promoting <= mapped, \
                "in-flight promotion targets an unmapped block"
        else:
            assert not demoted, "demoted entry without a host tier"


# ---------------------------------------------------------------------------
# Device side: pool init, writes, gather view, forwards
# ---------------------------------------------------------------------------


def _alloc_pool_buf(cfg: LlamaConfig, shape, dtype, mesh,
                    head_axis: int) -> jax.Array:
    """A pool-side buffer of arbitrary rank/dtype sharded over its
    kv-head axis under a serving mesh (the generalization of
    decode.alloc_kv_buffer the int8 codes/scales/tails need — their
    ranks and dtypes differ from the bf16 pool's)."""
    buf = jnp.zeros(shape, dtype)
    if (mesh is not None and D.mesh_tp(mesh) > 1
            and cfg.n_kv_heads % D.mesh_tp(mesh) == 0):
        from jax.sharding import NamedSharding

        from paddle_operator_tpu.parallel.sharding import logical_to_mesh

        spec = tuple("kv_heads" if i == head_axis else None
                     for i in range(len(shape)))
        buf = jax.device_put(
            buf, NamedSharding(mesh, logical_to_mesh(spec, None, mesh)))
    return buf


def init_paged_cache(cfg: LlamaConfig, slots: int, total_blocks: int,
                     block_size: int, mesh=None,
                     quant: str = "none") -> Dict[str, jax.Array]:
    """The paged ring state: k/v pools [L, total_blocks, H_kv, bs, D]
    (kv-head-sharded under a serving mesh, like the ring cache) plus
    the per-lane fill position vector.  ``total_blocks`` INCLUDES the
    trash block (PagedCacheManager.total).

    ``quant="int8"`` splits each pool into int8 codes (same shape, half
    the bytes) + f32 scales ``ks``/``vs`` [L, total_blocks, H_kv] (one
    per block per kv head), and adds the bf16 staging tails ``kt``/
    ``vt`` [L, slots + 1, H_kv, bs, D]: lane b's WRITE block accumulates
    exact rows in tail row b and quantizes into the pool once, on block
    completion — so a block's scale is computed exactly once from its
    full contents, never re-derived per token.  Tail row ``slots`` is
    the TRASH tail: rows that must not land anywhere (prefill pads,
    inactive-lane ticks) redirect there, the per-lane analogue of pool
    block 0.  Everything shards over the kv-head axis."""
    shape = (cfg.n_layers, total_blocks, cfg.n_kv_heads, block_size,
             cfg.head_dim)
    if quant == "none":
        return {
            "k": D.alloc_kv_buffer(cfg, shape, mesh),
            "v": D.alloc_kv_buffer(cfg, shape, mesh),
            "pos": jnp.zeros((slots,), jnp.int32),
        }
    if quant != "int8":
        raise ValueError(f"kv_quant {quant!r} not in {KV_QUANT_MODES}")
    scale_shape = (cfg.n_layers, total_blocks, cfg.n_kv_heads)
    tail_shape = (cfg.n_layers, slots + 1, cfg.n_kv_heads, block_size,
                  cfg.head_dim)
    return {
        "k": _alloc_pool_buf(cfg, shape, jnp.int8, mesh, 2),
        "v": _alloc_pool_buf(cfg, shape, jnp.int8, mesh, 2),
        "ks": _alloc_pool_buf(cfg, scale_shape, jnp.float32, mesh, 2),
        "vs": _alloc_pool_buf(cfg, scale_shape, jnp.float32, mesh, 2),
        "kt": _alloc_pool_buf(cfg, tail_shape, cfg.dtype, mesh, 2),
        "vt": _alloc_pool_buf(cfg, tail_shape, cfg.dtype, mesh, 2),
        "pos": jnp.zeros((slots,), jnp.int32),
    }


def _write_token_paged(pool: jax.Array, kv: jax.Array, li: jax.Array,
                       table: jax.Array, pos: jax.Array,
                       block_size: int) -> jax.Array:
    """[L, N, H, bs, D] pool <- [B, H, 1, D] new rows, lane b's row at
    pool block ``table[b, pos_b // bs]`` offset ``pos_b % bs``.  Static
    unroll over lanes for the same reason as batcher._write_lane_stacked
    (a vmapped ragged update lowers to a carry-copying scatter)."""
    for lane in range(kv.shape[0]):
        blk = table[lane, pos[lane] // block_size]
        pool = jax.lax.dynamic_update_slice(
            pool, kv[lane][None, None],
            (li, blk, 0, pos[lane] % block_size, 0))
    return pool


def _write_rows_paged(pool: jax.Array, kv: jax.Array, li: jax.Array,
                      table: jax.Array, pos: jax.Array, block_size: int,
                      limit: Optional[jax.Array] = None) -> jax.Array:
    """[L, N, H, bs, D] pool <- [B, H, T, D] rows at per-lane start
    positions ``pos`` — rows land in whatever pool block the table maps
    for their absolute position (a row span may straddle blocks; every
    row is placed independently).  Rows at/after ``limit`` (per-lane;
    suffix-prefill pads) are redirected to the trash block instead of
    being masked out — the unroll stays branch-free."""
    b, _, t, _ = kv.shape
    for lane in range(b):
        for j in range(t):
            p = pos[lane] + j
            blk = table[lane, p // block_size]
            if limit is not None:
                blk = jnp.where(p < limit[lane], blk, TRASH_BLOCK)
            pool = jax.lax.dynamic_update_slice(
                pool, kv[lane, :, j][None, None, :, None, :],
                (li, blk, 0, p % block_size, 0))
    return pool


def _write_blocks_paged(pool: jax.Array, kv: jax.Array, li: jax.Array,
                        table: jax.Array, pos: jax.Array,
                        block_size: int,
                        limit: Optional[jax.Array] = None) -> jax.Array:
    """:func:`_write_rows_paged` for the BLOCK-ALIGNED case (the
    N-lane prefill engine's slice programs, ISSUE 14): ``pos`` is a
    block multiple and ``t`` a multiple of ``block_size`` — both
    guaranteed statically by the caller — so the slab lands as
    whole-block writes, O(lanes x blocks) dynamic_update_slice ops
    instead of the per-row unroll's O(lanes x rows).  At production
    slice widths the per-row trace is pathological to COMPILE (the
    ops sit inside the layer scan's body), not just slow to run.

    Padding follows :func:`ops.decode_attention.scatter_prefill_blocks`
    — the exactness-with-padding contract, block-granular: a block
    whose FIRST row is real writes whole (pad rows past ``limit`` land
    in the lane's real block, never attendable — masked in-slice,
    overwritten by decode before its reads); a block entirely past
    ``limit`` routes to the trash block."""
    b, _, t, _ = kv.shape
    for lane in range(b):
        for jb in range(t // block_size):
            p0 = pos[lane] + jb * block_size
            blk = table[lane, p0 // block_size]
            if limit is not None:
                blk = jnp.where(p0 < limit[lane], blk, TRASH_BLOCK)
            pool = jax.lax.dynamic_update_slice(
                pool,
                kv[lane, :, jb * block_size:(jb + 1) * block_size][
                    None, None],
                (li, blk, 0, 0, 0))
    return pool


def _write_token_quant(pool: jax.Array, scales: jax.Array,
                       tail: jax.Array, kv: jax.Array, li: jax.Array,
                       table: jax.Array, pos: jax.Array,
                       rows_idx: jax.Array, block_size: int):
    """Quantized-pool single-token write: lane b's new row ([B, H, 1, D]
    at position ``pos[b]``) lands in its bf16 staging tail (row
    ``rows_idx[b]`` — the lane's own row, or the trash tail for
    inactive lanes) at offset ``pos % bs``; a row that COMPLETES its
    block quantizes the whole tail block into the pool — codes + one
    scale — at the lane's table entry.  The commit sits behind a
    ``lax.cond`` so the 1-in-``block_size`` completing tick is the ONLY
    one paying the tile quantize + pool write (an always-computed tile
    discarded into the trash block would cost ~block_size x the bf16
    path's single-row write traffic, per lane per layer per step).
    Retired/masked lanes stay safe: their zeroed table rows send even
    a "complete" commit to the trash block."""
    hkv, d2 = kv.shape[1], kv.shape[3]
    for lane in range(kv.shape[0]):
        row = rows_idx[lane]
        tail = jax.lax.dynamic_update_slice(
            tail, kv[lane][None, None],
            (li, row, 0, pos[lane] % block_size, 0))
        complete = (pos[lane] + 1) % block_size == 0
        dst = table[lane, pos[lane] // block_size]

        def _commit(ps, row=row, dst=dst, tail=tail):
            pool, scales = ps
            tile = jax.lax.dynamic_slice(
                tail, (li, row, 0, 0, 0), (1, 1, hkv, block_size, d2))
            codes, scale = quantize_kv(tile)
            return (jax.lax.dynamic_update_slice(pool, codes,
                                                 (li, dst, 0, 0, 0)),
                    jax.lax.dynamic_update_slice(scales, scale,
                                                 (li, dst, 0)))

        pool, scales = jax.lax.cond(complete, _commit, lambda ps: ps,
                                    (pool, scales))
    return pool, scales, tail


def _gather_lane_view_quant(pool: jax.Array, scales: jax.Array,
                            tail: jax.Array, table: jax.Array,
                            li: jax.Array, wb: jax.Array) -> jax.Array:
    """:func:`_gather_lane_view` for the INT8 pool: gather codes AND
    scales through the block tables, dequantize, then substitute lane
    b's bf16 staging tail for its write-frontier block ``wb[b]`` — the
    partial block's exact rows live in the tail, not the pool.  Columns
    past the fill are masked by the caller's attention mask exactly as
    in the bf16 view (stale tail rows are finite, so masked columns
    still contribute exact zeros)."""
    layer = jax.lax.dynamic_index_in_dim(pool, li, 0, keepdims=False)
    sl = jax.lax.dynamic_index_in_dim(scales, li, 0, keepdims=False)
    tl = jax.lax.dynamic_index_in_dim(tail, li, 0, keepdims=False)
    b, m = table.shape
    _, h, bs, d = layer.shape
    v = jnp.take(layer, table.reshape(-1), axis=0)      # [B*M, H, bs, D]
    s = jnp.take(sl, table.reshape(-1), axis=0)         # [B*M, H]
    deq = v.astype(jnp.float32) * s[..., None, None]
    deq = deq.reshape(b, m, h, bs, d).transpose(0, 2, 1, 3, 4)
    deq = deq.reshape(b, h, m * bs, d)
    lt = tl[:b].astype(jnp.float32)                     # [B, H, bs, D]
    tiled = jnp.tile(lt, (1, 1, m, 1))                  # [B, H, m*bs, D]
    use_tail = (jnp.arange(m * bs) // bs)[None, :] == wb[:, None]
    out = jnp.where(use_tail[:, None, :, None], tiled, deq)
    return out.astype(tail.dtype)


def _gather_lane_view(pool: jax.Array, table: jax.Array,
                      li: jax.Array) -> jax.Array:
    """XLA ``take`` fallback view: pool layer ``li`` gathered through
    the block tables into the contiguous [B, H, M*bs, D] layout the
    einsum attention expects.  This is a materialized copy per layer —
    exactly what the paged kernel's table-driven index map avoids — and
    exists for the CPU / odd-shape / GSPMD-einsum paths."""
    layer = jax.lax.dynamic_index_in_dim(pool, li, 0, keepdims=False)
    b, m = table.shape
    _, h, bs, d = layer.shape
    v = jnp.take(layer, table.reshape(-1), axis=0)      # [B*M, H, bs, D]
    v = v.reshape(b, m, h, bs, d).transpose(0, 2, 1, 3, 4)
    return v.reshape(b, h, m * bs, d)


def _attend_einsum(cfg: LlamaConfig, q: jax.Array, k_view: jax.Array,
                   v_view: jax.Array, pos: jax.Array) -> jax.Array:
    """batcher._layer_step's attention block, lifted so the paged
    forward runs the IDENTICAL einsum/mask/softmax op sequence over the
    gathered view — columns [0, pos_b] hold the same values as the
    contiguous ring, masked tail columns contribute exact zeros, so
    greedy streams stay bit-identical to the oracle."""
    b = q.shape[0]
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    n_rep = hq // hkv
    s = k_view.shape[2]
    qg = q.reshape(b, 1, hkv, n_rep, d)
    scores = jnp.einsum("bthrd,bhsd->bthrs", qg, k_view,
                        preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(d))
    mask = jnp.arange(s)[None, :] <= pos[:, None]        # [B, S]
    scores = jnp.where(mask[:, None, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bthrs,bhsd->bthrd", probs.astype(cfg.dtype),
                     v_view, preferred_element_type=jnp.float32)
    return out.reshape(b, 1, hq * d).astype(cfg.dtype)


def paged_ring_forward(cfg: LlamaConfig, params: Dict[str, Any],
                       tok: jax.Array, cache: Dict[str, jax.Array],
                       table: jax.Array, mesh=None, quant: bool = False,
                       active: Optional[jax.Array] = None, lora=None
                       ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """batcher._ring_forward over the paged pool: tok [B] at per-lane
    cache['pos'] -> (logits [B, V], advanced cache).  The pools ride
    the layer scan as CARRY (block ids are dynamic; slicing a layer out
    per step would materialize it anyway), the kernel path hands the
    stacked pools + table to paged_decode_attention, the einsum path
    gathers the lane view per layer.

    ``quant=True`` (SERVE_KV_QUANT=int8): the cache is the codes+scales
    +staging-tails dict (init_paged_cache quant) — new rows accumulate
    exact in the lane's bf16 tail and quantize into the pool on block
    completion (:func:`_write_token_quant`); attention reads codes with
    the dequant fused in-kernel (or the dequantizing gather view on the
    einsum path).  ``active`` [B] redirects inactive lanes' tail writes
    to the trash tail — a mid-prefill lane's tail is live state the
    resident chunk step must not touch (the tail analogue of masking
    prefill-pending table rows to the trash block)."""
    from paddle_operator_tpu.infer.executor import _qkv_ring

    pos = cache["pos"]
    adp, aid = lora if lora is not None else (None, None)
    block_size = cache["k"].shape[3]
    x = params["tok_embed"]["embedding"].astype(cfg.dtype)[tok[:, None]]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                cfg.rope_theta)

    attn_impl = cfg.resolved_decode_attn()
    use_sharded = D._use_sharded_kernel(cfg, mesh, attn_impl)
    if D.mesh_tp(mesh) > 1 and not use_sharded:
        attn_impl = "xla"
    if quant:
        return _paged_ring_forward_quant(
            cfg, params, x, cache, table, pos, block_size, cos, sin,
            attn_impl, use_sharded, active, mesh, lora=lora)
    xs = ((params["layers"], adp, jnp.arange(cfg.n_layers))
          if adp is not None
          else (params["layers"], jnp.arange(cfg.n_layers)))

    def _unpack(layer_in):
        if adp is not None:
            lp, adp_l, li = layer_in
            return lp, li, (adp_l, aid)
        lp, li = layer_in
        return lp, li, None

    if use_sharded:
        from paddle_operator_tpu.ops.decode_attention import (
            sharded_paged_decode_attention,
        )

        def body(carry, layer_in):
            x, kc, vc = carry
            lp, li, lo = _unpack(layer_in)
            q, k, v = _qkv_ring(cfg, lp, x, cos, sin, pos, lora=lo)
            kc = _write_token_paged(kc, k.transpose(0, 2, 1, 3), li,
                                    table, pos, block_size)
            vc = _write_token_paged(vc, v.transpose(0, 2, 1, 3), li,
                                    table, pos, block_size)
            proj = sharded_paged_decode_attention(
                mesh, q[:, 0], kc, vc, table, pos + 1,
                lp["attn"]["wo"]["kernel"], layer=li,
                interpret=(attn_impl == "pallas-interpret"),
                compute_dtype=cfg.dtype)
            x = x + proj[:, None].astype(cfg.dtype)
            return (D._ffn_residual(cfg, lp, x), kc, vc), ()
    elif attn_impl != "xla":
        from paddle_operator_tpu.ops.decode_attention import (
            paged_decode_attention,
        )

        b = x.shape[0]
        hq, d = cfg.n_heads, cfg.head_dim

        def body(carry, layer_in):
            x, kc, vc = carry
            lp, li, lo = _unpack(layer_in)
            q, k, v = _qkv_ring(cfg, lp, x, cos, sin, pos, lora=lo)
            kc = _write_token_paged(kc, k.transpose(0, 2, 1, 3), li,
                                    table, pos, block_size)
            vc = _write_token_paged(vc, v.transpose(0, 2, 1, 3), li,
                                    table, pos, block_size)
            out = paged_decode_attention(
                q[:, 0], kc, vc, table, pos + 1, layer=li,
                interpret=(attn_impl == "pallas-interpret"))
            out = out.reshape(b, 1, hq * d).astype(cfg.dtype)
            return (D._finish_layer(cfg, lp, x, out), kc, vc), ()
    else:
        def body(carry, layer_in):
            x, kc, vc = carry
            lp, li, lo = _unpack(layer_in)
            q, k, v = _qkv_ring(cfg, lp, x, cos, sin, pos, lora=lo)
            kc = _write_token_paged(kc, k.transpose(0, 2, 1, 3), li,
                                    table, pos, block_size)
            vc = _write_token_paged(vc, v.transpose(0, 2, 1, 3), li,
                                    table, pos, block_size)
            out = _attend_einsum(cfg, q,
                                 _gather_lane_view(kc, table, li),
                                 _gather_lane_view(vc, table, li), pos)
            return (D._finish_layer(cfg, lp, x, out), kc, vc), ()

    (x, k_new, v_new), _ = jax.lax.scan(
        body, (x, cache["k"], cache["v"]), xs)
    x = D._rms(x, params["final_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    logits = D._mm(x, params["lm_head"]["kernel"],
                   cfg.dtype).astype(jnp.float32)
    return logits[:, 0], {"k": k_new, "v": v_new, "pos": pos + 1}


def _paged_ring_forward_quant(cfg, params, x, cache, table, pos,
                              block_size, cos, sin, attn_impl,
                              use_sharded, active, mesh, lora=None):
    """The quantized-pool decode forward (split out of
    :func:`paged_ring_forward` so the bf16 path stays byte-identical):
    same layer math, with the token write going through the staging
    tail (:func:`_write_token_quant`) and the attention reading int8
    codes — fused-dequant kernel where eligible, dequantizing gather
    view on the einsum path."""
    from paddle_operator_tpu.infer.executor import _qkv_ring

    b = x.shape[0]
    hq, d = cfg.n_heads, cfg.head_dim
    adp, aid = lora if lora is not None else (None, None)
    trash_row = cache["kt"].shape[1] - 1
    lanes = jnp.arange(b)
    rows_idx = (jnp.where(active, lanes, trash_row)
                if active is not None else lanes)
    xs = ((params["layers"], adp, jnp.arange(cfg.n_layers))
          if adp is not None
          else (params["layers"], jnp.arange(cfg.n_layers)))

    def _unpack(layer_in):
        if adp is not None:
            lp, adp_l, li = layer_in
            return lp, li, (adp_l, aid)
        lp, li = layer_in
        return lp, li, None

    if use_sharded:
        from paddle_operator_tpu.ops.decode_attention import (
            sharded_paged_decode_attention,
        )

        def body(carry, layer_in):
            x, kc, vc, ks, vs, kt, vt = carry
            lp, li, lo = _unpack(layer_in)
            q, k, v = _qkv_ring(cfg, lp, x, cos, sin, pos, lora=lo)
            kc, ks, kt = _write_token_quant(
                kc, ks, kt, k.transpose(0, 2, 1, 3), li, table, pos,
                rows_idx, block_size)
            vc, vs, vt = _write_token_quant(
                vc, vs, vt, v.transpose(0, 2, 1, 3), li, table, pos,
                rows_idx, block_size)
            proj = sharded_paged_decode_attention(
                mesh, q[:, 0], kc, vc, table, pos + 1,
                lp["attn"]["wo"]["kernel"], layer=li,
                interpret=(attn_impl == "pallas-interpret"),
                compute_dtype=cfg.dtype,
                k_scale=ks, v_scale=vs, k_tail=kt, v_tail=vt)
            x = x + proj[:, None].astype(cfg.dtype)
            return (D._ffn_residual(cfg, lp, x), kc, vc, ks, vs,
                    kt, vt), ()
    elif attn_impl != "xla":
        from paddle_operator_tpu.ops.decode_attention import (
            paged_decode_attention,
        )

        def body(carry, layer_in):
            x, kc, vc, ks, vs, kt, vt = carry
            lp, li, lo = _unpack(layer_in)
            q, k, v = _qkv_ring(cfg, lp, x, cos, sin, pos, lora=lo)
            kc, ks, kt = _write_token_quant(
                kc, ks, kt, k.transpose(0, 2, 1, 3), li, table, pos,
                rows_idx, block_size)
            vc, vs, vt = _write_token_quant(
                vc, vs, vt, v.transpose(0, 2, 1, 3), li, table, pos,
                rows_idx, block_size)
            out = paged_decode_attention(
                q[:, 0], kc, vc, table, pos + 1, layer=li,
                interpret=(attn_impl == "pallas-interpret"),
                k_scale=ks, v_scale=vs, k_tail=kt, v_tail=vt)
            out = out.reshape(b, 1, hq * d).astype(cfg.dtype)
            return (D._finish_layer(cfg, lp, x, out), kc, vc, ks, vs,
                    kt, vt), ()
    else:
        wb = pos // block_size

        def body(carry, layer_in):
            x, kc, vc, ks, vs, kt, vt = carry
            lp, li, lo = _unpack(layer_in)
            q, k, v = _qkv_ring(cfg, lp, x, cos, sin, pos, lora=lo)
            kc, ks, kt = _write_token_quant(
                kc, ks, kt, k.transpose(0, 2, 1, 3), li, table, pos,
                rows_idx, block_size)
            vc, vs, vt = _write_token_quant(
                vc, vs, vt, v.transpose(0, 2, 1, 3), li, table, pos,
                rows_idx, block_size)
            out = _attend_einsum(
                cfg, q, _gather_lane_view_quant(kc, ks, kt, table, li, wb),
                _gather_lane_view_quant(vc, vs, vt, table, li, wb), pos)
            return (D._finish_layer(cfg, lp, x, out), kc, vc, ks, vs,
                    kt, vt), ()

    (x, k_new, v_new, ks_new, vs_new, kt_new, vt_new), _ = jax.lax.scan(
        body, (x, cache["k"], cache["v"], cache["ks"], cache["vs"],
               cache["kt"], cache["vt"]), xs)
    x = D._rms(x, params["final_norm"]["scale"], cfg.norm_eps, cfg.dtype)
    logits = D._mm(x, params["lm_head"]["kernel"],
                   cfg.dtype).astype(jnp.float32)
    return logits[:, 0], {"k": k_new, "v": v_new, "ks": ks_new,
                          "vs": vs_new, "kt": kt_new, "vt": vt_new,
                          "pos": pos + 1}


def make_paged_chunk_step(cfg: LlamaConfig, chunk_tokens: int,
                          top_k: Optional[int] = None,
                          top_p: Optional[float] = None, mesh=None,
                          check_finite: bool = False,
                          quant: bool = False):
    """The resident compiled decode program of the PAGED ring — the
    exact contract of batcher.make_chunk_step plus the block table:

    ``step(params, cache, table, tok, temp, keys, active)
    -> (cache', tok', toks [chunk, B])``

    Retired/inactive lanes additionally get their position ZEROED (the
    serving-status staleness fix) — their writes route to the trash
    block through the zeroed table row, so nothing they do can touch a
    re-allocated block.

    ``check_finite=True``: a fourth ``ok [B]`` output — the per-lane
    isfinite fold of every tick's logits (batcher NaN-lane quarantine;
    see make_chunk_step).

    ``quant=True``: the cache is the int8 codes+scales+tails dict;
    ``active`` additionally steers inactive lanes' tail writes to the
    trash tail (see paged_ring_forward)."""
    from paddle_operator_tpu.infer.executor import _sample_tokens

    def step(params, cache, table, tok, temp, keys, active, *lora_args):
        lora = tuple(lora_args) if lora_args else None

        def tick(carry, _):
            if check_finite:
                cache, tok, ok = carry
            else:
                cache, tok = carry
            logits, new_cache = paged_ring_forward(
                cfg, params, tok, cache, table, mesh=mesh, quant=quant,
                active=active if quant else None, lora=lora)
            nxt = _sample_tokens(logits, temp, keys, cache["pos"],
                                 top_k, top_p)
            new_cache["pos"] = jnp.where(active, new_cache["pos"], 0)
            nxt = jnp.where(active, nxt, tok)
            if check_finite:
                ok = ok & jnp.all(jnp.isfinite(logits), axis=-1)
                return (new_cache, nxt, ok), nxt
            return (new_cache, nxt), nxt

        if check_finite:
            (cache, tok, ok), toks = jax.lax.scan(
                tick, (cache, tok, jnp.ones(tok.shape, bool)), None,
                length=chunk_tokens)
            return cache, tok, toks, ok
        (cache, tok), toks = jax.lax.scan(
            tick, (cache, tok), None, length=chunk_tokens)
        return cache, tok, toks

    return jax.jit(step, donate_argnums=(1,))


def make_paged_megastep(cfg: LlamaConfig, chunk_tokens: int,
                        n_steps: int, top_k: Optional[int] = None,
                        top_p: Optional[float] = None, mesh=None,
                        check_finite: bool = False,
                        quant: bool = False):
    """N fused PAGED ring iterations in one compiled dispatch
    (ISSUE 11): ``make_paged_chunk_step``'s tick scanned ``n_steps``
    chunks with the host's boundary decisions — eos, token budget,
    step budget — carried on device (executor._mega_advance).  The
    paged pool is what makes a mid-megastep finish SAFE without host
    help: each fused chunk runs against an EFFECTIVE table whose dead
    lanes' rows are replaced wholesale by the trash block (the same
    redirect ``retire`` performs host-side by zeroing the row), so a
    dead lane's free-running writes — pool rows, quantize-on-completion
    commits, staging-tail rows (``active=live`` steers those to the
    trash tail under quant) — can never touch a real block.  Its fill
    position is restored from the pre-chunk snapshot at each boundary,
    which is what makes a lane frozen by its STEP budget (deadline
    ticks) resumable bit-identically in a later dispatch: its blocks,
    tail and position are exactly as its last consumed token left them.

    ``mega(params, cache, table, tok, temp, keys, active, eos, left,
    steps, *lora) -> (cache', tok', toks [n, chunk, B], counts [n, B]
    [, oks [n, B]])`` — the same output contract as
    executor.make_megastep, table operand added."""
    from paddle_operator_tpu.infer.executor import (
        _mega_continue,
        _sample_tokens,
    )

    def mega(params, cache, table, tok, temp, keys, active, eos, left,
             steps, *lora_args):
        lora = tuple(lora_args) if lora_args else None

        def outer(carry, _):
            cache, tok, live, lleft, lsteps = carry
            p0 = cache["pos"]
            tbl_eff = jnp.where(live[:, None], table, TRASH_BLOCK)

            def tick(c, _):
                if check_finite:
                    cache, tok, ok = c
                else:
                    cache, tok = c
                logits, new_cache = paged_ring_forward(
                    cfg, params, tok, cache, tbl_eff, mesh=mesh,
                    quant=quant, active=live if quant else None,
                    lora=lora)
                nxt = _sample_tokens(logits, temp, keys, cache["pos"],
                                     top_k, top_p)
                new_cache["pos"] = jnp.where(live, new_cache["pos"], 0)
                nxt = jnp.where(live, nxt, tok)
                if check_finite:
                    ok = ok & (jnp.all(jnp.isfinite(logits), axis=-1)
                               | ~live)
                    return (new_cache, nxt, ok), nxt
                return (new_cache, nxt), nxt

            if check_finite:
                (cache, tok, ok), toks = jax.lax.scan(
                    tick, (cache, tok, jnp.ones(tok.shape, bool)), None,
                    length=chunk_tokens)
            else:
                (cache, tok), toks = jax.lax.scan(
                    tick, (cache, tok), None, length=chunk_tokens)
            raw = jnp.where(live, chunk_tokens, 0).astype(jnp.int32)
            count, live2, left2, lsteps2 = _mega_continue(
                toks, raw, live, lleft, lsteps, eos)
            cache["pos"] = jnp.where(live, cache["pos"], p0)
            out = (toks, count, ok) if check_finite else (toks, count)
            return (cache, tok, live2, left2, lsteps2), out

        live0 = active & (left > 0) & (steps > 0)
        if check_finite:
            (cache, tok, _, _, _), (toks, counts, oks) = jax.lax.scan(
                outer, (cache, tok, live0, left, steps), None,
                length=n_steps)
            return cache, tok, toks, counts, oks
        (cache, tok, _, _, _), (toks, counts) = jax.lax.scan(
            outer, (cache, tok, live0, left, steps), None,
            length=n_steps)
        return cache, tok, toks, counts

    return jax.jit(mega, donate_argnums=(1,))


def _scatter_prompt_blocks(pool: jax.Array, lane: jax.Array,
                           table_row: jax.Array,
                           block_size: int) -> jax.Array:
    """Write a contiguous [L, 1, H, bucket, D] prefilled lane cache
    into the pool as block-aligned chunks at the lane's table entries —
    the block-granular prefill-write path, shared with the kernels'
    module (ops/decode_attention.py scatter_prefill_blocks has the
    whole-block-vs-per-row story)."""
    from paddle_operator_tpu.ops.decode_attention import (
        scatter_prefill_blocks,
    )

    return scatter_prefill_blocks(pool, lane, table_row, block_size)


def make_paged_prefill_insert(cfg: LlamaConfig, bucket: int,
                              block_size: int,
                              top_k: Optional[int] = None,
                              top_p: Optional[float] = None, mesh=None,
                              quant: bool = False):
    """Cold (no prefix hit) paged admission — the contiguous
    make_prefill_insert with the splice replaced by a block scatter.
    The prefill forward and first-token sample are the SAME compiled
    ops as the contiguous insert, which is what makes the first token
    bit-identical between the two rings.

    ``quant=True``: whole blocks quantize once into the int8 pool; the
    prompt's partial last block lands exact in the lane's staging tail
    (decode.paged_prefill quant contract).

    ``insert(params, cache, table_row, tok, temp, keys,
    prompt [1,bucket], prompt_len, slot, temp_val, seed)
    -> (cache', tok', temp', keys', first_token)``
    """
    from paddle_operator_tpu.infer.executor import _sample_tokens

    if bucket % block_size:
        raise ValueError(f"prefill bucket {bucket} not a multiple of the "
                         f"block size {block_size}")

    def insert(params, cache, table_row, tok, temp, keys, prompt,
               prompt_len, slot, temp_val, seed, *lora_args):
        lora = tuple(lora_args) if lora_args else None
        if quant:
            logits, new_cache, tail_k, tail_v = D.paged_prefill(
                params, cfg, prompt, cache, table_row,
                block_size=block_size, mesh=mesh, quant=True,
                prompt_len=prompt_len, lora=lora)
            new_cache["kt"] = jax.lax.dynamic_update_slice(
                new_cache["kt"], tail_k, (0, slot, 0, 0, 0))
            new_cache["vt"] = jax.lax.dynamic_update_slice(
                new_cache["vt"], tail_v, (0, slot, 0, 0, 0))
        else:
            logits, new_cache = D.paged_prefill(params, cfg, prompt,
                                                cache, table_row,
                                                block_size=block_size,
                                                mesh=mesh, lora=lora)
        logits = logits[0, prompt_len - 1]
        new_cache["pos"] = new_cache["pos"].at[slot].set(prompt_len)
        key = jax.random.PRNGKey(seed)
        first = _sample_tokens(
            logits[None], jnp.reshape(temp_val, (1,)).astype(jnp.float32),
            key[None], jnp.reshape(prompt_len - 1, (1,)),
            top_k, top_p)[0]
        return (new_cache,
                tok.at[slot].set(first),
                temp.at[slot].set(temp_val),
                keys.at[slot].set(key),
                first)

    return jax.jit(insert, donate_argnums=(1, 3, 4, 5))


def _slice_lane_tails(cache: Dict[str, jax.Array], slot):
    """One lane's staging tails as 2-row mini-arrays (row 0 = the lane,
    row 1 = a zeroed trash row) for a batch-of-one quant forward —
    _multi_forward_paged addresses tails by lane index with the LAST
    row as trash, so a B=1 call needs exactly this shape."""
    lcount, _, h, bs, d = cache["kt"].shape
    mk = jax.lax.dynamic_slice(cache["kt"], (0, slot, 0, 0, 0),
                               (lcount, 1, h, bs, d))
    mv = jax.lax.dynamic_slice(cache["vt"], (0, slot, 0, 0, 0),
                               (lcount, 1, h, bs, d))
    return (jnp.concatenate([mk, jnp.zeros_like(mk)], axis=1),
            jnp.concatenate([mv, jnp.zeros_like(mv)], axis=1))


def _restore_lane_tails(cache: Dict[str, jax.Array],
                        new_lane: Dict[str, jax.Array], slot):
    """Write a B=1 quant forward's mini-tail row back into the full
    per-slot tail arrays."""
    kt = jax.lax.dynamic_update_slice(
        cache["kt"], new_lane["kt"][:, :1], (0, slot, 0, 0, 0))
    vt = jax.lax.dynamic_update_slice(
        cache["vt"], new_lane["vt"][:, :1], (0, slot, 0, 0, 0))
    return kt, vt


def make_paged_suffix_insert(cfg: LlamaConfig, suffix_bucket: int,
                             block_size: int,
                             top_k: Optional[int] = None,
                             top_p: Optional[float] = None, mesh=None,
                             quant: bool = False):
    """Prefix-HIT paged admission: the lane's table already maps the
    cached prefix blocks (read-only; CoW'd where the suffix will
    write), so the forward runs over the SUFFIX ONLY — a multi-token
    per-lane-offset forward (speculative._multi_forward_paged) whose
    attention walks the block table.  A shared 2048-token system prompt
    costs its followers exactly the suffix; the prefill-call counter
    the tests assert on never ticks for the cached prefix.

    ``quant=True``: the suffix rows accumulate in the lane's staging
    tail (sliced to a 2-row mini-tail for the B=1 forward) and whole
    blocks quantize on completion; the CoW'd hit block's content must
    already be dequantized into the tail by the scheduler's tail-init
    dispatch when ``hit_len`` lands mid-block.

    ``insert(params, cache, table_row [M], tok, temp, keys,
    suffix [1, suffix_bucket], suffix_len, hit_len, slot, temp_val,
    seed) -> (cache', tok', temp', keys', first_token)``
    """
    from paddle_operator_tpu.infer.executor import _sample_tokens
    from paddle_operator_tpu.infer.speculative import _multi_forward_paged

    def insert(params, cache, table_row, tok, temp, keys, suffix,
               suffix_len, hit_len, slot, temp_val, seed, *lora_args):
        prompt_len = hit_len + suffix_len
        lane_cache = {"k": cache["k"], "v": cache["v"],
                      "pos": jnp.reshape(hit_len, (1,))}
        if quant:
            lane_cache["ks"], lane_cache["vs"] = cache["ks"], cache["vs"]
            lane_cache["kt"], lane_cache["vt"] = _slice_lane_tails(
                cache, slot)
        logits, new_lane = _multi_forward_paged(
            cfg, params, suffix, lane_cache, table_row[None, :],
            limit=jnp.reshape(prompt_len, (1,)), mesh=mesh, quant=quant,
            lora=tuple(lora_args) if lora_args else None)
        logits = logits[0, suffix_len - 1]
        new_cache = {"k": new_lane["k"], "v": new_lane["v"],
                     "pos": cache["pos"].at[slot].set(prompt_len)}
        if quant:
            new_cache["ks"], new_cache["vs"] = (new_lane["ks"],
                                                new_lane["vs"])
            new_cache["kt"], new_cache["vt"] = _restore_lane_tails(
                cache, new_lane, slot)
        key = jax.random.PRNGKey(seed)
        first = _sample_tokens(
            logits[None], jnp.reshape(temp_val, (1,)).astype(jnp.float32),
            key[None], jnp.reshape(prompt_len - 1, (1,)),
            top_k, top_p)[0]
        return (new_cache,
                tok.at[slot].set(first),
                temp.at[slot].set(temp_val),
                keys.at[slot].set(key),
                first)

    return jax.jit(insert, donate_argnums=(1, 3, 4, 5))


def make_paged_spec_prefill_insert(cfg: LlamaConfig, dcfg: LlamaConfig,
                                   bucket: int, block_size: int,
                                   top_k: Optional[int] = None,
                                   top_p: Optional[float] = None,
                                   mesh=None, quant: bool = False):
    """Speculative paged admission: target prefill scatters into the
    pool, the DRAFT lane stays a contiguous ring splice (the draft
    cache is small — paging it buys nothing, and the draft's propose
    loop keeps the fast contiguous write path).  ``quant=True``
    quantizes the TARGET pool only — the draft ring stays bf16, the
    same asymmetry (infer/speculative.py docstring).

    ``insert(params, dparams, cache, dcache, table_row, tok, temp,
    keys, prompt, prompt_len, slot, temp_val, seed)
    -> (cache', dcache', tok', temp', keys', first_token)``
    """
    from paddle_operator_tpu.infer.executor import (
        _sample_tokens,
        _splice_lane,
    )

    if bucket % block_size:
        raise ValueError(f"prefill bucket {bucket} not a multiple of the "
                         f"block size {block_size}")

    def insert(params, dparams, cache, dcache, table_row, tok, temp, keys,
               prompt, prompt_len, slot, temp_val, seed):
        if quant:
            logits, new_cache, tail_k, tail_v = D.paged_prefill(
                params, cfg, prompt, cache, table_row,
                block_size=block_size, mesh=mesh, quant=True,
                prompt_len=prompt_len)
            new_cache["kt"] = jax.lax.dynamic_update_slice(
                new_cache["kt"], tail_k, (0, slot, 0, 0, 0))
            new_cache["vt"] = jax.lax.dynamic_update_slice(
                new_cache["vt"], tail_v, (0, slot, 0, 0, 0))
        else:
            logits, new_cache = D.paged_prefill(params, cfg, prompt,
                                                cache, table_row,
                                                block_size=block_size,
                                                mesh=mesh)
        logits = logits[0, prompt_len - 1]
        new_cache["pos"] = new_cache["pos"].at[slot].set(prompt_len)
        dlane = D.init_cache(dcfg, 1, bucket)
        _, dlane = D._forward(dcfg, dparams, prompt, dlane,
                              last_only=True, mesh=mesh)
        new_dcache = _splice_lane(dcache, dlane, slot, prompt_len)
        key = jax.random.PRNGKey(seed)
        first = _sample_tokens(
            logits[None], jnp.reshape(temp_val, (1,)).astype(jnp.float32),
            key[None], jnp.reshape(prompt_len - 1, (1,)),
            top_k, top_p)[0]
        return (new_cache, new_dcache,
                tok.at[slot].set(first),
                temp.at[slot].set(temp_val),
                keys.at[slot].set(key),
                first)

    return jax.jit(insert, donate_argnums=(2, 3, 5, 6, 7))


def make_paged_prefill_chunk(cfg: LlamaConfig, slice_bucket: int,
                             block_size: int, mesh=None,
                             quant: bool = False):
    """One INTERMEDIATE chunked-prefill slice against the block pool
    (executor/scheduler ``prefill_mode="chunked"``): append the slice's
    KV rows at absolute positions [start, start + slice_bucket) through
    the lane's table — no lm head, no lane-state update, no first
    token; only the FINAL slice (which is exactly the SUFFIX insert
    with ``hit_len = rows already written``) does those.  Rows at or
    past ``limit`` route to the trash block, so a partial-tail radix
    hit can start a chunked prefill mid-block safely.

    ``chunk(params, cache, table_row [M], toks [1, slice_bucket],
    start, limit) -> cache'``

    ``quant=True`` adds a trailing ``slot`` argument (the tail rows
    address by lane): slices accumulate in the lane's staging tail and
    quantize whole blocks as they complete, so the tail state carried
    between slices IS the cache dict's — no extra bookkeeping.
    """
    from paddle_operator_tpu.infer.speculative import _multi_forward_paged

    def chunk(params, cache, table_row, toks, start, limit, *lora_args):
        lane_cache = {"k": cache["k"], "v": cache["v"],
                      "pos": jnp.reshape(start, (1,)).astype(jnp.int32)}
        _, new = _multi_forward_paged(
            cfg, params, toks, lane_cache, table_row[None, :],
            limit=jnp.reshape(limit, (1,)), mesh=mesh, head=False,
            lora=tuple(lora_args) if lora_args else None)
        return {"k": new["k"], "v": new["v"], "pos": cache["pos"]}

    def chunk_quant(params, cache, table_row, toks, start, limit, slot,
                    *lora_args):
        mk, mv = _slice_lane_tails(cache, slot)
        lane_cache = {"k": cache["k"], "v": cache["v"],
                      "ks": cache["ks"], "vs": cache["vs"],
                      "kt": mk, "vt": mv,
                      "pos": jnp.reshape(start, (1,)).astype(jnp.int32)}
        _, new = _multi_forward_paged(
            cfg, params, toks, lane_cache, table_row[None, :],
            limit=jnp.reshape(limit, (1,)), mesh=mesh, head=False,
            quant=True,
            lora=tuple(lora_args) if lora_args else None)
        kt, vt = _restore_lane_tails(cache, new, slot)
        return {"k": new["k"], "v": new["v"], "ks": new["ks"],
                "vs": new["vs"], "kt": kt, "vt": vt,
                "pos": cache["pos"]}

    return jax.jit(chunk_quant if quant else chunk, donate_argnums=(1,))


def make_paged_spec_suffix_insert(cfg: LlamaConfig, dcfg: LlamaConfig,
                                  suffix_bucket: int, bucket: int,
                                  block_size: int,
                                  top_k: Optional[int] = None,
                                  top_p: Optional[float] = None,
                                  mesh=None, quant: bool = False):
    """Final chunked-prefill slice for the SPECULATIVE paged ring: the
    target's remaining suffix rows ride the block table exactly like
    :func:`make_paged_suffix_insert`; the DRAFT prefills its whole
    prompt in one pass (it is depth/4 x heads/2 by construction) and
    splices contiguously, as everywhere else in spec mode.

    ``insert(params, dparams, cache, dcache, table_row, tok, temp,
    keys, suffix [1, suffix_bucket], suffix_len, hit_len, slot,
    prompt [1, bucket], prompt_len, temp_val, seed)
    -> (cache', dcache', tok', temp', keys', first_token)``
    """
    from paddle_operator_tpu.infer.executor import (
        _sample_tokens,
        _splice_lane,
    )
    from paddle_operator_tpu.infer.speculative import _multi_forward_paged

    def insert(params, dparams, cache, dcache, table_row, tok, temp,
               keys, suffix, suffix_len, hit_len, slot, prompt,
               prompt_len, temp_val, seed):
        lane_cache = {"k": cache["k"], "v": cache["v"],
                      "pos": jnp.reshape(hit_len, (1,))}
        if quant:
            lane_cache["ks"], lane_cache["vs"] = cache["ks"], cache["vs"]
            lane_cache["kt"], lane_cache["vt"] = _slice_lane_tails(
                cache, slot)
        logits, new_lane = _multi_forward_paged(
            cfg, params, suffix, lane_cache, table_row[None, :],
            limit=jnp.reshape(prompt_len, (1,)), mesh=mesh, quant=quant)
        logits = logits[0, suffix_len - 1]
        new_cache = {"k": new_lane["k"], "v": new_lane["v"],
                     "pos": cache["pos"].at[slot].set(prompt_len)}
        if quant:
            new_cache["ks"], new_cache["vs"] = (new_lane["ks"],
                                                new_lane["vs"])
            new_cache["kt"], new_cache["vt"] = _restore_lane_tails(
                cache, new_lane, slot)
        dlane = D.init_cache(dcfg, 1, bucket)
        _, dlane = D._forward(dcfg, dparams, prompt, dlane,
                              last_only=True, mesh=mesh)
        new_dcache = _splice_lane(dcache, dlane, slot, prompt_len)
        key = jax.random.PRNGKey(seed)
        first = _sample_tokens(
            logits[None], jnp.reshape(temp_val, (1,)).astype(jnp.float32),
            key[None], jnp.reshape(prompt_len - 1, (1,)),
            top_k, top_p)[0]
        return (new_cache, new_dcache,
                tok.at[slot].set(first),
                temp.at[slot].set(temp_val),
                keys.at[slot].set(key),
                first)

    return jax.jit(insert, donate_argnums=(2, 3, 5, 6, 7))


@functools.lru_cache(maxsize=8)
def make_pool_transfer(max_blocks: int, quant: bool = False):
    """The disaggregated HANDOFF op: copy ``max_blocks`` pool blocks
    from the prefill executor's (small, private) pool into the decode
    pool — all layers, K and V, one donated jit.  Block-id vectors are
    PADDED to ``max_blocks`` with the trash block so one compile serves
    every prompt length (writing garbage into the trash block is its
    job; gathering src block 0 reads the executor pool's own trash).
    This is the in-process device-to-device stand-in for DistServe's
    KV transfer; a DCN-crossing variant would replace only this op.

    ``transfer(dst_k, dst_v, src_k, src_v, src_ids [M], dst_ids [M])
    -> (dst_k', dst_v')``

    ``quant=True``: codes, scales AND the prompt's staging tail all
    cross (the tail is the partial last block the prefill executor
    could not finalize) — src tail row 0 (the executor pool is one
    lane wide) lands in decode tail row ``slot``:

    ``transfer(dst_k, dst_v, dst_ks, dst_vs, dst_kt, dst_vt,
    src_k, src_v, src_ks, src_vs, src_kt, src_vt, src_ids, dst_ids,
    slot) -> (dst_k', dst_v', dst_ks', dst_vs', dst_kt', dst_vt')``
    """

    def transfer(dst_k, dst_v, src_k, src_v, src_ids, dst_ids):
        gk = jnp.take(src_k, src_ids, axis=1)     # [L, M, H, bs, D]
        gv = jnp.take(src_v, src_ids, axis=1)
        return (dst_k.at[:, dst_ids].set(gk),
                dst_v.at[:, dst_ids].set(gv))

    def transfer_quant(dst_k, dst_v, dst_ks, dst_vs, dst_kt, dst_vt,
                       src_k, src_v, src_ks, src_vs, src_kt, src_vt,
                       src_ids, dst_ids, slot):
        dst_k, dst_v = transfer(dst_k, dst_v, src_k, src_v, src_ids,
                                dst_ids)
        dst_ks = dst_ks.at[:, dst_ids].set(
            jnp.take(src_ks, src_ids, axis=1))
        dst_vs = dst_vs.at[:, dst_ids].set(
            jnp.take(src_vs, src_ids, axis=1))
        dst_kt = jax.lax.dynamic_update_slice(
            dst_kt, src_kt[:, :1], (0, slot, 0, 0, 0))
        dst_vt = jax.lax.dynamic_update_slice(
            dst_vt, src_vt[:, :1], (0, slot, 0, 0, 0))
        return dst_k, dst_v, dst_ks, dst_vs, dst_kt, dst_vt

    if quant:
        return jax.jit(transfer_quant, donate_argnums=(0, 1, 2, 3, 4, 5))
    return jax.jit(transfer, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=8)
def make_pool_frame_transfer(max_blocks: int, quant: bool = False):
    """One streamed-handoff FRAME's device-to-device copy (ISSUE 14):
    like :func:`make_pool_transfer` but blocks only — no staging tail
    and no lane addressing — because intermediate frames carry only
    COMPLETE block groups (the tail is by definition the still-moving
    write frontier, and it crosses exactly once, on the terminal
    frame via :func:`make_pool_tail_copy`).  Id vectors pad with the
    trash block as everywhere else, so ONE compile serves every frame
    width.

    ``transfer(dst_k, dst_v[, dst_ks, dst_vs], src_k, src_v[, src_ks,
    src_vs], src_ids [M], dst_ids [M]) -> dst arrays``"""

    def transfer(dst_k, dst_v, src_k, src_v, src_ids, dst_ids):
        return (dst_k.at[:, dst_ids].set(jnp.take(src_k, src_ids,
                                                  axis=1)),
                dst_v.at[:, dst_ids].set(jnp.take(src_v, src_ids,
                                                  axis=1)))

    def transfer_quant(dst_k, dst_v, dst_ks, dst_vs, src_k, src_v,
                       src_ks, src_vs, src_ids, dst_ids):
        dst_k, dst_v = transfer(dst_k, dst_v, src_k, src_v, src_ids,
                                dst_ids)
        return (dst_k, dst_v,
                dst_ks.at[:, dst_ids].set(jnp.take(src_ks, src_ids,
                                                   axis=1)),
                dst_vs.at[:, dst_ids].set(jnp.take(src_vs, src_ids,
                                                   axis=1)))

    if quant:
        return jax.jit(transfer_quant, donate_argnums=(0, 1, 2, 3))
    return jax.jit(transfer, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=2)
def make_pool_tail_copy():
    """The terminal streamed-handoff's staging-tail copy (int8 pools
    only): src tail row ``src_row`` — the prefill ENGINE lane that ran
    the job, now that the pool is N lanes wide (ISSUE 14) — lands in
    decode tail row ``slot``.  The 1-lane monolithic path keeps the
    fused tail copy inside :func:`make_pool_transfer`; this exists for
    the multi-lane engine whose tail row is job-dependent.

    ``cp(dst_kt, dst_vt, src_kt, src_vt, src_row, slot)
    -> (dst_kt', dst_vt')``"""

    def cp(dst_kt, dst_vt, src_kt, src_vt, src_row, slot):
        lcount, _, h, bs, d = src_kt.shape
        kt = jax.lax.dynamic_slice(src_kt, (0, src_row, 0, 0, 0),
                                   (lcount, 1, h, bs, d))
        vt = jax.lax.dynamic_slice(src_vt, (0, src_row, 0, 0, 0),
                                   (lcount, 1, h, bs, d))
        return (jax.lax.dynamic_update_slice(dst_kt, kt,
                                             (0, slot, 0, 0, 0)),
                jax.lax.dynamic_update_slice(dst_vt, vt,
                                             (0, slot, 0, 0, 0)))

    return jax.jit(cp, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=4)
def make_block_fetch(quant: bool = False):
    """The DEMOTE read: slice ONE pool block's exact device bytes (all
    layers, K and V — plus its scale rows under int8) for the host
    fetch the spill tier stores.  Not donated: the pool stays live.
    ``fetch(k, v, blk) -> (kb [L,1,H,bs,D], vb)``; quant adds
    ``ks``/``vs`` -> ``(kb, vb, ksb [L,1,H], vsb)``."""

    def fetch(k, v, blk):
        lcount, _, h, bs, d = k.shape
        kb = jax.lax.dynamic_slice(k, (0, blk, 0, 0, 0),
                                   (lcount, 1, h, bs, d))
        vb = jax.lax.dynamic_slice(v, (0, blk, 0, 0, 0),
                                   (lcount, 1, h, bs, d))
        return kb, vb

    def fetch_quant(k, v, ks, vs, blk):
        lcount = k.shape[0]
        h = k.shape[2]
        kb, vb = fetch(k, v, blk)
        ksb = jax.lax.dynamic_slice(ks, (0, blk, 0), (lcount, 1, h))
        vsb = jax.lax.dynamic_slice(vs, (0, blk, 0), (lcount, 1, h))
        return kb, vb, ksb, vsb

    return jax.jit(fetch_quant if quant else fetch)


@functools.lru_cache(maxsize=8)
def make_promote_blocks(block_size: int, quant: bool = False,
                        donate: bool = True):
    """The PROMOTE upload: scatter a batch of host payloads into their
    reserved pool blocks in ONE donated jit — the bf16 path is exactly
    the whole-block ``scatter_prefill_blocks`` write the prefill path
    uses (the payload batch rides as one contiguous
    ``[L, 1, H, n*bs, D]`` slab, block j landing at ``ids[j]``); the
    int8 path copies codes AND scale rows verbatim
    (ops/decode_attention.py ``scatter_promote_blocks_quant``) — a
    promote never re-quantizes, which is what makes a host hit
    bit-identical to the HBM hit it demoted from.  Callers pad ``ids``
    with the trash block (and the slab with zeros) to a small shape
    ladder so a handful of compiles serves every batch size.

    ``up(pool_k, pool_v, rows_k, rows_v, ids) -> (pool_k', pool_v')``;
    quant: ``up(pool_k, pool_v, ks, vs, rows_k, rows_v, srow_k,
    srow_v, ids) -> (pool_k', pool_v', ks', vs')`` with ``srow_*``
    [L, n, H] scale rows.

    ``donate=False`` (ISSUE 14): the multi-lane prefill engine's
    prefix-hit upload — its streamed-handoff frames hold version
    snapshots of the SAME pool arrays, and donating a buffer a posted
    frame still references would delete it under the decode side's
    transfer."""
    from paddle_operator_tpu.ops.decode_attention import (
        scatter_prefill_blocks,
        scatter_promote_blocks_quant,
    )

    def up(pool_k, pool_v, rows_k, rows_v, ids):
        pool_k = scatter_prefill_blocks(pool_k, rows_k, ids, block_size)
        pool_v = scatter_prefill_blocks(pool_v, rows_v, ids, block_size)
        return pool_k, pool_v

    def up_quant(pool_k, pool_v, ks, vs, rows_k, rows_v, srow_k, srow_v,
                 ids):
        pool_k, ks = scatter_promote_blocks_quant(
            pool_k, ks, rows_k, srow_k, ids, block_size)
        pool_v, vs = scatter_promote_blocks_quant(
            pool_v, vs, rows_v, srow_v, ids, block_size)
        return pool_k, pool_v, ks, vs

    if quant:
        return jax.jit(up_quant,
                       donate_argnums=(0, 1, 2, 3) if donate else ())
    return jax.jit(up, donate_argnums=(0, 1) if donate else ())


@functools.lru_cache(maxsize=4)
def make_block_copier(quant: bool = False):
    """The CoW device op: copy pool block ``src`` over block ``dst``
    (all layers, K and V) in one donated jit — dispatched once per
    copy-on-write admission, BEFORE the admission insert, so the
    insert's gather reads the private copy.  ``quant=True`` copies
    codes AND scales: ``cp(k, v, ks, vs, src, dst)``."""

    def cp(k, v, src, dst):
        ks = jax.lax.dynamic_slice_in_dim(k, src, 1, axis=1)
        vs = jax.lax.dynamic_slice_in_dim(v, src, 1, axis=1)
        k = jax.lax.dynamic_update_slice_in_dim(k, ks, dst, axis=1)
        v = jax.lax.dynamic_update_slice_in_dim(v, vs, dst, axis=1)
        return k, v

    def cp_quant(k, v, ks, vs, src, dst):
        k, v = cp(k, v, src, dst)
        kss = jax.lax.dynamic_slice_in_dim(ks, src, 1, axis=1)
        vss = jax.lax.dynamic_slice_in_dim(vs, src, 1, axis=1)
        ks = jax.lax.dynamic_update_slice_in_dim(ks, kss, dst, axis=1)
        vs = jax.lax.dynamic_update_slice_in_dim(vs, vss, dst, axis=1)
        return k, v, ks, vs

    if quant:
        return jax.jit(cp_quant, donate_argnums=(0, 1, 2, 3))
    return jax.jit(cp, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=4)
def make_tail_init():
    """Quant-pool admission helper: a lane starting MID-BLOCK (a
    partial-tail radix hit, or a full hit capped at n-1 tokens) will
    write into a block that already holds quantized content (its CoW'd
    private copy) — seed the lane's bf16 staging tail with that block's
    DEQUANTIZED rows so the suffix forward reads [block_start, hit_len)
    exactly as every other reader does, then overwrites from hit_len
    on.  One tiny donated dispatch, scheduler-side, after the CoW copy.

    ``init(kt, vt, k, ks, v, vs, slot, blk) -> (kt', vt')``
    """

    def init(kt, vt, k, ks, v, vs, slot, blk):
        lcount, _, h, bs, d = kt.shape
        ktile = dequantize_kv(
            jax.lax.dynamic_slice(k, (0, blk, 0, 0, 0),
                                  (lcount, 1, h, bs, d)),
            jax.lax.dynamic_slice(ks, (0, blk, 0), (lcount, 1, h)),
            kt.dtype)
        vtile = dequantize_kv(
            jax.lax.dynamic_slice(v, (0, blk, 0, 0, 0),
                                  (lcount, 1, h, bs, d)),
            jax.lax.dynamic_slice(vs, (0, blk, 0), (lcount, 1, h)),
            vt.dtype)
        kt = jax.lax.dynamic_update_slice(kt, ktile, (0, slot, 0, 0, 0))
        vt = jax.lax.dynamic_update_slice(vt, vtile, (0, slot, 0, 0, 0))
        return kt, vt

    return jax.jit(init, donate_argnums=(0, 1))
