"""paddle_operator_tpu — a TPU-native distributed training job framework.

A ground-up rebuild of the capability set of ``renhuanyu/paddle-operator``
(a Kubernetes operator that schedules PaddlePaddle jobs onto GPU nodes),
re-designed for TPU pod slices:

- ``api``        — the ``TPUJob`` custom-resource types and CRD schema
                   (capability parity: reference ``api/v1/paddlejob_types.go``).
- ``controller`` — the reconciler state machine and the pure pod/service/
                   configmap builders (reference ``controllers/``), plus the
                   native host-port allocator (reference
                   ``third_party/hostport-allocator``).
- ``launch``     — the in-pod launcher: reads the injected rendezvous env
                   contract and brings up ``jax.distributed`` over ICI/DCN
                   (the reference delegates this to
                   ``paddle.distributed.launch`` inside user containers).
- ``parallel``   — device-mesh construction, sharding rules, ring attention
                   (context parallel), pipeline parallel, PS embedding tier.
- ``models``     — flagship workloads matching the reference's benchmark
                   configs: LLaMA, ERNIE-style encoder, ResNet, Wide&Deep.
- ``ops``        — TPU pallas kernels (flash attention) with XLA fallbacks.
- ``train``      — sharded train step, optimizer, checkpoint/resume.
- ``utils``      — logging, registry, misc helpers.

The control plane is pure Python (kubernetes-client gated behind an API
interface so it is fully testable in-process); the hot allocator is C++
(``native/``); the compute path is JAX/XLA/pallas.
"""

__version__ = "0.1.0"

GROUP = "batch.tpujob.dev"
VERSION = "v1"
KIND = "TPUJob"
PLURAL = "tpujobs"
SHORT_NAME = "tpj"
